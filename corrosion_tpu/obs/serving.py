"""Serving query-cost plane: the fallback-cliff heatmap + standing gate.

Input: a ``fanout_storm`` run block produced with ``sub_costs=True`` —
its ``sub_costs`` block carries the ``corro-sub-cost/1`` ledger snapshot
(per-subscription counters + the query-plan classifier's record), the
oracle-group -> matcher-sub mapping, and the oracle's delivery records
(per-stream delivered mass + wall/mono-stamped deliveries).

:func:`build_serving_report` joins them into per-subscription
lag-vs-cost attribution:

- **top-K slow subscriptions** by total eval seconds, with their class
  and cost counters;
- **fallback share**: what fraction of all matcher eval seconds the
  fallback-bound population burned — the number ROADMAP item 3's
  incremental matcher must drive down;
- **per-class delivery-lag percentiles** (window / aggregate / join /
  simple), computed per delivery against the commit's monotonic ack;
- **exact mass reconciliation**: each mapped subscription's ledger
  fan-out events (+ replayed rows) must equal the oracle's delivered
  change count for its streams — the ledger cannot under- or
  over-report what the oracle independently observed.

:func:`check_serving_cost_budget` gates the report against the
``serving_cost`` entry of bench_budget.json, including the
machinery-fired rule: a storm where no fallback-bound subscription was
ever observed evaluating is a **test-harness failure** (the gate exists
to measure the cliff; green-with-idle-machinery means the storm never
reached it). :func:`diff_serving_reports` compares a candidate report
against the committed ``SERVING_COST_BASELINE.json``.

Everything here is jax-free (obs analyzers run on any host).
"""

from __future__ import annotations

import json

LEDGER_KIND = "corro-sub-cost"
LEDGER_VERSION = 1
REPORT_KIND = "corro-serving-cost"
REPORT_VERSION = 1

# Dimensions the budget must match exactly (cf. loadgen/report.py
# SERVING_DIMS): a shrunk smoke config cannot silently loosen the gate.
SERVING_COST_DIMS = ("platform", "scenario", "streams")


def _get(obj, path: str):
    cur = obj
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def write_cost_ledger(path: str, snapshot: dict, context: dict | None = None) -> None:
    """Write a SubsManager.cost_snapshot() as a self-describing
    ``corro-sub-cost/1`` JSONL artifact: one header record, then one
    record per subscription."""
    header = {
        "kind": LEDGER_KIND,
        "version": LEDGER_VERSION,
        "enabled": snapshot.get("enabled", False),
        "subs_total": snapshot.get("subs_total"),
        "totals": snapshot.get("totals", {}),
    }
    if context:
        header["context"] = context
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(header, separators=(",", ":")) + "\n")
        for rec in snapshot.get("subs", ()):
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")


def read_cost_ledger(path: str) -> dict:
    """Read a ``corro-sub-cost/1`` artifact back into snapshot shape;
    refuses files of the wrong kind/version."""
    with open(path, encoding="utf-8") as f:
        lines = [ln for ln in f if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty cost-ledger file")
    header = json.loads(lines[0])
    if header.get("kind") != LEDGER_KIND:
        raise ValueError(
            f"{path}: kind {header.get('kind')!r}, expected {LEDGER_KIND!r}"
        )
    if header.get("version") != LEDGER_VERSION:
        raise ValueError(
            f"{path}: version {header.get('version')!r}, expected "
            f"{LEDGER_VERSION}"
        )
    return {
        "kind": LEDGER_KIND,
        "version": LEDGER_VERSION,
        "enabled": header.get("enabled", False),
        "subs_total": header.get("subs_total"),
        "totals": header.get("totals", {}),
        "subs": [json.loads(ln) for ln in lines[1:]],
    }


def _pct(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _lag_block(lags_ms: list[float]) -> dict:
    s = sorted(lags_ms)
    return {
        "count": len(s),
        "p50": round(_pct(s, 0.50), 3) if s else None,
        "p90": round(_pct(s, 0.90), 3) if s else None,
        "p99": round(_pct(s, 0.99), 3) if s else None,
        "max": round(s[-1], 3) if s else None,
    }


def build_serving_report(run: dict, top_k: int = 10) -> dict:
    """Join the run's cost ledger with its oracle delivery records into
    the ``corro-serving-cost/1`` attribution report (see module
    docstring). Raises ``ValueError`` when the run carries no
    ``sub_costs`` block — a heatmap without a ledger would silently
    attribute nothing."""
    sc = run.get("sub_costs")
    if not sc or not sc.get("ledger"):
        raise ValueError(
            "run has no sub_costs ledger — rerun the storm with the "
            "query-cost plane armed (loadgen run --sub-costs)"
        )
    ledger = sc["ledger"]
    records = sc.get("oracle_records") or {}
    streams = records.get("streams") or []
    if not streams:
        raise ValueError(
            "run has no oracle stream records — the serving-cost join "
            "needs delivery counts per stream (keep_deliveries)"
        )
    groups_map = {int(g): sid for g, sid in (sc.get("groups") or {}).items()}
    subs_by_id = {rec["sub_id"]: rec for rec in ledger.get("subs", ())}

    # sub_id -> [oracle groups]; every mapped group is one distinct query
    # hence one matcher handle.
    groups_of: dict[str, list[int]] = {}
    for g, sid in groups_map.items():
        groups_of.setdefault(sid, []).append(g)

    # Per-group delivered change mass + per-group delivery lags.
    delivered_by_group: dict[int, int] = {}
    group_of_sid: dict[int, int | None] = {}
    for st in streams:
        group_of_sid[st["sid"]] = st.get("group")
        g = st.get("group")
        if g is not None:
            delivered_by_group[g] = (
                delivered_by_group.get(g, 0) + st.get("delivered_changes", 0)
            )
    ack_by_key_group: dict[tuple, float] = {}
    for w in records.get("writes", ()):
        if w.get("t_ack_mono") is not None:
            ack_by_key_group[(w["key"], w.get("group"))] = w["t_ack_mono"]
    lags_by_group: dict[int, list[float]] = {}
    for d in records.get("deliveries", ()):
        if d.get("kind") != "change" or d.get("t_mono") is None:
            continue
        g = group_of_sid.get(d["sid"])
        if g is None:
            continue
        t_ack = ack_by_key_group.get((d["key"], g))
        if t_ack is None:
            continue
        lags_by_group.setdefault(g, []).append(
            max(0.0, d["t_mono"] - t_ack) * 1000.0
        )

    # Per-subscription rows: cost + class + delivered + lag + exact
    # reconciliation (fan-out enqueued + replayed == oracle delivered).
    per_sub: list[dict] = []
    mismatches: list[str] = []
    classes: dict[str, dict] = {}
    fallback_observed = False
    eval_total = eval_fallback = 0.0
    for rec in ledger.get("subs", ()):
        cost = rec.get("cost") or {}
        plan = rec.get("plan") or {}
        cls = plan.get("class", "unknown")
        eval_s = cost.get("eval_seconds_total", 0.0)
        eval_total += eval_s
        eval_fallback += cost.get("eval_seconds_fallback", 0.0)
        if plan.get("fallback_bound") and cost.get("fallback_evals", 0) > 0:
            fallback_observed = True
        sub_groups = groups_of.get(rec["sub_id"], [])
        delivered = sum(delivered_by_group.get(g, 0) for g in sub_groups)
        lags = sorted(
            lag for g in sub_groups for lag in lags_by_group.get(g, ())
        )
        row = {
            "sub_id": rec["sub_id"],
            "sql": rec.get("sql"),
            "class": cls,
            "fallback_bound": bool(plan.get("fallback_bound")),
            "groups": sub_groups,
            "eval_ms": round(eval_s * 1000.0, 3),
            "eval_ms_fallback": round(
                cost.get("eval_seconds_fallback", 0.0) * 1000.0, 3
            ),
            "fallback_evals": cost.get("fallback_evals", 0),
            "candidate_evals": cost.get("candidate_evals", 0),
            "rows_scanned": cost.get("rows_scanned", 0),
            "fanout_events": cost.get("fanout_events", 0),
            "fanout_bytes": cost.get("fanout_bytes", 0),
            "replay_rows": cost.get("replay_rows", 0),
            "queue_depth_hwm": cost.get("queue_depth_hwm", 0),
            "delivered_changes": delivered,
            "lag_ms": _lag_block(lags),
        }
        if sub_groups:
            expected = (
                cost.get("fanout_events", 0) + cost.get("replay_rows", 0)
            )
            row["mass_reconciled"] = expected == delivered
            if not row["mass_reconciled"]:
                mismatches.append(
                    f"sub {rec['sub_id'][:8]} ({cls}): ledger enqueued+"
                    f"replayed {expected} != oracle delivered {delivered}"
                )
        per_sub.append(row)
        c = classes.setdefault(cls, {
            "subs": 0, "fallback_bound": 0, "eval_ms": 0.0,
            "delivered_changes": 0, "_lags": [],
        })
        c["subs"] += 1
        c["fallback_bound"] += 1 if plan.get("fallback_bound") else 0
        c["eval_ms"] += eval_s * 1000.0
        c["delivered_changes"] += delivered
        c["_lags"].extend(lags)

    for c in classes.values():
        c["lag_ms"] = _lag_block(c.pop("_lags"))
        c["eval_ms"] = round(c["eval_ms"], 3)

    per_sub.sort(key=lambda r: r["eval_ms"], reverse=True)
    checked = [r for r in per_sub if "mass_reconciled" in r]
    n_streams = len(streams)
    fallback_bound_subs = sum(1 for r in per_sub if r["fallback_bound"])
    return {
        "kind": REPORT_KIND,
        "version": REPORT_VERSION,
        "streams": n_streams,
        "subs": len(per_sub),
        "eval_ms": {
            "total": round(eval_total * 1000.0, 3),
            "fallback": round(eval_fallback * 1000.0, 3),
            "candidate": round((eval_total - eval_fallback) * 1000.0, 3),
        },
        "fallback": {
            "bound_subs": fallback_bound_subs,
            "observed": fallback_observed,
            "share_of_eval_seconds": round(
                eval_fallback / eval_total, 4
            ) if eval_total > 0 else 0.0,
        },
        "classes": classes,
        "top": per_sub[:top_k],
        "reconciliation": {
            "ok": not mismatches,
            "checked": len(checked),
            "mismatches": mismatches[:16],
        },
        "oracle": {
            "violations": _get(run, "oracle.violations"),
            "delivered_changes": _get(run, "oracle.delivered_changes"),
            "fanout_lag_ms": _get(run, "oracle.fanout_lag_ms"),
        },
    }


def render_serving_report(rep: dict) -> str:
    lines = [
        f"serving query-cost report ({rep['subs']} subs, "
        f"{rep['streams']} streams)",
        f"  eval total {rep['eval_ms']['total']:.1f} ms — fallback "
        f"{rep['eval_ms']['fallback']:.1f} ms "
        f"({rep['fallback']['share_of_eval_seconds'] * 100:.1f}% of eval "
        f"burn, {rep['fallback']['bound_subs']} fallback-bound subs, "
        f"observed={rep['fallback']['observed']})",
        f"  reconciliation: "
        f"{'ok' if rep['reconciliation']['ok'] else 'MISMATCH'} "
        f"({rep['reconciliation']['checked']} subs checked)",
        "  per-class lag:",
    ]
    for cls in sorted(rep.get("classes", {})):
        c = rep["classes"][cls]
        lag = c["lag_ms"]
        lines.append(
            f"    {cls:<10} subs={c['subs']:<4} eval={c['eval_ms']:.1f} ms "
            f"lag p50={lag['p50']} p99={lag['p99']} max={lag['max']} ms"
        )
    lines.append("  top subscriptions by eval cost:")
    for r in rep.get("top", ())[:5]:
        lines.append(
            f"    {r['sub_id'][:8]} {r['class']:<9} "
            f"{'fallback' if r['fallback_bound'] else 'incremental'} "
            f"eval={r['eval_ms']:.1f} ms rows={r['rows_scanned']} "
            f"fanout={r['fanout_events']}"
        )
    for m in rep.get("reconciliation", {}).get("mismatches", ()):
        lines.append(f"  MISMATCH: {m}")
    return "\n".join(lines)


def diff_serving_reports(
    base: dict, cand: dict, tolerance: float = 1.5, floor_ms: float = 5.0
) -> tuple[bool, list[dict]]:
    """Compare a candidate serving-cost report against the committed
    baseline. Latency/eval paths regress when the candidate exceeds
    ``max(base * tolerance, floor_ms)`` (the floor keeps a 0.3 ms
    loopback baseline from weaponizing scheduler noise); the fallback
    share regresses past ``base + 0.15`` absolute. Returns
    ``(ok, rows)``; rows carry ``{path, base, cand, ok}``."""
    rows: list[dict] = []

    def num(path: str):
        b, c = _get(base, path), _get(cand, path)
        if b is None or c is None:
            return
        limit = max(float(b) * tolerance, floor_ms)
        rows.append({
            "path": path, "base": b, "cand": c,
            "limit": round(limit, 3), "ok": float(c) <= limit,
        })

    num("eval_ms.total")
    num("eval_ms.fallback")
    for cls in sorted(set(base.get("classes", {})) | set(cand.get("classes", {}))):
        num(f"classes.{cls}.lag_ms.p99")
    b_share = _get(base, "fallback.share_of_eval_seconds")
    c_share = _get(cand, "fallback.share_of_eval_seconds")
    if b_share is not None and c_share is not None:
        limit = min(1.0, float(b_share) + 0.15)
        rows.append({
            "path": "fallback.share_of_eval_seconds",
            "base": b_share, "cand": c_share, "limit": round(limit, 4),
            "ok": float(c_share) <= limit,
        })
    return all(r["ok"] for r in rows), rows


def check_serving_cost_budget(
    measured: dict, budget: dict
) -> tuple[bool, list[str]]:
    """Gate a serving-cost measurement against the ``serving_cost``
    entry of bench_budget.json. ``measured`` is the emitted smoke report
    (provenance + ``run`` + ``serving``). Returns ``(ok, breaches)``.

    Budget keys:

    - dimension keys (``SERVING_COST_DIMS``): exact match required;
    - ``tolerance``: multiplier on every ``ceilings_ms`` entry;
    - ``ceilings_ms``: dotted-path -> max ms; missing measurement is a
      breach;
    - ``fallback_share_max``: absolute ceiling on
      ``serving.fallback.share_of_eval_seconds``;
    - ``oracle_violations_max`` (default 0): absolute, never scaled;
    - ``require_fallback_observed`` (default True): the machinery-fired
      rule — a storm where no fallback-bound subscription ever
      evaluated is a harness failure, not a pass;
    - ``require_mass_reconciled`` (default True): the ledger must
      reconcile exactly against oracle delivery counts.
    """
    tol = float(budget.get("tolerance", 1.25))
    breaches: list[str] = []
    for dim in SERVING_COST_DIMS:
        if dim in budget and _get(measured, dim) != budget[dim]:
            breaches.append(
                f"{dim}: measured at {_get(measured, dim)!r} but the "
                f"budget was refreshed at {budget[dim]!r} — rerun with "
                f"--update"
            )
    for path, limit in budget.get("ceilings_ms", {}).items():
        got = _get(measured, path)
        if got is None:
            breaches.append(f"{path}: missing from measurement")
        elif float(got) > float(limit) * tol:
            breaches.append(
                f"{path}: {float(got):.1f} ms > budget "
                f"{float(limit):.1f} ms x{tol}"
            )
    share_max = budget.get("fallback_share_max")
    share = _get(measured, "serving.fallback.share_of_eval_seconds")
    if share_max is not None:
        if share is None:
            breaches.append(
                "serving.fallback.share_of_eval_seconds: missing"
            )
        elif float(share) > float(share_max):
            breaches.append(
                f"fallback share: {float(share):.3f} > "
                f"{float(share_max):.3f} — the fallback-bound population "
                f"burns more of the eval budget than budgeted"
            )
    viol_max = int(budget.get("oracle_violations_max", 0))
    viol = _get(measured, "run.oracle.violations")
    if viol is not None and int(viol) > viol_max:
        breaches.append(
            f"oracle violations: {viol} > {viol_max} — exactly-once "
            f"delivery broke under the cost-plane storm"
        )
    if budget.get("require_fallback_observed", True):
        if not _get(measured, "serving.fallback.observed"):
            breaches.append(
                "test-harness failure: no fallback-bound subscription was "
                "ever observed evaluating — the storm never exercised the "
                "fallback cliff this gate exists to measure (add window-"
                "function subscriptions / check fallback_subs)"
            )
    if budget.get("require_mass_reconciled", True):
        if not _get(measured, "serving.reconciliation.ok"):
            breaches.append(
                "mass reconciliation failed: per-sub ledger fan-out mass "
                "!= oracle delivered counts ("
                + "; ".join(
                    (_get(measured, "serving.reconciliation.mismatches")
                     or ["no detail"])[:3]
                )
                + ")"
            )
    return not breaches, breaches
