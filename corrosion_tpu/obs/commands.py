"""`corrosion obs ...` command implementations.

Promoted out of ``cli.py`` so the observability logic lives with the
plane it operates on: ``report``/``tail``/``diff``/``record`` drive the
kernel convergence plane (``sim/health.py``), ``epidemic`` drives the
propagation-topology analyzer (:mod:`corrosion_tpu.obs.epidemic`), and
``timeline`` drives the causal-tracing correlator
(:mod:`corrosion_tpu.obs.timeline` + :mod:`corrosion_tpu.obs.journey`).
``cli.py`` keeps the argparse surface and delegates here.

Exit codes: 0 = verdict ok, 1 = regression / failed invariant, 2 =
usage. Note any ``corrosion_tpu.sim`` import pulls in jax (the package
__init__ loads the engines), so obs startup costs the jax import even
for pure-JSONL report/tail/diff; ``timeline`` without ``--flight``
avoids it.
"""

from __future__ import annotations

import json
import sys


def run(args) -> int:
    if args.obs_cmd == "timeline":
        return _timeline(args)
    if args.obs_cmd == "cost":
        return _cost(args)
    if args.obs_cmd == "trajectory":
        return _trajectory(args)
    if args.obs_cmd == "epidemic":
        return _epidemic(args)
    if args.obs_cmd == "soak":
        return _soak(args)
    if args.obs_cmd == "serving":
        return _serving(args)

    from corrosion_tpu.sim import health

    if args.obs_cmd == "report":
        rep = health.report_from_flight(
            args.flight, round_ms=args.round_ms,
            kill_rounds=args.kill_round,
        )
        if args.json:
            print(json.dumps(rep.to_dict()))
        else:
            print(rep.render())
        return 0

    if args.obs_cmd == "tail":
        last_round: dict = {}
        n_rounds = 0
        for rec in health.iter_flight(
            args.flight, follow=args.follow, poll_s=args.poll,
            idle_timeout_s=args.idle_timeout,
        ):
            kind = rec.get("kind")
            if kind == "flight":
                print(
                    f"[flight] engine={rec.get('engine', '?')} "
                    f"version={rec.get('version', '?')}"
                )
            elif kind == "round":
                last_round = rec
                n_rounds += 1
                if args.rounds:
                    print(json.dumps(rec))
            elif kind == "chunk" and not args.rounds:
                wall = rec.get("wall_s")
                tail = {
                    k: last_round.get(k)
                    for k in (
                        "need", "mismatches", "staleness_sum",
                        "queue_backlog", "swim_undetected_deaths",
                    )
                    if k in last_round
                }
                print(
                    f"[chunk] rounds {rec.get('start')}.."
                    f"{rec.get('start', 0) + rec.get('rounds', 0) - 1}"
                    + (f" wall={wall}s" if wall is not None else "")
                    + f" {json.dumps(tail)}"
                )
        print(f"[tail] {n_rounds} round records")
        return 0

    if args.obs_cmd == "diff":
        base = health.load_report(args.baseline, round_ms=args.round_ms)
        cand = health.load_report(args.candidate, round_ms=args.round_ms)
        diff = health.diff_reports(base, cand, tolerance=args.tolerance)
        if args.json:
            print(json.dumps(diff))
        else:
            for row in diff["rows"]:
                mark = "ok" if row["ok"] else "REGRESSION"
                print(
                    f"{row['metric']}: {row['baseline']} -> "
                    f"{row['candidate']} [{mark}]"
                )
            for r in diff["regressions"]:
                print(f"REGRESSION: {r}", file=sys.stderr)
        return 1 if diff["regressions"] else 0

    if args.obs_cmd == "record":
        facts = health.record_demo_flight(
            args.out, nodes=args.nodes, rounds=args.rounds,
            churn=args.churn, seed=args.seed, progress=sys.stderr,
            geo=args.geo, adaptive=getattr(args, "adaptive", False),
        )
        print(json.dumps(facts))
        return 0
    return 2


def _epidemic(args) -> int:
    """`obs epidemic {report,fit,diff}` — the propagation-topology
    plane's analyzer (obs/epidemic.py, docs/OBSERVABILITY.md
    "Propagation plane"). Exit 0 = verdict ok, 1 = regression or an
    accounting identity failed to reconcile, 2 = usage."""
    from corrosion_tpu.obs import epidemic

    kw = dict(
        fanout=args.fanout, nodes=args.nodes, round_ms=args.round_ms,
        geo_regions=args.geo_regions,
    )

    if args.epidemic_cmd == "report":
        try:
            rep = epidemic.report_from_flight(args.flight, **kw)
        except (OSError, ValueError) as e:
            print(f"obs epidemic report: {e}", file=sys.stderr)
            return 2
        if args.oracle_records:
            try:
                with open(args.oracle_records) as f:
                    rep["oracle"] = epidemic.oracle_coverage(
                        json.load(f), round_ms=args.round_ms
                    )
            except (OSError, ValueError) as e:
                print(
                    f"obs epidemic report: bad --oracle-records: {e!r}",
                    file=sys.stderr,
                )
                return 2
        if args.out:
            with open(args.out, "w") as f:
                f.write(json.dumps(rep, indent=2) + "\n")
        print(json.dumps(rep) if args.json else epidemic.render_report(rep))
        if not rep["checks_ok"]:
            for p in rep["check_problems"]:
                print(f"obs epidemic report: ACCOUNTING: {p}",
                      file=sys.stderr)
            return 1
        return 0

    if args.epidemic_cmd == "fit":
        try:
            rep = epidemic.report_from_flight(args.flight, **kw)
        except (OSError, ValueError) as e:
            print(f"obs epidemic fit: {e}", file=sys.stderr)
            return 2
        fit = rep["fit"]
        if args.json:
            print(json.dumps(fit))
        else:
            for p in fit["points"]:
                logit = p.get("logit")
                print(
                    f"age<={p['age']:g}r coverage={p['coverage']:.4f}"
                    + (f" logit={logit:+.3f}" if logit is not None else "")
                )
            if fit["fitted"]:
                print(
                    f"beta={fit['spread_exponent']:.4f}/round "
                    f"half={fit['half_coverage_round']:.1f}r "
                    f"r2={fit['r2']:.3f}"
                )
            else:
                print("fit abstained (fewer than 2 interior points)")
        return 0 if fit["fitted"] else 1

    if args.epidemic_cmd == "diff":
        try:
            base = epidemic.load_report(args.baseline, **kw)
            cand = epidemic.load_report(args.candidate, **kw)
        except (OSError, ValueError) as e:
            print(f"obs epidemic diff: {e}", file=sys.stderr)
            return 2
        diff = epidemic.diff_reports(base, cand, tolerance=args.tolerance)
        if args.json:
            print(json.dumps(diff))
        else:
            for row in diff["rows"]:
                mark = "ok" if row["ok"] else "REGRESSION"
                print(
                    f"{row['metric']}: {row['baseline']} -> "
                    f"{row['candidate']} [{mark}]"
                )
            for r in diff["regressions"]:
                print(f"REGRESSION: {r}", file=sys.stderr)
        return 1 if diff["regressions"] else 0
    return 2


def _soak(args) -> int:
    """`obs soak {report,diff}` — the endurance plane's analyzer
    (obs/series.py + obs/endurance.py, docs/OBSERVABILITY.md "Endurance
    plane"). jax-free: judging a recorded series must not pay the
    kernel import. Exit 0 = verdict ok, 1 = breach/regression, 2 =
    usage."""
    from corrosion_tpu.obs import endurance
    from corrosion_tpu.obs.series import replay_series

    if args.soak_cmd == "report":
        try:
            samples = replay_series(args.series)["samples"]
        except (OSError, ValueError) as e:
            print(f"obs soak report: {e}", file=sys.stderr)
            return 2
        ceilings: dict = {}
        for spec in args.leak_ceiling or ():
            name, _, val = spec.partition("=")
            try:
                ceilings[name] = float(val)
            except ValueError:
                print(
                    f"obs soak report: bad --leak-ceiling {spec!r} "
                    f"(want NAME=UNITS_PER_HOUR)", file=sys.stderr,
                )
                return 2
        rep = endurance.build_report(
            samples, t_scale_s=args.t_scale_s, label=args.label,
            leak_ceilings=ceilings or None,
            wedge_min_span_s=args.wedge_min_span_s,
        )
        _emit(
            rep, args,
            text=None if args.json else endurance.render_report(rep),
        )
        for b in rep["breaches"]:
            print(f"obs soak report: BREACH: {b}", file=sys.stderr)
        return 0 if rep["ok"] else 1

    if args.soak_cmd == "diff":
        try:
            with open(args.baseline) as f:
                base = json.load(f)
            with open(args.candidate) as f:
                cand = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs soak diff: {e}", file=sys.stderr)
            return 2
        diff = endurance.diff_soak(base, cand, tolerance=args.tolerance)
        if args.json:
            print(json.dumps(diff))
        else:
            for row in diff["rows"]:
                mark = "ok" if row["ok"] else "REGRESSION"
                print(
                    f"{row['metric']}: {row['baseline']} -> "
                    f"{row['candidate']} [{mark}]"
                )
            for r in diff["regressions"]:
                print(f"REGRESSION: {r}", file=sys.stderr)
        return 1 if diff["regressions"] else 0
    return 2


def _serving(args) -> int:
    """`obs serving {report,diff}` — the serving query-cost plane's
    analyzer (obs/serving.py, docs/SERVING.md "Query-cost plane").
    jax-free: joining a recorded ledger with oracle delivery records
    must not pay the kernel import. Exit 0 = verdict ok, 1 =
    reconciliation/regression failure, 2 = usage."""
    from corrosion_tpu.obs import serving

    if args.serving_cmd == "report":
        try:
            with open(args.from_run) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs serving report: {e!r}", file=sys.stderr)
            return 2
        run = report.get("run", report)
        try:
            rep = serving.build_serving_report(run, top_k=args.top)
        except ValueError as e:
            print(f"obs serving report: {e}", file=sys.stderr)
            return 2
        _emit(
            rep, args,
            text=None if args.json else serving.render_serving_report(rep),
        )
        ok = rep["reconciliation"]["ok"] and rep["fallback"]["observed"]
        if not rep["fallback"]["observed"]:
            print(
                "obs serving report: no fallback-bound subscription was "
                "ever observed evaluating (machinery-fired rule)",
                file=sys.stderr,
            )
        for m in rep["reconciliation"]["mismatches"]:
            print(f"obs serving report: MISMATCH: {m}", file=sys.stderr)
        return 0 if ok else 1

    if args.serving_cmd == "diff":
        try:
            with open(args.baseline) as f:
                base = json.load(f)
            with open(args.candidate) as f:
                cand = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs serving diff: {e!r}", file=sys.stderr)
            return 2
        # Accept either a bare corro-serving-cost/1 report or a smoke
        # gate report that nests one under "serving".
        base = base.get("serving", base)
        cand = cand.get("serving", cand)
        ok, rows = serving.diff_serving_reports(
            base, cand, tolerance=args.tolerance, floor_ms=args.floor_ms,
        )
        if args.json:
            print(json.dumps({"ok": ok, "rows": rows}))
        else:
            for row in rows:
                mark = "ok" if row["ok"] else "REGRESSION"
                print(
                    f"{row['path']}: {row['base']} -> {row['cand']} "
                    f"(limit {row['limit']}) [{mark}]"
                )
        for row in rows:
            if not row["ok"]:
                print(
                    f"obs serving diff: REGRESSION: {row['path']} "
                    f"{row['base']} -> {row['cand']}", file=sys.stderr,
                )
        return 0 if ok else 1
    return 2


def _ensure_devices(n: int) -> bool:
    """Provision ``n`` virtual CPU devices when possible. XLA reads
    XLA_FLAGS at BACKEND initialization, not at jax import (the
    package __init__ has already imported jax by CLI-dispatch time), so
    setting the flag here works as long as nothing has touched
    ``jax.devices()`` yet; returns False when a backend is already up
    with fewer devices (the caller reports the usage error)."""
    import os

    if n <= 1:
        return True
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    if jax.config.jax_platforms and "axon" in jax.config.jax_platforms:
        # The environment's sitecustomize grabs the real TPU chip at
        # interpreter start; the virtual mesh needs the CPU platform
        # (same override scripts/multichip_smoke.py applies).
        jax.config.update("jax_platforms", "cpu")
    return len(jax.devices()) >= n


def _emit(payload: dict, args, text: str | None = None) -> None:
    """Shared artifact output: pretty/compact JSON to stdout (or the
    rendered text form), plus --out."""
    body = json.dumps(payload, indent=None if args.json else 2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(payload, indent=2) + "\n")
    print(body if text is None else text)


def _cost(args) -> int:
    """`obs cost {show,diff,capacity}` — the device-cost plane
    (obs/costs.py, docs/PERFORMANCE.md "Cost model & roofline")."""
    from corrosion_tpu.obs import costs

    if args.cost_cmd == "show":
        devices = [int(d) for d in args.devices.split(",") if d.strip()]
        if not _ensure_devices(max(devices)):
            print(
                f"obs cost: need {max(devices)} devices but jax is "
                f"already initialized with fewer — set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={max(devices)}",
                file=sys.stderr,
            )
            return 2
        model = costs.build_cost_model(
            engines=tuple(
                e.strip() for e in args.engines.split(",") if e.strip()
            ),
            variants=tuple(
                v.strip() for v in args.variants.split(",") if v.strip()
            ),
            device_counts=tuple(devices),
            progress=sys.stderr,
        )
        _emit(model, args)
        return 0

    if args.cost_cmd == "diff":
        try:
            base = costs.load_model(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"obs cost diff: {e!r}", file=sys.stderr)
            return 2
        dmax = max(base.get("device_counts", [1]))
        if not _ensure_devices(dmax):
            print(
                f"obs cost diff: baseline covers device_count={dmax}; "
                f"set XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{dmax}", file=sys.stderr,
            )
            return 2
        cand = costs.build_cost_model(
            engines=tuple(base.get("engines", costs.ENGINES)),
            variants=tuple(base.get("variants", costs.VARIANTS)),
            device_counts=tuple(base.get("device_counts", (1,))),
            progress=sys.stderr,
        )
        ok, breaches, notes = costs.diff_cost_models(
            base, cand, tolerance=args.tolerance
        )
        report = {
            "ok": ok, "breaches": breaches, "notes": notes,
            "baseline": args.baseline, "measured": cand,
        }
        _emit(report, args)
        for b in breaches:
            print(f"obs cost diff: BREACH {b}", file=sys.stderr)
        for n in notes:
            print(f"obs cost diff: note: {n}", file=sys.stderr)
        return 0 if ok else 1

    if args.cost_cmd == "capacity":
        if not _ensure_devices(args.devices):
            print(
                f"obs cost capacity: need {args.devices} devices — set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{args.devices}", file=sys.stderr,
            )
            return 2
        nodes = (
            costs.CAPACITY_NODE_GRID if args.nodes is None
            else tuple(
                int(x) for x in args.nodes.split(",") if x.strip()
            )
        )
        try:
            model = costs.capacity_model(
                node_counts=nodes,
                device_count=args.devices,
                validate_live=not args.no_validate,
                hbm_bytes=int(args.hbm_gib * 2**30),
            )
        except ValueError as e:
            print(f"obs cost capacity: RECONCILE FAILED: {e}",
                  file=sys.stderr)
            return 1
        _emit(model, args)
        return 0
    return 2


def _trajectory(args) -> int:
    """`obs trajectory` — the committed bench artifacts as one
    provenance-checked series (obs/trajectory.py)."""
    from corrosion_tpu.obs import trajectory as traj_mod

    traj = traj_mod.build_trajectory(args.root)
    if not traj["bench"] and not traj["multichip"]:
        print(
            f"obs trajectory: no BENCH_r*/MULTICHIP_r* artifacts under "
            f"{args.root}", file=sys.stderr,
        )
        return 2
    _emit(
        traj, args,
        text=None if args.json else traj_mod.render_trajectory(traj),
    )
    return 0


def _timeline(args) -> int:
    """`obs timeline`: correlate a traced loadgen run's span exports +
    oracle delivery records (and optionally a kernel flight + write
    trace) into one corro-timeline/1 artifact."""
    from corrosion_tpu.obs.timeline import (
        build_timeline,
        load_spans,
        timeline_ok,
    )

    if args.from_run:
        try:
            with open(args.from_run) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs timeline: {e!r}", file=sys.stderr)
            return 2
        run = report.get("run", report)
        trace_blk = run.get("trace")
        if not trace_blk:
            print(
                "obs timeline: report has no run.trace block — rerun "
                "`loadgen run --trace-dir DIR`", file=sys.stderr,
            )
            return 2
        spans = load_spans(trace_blk["span_files"])
        records = trace_blk["oracle_records"]
        sample = float(trace_blk.get("sample", 1.0))
    elif args.spans and args.records:
        spans = load_spans(args.spans)
        try:
            with open(args.records) as f:
                records = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs timeline: bad --records: {e!r}", file=sys.stderr)
            return 2
        sample = args.sample
    else:
        print(
            "obs timeline: need --from-run REPORT or --spans FILE... "
            "--records FILE", file=sys.stderr,
        )
        return 2

    timeline = build_timeline(
        spans, records, sample=sample, tolerance_ms=args.tolerance_ms,
    )

    if args.flight and args.trace:
        from corrosion_tpu.obs.journey import reconstruct_write_journeys
        from corrosion_tpu.sim.trace import Trace

        try:
            timeline["kernel"] = reconstruct_write_journeys(
                args.flight, Trace.load(args.trace),
                round_ms=args.round_ms,
            )
        except (OSError, ValueError) as e:
            print(f"obs timeline: kernel join failed: {e!r}",
                  file=sys.stderr)
            return 2
    elif args.flight or args.trace:
        print(
            "obs timeline: --flight and --trace go together (the "
            "journey reconstructor needs both)", file=sys.stderr,
        )
        return 2

    text = json.dumps(timeline, indent=None if args.json else 2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    ok, problems = timeline_ok(timeline, min_coverage=args.min_coverage)
    for p in problems:
        print(f"obs timeline: {p}", file=sys.stderr)
    return 0 if ok else 1
