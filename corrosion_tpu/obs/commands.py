"""`corrosion obs ...` command implementations.

Promoted out of ``cli.py`` so the observability logic lives with the
plane it operates on: ``report``/``tail``/``diff``/``record`` drive the
kernel convergence plane (``sim/health.py``), ``timeline`` drives the
causal-tracing correlator (:mod:`corrosion_tpu.obs.timeline` +
:mod:`corrosion_tpu.obs.journey`). ``cli.py`` keeps the argparse surface
and delegates here.

Exit codes: 0 = verdict ok, 1 = regression / failed invariant, 2 =
usage. Note any ``corrosion_tpu.sim`` import pulls in jax (the package
__init__ loads the engines), so obs startup costs the jax import even
for pure-JSONL report/tail/diff; ``timeline`` without ``--flight``
avoids it.
"""

from __future__ import annotations

import json
import sys


def run(args) -> int:
    if args.obs_cmd == "timeline":
        return _timeline(args)

    from corrosion_tpu.sim import health

    if args.obs_cmd == "report":
        rep = health.report_from_flight(
            args.flight, round_ms=args.round_ms,
            kill_rounds=args.kill_round,
        )
        if args.json:
            print(json.dumps(rep.to_dict()))
        else:
            print(rep.render())
        return 0

    if args.obs_cmd == "tail":
        last_round: dict = {}
        n_rounds = 0
        for rec in health.iter_flight(
            args.flight, follow=args.follow, poll_s=args.poll,
            idle_timeout_s=args.idle_timeout,
        ):
            kind = rec.get("kind")
            if kind == "flight":
                print(
                    f"[flight] engine={rec.get('engine', '?')} "
                    f"version={rec.get('version', '?')}"
                )
            elif kind == "round":
                last_round = rec
                n_rounds += 1
                if args.rounds:
                    print(json.dumps(rec))
            elif kind == "chunk" and not args.rounds:
                wall = rec.get("wall_s")
                tail = {
                    k: last_round.get(k)
                    for k in (
                        "need", "mismatches", "staleness_sum",
                        "queue_backlog", "swim_undetected_deaths",
                    )
                    if k in last_round
                }
                print(
                    f"[chunk] rounds {rec.get('start')}.."
                    f"{rec.get('start', 0) + rec.get('rounds', 0) - 1}"
                    + (f" wall={wall}s" if wall is not None else "")
                    + f" {json.dumps(tail)}"
                )
        print(f"[tail] {n_rounds} round records")
        return 0

    if args.obs_cmd == "diff":
        base = health.load_report(args.baseline, round_ms=args.round_ms)
        cand = health.load_report(args.candidate, round_ms=args.round_ms)
        diff = health.diff_reports(base, cand, tolerance=args.tolerance)
        if args.json:
            print(json.dumps(diff))
        else:
            for row in diff["rows"]:
                mark = "ok" if row["ok"] else "REGRESSION"
                print(
                    f"{row['metric']}: {row['baseline']} -> "
                    f"{row['candidate']} [{mark}]"
                )
            for r in diff["regressions"]:
                print(f"REGRESSION: {r}", file=sys.stderr)
        return 1 if diff["regressions"] else 0

    if args.obs_cmd == "record":
        facts = health.record_demo_flight(
            args.out, nodes=args.nodes, rounds=args.rounds,
            churn=args.churn, seed=args.seed, progress=sys.stderr,
        )
        print(json.dumps(facts))
        return 0
    return 2


def _timeline(args) -> int:
    """`obs timeline`: correlate a traced loadgen run's span exports +
    oracle delivery records (and optionally a kernel flight + write
    trace) into one corro-timeline/1 artifact."""
    from corrosion_tpu.obs.timeline import (
        build_timeline,
        load_spans,
        timeline_ok,
    )

    if args.from_run:
        try:
            with open(args.from_run) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs timeline: {e!r}", file=sys.stderr)
            return 2
        run = report.get("run", report)
        trace_blk = run.get("trace")
        if not trace_blk:
            print(
                "obs timeline: report has no run.trace block — rerun "
                "`loadgen run --trace-dir DIR`", file=sys.stderr,
            )
            return 2
        spans = load_spans(trace_blk["span_files"])
        records = trace_blk["oracle_records"]
        sample = float(trace_blk.get("sample", 1.0))
    elif args.spans and args.records:
        spans = load_spans(args.spans)
        try:
            with open(args.records) as f:
                records = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs timeline: bad --records: {e!r}", file=sys.stderr)
            return 2
        sample = args.sample
    else:
        print(
            "obs timeline: need --from-run REPORT or --spans FILE... "
            "--records FILE", file=sys.stderr,
        )
        return 2

    timeline = build_timeline(
        spans, records, sample=sample, tolerance_ms=args.tolerance_ms,
    )

    if args.flight and args.trace:
        from corrosion_tpu.obs.journey import reconstruct_write_journeys
        from corrosion_tpu.sim.trace import Trace

        try:
            timeline["kernel"] = reconstruct_write_journeys(
                args.flight, Trace.load(args.trace),
                round_ms=args.round_ms,
            )
        except (OSError, ValueError) as e:
            print(f"obs timeline: kernel join failed: {e!r}",
                  file=sys.stderr)
            return 2
    elif args.flight or args.trace:
        print(
            "obs timeline: --flight and --trace go together (the "
            "journey reconstructor needs both)", file=sys.stderr,
        )
        return 2

    text = json.dumps(timeline, indent=None if args.json else 2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    ok, problems = timeline_ok(timeline, min_coverage=args.min_coverage)
    for p in problems:
        print(f"obs timeline: {p}", file=sys.stderr)
    return 0 if ok else 1
