"""Runtime compile ledger: every XLA compilation becomes a recorded,
attributable, gateable event.

The repo already catches steady-state retraces OFFLINE — ``corrosion
lint --sanitize`` runs tiny engine instances and checks every jitted
function's compile-cache count (analysis/sanitize.py CT030-32) — but a
retrace on a REAL run is invisible until it shows up as wall time (the
r04→r05 10.6× step mystery was exactly this class: nothing in the run
itself said "you are recompiling"). This module closes that gap:

- **One registry of watched jitted functions.**
  :func:`jitted_functions` is the single discovery of a module's
  compiled entry points (anything exposing jax's ``_cache_size``);
  the sanitize pass, the runtime ledger, and the perf-plane
  cache-count pins all call it, so the three watchers can never drift
  onto different function sets.
- **A ledger of compilation events.** :class:`CompileLedger` registers
  one ``jax.monitoring`` listener (``backend_compile`` durations) and
  snapshots watched cache sizes around :meth:`CompileLedger.window`
  scopes, producing per-window records — which functions gained cache
  entries, how many backend compiles fired, and the summed compile
  wall-ms — that flow into the flight recorder (``kind: "compile"``)
  and the metrics registry (``corro_kernel_compiles_total`` /
  ``corro_kernel_compile_ms``).
- **A live retrace tripwire.** :meth:`CompileLedger.arm` declares
  "everything is compiled now": any further backend compile (or watched
  cache growth at a window boundary) raises :class:`RetraceError`
  naming the window, instead of silently eating wall time. bench.py and
  scripts/bench_smoke.py arm it around their timed runs, so a
  steady-state recompile aborts the bench rather than skewing it — and
  CI gates ``steady_compiles == 0`` through
  ``telemetry.check_bench_invariants``.

Honesty note on attribution: jax's monitoring events carry durations
but not function identities, so a window with several compiles reports
their SUMMED wall against the set of watched functions that grew. The
window label (engine + start round) is the shape-signature seam — the
caller names what was being dispatched; the ledger does not invent a
signature it cannot observe.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field

import jax

# The monitoring event that brackets an actual XLA backend compile.
# Trace/lowering events are deliberately excluded: a cache HIT still
# traces, and counting it would cry wolf on every warm chunk.
_COMPILE_EVENTS = ("/jax/core/compile/backend_compile_duration",)

#: Engine name -> module path, the watch set the engine drivers and the
#: sanitize pass share (analysis/sanitize.py imports its runners from
#: the same names).
ENGINE_MODULES = {
    "dense": "corrosion_tpu.sim.engine",
    "sparse": "corrosion_tpu.sim.sparse_engine",
    "chunk": "corrosion_tpu.sim.chunk_engine",
    "mixed": "corrosion_tpu.sim.mixed_engine",
}


class RetraceError(RuntimeError):
    """A compilation fired while the ledger was armed steady-state."""


def jitted_functions(module) -> dict[str, object]:
    """Every watched jitted function of ``module``, by name.

    THE one registry discovery shared by the runtime ledger, the
    sanitize retrace tripwire (CT030-32), and the perf-plane
    cache-count pins — one implementation, so the offline and live
    watchers can never watch different sets. Detection is jax's
    ``_cache_size`` attribute (present on every ``jax.jit`` product,
    donated twins included)."""
    return {
        name: obj
        for name in dir(module)
        if callable(obj := getattr(module, name, None))
        and hasattr(obj, "_cache_size")
    }


def cache_sizes(fns: dict[str, object]) -> dict[str, int]:
    """Current compile-cache entry count per watched function."""
    return {name: fn._cache_size() for name, fn in fns.items()}


# ---------------------------------------------------------------------------
# One process-wide monitoring listener fanning out to active ledgers.
# jax.monitoring has no per-listener unregister (clear_event_listeners
# nukes everyone's), so registration is once-per-process and activation
# is membership in _ACTIVE.

_LISTENER_LOCK = threading.Lock()
_ACTIVE: list["CompileLedger"] = []
_INSTALLED = False


def _listener(name: str, secs: float, **kw) -> None:
    if name not in _COMPILE_EVENTS:
        return
    for led in list(_ACTIVE):
        led._on_compile(secs)


def _ensure_listener() -> None:
    global _INSTALLED
    with _LISTENER_LOCK:
        if not _INSTALLED:
            jax.monitoring.register_event_duration_secs_listener(_listener)
            _INSTALLED = True


@dataclass
class CompileWindow:
    """One observed dispatch scope: which watched functions compiled,
    how many backend compiles fired, their summed wall. ``nested``
    windows are inert placeholders: their events were attributed to the
    enclosing window, so they report nothing and are never published.
    ``published`` marks windows a live sink (KernelTelemetry.run_chunk)
    already folded into a registry, so :meth:`CompileLedger.publish`
    cannot double-count them."""

    label: str
    compiles: int = 0
    compile_ms: float = 0.0
    fns: dict = field(default_factory=dict)  # fn name -> new cache entries
    wall_ms: float = 0.0
    nested: bool = False
    published: bool = False

    def to_record(self) -> dict:
        """Flight-recorder line (``kind: "compile"``)."""
        return {
            "kind": "compile",
            "label": self.label,
            "compiles": self.compiles,
            "compile_ms": round(self.compile_ms, 3),
            "fns": dict(self.fns),
        }


class CompileLedger:
    """Records every compilation event and arms the retrace tripwire.

    Usage (the engine-driver integration rides
    ``telemetry.KernelTelemetry(ledger=...)``, which opens a window per
    chunk)::

        led = CompileLedger()
        led.watch_engines(("dense",))
        with led:                       # activates the monitoring tap
            with led.window("first_run") as w:
                run_once()              # compiles here are expected
            compile_ms = w.compile_ms
            led.arm("timed run")        # steady state: compiling = bug
            run_again()                 # RetraceError on any compile
            led.disarm()
    """

    def __init__(self):
        self.watched: dict[str, object] = {}
        self.windows: list[CompileWindow] = []
        self.total_compiles = 0
        self.total_compile_ms = 0.0
        self.armed_compiles = 0
        self._armed: str | None = None
        self._current: CompileWindow | None = None
        self._active = False

    # -- watch set ---------------------------------------------------------

    def watch(self, module) -> "CompileLedger":
        """Merge a module's jitted functions into the watch set."""
        self.watched.update(jitted_functions(module))
        return self

    def watch_engines(self, engines=tuple(ENGINE_MODULES)) -> "CompileLedger":
        import importlib

        for name in engines:
            self.watch(importlib.import_module(ENGINE_MODULES[name]))
        return self

    # -- activation --------------------------------------------------------

    def install(self) -> "CompileLedger":
        _ensure_listener()
        with _LISTENER_LOCK:
            if self not in _ACTIVE:
                _ACTIVE.append(self)
        self._active = True
        return self

    def uninstall(self) -> None:
        with _LISTENER_LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        self._active = False

    def __enter__(self) -> "CompileLedger":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- the tap -----------------------------------------------------------

    def _on_compile(self, secs: float) -> None:
        ms = secs * 1000.0
        self.total_compiles += 1
        self.total_compile_ms += ms
        win = self._current
        if win is not None:
            win.compiles += 1
            win.compile_ms += ms
        if self._armed is not None:
            self.armed_compiles += 1
            where = f" in window {win.label!r}" if win is not None else ""
            raise RetraceError(
                f"steady-state recompile ({ms:.1f} ms){where}: the ledger "
                f"was armed ({self._armed}) — a host value is leaking into "
                f"a trace, or the warm-up did not cover this shape "
                f"(docs/PERFORMANCE.md 'Compile ledger')"
            )

    # -- windows -----------------------------------------------------------

    @contextlib.contextmanager
    def window(self, label: str):
        """Scope one dispatch; yields the :class:`CompileWindow` being
        filled (read it after the ``with`` exits). Windows do not
        sub-attribute: a window opened inside another (a telemetry
        chunk inside a caller's first-run scope) attributes its events
        to the OUTER window and yields an inert ``nested`` placeholder
        — so a per-chunk sink reading its own window can never re-count
        the enclosing scope's cumulative totals."""
        if self._current is not None:
            yield CompileWindow(label=label, nested=True)
            return
        before = cache_sizes(self.watched)
        win = CompileWindow(label=label)
        self._current = win
        t0 = time.perf_counter()
        try:
            yield win
        finally:
            win.wall_ms = (time.perf_counter() - t0) * 1000.0
            self._current = None
            after = cache_sizes(self.watched)
            win.fns = {
                name: after[name] - before.get(name, 0)
                for name in after
                if after[name] > before.get(name, 0)
            }
            self.windows.append(win)
        # Persistent-compilation-cache hits skip backend_compile but
        # still retrace + add a cache entry — cache growth under arms is
        # a violation even when the monitoring tap saw nothing.
        if self._armed is not None and win.fns and not win.compiles:
            self.armed_compiles += 1
            raise RetraceError(
                f"steady-state retrace in window {win.label!r}: watched "
                f"functions gained cache entries {win.fns} while the "
                f"ledger was armed ({self._armed})"
            )

    # -- tripwire ----------------------------------------------------------

    def arm(self, reason: str = "steady state") -> None:
        """Declare warm-up over: any further compile raises
        :class:`RetraceError` (the live analogue of sanitize CT030)."""
        if not self._active:
            self.install()
        self._armed = reason

    def disarm(self) -> None:
        self._armed = None

    @property
    def armed(self) -> bool:
        return self._armed is not None

    # -- outputs -----------------------------------------------------------

    def publish_window(self, registry, win: CompileWindow,
                       engine: str = "dense") -> None:
        """Fold ONE window into a MetricsRegistry and mark it
        published — the single emit implementation shared by the live
        per-chunk sink (``KernelTelemetry.run_chunk``) and the run-end
        :meth:`publish`, so a window can never be counted twice and
        both paths use one label scheme:
        ``corro_kernel_compiles_total{engine,fn}`` (an
        ``fn="(unwatched)"`` bucket carries backend compiles no watched
        function accounts for) and
        ``corro_kernel_compile_ms{engine}``."""
        if win.nested or win.published:
            return
        win.published = True
        per_fn = dict(win.fns)
        accounted = sum(per_fn.values())
        if win.compiles > accounted:
            per_fn["(unwatched)"] = win.compiles - accounted
        if per_fn:
            c = registry.counter(
                "corro_kernel_compiles_total",
                "kernel plane: XLA compilation events (compile ledger)",
            )
            for name, cnt in per_fn.items():
                c.inc(float(cnt), engine=engine, fn=name)
        if win.compile_ms:
            registry.counter(
                "corro_kernel_compile_ms",
                "kernel plane: summed XLA backend-compile wall (ms)",
            ).inc(win.compile_ms, engine=engine)

    def publish(self, registry, engine: str = "dense") -> None:
        """Fold every not-yet-published window into the registry
        (windows a live KernelTelemetry sink already emitted are
        skipped — idempotent against the per-chunk path)."""
        for w in self.windows:
            self.publish_window(registry, w, engine=engine)

    def compile_counts(self) -> dict[str, int]:
        """Cumulative new-cache-entry count per watched function."""
        out: dict[str, int] = {}
        for w in self.windows:
            for name, cnt in w.fns.items():
                out[name] = out.get(name, 0) + cnt
        return out
