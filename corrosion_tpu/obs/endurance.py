"""Endurance detectors over recorded metric series (``corro-endurance/1``).

The analysis half of the endurance plane: given the samples of a
``corro-metric-series/1`` record (:mod:`corrosion_tpu.obs.series`),
derive the verdicts an hours-long soak needs — without trusting any
end-of-run point:

- **Leak trends**: robust Theil–Sen slope fits (median of pairwise
  slopes — one GC pause or compaction spike cannot drag the fit) over
  the process gauges (``corro_runtime_rss_bytes``/``_open_fds``),
  queue-backlog and staleness watermarks, reported in units/hour and
  flagged against per-series ceilings.
- **Counter-reset handling**: monotonic cumulatives are rebased across
  discontinuities, each classified as *restart* (an agent relaunched —
  hostchaos ``kill_restart`` — drops its counters to ~0), *wraparound*
  (the value sat near a 2^32/2^64 base), or *genuine decrease* (a
  monotonic-contract violation; the cumulative holds flat). Relaunches
  therefore don't fake leaks or un-fake wedges.
- **Wedge detection**: progress counters (changes applied/committed)
  flat across a sustained run of samples while the workload side says
  work was offered.
- **Loop-lag stall runs**: consecutive samples with the event-loop lag
  gauge above threshold — the blocked-loop signal, as a run length
  rather than a point.
- **SLO burn rates**: service objectives (fan-out lag p99, convergence
  staleness, probe false-alarm budget) evaluated as MULTI-WINDOW burn
  rates over the series (the production SRE slow-burn methodology: a
  breach requires both the fast and the slow window to burn budget
  above threshold), not end-of-run points.

``check_soak_budget`` gates a soak report against the ``soak`` entry of
bench_budget.json: leak-slope ceilings are tolerance-scaled; wedge /
SLO-breach / stall maxima, the detectors-armed rule (a soak passing
with detectors never armed is a harness failure), and the kernel series
determinism requirement are NEVER tolerance-scaled.

Deliberately jax-free, like obs/series.py.
"""

from __future__ import annotations

ENDURANCE_SCHEMA = "corro-endurance/1"
SOAK_SCHEMA = "corro-soak/1"

# Wrap bases a monotonic counter can legitimately fall back from.
WRAP_BASES = (2.0 ** 32, 2.0 ** 64)

# Leak-scan targets, by series-name stem (labels aggregated): the
# process self-observability gauges plus the host/kernel backlog and
# staleness watermarks ROADMAP item 6 names as leak/wedge oracles.
DEFAULT_LEAK_SERIES = (
    "corro_runtime_rss_bytes",
    "corro_runtime_open_fds",
    "corro_broadcast_pending",
    "corro_sync_needs",
    "corro_kernel_health_queue_backlog_last",
    "corro_kernel_health_staleness_sum_last",
)

# Units-per-hour ceilings for standalone `obs soak report` use; the CI
# lane's committed budget (bench_budget.json `soak`) is authoritative
# there and refreshed with x3 headroom like every other gate. Generous:
# a CI-sized window extrapolated to an hour amplifies sampling noise.
DEFAULT_LEAK_CEILINGS = {
    "corro_runtime_rss_bytes": 512 * 2 ** 20,  # 512 MiB/h
    "corro_runtime_open_fds": 600.0,
    "corro_broadcast_pending": 20000.0,
    "corro_sync_needs": 20000.0,
    "corro_kernel_health_queue_backlog_last": 50000.0,
    "corro_kernel_health_staleness_sum_last": 50000.0,
}

# (offered, progress) counter-stem pairs for wedge detection: local
# commits keep arriving while the apply pipeline delivers nothing.
DEFAULT_WEDGE_PAIRS = (
    ("corro_changes_committed", "corro_changes_applied"),
)

DEFAULT_WINDOWS = (("fast", 0.1), ("slow", 0.5))

# Host-plane SLO catalog (agent runtime series). Kernel-plane lanes
# pass their own (engine-labeled level gauges, round-unit clock).
DEFAULT_SLOS = (
    {
        "name": "fanout_lag_p99",
        "kind": "histogram",
        "series": "corro_broadcast_recv_lag_seconds",
        "threshold_s": 2.0,
        "objective": 0.99,
    },
    {
        "name": "convergence_staleness",
        "kind": "gauge",
        "series": "corro_sync_needs",
        "ceiling": 500.0,
        "objective": 0.90,
    },
    {
        "name": "probe_false_alarm_budget",
        "kind": "counter_budget",
        "series": "corro_gossip_member_removed",
        "allowed_per_hour": 720.0,
    },
)


# -- robust trend fit --------------------------------------------------------


def theil_sen(
    ts: list[float], ys: list[float], max_pairs: int = 4000,
) -> float | None:
    """Theil–Sen slope: the median of all pairwise slopes. Robust to
    ~29% outlier contamination, which is what a soak series needs — a
    single compaction spike or GC pause must not set the verdict. Past
    ``max_pairs`` the pair set is thinned by a DETERMINISTIC stride (no
    RNG: seeded reruns must reproduce the verdict bit for bit)."""
    n = len(ts)
    if n < 2:
        return None
    total = n * (n - 1) // 2
    step = max(1, total // max_pairs)
    slopes: list[float] = []
    idx = 0
    for i in range(n - 1):
        for j in range(i + 1, n):
            if idx % step == 0:
                dt = ts[j] - ts[i]
                if dt > 0:
                    slopes.append((ys[j] - ys[i]) / dt)
            idx += 1
    if not slopes:
        return None
    slopes.sort()
    m = len(slopes)
    return 0.5 * (slopes[m // 2] + slopes[(m - 1) // 2])


# -- counter-reset / restart discontinuities ---------------------------------


def rebase_counter(
    values: list[float], wrap_slack: float = 0.05,
) -> tuple[list[float], list[dict]]:
    """Rebase a monotonic-cumulative series across discontinuities.

    Every decrease is classified and absorbed so downstream deltas stay
    meaningful across agent relaunches:

    - *wraparound*: the previous value sat within ``wrap_slack`` of a
      wrap base (2^32 / 2^64) — the base is added, so the true delta
      ``base - prev + new`` survives;
    - *restart*: the value fell to (at most half of) its previous level
      with no wrap base in reach — a new life counting from ~0; the
      previous cumulative becomes the new base;
    - *decrease*: anything else is a monotonic-contract violation; the
      cumulative holds flat rather than inventing negative work.

    Returns ``(rebased, events)`` with one event per discontinuity.
    """
    out: list[float] = []
    events: list[dict] = []
    base = 0.0
    prev: float | None = None
    for i, v in enumerate(values):
        if prev is not None and v < prev:
            wrapped = next(
                (
                    wb for wb in WRAP_BASES
                    if prev <= wb and prev >= (1.0 - wrap_slack) * wb
                ),
                None,
            )
            if wrapped is not None:
                kind = "wraparound"
                base += wrapped
            elif v <= 0.5 * prev:
                kind = "restart"
                base += prev
            else:
                kind = "decrease"
                base += prev - v
            events.append(
                {"i": i, "kind": kind, "prev": prev, "value": v}
            )
        prev = v
        out.append(base + v)
    return out, events


# -- series extraction helpers -----------------------------------------------


def _stem(name: str) -> str:
    return name.split("{", 1)[0]


def stem_values(
    samples: list[dict], stem: str, families=("counters", "gauges"),
) -> tuple[list[float], list[float]]:
    """Aggregated ``(ts, values)`` for every labeled variant of a series
    stem, summed per sample (an agent restart drops ALL its labelsets at
    once, so the summed series still rebases cleanly)."""
    ts: list[float] = []
    vals: list[float] = []
    for s in samples:
        total = 0.0
        hit = False
        for fam in families:
            for k, v in s.get(fam, {}).items():
                if _stem(k) == stem:
                    total += float(v)
                    hit = True
        if hit:
            ts.append(float(s["t"]))
            vals.append(total)
    return ts, vals


def stem_histograms(
    samples: list[dict], stem: str,
) -> tuple[list[float], list[dict]]:
    """Aggregated ``(ts, hists)`` for a histogram stem: per sample, the
    labeled variants' bucket vectors summed edge-wise."""
    ts: list[float] = []
    hists: list[dict] = []
    for s in samples:
        agg: dict | None = None
        for k, h in s.get("histograms", {}).items():
            if _stem(k) != stem:
                continue
            if agg is None:
                agg = {
                    "le": list(h["le"]),
                    "counts": list(h["counts"]),
                    "sum": float(h["sum"]),
                    "count": int(h["count"]),
                }
            elif agg["le"] == h["le"]:
                agg["counts"] = [
                    a + b for a, b in zip(agg["counts"], h["counts"])
                ]
                agg["sum"] += float(h["sum"])
                agg["count"] += int(h["count"])
        if agg is not None:
            ts.append(float(s["t"]))
            hists.append(agg)
    return ts, hists


# -- detectors ---------------------------------------------------------------


def fit_leaks(
    samples: list[dict],
    *,
    t_scale_s: float = 1.0,
    leak_series=DEFAULT_LEAK_SERIES,
    ceilings: dict | None = None,
    min_points: int = 4,
) -> dict:
    """Theil–Sen units-per-hour verdicts for every leak-scan stem that
    appears in the series."""
    ceilings = dict(DEFAULT_LEAK_CEILINGS, **(ceilings or {}))
    out: dict[str, dict] = {}
    for stem in leak_series:
        ts, vals = stem_values(samples, stem)
        if not ts:
            continue
        slope_t = theil_sen(ts, vals)
        entry: dict = {
            "points": len(ts),
            "first": vals[0],
            "last": vals[-1],
            "growth": vals[-1] - vals[0],
        }
        if slope_t is None or len(ts) < min_points:
            entry.update(
                {"slope_per_hour": None, "flagged": False,
                 "armed": False}
            )
        else:
            per_hour = slope_t / t_scale_s * 3600.0
            ceiling = ceilings.get(stem)
            entry.update({
                "slope_per_hour": round(per_hour, 3),
                "ceiling_per_hour": ceiling,
                "armed": True,
                "flagged": bool(
                    ceiling is not None
                    and per_hour > ceiling
                    and entry["growth"] > 0
                ),
            })
        out[stem] = entry
    return out


def detect_wedges(
    samples: list[dict],
    *,
    t_scale_s: float = 1.0,
    pairs=DEFAULT_WEDGE_PAIRS,
    min_samples: int = 3,
    min_span_s: float = 5.0,
) -> tuple[dict, dict]:
    """Longest offered-but-no-progress run per (offered, progress)
    counter pair; a pair is wedged when the run spans at least
    ``min_samples`` intervals AND ``min_span_s`` seconds. Returns
    ``(wedges, resets)`` — resets aggregates the rebase discontinuities
    seen on the way (the relaunch evidence)."""
    wedges: dict[str, dict] = {}
    resets: dict[str, list] = {}
    for offered_stem, progress_stem in pairs:
        ts_o, off = stem_values(samples, offered_stem, ("counters",))
        ts_p, prog = stem_values(samples, progress_stem, ("counters",))
        label = f"{offered_stem}->{progress_stem}"
        if len(ts_o) < 2 or len(ts_p) < 2:
            wedges[label] = {"armed": False, "wedged": False}
            continue
        off_rb, ev_o = rebase_counter(off)
        prog_rb, ev_p = rebase_counter(prog)
        if ev_o:
            resets[offered_stem] = ev_o
        if ev_p:
            resets[progress_stem] = ev_p
        # Align on sample timestamps both series cover.
        by_t_p = dict(zip(ts_p, prog_rb))
        t_al = [t for t in ts_o if t in by_t_p]
        o_al = [off_rb[i] for i, t in enumerate(ts_o) if t in by_t_p]
        p_al = [by_t_p[t] for t in t_al]
        best = {"samples": 0, "span_s": 0.0, "offered": 0.0}
        run_start = None
        run_offered = 0.0
        offered_any = False
        for i in range(1, len(t_al)):
            d_off = o_al[i] - o_al[i - 1]
            d_prog = p_al[i] - p_al[i - 1]
            offered_any = offered_any or d_off > 0
            if d_off > 0 and d_prog <= 0:
                if run_start is None:
                    run_start = i - 1
                    run_offered = 0.0
                run_offered += d_off
                span = (t_al[i] - t_al[run_start]) * t_scale_s
                if i - run_start > best["samples"]:
                    best = {
                        "samples": i - run_start,
                        "span_s": round(span, 3),
                        "offered": run_offered,
                    }
            else:
                run_start = None
        wedges[label] = {
            "armed": offered_any,
            "wedged": bool(
                best["samples"] >= min_samples
                and best["span_s"] >= min_span_s
            ),
            "longest_run": best,
        }
    return wedges, resets


def detect_stalls(
    samples: list[dict],
    *,
    t_scale_s: float = 1.0,
    gauge: str = "corro_runtime_loop_lag_last_seconds",
    threshold_s: float = 0.5,
    min_run: int = 3,
) -> dict:
    """Loop-lag stall runs: consecutive samples with the lag gauge above
    ``threshold_s``. Reports the longest run and how many qualifying
    runs (length >= min_run) occurred."""
    ts, vals = stem_values(samples, gauge, ("gauges",))
    if len(ts) < 2:
        return {"armed": False, "runs": 0, "longest": 0}
    runs = 0
    longest = 0
    longest_span = 0.0
    cur = 0
    start_t = None
    for t, v in zip(ts, vals):
        if v > threshold_s:
            if cur == 0:
                start_t = t
            cur += 1
            if cur > longest:
                longest = cur
                longest_span = (t - start_t) * t_scale_s
            if cur == min_run:
                runs += 1
        else:
            cur = 0
    return {
        "armed": True,
        "threshold_s": threshold_s,
        "runs": runs,
        "longest": longest,
        "longest_span_s": round(longest_span, 3),
    }


# -- SLO burn rates ----------------------------------------------------------


def _hist_bad_cum(hist: dict, threshold_s: float) -> int:
    """Events strictly above the threshold bucket, cumulatively: total
    minus the cumulative count at the first edge >= threshold."""
    good = 0
    for edge, c in zip(hist["le"], hist["counts"]):
        if edge >= threshold_s:
            good = c
            break
    else:
        good = hist["counts"][-1] if hist["counts"] else 0
    return int(hist["count"]) - int(good)


def eval_slo(
    samples: list[dict], slo: dict, *, t_scale_s: float = 1.0,
    windows=DEFAULT_WINDOWS, burn_threshold: float = 1.0,
) -> dict:
    """One SLO's multi-window burn rates. ``breached`` requires EVERY
    armed window to burn at or above threshold — and at least one window
    to be armed — so a single late blip (fast window only) or ancient
    history (slow window only) cannot breach alone."""
    kind = slo["kind"]
    out: dict = {
        "kind": kind, "series": slo["series"], "windows": {},
    }
    win_results: list[dict] = []

    def window_start(n: int, frac: float) -> int:
        k = max(3, int(round(n * frac)))
        return max(0, n - k)

    if kind == "histogram":
        ts, hists = stem_histograms(samples, slo["series"])
        budget = max(1e-9, 1.0 - float(slo["objective"]))
        for wname, frac in windows:
            if len(ts) < 2:
                win_results.append({"name": wname, "armed": False})
                continue
            i0 = window_start(len(ts), frac)
            d_total = hists[-1]["count"] - hists[i0]["count"]
            d_bad = (
                _hist_bad_cum(hists[-1], slo["threshold_s"])
                - _hist_bad_cum(hists[i0], slo["threshold_s"])
            )
            if d_total <= 0:
                win_results.append({"name": wname, "armed": False})
                continue
            bad_frac = max(0.0, d_bad / d_total)
            win_results.append({
                "name": wname, "armed": True, "events": int(d_total),
                "bad_frac": round(bad_frac, 5),
                "burn": round(bad_frac / budget, 3),
            })
    elif kind == "gauge":
        ts, vals = stem_values(samples, slo["series"], ("gauges",))
        budget = max(1e-9, 1.0 - float(slo["objective"]))
        for wname, frac in windows:
            if len(ts) < 2:
                win_results.append({"name": wname, "armed": False})
                continue
            i0 = window_start(len(ts), frac)
            wvals = vals[i0:]
            bad_frac = sum(
                1 for v in wvals if v > slo["ceiling"]
            ) / len(wvals)
            win_results.append({
                "name": wname, "armed": True, "samples": len(wvals),
                "bad_frac": round(bad_frac, 5),
                "burn": round(bad_frac / budget, 3),
            })
    elif kind == "counter_budget":
        ts, vals = stem_values(samples, slo["series"])
        if vals:
            vals, _ev = rebase_counter(vals)
        for wname, frac in windows:
            if len(ts) < 2:
                win_results.append({"name": wname, "armed": False})
                continue
            i0 = window_start(len(ts), frac)
            span_h = (ts[-1] - ts[i0]) * t_scale_s / 3600.0
            if span_h <= 0:
                win_results.append({"name": wname, "armed": False})
                continue
            events = max(0.0, vals[-1] - vals[i0])
            rate = events / span_h
            win_results.append({
                "name": wname, "armed": True, "events": events,
                "per_hour": round(rate, 3),
                "burn": round(rate / float(slo["allowed_per_hour"]), 3),
            })
    else:
        raise ValueError(f"unknown SLO kind {kind!r}")

    armed = [w for w in win_results if w.get("armed")]
    out["windows"] = {w["name"]: w for w in win_results}
    out["armed"] = bool(armed)
    out["breached"] = bool(armed) and all(
        w["burn"] >= burn_threshold for w in armed
    )
    return out


# -- the corro-endurance/1 report --------------------------------------------


def build_report(
    samples: list[dict],
    *,
    t_scale_s: float = 1.0,
    label: str = "",
    leak_series=DEFAULT_LEAK_SERIES,
    leak_ceilings: dict | None = None,
    min_points: int = 4,
    wedge_pairs=DEFAULT_WEDGE_PAIRS,
    wedge_min_samples: int = 3,
    wedge_min_span_s: float = 5.0,
    stall_gauge: str = "corro_runtime_loop_lag_last_seconds",
    stall_threshold_s: float = 0.5,
    stall_min_run: int = 3,
    slos=DEFAULT_SLOS,
    windows=DEFAULT_WINDOWS,
    burn_threshold: float = 1.0,
) -> dict:
    """Run every detector over one series' samples and assemble the
    self-describing verdict artifact."""
    span_s = (
        (float(samples[-1]["t"]) - float(samples[0]["t"])) * t_scale_s
        if len(samples) >= 2 else 0.0
    )
    leaks = fit_leaks(
        samples, t_scale_s=t_scale_s, leak_series=leak_series,
        ceilings=leak_ceilings, min_points=min_points,
    )
    wedges, resets = detect_wedges(
        samples, t_scale_s=t_scale_s, pairs=wedge_pairs,
        min_samples=wedge_min_samples, min_span_s=wedge_min_span_s,
    )
    stalls = detect_stalls(
        samples, t_scale_s=t_scale_s, gauge=stall_gauge,
        threshold_s=stall_threshold_s, min_run=stall_min_run,
    )
    slo_out = {
        s["name"]: eval_slo(
            samples, s, t_scale_s=t_scale_s, windows=windows,
            burn_threshold=burn_threshold,
        )
        for s in slos
    }

    breaches: list[str] = []
    for stem, e in leaks.items():
        if e.get("flagged"):
            breaches.append(
                f"leak: {stem} slope {e['slope_per_hour']:g}/h > "
                f"ceiling {e['ceiling_per_hour']:g}/h"
            )
    for pair, w in wedges.items():
        if w.get("wedged"):
            breaches.append(
                f"wedge: {pair} flat for {w['longest_run']['span_s']}s "
                f"while {w['longest_run']['offered']:g} offered"
            )
    if stalls.get("runs", 0) > 0:
        breaches.append(
            f"stall: {stalls['runs']} loop-lag runs >= "
            f"{stall_min_run} samples above {stall_threshold_s}s "
            f"(longest {stalls['longest']})"
        )
    for name, s in slo_out.items():
        if s["breached"]:
            burns = {
                w: s["windows"][w].get("burn")
                for w in s["windows"] if s["windows"][w].get("armed")
            }
            breaches.append(f"slo: {name} burn over threshold: {burns}")

    return {
        "schema": ENDURANCE_SCHEMA,
        "label": label,
        "samples": len(samples),
        "span_s": round(span_s, 3),
        "t_scale_s": t_scale_s,
        "resets": {
            stem: {
                "events": len(evs),
                "kinds": sorted({e["kind"] for e in evs}),
            }
            for stem, evs in resets.items()
        },
        "leaks": leaks,
        "wedges": wedges,
        "stalls": stalls,
        "slo": slo_out,
        "detectors_armed": {
            "leak": any(e.get("armed") for e in leaks.values()),
            "wedge": any(w.get("armed") for w in wedges.values()),
            "stall": bool(stalls.get("armed")),
            "slo": any(s.get("armed") for s in slo_out.values()),
        },
        "breaches": breaches,
        "ok": not breaches,
    }


def render_report(report: dict) -> str:
    """Human-readable form of a corro-endurance/1 report."""
    lines = [
        f"endurance[{report.get('label') or '-'}]: "
        f"{report['samples']} samples over {report['span_s']}s "
        f"({'ok' if report['ok'] else 'BREACHED'})"
    ]
    for stem, e in sorted(report["leaks"].items()):
        if e.get("slope_per_hour") is None:
            continue
        mark = "LEAK" if e["flagged"] else "ok"
        lines.append(
            f"  leak {stem}: {e['slope_per_hour']:+g}/h "
            f"(ceiling {e.get('ceiling_per_hour')}) [{mark}]"
        )
    for pair, w in sorted(report["wedges"].items()):
        if not w.get("armed"):
            continue
        mark = "WEDGE" if w["wedged"] else "ok"
        lines.append(
            f"  wedge {pair}: longest run "
            f"{w['longest_run']['samples']} samples/"
            f"{w['longest_run']['span_s']}s [{mark}]"
        )
    st = report["stalls"]
    if st.get("armed"):
        lines.append(
            f"  stalls: {st['runs']} runs, longest {st['longest']} "
            f"samples [{'STALL' if st['runs'] else 'ok'}]"
        )
    for name, s in sorted(report["slo"].items()):
        if not s.get("armed"):
            continue
        burns = ", ".join(
            f"{w}={s['windows'][w].get('burn')}"
            for w in s["windows"] if s["windows"][w].get("armed")
        )
        lines.append(
            f"  slo {name}: {burns} "
            f"[{'BREACH' if s['breached'] else 'ok'}]"
        )
    for b in report["breaches"]:
        lines.append(f"  BREACH: {b}")
    return "\n".join(lines)


# -- soak report diff + budget gate ------------------------------------------


def endurance_blocks(report: dict) -> dict[str, dict]:
    """Every corro-endurance/1 block inside a report, keyed by path
    label: a bare endurance report maps to ``{"": report}``; a
    corro-soak/1 report contributes ``kernel`` and ``host.n<i>``."""
    if report.get("schema") == ENDURANCE_SCHEMA:
        return {"": report}
    out: dict[str, dict] = {}
    k = (report.get("kernel") or {}).get("endurance")
    if k:
        out["kernel"] = k
    host_end = (report.get("host") or {}).get("endurance") or {}
    for name, blk in (host_end.get("agents") or {}).items():
        out[f"host.{name}"] = blk
    return out


def _slope_floor(stem: str) -> float:
    """Absolute noise floor for slope diffs: a quarter of the default
    ceiling (short windows extrapolated to /hour jitter hard)."""
    return 0.25 * DEFAULT_LEAK_CEILINGS.get(stem, 800.0)


def diff_soak(base: dict, cand: dict, tolerance: float = 0.5) -> dict:
    """Diff two soak (or bare endurance) reports: leak-slope regressions
    at ``tolerance`` above an absolute noise floor; NEW breaches, lost
    detector arming, and series-coverage collapse are never tolerated."""
    rows: list[dict] = []
    regressions: list[str] = []
    bb, cb = endurance_blocks(base), endurance_blocks(cand)
    if not bb:
        regressions.append("baseline carries no endurance blocks")
    for label, b in bb.items():
        c = cb.get(label)
        if c is None:
            regressions.append(f"{label}: endurance block missing")
            continue
        if c["samples"] < max(2, b["samples"] // 2):
            regressions.append(
                f"{label}: series coverage collapsed "
                f"({b['samples']} -> {c['samples']} samples)"
            )
        for stem, be in b["leaks"].items():
            ce = (c["leaks"] or {}).get(stem)
            if (
                ce is None or be.get("slope_per_hour") is None
                or ce.get("slope_per_hour") is None
            ):
                continue
            bs, cs = be["slope_per_hour"], ce["slope_per_hour"]
            limit = max(bs, 0.0) * (1.0 + tolerance) + _slope_floor(stem)
            ok = cs <= limit
            rows.append({
                "metric": f"{label}:{stem}.slope_per_hour",
                "baseline": bs, "candidate": cs, "ok": ok,
            })
            if not ok:
                regressions.append(
                    f"{label}: {stem} leak slope {bs:g}/h -> {cs:g}/h "
                    f"(limit {limit:g}/h)"
                )
        if not b["breaches"] and c["breaches"]:
            regressions.append(
                f"{label}: new breaches: {c['breaches'][:3]}"
            )
        for det, was in b["detectors_armed"].items():
            if was and not c["detectors_armed"].get(det):
                regressions.append(
                    f"{label}: detector {det!r} no longer armed — "
                    f"harness coverage regressed"
                )
    if (
        (base.get("kernel") or {}).get("determinism_ok")
        and not (cand.get("kernel") or {}).get("determinism_ok")
    ):
        regressions.append("kernel series replay determinism lost")
    return {"rows": rows, "regressions": regressions}


def check_soak_budget(report: dict, budget: dict) -> tuple[bool, list]:
    """Gate a corro-soak/1 report against the bench_budget.json ``soak``
    entry. Leak-slope ceilings and the wall ceiling are tolerance-
    scaled; wedge/SLO/stall maxima, the detectors-armed rule, and the
    determinism requirement never are."""
    breaches: list[str] = []
    tol = float(budget.get("tolerance", 1.0))

    for k in ("platform", "scenario"):
        want = budget.get(k)
        if want is not None and report.get(k) != want:
            breaches.append(
                f"dims: {k} {report.get(k)!r} != budget {want!r}"
            )

    blocks = endurance_blocks(report)
    if not blocks:
        breaches.append("report carries no endurance blocks")

    wedge_max = int(budget.get("wedge_max", 0))
    slo_max = int(budget.get("slo_breach_max", 0))
    stall_max = int(budget.get("stall_runs_max", 0))
    for label, blk in sorted(blocks.items()):
        wedged = sum(
            1 for w in blk["wedges"].values() if w.get("wedged")
        )
        if wedged > wedge_max:  # never tolerance-scaled
            breaches.append(
                f"{label}: {wedged} wedge(s) > max {wedge_max}"
            )
        slo_breached = sum(
            1 for s in blk["slo"].values() if s.get("breached")
        )
        if slo_breached > slo_max:  # never tolerance-scaled
            breaches.append(
                f"{label}: {slo_breached} SLO breach(es) > max {slo_max}"
            )
        if blk["stalls"].get("runs", 0) > stall_max:
            breaches.append(
                f"{label}: {blk['stalls']['runs']} stall run(s) > max "
                f"{stall_max}"
            )

    for path, ceiling in (
        budget.get("leak_ceilings_per_hour") or {}
    ).items():
        prefix, _, stem = path.partition(":")
        matched = False
        for label, blk in blocks.items():
            if not (label == prefix or label.startswith(prefix + ".")):
                continue
            e = blk["leaks"].get(stem)
            if e is None or e.get("slope_per_hour") is None:
                continue
            matched = True
            if e["slope_per_hour"] > ceiling * tol:
                breaches.append(
                    f"{label}: {stem} slope {e['slope_per_hour']:g}/h "
                    f"> budget {ceiling:g}/h x{tol:g}"
                )
        if not matched:
            breaches.append(
                f"budget ceiling {path!r} matched no measured series — "
                f"coverage hole"
            )

    if budget.get("require_detectors_armed", True):
        armed: set[str] = set()
        for blk in blocks.values():
            armed.update(
                d for d, on in blk["detectors_armed"].items() if on
            )
        unarmed = sorted(
            {"leak", "wedge", "stall", "slo"} - armed
        )
        if unarmed:
            # Machinery-fired rule: green verdicts from detectors that
            # never evaluated anything mean the harness failed to apply
            # coverage, not that the system holds.
            breaches.append(
                f"test-harness failure: soak passed with detectors "
                f"never armed: {unarmed}"
            )

    if budget.get("require_determinism", False):
        if not (report.get("kernel") or {}).get("determinism_ok"):
            breaches.append(
                "kernel series file is not replay-deterministic"
            )

    ceiling_s = budget.get("wall_ceiling_s")
    if ceiling_s is not None:
        wall = float(report.get("wall_s", 0.0))
        if wall > float(ceiling_s) * tol:
            breaches.append(
                f"wall {wall:g}s > ceiling {ceiling_s:g}s x{tol:g}"
            )

    return not breaches, breaches
