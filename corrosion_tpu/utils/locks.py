"""Lock registry: labeled, age-tracked lock diagnostics.

The reference wraps every Bookie/Booked lock in a CountedTokioRwLock whose
registry records label, kind, state, and age, surfaced live by `corrosion
locks --top N` for production deadlock/contention diagnosis
(corro-types/src/agent.rs:593-893, corro-admin/src/lib.rs:186-207). Same
contract here: the store's writer lock and any agent-level critical section
register acquisitions; the admin RPC serves ranked snapshots.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

ACQUIRING, LOCKED = "acquiring", "locked"


@dataclass
class LockMeta:
    id: int
    label: str
    kind: str  # read | write
    state: str
    started_at: float

    def age_ms(self) -> float:
        return (time.monotonic() - self.started_at) * 1000.0


class LockRegistry:
    """Tracks in-flight lock acquisitions (LockRegistry, agent.rs:720-869)."""

    def __init__(self) -> None:
        self._seq = itertools.count(1)
        self._live: dict[int, LockMeta] = {}
        self._guard = threading.Lock()

    @contextmanager
    def acquire(self, lock: threading.Lock, label: str, kind: str = "write"):
        meta = LockMeta(
            id=next(self._seq), label=label, kind=kind,
            state=ACQUIRING, started_at=time.monotonic(),
        )
        with self._guard:
            self._live[meta.id] = meta
        lock.acquire()
        meta.state = LOCKED
        meta.started_at = time.monotonic()
        try:
            yield
        finally:
            lock.release()
            with self._guard:
                self._live.pop(meta.id, None)

    def snapshot(self, top: int = 10) -> list[dict]:
        """Longest-held/waited first (`corrosion locks --top N`)."""
        with self._guard:
            metas = list(self._live.values())
        metas.sort(key=lambda m: -m.age_ms())
        return [
            {
                "id": m.id,
                "label": m.label,
                "kind": m.kind,
                "state": m.state,
                "age_ms": round(m.age_ms(), 1),
            }
            for m in metas[:top]
        ]
