"""Metrics facade + Prometheus text exposition.

The reference uses the `metrics` crate with a Prometheus exporter (custom
histogram buckets 1 ms-60 s, corrosion/src/command/agent.rs:65-85) and ~45
documented series (doc/telemetry/prometheus.md). This module provides the
same shape: process-local registries of counters/gauges/histograms with
label sets, rendered in the Prometheus text format, served by a tiny
asyncio HTTP endpoint when `[telemetry] prometheus_addr` is configured.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from dataclasses import dataclass, field

# command/agent.rs:70-80: 1 ms … 60 s
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Label-cardinality cap per metric (registry-created metrics only).
# Per-peer series (corro_peer_breaker_trips_total{addr=...}) grow one
# labelset per address forever, so under churn+relaunch soaks the
# registry itself leaks; past the cap, NEW labelsets fold into an
# `other` overflow bucket and corro_metrics_labelsets_dropped_total
# counts the folded samples. 64 is an order of magnitude above any
# legitimate labelset count in this codebase (routes, engines, planes).
DEFAULT_MAX_LABELSETS = 64


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _overflow_key(key: tuple) -> tuple:
    """The `other` bucket for a folded labelset: same label NAMES, every
    value replaced — the series keeps its shape for scrapers while the
    value-space cardinality stays bounded."""
    return tuple((k, "other") for k, _v in key)


def _admit_key(key: tuple, container: dict, max_labelsets) -> tuple[tuple, bool]:
    """Storage key for ``key`` under the cardinality cap (call holding
    the metric's lock). Existing labelsets always pass; a NEW one past
    the cap folds into the overflow bucket. Returns (key, folded)."""
    if (
        not key
        or max_labelsets is None
        or key in container
        or len(container) < max_labelsets
    ):
        return key, False
    return _overflow_key(key), True


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


@dataclass
class Counter:
    name: str
    help: str = ""
    max_labelsets: int | None = None
    on_fold: object = None  # callable, invoked OUTSIDE the lock
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            key, folded = _admit_key(key, self._values, self.max_labelsets)
            self._values[key] = self._values.get(key, 0.0) + value
        if folded and self.on_fold is not None:
            self.on_fold()

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} counter"]
        if self.help:
            out.insert(0, f"# HELP {self.name} {self.help}")
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v:g}")
        if len(out) <= (2 if self.help else 1):
            out.append(f"{self.name} 0")
        return out


@dataclass
class Gauge:
    name: str
    help: str = ""
    max_labelsets: int | None = None
    on_fold: object = None
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def set(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            key, folded = _admit_key(key, self._values, self.max_labelsets)
            self._values[key] = float(value)
        if folded and self.on_fold is not None:
            self.on_fold()

    def add(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            key, folded = _admit_key(key, self._values, self.max_labelsets)
            self._values[key] = self._values.get(key, 0.0) + value
        if folded and self.on_fold is not None:
            self.on_fold()

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} gauge"]
        if self.help:
            out.insert(0, f"# HELP {self.name} {self.help}")
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v:g}")
        if len(out) <= (2 if self.help else 1):
            out.append(f"{self.name} 0")
        return out


@dataclass
class Histogram:
    name: str
    help: str = ""
    buckets: tuple = DEFAULT_BUCKETS
    max_labelsets: int | None = None
    on_fold: object = None
    _counts: dict[tuple, list] = field(default_factory=dict)
    _sums: dict[tuple, float] = field(default_factory=dict)
    _totals: dict[tuple, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            key, folded = _admit_key(key, self._totals, self.max_labelsets)
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
        if folded and self.on_fold is not None:
            self.on_fold()

    def count(self, **labels: str) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from bucket counts (diagnostics).

        Interpolates linearly WITHIN the winning bucket (Prometheus'
        histogram_quantile rule) instead of returning its upper bound —
        the latter biased p50/p99 up by as much as one bucket width.
        Observations beyond the last bucket still report +inf.
        """
        key = _label_key(labels)
        with self._lock:
            total = self._totals.get(key, 0)
            if total == 0:
                return float("nan")
            counts = list(self._counts[key])
        target = q * total
        for i, b in enumerate(self.buckets):
            if counts[i] >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                below = counts[i - 1] if i > 0 else 0
                in_bucket = counts[i] - below
                if in_bucket <= 0:
                    return lo
                frac = (target - below) / in_bucket
                return lo + frac * (b - lo)
        return float("inf")

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} histogram"]
        if self.help:
            out.insert(0, f"# HELP {self.name} {self.help}")
        # Snapshot under the lock so a concurrent observe() can neither
        # resize the dicts mid-iteration nor tear a bucket/sum/count trio.
        with self._lock:
            snap = [
                (key, list(self._counts[key]), self._sums[key],
                 self._totals[key])
                for key in sorted(self._totals)
            ]
        for key, counts, total_sum, total in snap:
            for i, b in enumerate(self.buckets):
                lk = key + (("le", f"{b:g}"),)
                out.append(
                    f"{self.name}_bucket{_fmt_labels(lk)} {counts[i]}"
                )
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {total}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {total_sum:g}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {total}")
        return out


class MetricsRegistry:
    """Per-agent metric registry (the `metrics` facade role).

    Registration is get-or-create BY NAME and type-checked: re-requesting
    an existing series returns the same object (so re-registration on an
    in-process agent relaunch is idempotent), while re-requesting it as
    a different metric kind raises instead of handing back an object
    whose API the caller will misuse. Registry-created metrics carry the
    label-cardinality cap (``max_labelsets``); samples folded into the
    `other` overflow bucket tick ``corro_metrics_labelsets_dropped_total``.
    """

    def __init__(self, max_labelsets: int | None = DEFAULT_MAX_LABELSETS):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()
        self.max_labelsets = max_labelsets
        self._labelsets_dropped = self.counter(
            "corro_metrics_labelsets_dropped_total",
            "samples folded into the `other` overflow labelset by the "
            "label-cardinality cap",
        )

    def _note_fold(self) -> None:
        self._labelsets_dropped.inc()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(
            name, Counter,
            lambda: Counter(
                name, help, max_labelsets=self.max_labelsets,
                on_fold=self._note_fold,
            ),
        )

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(
            name, Gauge,
            lambda: Gauge(
                name, help, max_labelsets=self.max_labelsets,
                on_fold=self._note_fold,
            ),
        )

    def histogram(
        self, name: str, help: str = "", buckets: tuple = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get(
            name, Histogram,
            lambda: Histogram(
                name, help, buckets, max_labelsets=self.max_labelsets,
                on_fold=self._note_fold,
            ),
        )

    def _get(self, name: str, kind: type, mk):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = mk()
            elif not isinstance(m, kind):
                raise ValueError(
                    f"metric {name!r} is already registered as a "
                    f"{type(m).__name__}, not a {kind.__name__}"
                )
            return m

    def render(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat dict for the admin RPC / tests."""
        out: dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.items())
        for name, m in metrics:
            if isinstance(m, (Counter, Gauge)):
                with m._lock:
                    items = list(m._values.items())
                for key, v in items:
                    out[name + _fmt_labels(key)] = v
            elif isinstance(m, Histogram):
                with m._lock:
                    items = [
                        (key, t, m._sums[key])
                        for key, t in m._totals.items()
                    ]
                for key, t, s in items:
                    out[name + "_count" + _fmt_labels(key)] = t
                    out[name + "_sum" + _fmt_labels(key)] = s
        return out

    def series_snapshot(self) -> dict:
        """Typed whole-registry snapshot for the endurance plane's
        MetricSeriesRecorder (obs/series.py): counters and gauges as
        ``{rendered_name: value}``, histograms as bucket VECTORS — the
        flat ``snapshot()`` collapses them to ``_count``/``_sum``, which
        loses the distribution the SLO burn-rate windows need. Each
        metric is read under its own lock so a bucket/sum/total trio can
        never tear; cross-metric skew is bounded by one sampling pass."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            metrics = list(self._metrics.items())
        for name, m in metrics:
            if isinstance(m, Counter):
                with m._lock:
                    items = list(m._values.items())
                for key, v in items:
                    out["counters"][name + _fmt_labels(key)] = v
            elif isinstance(m, Gauge):
                with m._lock:
                    items = list(m._values.items())
                for key, v in items:
                    out["gauges"][name + _fmt_labels(key)] = v
            elif isinstance(m, Histogram):
                with m._lock:
                    snap = [
                        (key, list(m._counts[key]), m._sums[key],
                         m._totals[key])
                        for key in sorted(m._totals)
                    ]
                for key, counts, s, t in snap:
                    out["histograms"][name + _fmt_labels(key)] = {
                        "le": [float(b) for b in m.buckets],
                        "counts": counts,
                        "sum": s,
                        "count": t,
                    }
        return out


async def serve_prometheus(
    registry: MetricsRegistry, host: str, port: int
) -> tuple[asyncio.AbstractServer, tuple[str, int]]:
    """Minimal GET /metrics endpoint (setup_prometheus, command/agent.rs:65)."""

    async def on_conn(reader: asyncio.StreamReader, writer):
        try:
            line = await reader.readline()
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            # Parse the request line properly: "METHOD SP PATH SP VERSION".
            # Substring matching (`b"/metrics" in line`) accepted any URL
            # merely containing "metrics".
            parts = line.split()
            method = parts[0] if len(parts) >= 1 else b""
            path = parts[1].split(b"?", 1)[0] if len(parts) >= 2 else b""
            ok = method == b"GET" and path in (b"/metrics", b"/")
            body = registry.render().encode() if ok else b""
            status = (
                b"HTTP/1.1 200 OK\r\n" if ok else b"HTTP/1.1 404 Not Found\r\n"
            )
            writer.write(
                status
                + b"content-type: text/plain; version=0.0.4\r\n"
                + f"content-length: {len(body)}\r\n\r\n".encode()
                + body
            )
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(on_conn, host, port)
    sock = server.sockets[0].getsockname()
    return server, (sock[0], sock[1])


def process_rss_bytes() -> int | None:
    """Resident set size of this process, or None where unknowable.
    /proc is authoritative on Linux; the resource fallback (macOS)
    reports ru_maxrss (peak, in bytes there) — close enough for a
    soak-growth signal."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    except Exception:
        return None


def process_open_fds() -> int | None:
    """Open file descriptors of this process (None where /proc-less and
    uncountable). The serving plane is FD-bound — one client + one
    server socket per subscription — so fd growth is the leak signal
    hours-long soaks need."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def process_stats() -> dict:
    """One self-observability sample: RSS + open-fd count, JSON-ready.
    Event-loop lag is measured where a loop runs (the agent's runtime
    metrics loop exports it; soak reports record how long their
    synchronous kernel sections held the loop)."""
    return {
        "rss_bytes": process_rss_bytes(),
        "open_fds": process_open_fds(),
    }


def register_process_gauges(registry: "MetricsRegistry") -> tuple:
    """Create the process self-observability gauges on ``registry``:
    ``corro_runtime_rss_bytes``, ``corro_runtime_open_fds``, and
    ``corro_runtime_loop_lag_last_seconds`` (the most recent event-loop
    wakeup lag — the gauge companion of the existing
    ``corro_runtime_loop_lag_seconds`` histogram). Returns the three
    gauges; the caller's sampling loop sets them.

    Idempotent: registration is get-or-create by name, so calling this
    again (an agent relaunched in the same process, a second recorder
    install) returns the SAME gauge objects — no raise, no duplicate
    series, no double-sampling."""
    return (
        registry.gauge(
            "corro_runtime_rss_bytes", "process resident set size"
        ),
        registry.gauge(
            "corro_runtime_open_fds", "open file descriptors"
        ),
        registry.gauge(
            "corro_runtime_loop_lag_last_seconds",
            "most recent event-loop wakeup lag sample",
        ),
    )


class StepTimer:
    """Wall-clock section timer feeding a histogram (tokio-metrics role)."""

    def __init__(self, hist: Histogram, **labels: str) -> None:
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0, **self.labels)
        return False
