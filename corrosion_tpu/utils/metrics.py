"""Metrics facade + Prometheus text exposition.

The reference uses the `metrics` crate with a Prometheus exporter (custom
histogram buckets 1 ms-60 s, corrosion/src/command/agent.rs:65-85) and ~45
documented series (doc/telemetry/prometheus.md). This module provides the
same shape: process-local registries of counters/gauges/histograms with
label sets, rendered in the Prometheus text format, served by a tiny
asyncio HTTP endpoint when `[telemetry] prometheus_addr` is configured.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from dataclasses import dataclass, field

# command/agent.rs:70-80: 1 ms … 60 s
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


@dataclass
class Counter:
    name: str
    help: str = ""
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} counter"]
        if self.help:
            out.insert(0, f"# HELP {self.name} {self.help}")
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v:g}")
        if len(out) <= (2 if self.help else 1):
            out.append(f"{self.name} 0")
        return out


@dataclass
class Gauge:
    name: str
    help: str = ""
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} gauge"]
        if self.help:
            out.insert(0, f"# HELP {self.name} {self.help}")
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v:g}")
        if len(out) <= (2 if self.help else 1):
            out.append(f"{self.name} 0")
        return out


@dataclass
class Histogram:
    name: str
    help: str = ""
    buckets: tuple = DEFAULT_BUCKETS
    _counts: dict[tuple, list] = field(default_factory=dict)
    _sums: dict[tuple, float] = field(default_factory=dict)
    _totals: dict[tuple, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from bucket counts (diagnostics).

        Interpolates linearly WITHIN the winning bucket (Prometheus'
        histogram_quantile rule) instead of returning its upper bound —
        the latter biased p50/p99 up by as much as one bucket width.
        Observations beyond the last bucket still report +inf.
        """
        key = _label_key(labels)
        with self._lock:
            total = self._totals.get(key, 0)
            if total == 0:
                return float("nan")
            counts = list(self._counts[key])
        target = q * total
        for i, b in enumerate(self.buckets):
            if counts[i] >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                below = counts[i - 1] if i > 0 else 0
                in_bucket = counts[i] - below
                if in_bucket <= 0:
                    return lo
                frac = (target - below) / in_bucket
                return lo + frac * (b - lo)
        return float("inf")

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} histogram"]
        if self.help:
            out.insert(0, f"# HELP {self.name} {self.help}")
        # Snapshot under the lock so a concurrent observe() can neither
        # resize the dicts mid-iteration nor tear a bucket/sum/count trio.
        with self._lock:
            snap = [
                (key, list(self._counts[key]), self._sums[key],
                 self._totals[key])
                for key in sorted(self._totals)
            ]
        for key, counts, total_sum, total in snap:
            for i, b in enumerate(self.buckets):
                lk = key + (("le", f"{b:g}"),)
                out.append(
                    f"{self.name}_bucket{_fmt_labels(lk)} {counts[i]}"
                )
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {total}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {total_sum:g}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {total}")
        return out


class MetricsRegistry:
    """Per-agent metric registry (the `metrics` facade role)."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help))

    def histogram(
        self, name: str, help: str = "", buckets: tuple = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get(name, lambda: Histogram(name, help, buckets))

    def _get(self, name: str, mk):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = mk()
            return m

    def render(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat dict for the admin RPC / tests."""
        out: dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.items())
        for name, m in metrics:
            if isinstance(m, (Counter, Gauge)):
                with m._lock:
                    items = list(m._values.items())
                for key, v in items:
                    out[name + _fmt_labels(key)] = v
            elif isinstance(m, Histogram):
                with m._lock:
                    items = [
                        (key, t, m._sums[key])
                        for key, t in m._totals.items()
                    ]
                for key, t, s in items:
                    out[name + "_count" + _fmt_labels(key)] = t
                    out[name + "_sum" + _fmt_labels(key)] = s
        return out


async def serve_prometheus(
    registry: MetricsRegistry, host: str, port: int
) -> tuple[asyncio.AbstractServer, tuple[str, int]]:
    """Minimal GET /metrics endpoint (setup_prometheus, command/agent.rs:65)."""

    async def on_conn(reader: asyncio.StreamReader, writer):
        try:
            line = await reader.readline()
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            # Parse the request line properly: "METHOD SP PATH SP VERSION".
            # Substring matching (`b"/metrics" in line`) accepted any URL
            # merely containing "metrics".
            parts = line.split()
            method = parts[0] if len(parts) >= 1 else b""
            path = parts[1].split(b"?", 1)[0] if len(parts) >= 2 else b""
            ok = method == b"GET" and path in (b"/metrics", b"/")
            body = registry.render().encode() if ok else b""
            status = (
                b"HTTP/1.1 200 OK\r\n" if ok else b"HTTP/1.1 404 Not Found\r\n"
            )
            writer.write(
                status
                + b"content-type: text/plain; version=0.0.4\r\n"
                + f"content-length: {len(body)}\r\n\r\n".encode()
                + body
            )
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(on_conn, host, port)
    sock = server.sockets[0].getsockname()
    return server, (sock[0], sock[1])


def process_rss_bytes() -> int | None:
    """Resident set size of this process, or None where unknowable.
    /proc is authoritative on Linux; the resource fallback (macOS)
    reports ru_maxrss (peak, in bytes there) — close enough for a
    soak-growth signal."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    except Exception:
        return None


def process_open_fds() -> int | None:
    """Open file descriptors of this process (None where /proc-less and
    uncountable). The serving plane is FD-bound — one client + one
    server socket per subscription — so fd growth is the leak signal
    hours-long soaks need."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def process_stats() -> dict:
    """One self-observability sample: RSS + open-fd count, JSON-ready.
    Event-loop lag is measured where a loop runs (the agent's runtime
    metrics loop exports it; soak reports record how long their
    synchronous kernel sections held the loop)."""
    return {
        "rss_bytes": process_rss_bytes(),
        "open_fds": process_open_fds(),
    }


def register_process_gauges(registry: "MetricsRegistry") -> tuple:
    """Create the process self-observability gauges on ``registry``:
    ``corro_runtime_rss_bytes``, ``corro_runtime_open_fds``, and
    ``corro_runtime_loop_lag_last_seconds`` (the most recent event-loop
    wakeup lag — the gauge companion of the existing
    ``corro_runtime_loop_lag_seconds`` histogram). Returns the three
    gauges; the caller's sampling loop sets them."""
    return (
        registry.gauge(
            "corro_runtime_rss_bytes", "process resident set size"
        ),
        registry.gauge(
            "corro_runtime_open_fds", "open file descriptors"
        ),
        registry.gauge(
            "corro_runtime_loop_lag_last_seconds",
            "most recent event-loop wakeup lag sample",
        ),
    )


class StepTimer:
    """Wall-clock section timer feeding a histogram (tokio-metrics role)."""

    def __init__(self, hist: Histogram, **labels: str) -> None:
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0, **self.labels)
        return False
