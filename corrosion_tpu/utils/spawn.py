"""Counted task spawning with drain-on-shutdown.

Equivalent of the reference's crates/spawn (lib.rs:13-134): every spawned task
is registered; ``wait_for_all_pending_handles`` polls until all tasks finish
(100 ms poll, capped wait), doubling as a task-leak detector in tests.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Coroutine

log = logging.getLogger(__name__)


class TaskRegistry:
    """Tracks live tasks; global default instance mirrors PENDING_HANDLES."""

    def __init__(self) -> None:
        self._tasks: set[asyncio.Task] = set()

    def spawn(
        self, coro: Coroutine[Any, Any, Any] | Awaitable[Any], name: str | None = None
    ) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        if name:
            task.set_name(name)
        self._tasks.add(task)
        task.add_done_callback(self._on_done)
        return task

    def _on_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if not task.cancelled():
            exc = task.exception()
            if exc is not None:
                log.error("task %s failed: %r", task.get_name(), exc)

    @property
    def pending(self) -> int:
        return len(self._tasks)

    async def wait_for_all_pending_handles(self, cap: float = 60.0) -> bool:
        """Poll every 100 ms until no tasks remain or ``cap`` seconds elapse.

        Returns True if fully drained (spawn/lib.rs:116-134 semantics).
        """
        waited = 0.0
        while self._tasks and waited < cap:
            await asyncio.sleep(0.1)
            waited += 0.1
        if self._tasks:
            log.warning(
                "shutdown cap reached with %d pending tasks: %s",
                len(self._tasks),
                [t.get_name() for t in self._tasks],
            )
            return False
        return True

    async def cancel_all(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)


GLOBAL = TaskRegistry()
spawn_counted = GLOBAL.spawn
wait_for_all_pending_handles = GLOBAL.wait_for_all_pending_handles
