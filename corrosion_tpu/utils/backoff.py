"""Exponential backoff iterator with jitter.

Equivalent of the reference's crates/backoff (lib.rs:7-150): an iterator of
wait durations growing by ``factor`` from ``min_wait`` to ``max_wait``, with
optional full jitter, and an optional cap on the number of retries.

``seed`` makes the jitter deterministic (chaos/regression tests pin the
exact wait sequence); ``on_wait`` is an observability hook called with
each yielded wait — the agent wires it to
``corro_peer_backoff_retries_total`` so retry pressure is visible on
/metrics instead of only in debug logs.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class Backoff:
    """Iterator of backoff delays in seconds."""

    min_wait: float = 1.0
    max_wait: float = 60.0
    factor: float = 2.0
    jitter: bool = True
    max_retries: int | None = None
    seed: int | None = None
    on_wait: Callable[[float], None] | None = None
    _attempt: int = field(default=0, repr=False)
    _rng: random.Random = field(default_factory=random.Random, repr=False)

    def __post_init__(self) -> None:
        if self.seed is not None:
            self._rng = random.Random(self.seed)

    def __iter__(self) -> "Backoff":
        return self

    def __next__(self) -> float:
        if self.max_retries is not None and self._attempt >= self.max_retries:
            raise StopIteration
        wait = min(self.max_wait, self.min_wait * (self.factor**self._attempt))
        self._attempt += 1
        if self.jitter:
            # Full jitter in [min_wait, wait] keeps retries spread out while
            # never hammering faster than the configured floor.
            wait = self._rng.uniform(self.min_wait, max(self.min_wait, wait))
        if self.on_wait is not None:
            self.on_wait(wait)
        return wait

    def reset(self) -> None:
        self._attempt = 0
