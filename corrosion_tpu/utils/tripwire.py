"""Graceful-shutdown primitive.

Equivalent of the reference's crates/tripwire (tripwire.rs:21-174): a future
that resolves when shutdown is requested (signal or programmatic), plus helpers
to run work preemptibly — ``outcome`` distinguishes completed work from
preempted work like the reference's ``Outcome::{Completed, Preempted}``.
"""

from __future__ import annotations

import asyncio
import contextlib
import signal
from dataclasses import dataclass
from typing import Any, Awaitable, Literal


@dataclass
class Outcome:
    kind: Literal["completed", "preempted"]
    value: Any = None

    @property
    def completed(self) -> bool:
        return self.kind == "completed"

    @property
    def preempted(self) -> bool:
        return self.kind == "preempted"


class Tripwire:
    """One-shot shutdown latch shareable across tasks."""

    def __init__(self) -> None:
        self._event = asyncio.Event()

    @classmethod
    def new_signals(cls) -> "Tripwire":
        """Trip on SIGINT/SIGTERM, like Tripwire::new_signals (tripwire.rs:54)."""
        tw = cls()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError):
                loop.add_signal_handler(sig, tw.trip)
        return tw

    def trip(self) -> None:
        self._event.set()

    @property
    def tripped(self) -> bool:
        return self._event.is_set()

    async def wait(self) -> None:
        await self._event.wait()

    async def preemptible(self, aw: Awaitable[Any]) -> Outcome:
        """Run ``aw`` until completion or until the tripwire fires."""
        task = asyncio.ensure_future(aw)
        waiter = asyncio.ensure_future(self._event.wait())
        done, _ = await asyncio.wait(
            {task, waiter}, return_when=asyncio.FIRST_COMPLETED
        )
        if task in done:
            waiter.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await waiter
            return Outcome("completed", task.result())
        task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await task
        return Outcome("preempted")


async def timeout(aw: Awaitable[Any], seconds: float) -> Any:
    """TimeoutFutureExt equivalent — plain asyncio.wait_for wrapper."""
    return await asyncio.wait_for(aw, seconds)
