"""Persistent XLA compilation cache for the measurement entry points.

The flagship graphs take minutes to compile cold (the 10k bench ~130 s,
the 100k configs more); the persistent cache cuts repeat invocations —
including the driver's end-of-round bench run — to seconds. Call before
the first jit. Safe to call under pytest/CPU too; entries are keyed by
platform + HLO so devices never collide.
"""

from __future__ import annotations

import os


def enable_persistent_cache(path: str | None = None) -> str | None:
    import jax

    candidates = [
        path,
        os.environ.get("JAX_COMPILATION_CACHE_DIR"),
        # Source checkout: keep the cache next to the code (gitignored).
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            ".jax_cache",
        ),
        # Installed package (read-only site-packages): user cache dir.
        os.path.join(
            os.path.expanduser("~"), ".cache", "corrosion_tpu", "jax"
        ),
    ]
    for cand in candidates:
        if not cand:
            continue
        try:
            os.makedirs(cand, exist_ok=True)
        except OSError:
            continue
        jax.config.update("jax_compilation_cache_dir", cand)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        return cand
    return None  # no writable location: run uncached
