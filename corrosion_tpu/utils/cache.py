"""Persistent XLA compilation cache for the measurement entry points.

The flagship graphs take minutes to compile cold (the 10k bench ~130 s,
the 100k configs more); the persistent cache cuts repeat invocations —
including the driver's end-of-round bench run — to seconds. Call before
the first jit. Safe to call under pytest/CPU too; entries are keyed by
platform + HLO so devices never collide.
"""

from __future__ import annotations

import os


def enable_persistent_cache(path: str | None = None) -> str | None:
    import jax

    candidates = [
        path,
        os.environ.get("JAX_COMPILATION_CACHE_DIR"),
        # Source checkout: keep the cache next to the code (gitignored).
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            ".jax_cache",
        ),
        # Installed package (read-only site-packages): user cache dir.
        os.path.join(
            os.path.expanduser("~"), ".cache", "corrosion_tpu", "jax"
        ),
    ]
    for cand in candidates:
        if not cand:
            continue
        try:
            os.makedirs(cand, exist_ok=True)
        except OSError:
            continue
        jax.config.update("jax_compilation_cache_dir", cand)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        return cand
    return None  # no writable location: run uncached


def ensure_live_backend(timeout_s: float = 120.0) -> str:
    """Fall back to CPU when the accelerator tunnel is unreachable.

    The axon relay can die out from under the session (observed: the
    terminal-side service at 127.0.0.1:8083 stops listening), and
    ``jax.devices()`` then HANGS instead of raising — wedging any
    measurement script and the driver's bench run with it. Probe backend
    init in a SUBPROCESS (which inherits the same sitecustomize) under a
    timeout, and pin the platform to CPU before this process touches a
    backend when the probe fails. Returns the platform decision.

    Call BEFORE the first jax.devices()/jit in entry-point scripts; a
    healthy tunnel costs one subprocess backend init (~seconds)."""
    import jax

    plats = jax.config.jax_platforms or ""
    if plats and "axon" not in plats:
        return plats
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        jax.config.update("jax_platforms", "cpu")
        print(
            "[cache] accelerator tunnel unreachable - falling back to "
            "CPU for this run",
            file=__import__("sys").stderr,
        )
        return "cpu-fallback"
    return "axon"
