"""Infra utilities: counted task spawning, graceful shutdown, backoff.

Rebuilds the reference's infra crates (crates/spawn, crates/tripwire,
crates/backoff — see SURVEY.md §2) on asyncio.
"""

from .backoff import Backoff
from .spawn import TaskRegistry
from .tripwire import Tripwire

__all__ = ["Backoff", "TaskRegistry", "Tripwire"]
