"""Process log formatting — plaintext or JSON lines.

The reference selects its tracing-subscriber format from config
(LogFormat, corro-types/src/config.rs:318-326; wired in
corrosion/src/main.rs): human-readable plaintext (optionally colored) or
one JSON object per line for log shippers. Same selection here for the
stdlib logging stack the agent uses.
"""

from __future__ import annotations

import json
import logging
import sys
import time

_COLORS = {
    "DEBUG": "\x1b[36m",
    "INFO": "\x1b[32m",
    "WARNING": "\x1b[33m",
    "ERROR": "\x1b[31m",
    "CRITICAL": "\x1b[35m",
}
_RESET = "\x1b[0m"


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts/level/target/msg (+ exception)."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            obj["exception"] = self.formatException(record.exc_info)
        return json.dumps(obj, separators=(",", ":"))


class PlainFormatter(logging.Formatter):
    def __init__(self, colors: bool = False) -> None:
        super().__init__(
            "%(asctime)s %(levelname)s %(name)s: %(message)s",
            "%Y-%m-%dT%H:%M:%S",
        )
        self._colors = colors

    def format(self, record: logging.LogRecord) -> str:
        out = super().format(record)
        if self._colors:
            col = _COLORS.get(record.levelname)
            if col:
                out = col + out + _RESET
        return out


def setup_logging(fmt: str = "plaintext", colors: bool = False,
                  level: int = logging.INFO) -> None:
    """Install the selected formatter on the root logger (idempotent:
    replaces handlers this function installed before)."""
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        if getattr(h, "_corro_log", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    handler._corro_log = True
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        use_colors = colors and sys.stderr.isatty()
        handler.setFormatter(PlainFormatter(colors=use_colors))
    root.addHandler(handler)
