"""Span tracing with cross-node propagation.

The reference instruments everything with `tracing` spans and exports OTLP
(corrosion/src/main.rs:64-117); sync sessions carry W3C traceparent inside
the wire protocol (SyncTraceContextV1, corro-types/src/sync.rs:32-67,
injected peer.rs:941-944, extracted peer.rs:1296-1298). This module is the
in-process analogue: explicit span context managers backed by contextvars,
a bounded in-memory ring of finished spans (plus an optional JSON-lines
file export — there is no egress for a collector), and W3C
traceparent strings for carrying trace context across agents in sync
frames.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "corro_current_span", default=None
)


@dataclass
class Span:
    tracer: "Tracer"
    name: str
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: str | None
    attrs: dict = field(default_factory=dict)
    start_ns: int = 0
    end_ns: int = 0
    _token: object = None

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self.start_ns = time.time_ns()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end_ns = time.time_ns()
        if exc_type is not None:
            self.attrs["error"] = repr(exc)
        _current_span.reset(self._token)
        self.tracer._record(self)
        return False

    @property
    def traceparent(self) -> str:
        """W3C traceparent header value (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_json_obj(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "duration_us": (self.end_ns - self.start_ns) // 1000,
            "attrs": self.attrs,
        }


class Tracer:
    """Per-agent tracer: bounded finished-span ring + optional file export."""

    def __init__(
        self, service: str = "corrosion-tpu", capacity: int = 4096,
        export_path: str | None = None,
    ) -> None:
        self.service = service
        self.finished: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._export_path = export_path
        self._export_f = None

    def span(self, name: str, traceparent: str | None = None, **attrs) -> Span:
        """Open a span. Parentage: explicit ``traceparent`` (remote
        continuation) > ambient current span > fresh trace."""
        parent = _current_span.get()
        if traceparent is not None:
            ctx = parse_traceparent(traceparent)
            trace_id = ctx[0] if ctx else os.urandom(16).hex()
            parent_id = ctx[1] if ctx else None
        elif parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        else:
            trace_id = os.urandom(16).hex()
            parent_id = None
        return Span(
            tracer=self,
            name=name,
            trace_id=trace_id,
            span_id=os.urandom(8).hex(),
            parent_id=parent_id,
            attrs=dict(attrs),
        )

    def current_traceparent(self) -> str | None:
        span = _current_span.get()
        return span.traceparent if span is not None else None

    def _record(self, span: Span) -> None:
        with self._lock:
            self.finished.append(span)
            if self._export_path is not None:
                if self._export_f is None:
                    self._export_f = open(self._export_path, "a")
                self._export_f.write(
                    json.dumps(span.to_json_obj(), default=str) + "\n"
                )
                self._export_f.flush()

    def recent(self, limit: int = 100, name: str | None = None) -> list[dict]:
        with self._lock:
            spans = list(self.finished)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return [s.to_json_obj() for s in spans[-limit:]]

    def close(self) -> None:
        if self._export_f is not None:
            self._export_f.close()
            self._export_f = None


def parse_traceparent(value: str) -> tuple[str, str] | None:
    """(trace_id, span_id) from a W3C traceparent, or None if malformed."""
    parts = value.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id
