"""Span tracing with cross-node propagation.

The reference instruments everything with `tracing` spans and exports OTLP
(corrosion/src/main.rs:64-117); sync sessions carry W3C traceparent inside
the wire protocol (SyncTraceContextV1, corro-types/src/sync.rs:32-67,
injected peer.rs:941-944, extracted peer.rs:1296-1298). This module is the
in-process analogue: explicit span context managers backed by contextvars,
a bounded in-memory ring of finished spans, optional JSON-lines file
export, an optional batched OTLP/JSON exporter POSTing to a collector's
``/v1/traces`` (the `main.rs` OTLP pipeline's role), and W3C traceparent
strings for carrying trace context across agents in sync frames.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "corro_current_span", default=None
)


@dataclass
class Span:
    tracer: "Tracer"
    name: str
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: str | None
    attrs: dict = field(default_factory=dict)
    start_ns: int = 0
    end_ns: int = 0
    _token: object = None

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self.start_ns = time.time_ns()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end_ns = time.time_ns()
        if exc_type is not None:
            self.attrs["error"] = repr(exc)
        _current_span.reset(self._token)
        self.tracer._record(self)
        return False

    def start(self) -> "Span":
        """Begin timing WITHOUT becoming the ambient span. For batched
        span sets whose lifetimes overlap non-LIFO (the ingest loop opens
        one span per changeset in a batch and closes them all after the
        flush) — contextvar tokens must reset LIFO, so the context
        manager cannot model that shape. Pair with :meth:`finish`."""
        self.start_ns = time.time_ns()
        return self

    def finish(self) -> None:
        self.end_ns = time.time_ns()
        self.tracer._record(self)

    @property
    def traceparent(self) -> str:
        """W3C traceparent header value (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_json_obj(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            # Which agent emitted this span — the timeline correlator
            # separates same-trace spans from different cluster members
            # by it (OTLP carries it at the resource level instead).
            "service": self.tracer.service if self.tracer else None,
            "start_ns": self.start_ns,
            "duration_us": (self.end_ns - self.start_ns) // 1000,
            "attrs": self.attrs,
        }


def spans_to_otlp(service: str, spans: list[dict]) -> dict:
    """Batch finished spans into an OTLP/JSON ExportTraceServiceRequest
    (the shape `main.rs:64-117`'s OTLP pipeline emits: resourceSpans →
    scopeSpans → spans with hex ids, unix-nano times, and key-value
    attributes) so any OTLP/HTTP collector ingests the file or POST body
    as-is."""
    return {
        "resourceSpans": [{
            "resource": {
                "attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": service},
                }],
            },
            "scopeSpans": [{
                "scope": {"name": "corrosion-tpu"},
                "spans": [
                    {
                        "traceId": s["trace_id"],
                        "spanId": s["span_id"],
                        **(
                            {"parentSpanId": s["parent_id"]}
                            if s.get("parent_id") else {}
                        ),
                        "name": s["name"],
                        "kind": 1,  # SPAN_KIND_INTERNAL
                        "startTimeUnixNano": str(s["start_ns"]),
                        "endTimeUnixNano": str(
                            s["start_ns"] + s["duration_us"] * 1000
                        ),
                        "attributes": [
                            {"key": k, "value": {"stringValue": str(v)}}
                            for k, v in s.get("attrs", {}).items()
                        ],
                    }
                    for s in spans
                ],
            }],
        }],
    }


def trace_sampled(trace_id: str, rate: float) -> bool:
    """Deterministic trace-id-keyed sampling decision.

    Hash-based (the first 8 hex chars of the trace id against the rate),
    not random-per-call: every hop of a multi-hop write chain — and every
    agent of a cluster — makes the SAME keep/drop decision for a given
    trace without propagating a sampled flag, so a kept trace is kept
    end-to-end and a dropped one costs nothing anywhere. The W3C
    tail-sampling consistency trick; rate 1.0 keeps everything, 0.0
    drops everything.
    """
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) < rate * 0x100000000


class Tracer:
    """Per-agent tracer: bounded finished-span ring + optional export.

    ``export_path`` appends one JSON object per span; with
    ``otlp_endpoint`` set, a single long-lived worker thread batches
    finished spans (256 spans or 5 s idle, whichever first — the
    reference's batch exporter, main.rs:103-109) and POSTs OTLP/JSON to
    ``<endpoint>/v1/traces``; close() drains the queue so shutdown never
    drops buffered spans.

    ``sample`` (0.0–1.0) gates :meth:`maybe_span` by trace id
    (``trace_sampled``): high-rate span sources (the write path under a
    2k-subscription storm) thin deterministically and consistently
    across hops. Explicit :meth:`span` calls always record — sampling is
    opt-in per call site."""

    OTLP_BATCH = 256
    OTLP_FLUSH_S = 5.0

    def __init__(
        self, service: str = "corrosion-tpu", capacity: int = 4096,
        export_path: str | None = None, otlp_endpoint: str | None = None,
        sample: float = 1.0,
    ) -> None:
        import queue

        self.service = service
        self.sample = sample
        self.finished: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._export_path = export_path
        self._export_f = None
        self._otlp_endpoint = otlp_endpoint
        self.otlp_export_errors = 0
        self._otlp_q: "queue.Queue | None" = None
        self._otlp_thread: threading.Thread | None = None
        if otlp_endpoint is not None:
            self._otlp_q = queue.Queue(maxsize=10240)
            self._otlp_thread = threading.Thread(
                target=self._otlp_worker, daemon=True
            )
            self._otlp_thread.start()

    def _otlp_worker(self) -> None:
        import queue

        batch: list[dict] = []
        while True:
            try:
                # Read per-iteration: tests shrink the flush window live.
                item = self._otlp_q.get(timeout=self.OTLP_FLUSH_S or 0.05)
            except queue.Empty:
                if batch:
                    self._otlp_post(batch)
                    batch = []
                continue
            if item is None:  # close sentinel: drain and exit
                if batch:
                    self._otlp_post(batch)
                return
            batch.append(item)
            if len(batch) >= self.OTLP_BATCH:
                self._otlp_post(batch)
                batch = []

    def _otlp_post(self, batch: list[dict]) -> None:
        import urllib.request

        body = json.dumps(
            spans_to_otlp(self.service, batch), default=str
        ).encode()
        req = urllib.request.Request(
            self._otlp_endpoint.rstrip("/") + "/v1/traces",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception:
            # Collectors come and go; drop the batch. Only the worker
            # thread touches this counter.
            self.otlp_export_errors += 1

    def span(self, name: str, traceparent: str | None = None, **attrs) -> Span:
        """Open a span. Parentage: explicit ``traceparent`` (remote
        continuation) > ambient current span > fresh trace."""
        parent = _current_span.get()
        if traceparent is not None:
            ctx = parse_traceparent(traceparent)
            trace_id = ctx[0] if ctx else os.urandom(16).hex()
            parent_id = ctx[1] if ctx else None
        elif parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        else:
            trace_id = os.urandom(16).hex()
            parent_id = None
        return Span(
            tracer=self,
            name=name,
            trace_id=trace_id,
            span_id=os.urandom(8).hex(),
            parent_id=parent_id,
            attrs=dict(attrs),
        )

    def maybe_span(
        self, name: str, traceparent: str | None = None, **attrs
    ) -> Span | None:
        """Sampled :meth:`span`: resolve the trace id exactly as span()
        would (explicit remote parent > ambient parent > fresh trace),
        then return None when the trace is not kept at this tracer's
        ``sample`` rate. Callers guard with ``if span is not None`` —
        an unsampled write allocates no Span at all."""
        parent = _current_span.get()
        if traceparent is not None:
            ctx = parse_traceparent(traceparent)
            trace_id = ctx[0] if ctx else os.urandom(16).hex()
            parent_id = ctx[1] if ctx else None
        elif parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        else:
            trace_id = os.urandom(16).hex()
            parent_id = None
        # Decide on the id the span will actually CARRY (a fresh root's
        # random id included): downstream hops re-check the propagated
        # id, so deciding on any other value would let a kept root's
        # children drop mid-chain.
        if not trace_sampled(trace_id, self.sample):
            return None
        return Span(
            tracer=self,
            name=name,
            trace_id=trace_id,
            span_id=os.urandom(8).hex(),
            parent_id=parent_id,
            attrs=dict(attrs),
        )

    def current_traceparent(self) -> str | None:
        span = _current_span.get()
        return span.traceparent if span is not None else None

    def _record(self, span: Span) -> None:
        obj = span.to_json_obj() if (
            self._export_path is not None or self._otlp_q is not None
        ) else None
        # Open the export file OUTSIDE the lock (first record only): disk
        # I/O under the tracer lock would stall every span-finishing
        # thread behind one slow open (corro lint CT020). Double-checked:
        # a losing racer closes its handle.
        opened = None
        if self._export_path is not None and self._export_f is None:
            opened = open(self._export_path, "a")
        with self._lock:
            self.finished.append(span)
            if opened is not None:
                if self._export_f is None:
                    self._export_f = opened
                else:
                    opened.close()
            if self._export_f is not None:
                self._export_f.write(json.dumps(obj, default=str) + "\n")
                self._export_f.flush()
        if self._otlp_q is not None:
            try:
                self._otlp_q.put_nowait(obj)
            except Exception:
                self.otlp_export_errors += 1  # queue full: shed

    def recent(self, limit: int = 100, name: str | None = None) -> list[dict]:
        with self._lock:
            spans = list(self.finished)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return [s.to_json_obj() for s in spans[-limit:]]

    def close(self) -> None:
        if self._export_f is not None:
            self._export_f.close()
            self._export_f = None
        if self._otlp_q is not None:
            self._otlp_q.put(None)  # drain sentinel
            self._otlp_thread.join(timeout=5.0)
            self._otlp_q = None
            self._otlp_thread = None


def current_span() -> "Span | None":
    """The calling context's ambient span, if any — the guard fan-out
    instrumentation uses to attach only inside an already-traced write
    instead of minting noise root traces."""
    return _current_span.get()


def parse_traceparent(value: str) -> tuple[str, str] | None:
    """(trace_id, span_id) from a W3C traceparent, or None if malformed."""
    parts = value.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id
