"""The three standing serving-plane scenarios (docs/SERVING.md).

- :func:`fanout_storm` (``loadgen run``): thousands of concurrent NDJSON
  subscriptions plus a sustained open-loop write storm through
  /v1/transactions, pooled reads through /v1/queries and the PG wire
  server — with the fan-out oracle asserting exactly-once delivery and
  monotonic change ids on every stream.
- :func:`saturation_sweep` (``loadgen sweep``): ramp the transaction
  arrival rate past ``api_concurrency`` and verify the admission-control
  promise empirically: shed requests 503 fast, admitted p99 stays
  bounded across the ramp, and the client-side shed count matches the
  server's own ``corro_api_shed_total`` accounting.
- :func:`intake_policy` (``loadgen soak``): the docs/SCALING.md
  queue-policy collapse rule, measured: run the kernel plane's gossip
  engine with ``rebroadcast_intake`` above and below the cluster write
  rate and show the undelivered-version backlog (staleness mass) stays
  bounded above the threshold and diverges below it.

Scenarios launch their own in-process agents (agent/testing — real TCP
over loopback, like every cluster test) so `loadgen` is self-contained
on a CI runner; each returns a plain dict that the caller funnels
through :func:`corrosion_tpu.loadgen.report.emit_serving_report`.
"""

from __future__ import annotations

import asyncio
import os
import resource
import tempfile
import time

from corrosion_tpu.agent.testing import launch_test_cluster, stop_cluster
from corrosion_tpu.loadgen.harness import (
    LoadHarness,
    SubscriptionPump,
    stop_pumps,
)
from corrosion_tpu.loadgen.oracle import FanoutOracle
from corrosion_tpu.loadgen.pgread import PgReadClient
from corrosion_tpu.loadgen.report import serving_context
from corrosion_tpu.loadgen.schedule import Arrival, open_loop

# Stream fan-out is FD-bound (one client + one server socket per
# subscription): lift the soft NOFILE limit to the hard one before a big
# storm so "sustains >= 2k concurrent subscriptions" doesn't depend on
# the shell's default ulimit.
def _raise_nofile() -> None:
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < hard:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ValueError, OSError):
        pass


# Cluster launch/teardown now live in agent/testing (shared with the
# fidelity harness and the CLI). Load scenarios skip the membership
# barrier — a 1-agent storm has no peers and the pumps attach anyway.
async def _launch_cluster(data_dir: str, n_agents: int, **cfg):
    return await launch_test_cluster(
        data_dir, n_agents, wait_membership=False, **cfg
    )


_stop_cluster = stop_cluster


def _payload(k: int) -> str:
    return f"loadgen-w{k}"


def _window_sub_sql(fallback_groups: int, g: int) -> str:
    """A deliberately fallback-bound subscription query: the window
    function in the select list defeats PK injection (whole-row identity,
    full-snapshot re-evaluation per batch — the VERDICT r5 #8 cliff), yet
    stays oracle-compatible: ``min(id) OVER (PARTITION BY id)`` is the
    row's own id, so the delivered payload ``(text, id)`` is deterministic
    per key and never changes as other rows arrive."""
    return (
        "SELECT id, text, min(id) OVER (PARTITION BY id) AS w"
        f" FROM tests WHERE id % {fallback_groups} = {g}"
    )


async def fanout_storm(
    data_dir: str,
    *,
    subs: int = 2000,
    writes: int = 80,
    write_rate: float = 10.0,
    read_rate: float = 20.0,
    pg_rate: float = 10.0,
    sub_groups: int = 4,
    n_agents: int = 1,
    drain_timeout_s: float = 30.0,
    attach_batch: int = 64,
    trace_dir: str | None = None,
    trace_sample: float = 1.0,
    sub_costs: bool = False,
    fallback_subs: int = 0,
    fallback_groups: int = 2,
    progress=None,
) -> dict:
    """Scenario (b): the subscription fan-out storm. Returns the ``run``
    report block (routes + oracle verdict + achieved concurrency).

    ``trace_dir`` switches the run into causal-tracing mode: agents
    launch with ``trace_writes`` on and per-agent span export files
    under the directory, every write carries a client-minted W3C
    traceparent, and the report gains a ``trace`` block (span files +
    oracle delivery records) — everything ``obs timeline`` needs to
    reconstruct each acked write's journey (docs/OBSERVABILITY.md
    "Causal tracing").

    ``sub_costs`` arms the serving query-cost plane: agents launch with
    ``AgentConfig.sub_costs`` on, the oracle keeps per-delivery records,
    and the report gains a ``sub_costs`` block (the ``corro-sub-cost/1``
    ledger snapshot + group->sub_id mapping + oracle records) — the
    input of ``obs serving report``. ``fallback_subs`` additionally
    attaches that many deliberately fallback-bound window-function
    subscriptions spread over ``fallback_groups`` distinct queries, so a
    storm exercises the fallback cliff on purpose (the machinery-fired
    rule requires it)."""

    def note(msg):
        if progress is not None:
            progress.write(f"[loadgen run] {msg}\n")
            progress.flush()

    _raise_nofile()
    cluster_kw: dict = {}
    span_files: list[str] = []
    if trace_dir is not None:
        os.makedirs(trace_dir, exist_ok=True)
        span_files = [
            os.path.join(trace_dir, f"spans-agent{i}.jsonl")
            for i in range(n_agents)
        ]
        cluster_kw = dict(
            trace_writes=True,
            trace_sample=trace_sample,
            cfg_for=lambda i: {"trace_export_path": span_files[i]},
        )
    if sub_costs:
        cluster_kw["sub_costs"] = True
    agents = await _launch_cluster(data_dir, n_agents, **cluster_kw)
    harness = LoadHarness()
    oracle = FanoutOracle(
        registry=harness.registry,
        keep_deliveries=trace_dir is not None or sub_costs,
    )
    pumps: list[SubscriptionPump] = []
    pg_server = pg_client = None
    try:
        pg_server, (pg_host, pg_port) = await _serve_pg(agents[0])
        # Subscriptions spread over `sub_groups` DISTINCT queries (each
        # group is its own matcher — fan-out cost AND match cost scale)
        # on the first agent; writes round-robin the cluster.
        note(f"attaching {subs} subscriptions in {sub_groups} groups")
        for base in range(0, subs, attach_batch):
            batch = []
            for i in range(base, min(base + attach_batch, subs)):
                g = i % sub_groups
                pump = SubscriptionPump(
                    agents[0].client,
                    f"SELECT id, text FROM tests WHERE id % {sub_groups} "
                    f"= {g}",
                    oracle, group=g, label=f"sub{i}",
                )
                pumps.append(pump)
                batch.append(pump.start())
            await asyncio.gather(*batch)
        if fallback_subs:
            # Fallback-bound window streams ride their own oracle groups
            # (sub_groups + wg): each write registers a second commit with
            # the window payload, so exactly-once/no-loss obligations hold
            # for the cliff population too.
            note(
                f"attaching {fallback_subs} fallback-bound window subs "
                f"in {fallback_groups} groups"
            )
            for base in range(0, fallback_subs, attach_batch):
                batch = []
                for j in range(
                    base, min(base + attach_batch, fallback_subs)
                ):
                    wg = j % fallback_groups
                    pump = SubscriptionPump(
                        agents[0].client,
                        _window_sub_sql(fallback_groups, wg),
                        oracle, group=sub_groups + wg, label=f"wsub{j}",
                    )
                    pumps.append(pump)
                    batch.append(pump.start())
                await asyncio.gather(*batch)
        note("subscriptions live; starting storm")

        loop = asyncio.get_running_loop()
        next_key = iter(range(10**9))

        async def fire_write(a: Arrival):
            k = next(next_key)
            payload = _payload(k)
            ta = agents[k % len(agents)]

            async def go():
                tp = trace_id = t_send = t_send_mono = None
                if trace_dir is not None:
                    # The CLIENT mints the trace id (Dapper-style): the
                    # agent's api_write root continues it, so spans,
                    # this commit record, and the stream deliveries for
                    # key k all join on one id. Send time is stamped on
                    # BOTH clocks: epoch (joins the span domain) and
                    # monotonic (the independent wall the correlator
                    # reconciles the epoch-derived stage sum against).
                    trace_id = os.urandom(16).hex()
                    tp = f"00-{trace_id}-{os.urandom(8).hex()}-01"
                    t_send = time.time()
                    t_send_mono = loop.time()
                await ta.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [k, payload]]],
                    traceparent=tp,
                )
                t_ack = loop.time()
                oracle.commit(
                    k, (payload,), t_ack, group=k % sub_groups,
                    trace_id=trace_id, t_send_wall=t_send,
                    t_ack_wall=(
                        time.time() if trace_dir is not None else None
                    ),
                    t_send_mono=t_send_mono,
                )
                if fallback_subs:
                    # The same row reaches the window streams with the
                    # window column appended: a distinct (key, payload)
                    # commit on the window group, same ack time.
                    oracle.commit(
                        k, (payload, k), t_ack,
                        group=sub_groups + (k % fallback_groups),
                    )

            # Deadline scales with fan-out: every commit costs the
            # server O(subs) queue pushes + socket writes, and the
            # loadgen process itself drains every one of those lines —
            # at 2k streams a fixed 15 s ceiling measures the harness,
            # not the server.
            await harness.timed(
                "transactions", a, go,
                deadline_s=15.0 + (subs + fallback_subs) / 100.0,
            )

        async def fire_read(a: Arrival):
            ta = agents[a.stage % len(agents)]
            await harness.timed(
                "queries", a,
                lambda: ta.client.query("SELECT count(*) FROM tests"),
            )

        pg_client = await PgReadClient.connect(pg_host, pg_port)
        pg_lock = asyncio.Lock()

        async def fire_pg(a: Arrival):
            async def go():
                # One PG connection, serialized queries (the pooled-read
                # realistic shape; rate is modest by design).
                async with pg_lock:
                    return await pg_client.query(
                        "SELECT count(*) FROM tests"
                    )

            await harness.timed("pg", a, go)

        duration = writes / write_rate
        await asyncio.gather(
            harness.run_arrivals(
                open_loop(write_rate, writes), fire_write
            ),
            harness.run_arrivals(
                open_loop(read_rate, max(1, int(read_rate * duration))),
                fire_read,
            ),
            harness.run_arrivals(
                open_loop(pg_rate, max(1, int(pg_rate * duration))),
                fire_pg,
            ),
        )
        note("storm done; draining fan-out")
        deadline = loop.time() + drain_timeout_s
        while oracle.pending(limit=1) and loop.time() < deadline:
            await asyncio.sleep(0.1)
        note(f"drained (pending={oracle.pending(limit=100)})")
        for base in range(0, len(pumps), 256):
            await asyncio.gather(
                *(p.stop() for p in pumps[base:base + 256])
            )
        verdict = oracle.finish()
        out = {
            "subs": subs,
            "sub_groups": sub_groups,
            "fallback_subs": fallback_subs,
            "fallback_groups": fallback_groups if fallback_subs else 0,
            "agents": n_agents,
            "writes": writes,
            "write_rate_hz": write_rate,
            "routes": {
                r: harness.route_report(r)
                for r in ("transactions", "queries", "pg")
            },
            "oracle": verdict,
        }
        if trace_dir is not None:
            out["trace"] = {
                "span_files": span_files,
                "sample": trace_sample,
                "oracle_records": oracle.delivery_records(),
            }
        if sub_costs:
            # Query-cost plane export: the live ledger snapshot, the
            # oracle group -> matcher sub_id mapping (each group is one
            # distinct query, hence one MatcherHandle), and the oracle's
            # delivery records — everything `obs serving report` joins.
            mgr = agents[0].agent.subs
            groups_map: dict[str, str] = {}
            for g in range(sub_groups):
                groups_map[str(g)] = mgr.subscribe(
                    f"SELECT id, text FROM tests WHERE id % {sub_groups} "
                    f"= {g}"
                ).id
            for wg in range(fallback_groups if fallback_subs else 0):
                groups_map[str(sub_groups + wg)] = mgr.subscribe(
                    _window_sub_sql(fallback_groups, wg)
                ).id
            out["sub_costs"] = {
                "enabled": True,
                "ledger": mgr.cost_snapshot(),
                "groups": groups_map,
                "oracle_records": (
                    out["trace"]["oracle_records"]
                    if trace_dir is not None
                    else oracle.delivery_records()
                ),
            }
        return out
    finally:
        # Everything the scenario opened closes here, success or not —
        # a failing assertion mid-storm must not leak the PG server,
        # its connection, or auto-reconnecting pump tasks onto the
        # caller's event loop. _stopping is flipped BEFORE the streams
        # close so a pump whose `async for` breaks exits instead of
        # spending reconnect retries against the stopping cluster.
        if pg_client is not None:
            pg_client.close()
        if pg_server is not None:
            pg_server.close()
        await stop_pumps(pumps)
        await _stop_cluster(agents)


async def _serve_pg(ta):
    from corrosion_tpu.agent.pg import serve_pg

    return await serve_pg(ta.agent)


async def saturation_sweep(
    data_dir: str,
    *,
    api_concurrency: int = 4,
    rates: tuple = (50.0, 200.0, 400.0),
    stage_duration_s: float = 2.0,
    burst: int = 16,
    bounded_p99_ms: float = 5000.0,
    progress=None,
) -> dict:
    """Scenario (a): ramp transaction arrivals past ``api_concurrency``.

    The agent runs with a deliberately small admission limit so the CI
    smoke saturates at loopback-feasible rates; ``burst`` packs arrivals
    so that more than ``api_concurrency`` requests are concurrently
    in-flight at the top stages regardless of service-time jitter.
    Verifies, per stage: shed requests fail fast (their latency rides
    the same histogram), admitted p99 stays under ``bounded_p99_ms``,
    and the client-observed shed count equals the server's
    ``corro_api_shed_total{route=/v1/transactions}``.
    """

    def note(msg):
        if progress is not None:
            progress.write(f"[loadgen sweep] {msg}\n")
            progress.flush()

    _raise_nofile()
    agents = await _launch_cluster(
        data_dir, 1, api_concurrency=api_concurrency
    )
    ta = agents[0]
    harness = LoadHarness()
    try:
        next_key = iter(range(10**9))

        async def fire(a: Arrival):
            k = next(next_key)
            await harness.timed(
                "transactions", a,
                lambda: ta.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [k, _payload(k)]]]
                ),
                deadline_s=10.0,
            )

        # Burst only on the TOP stage: below capacity arrivals fire on
        # the plain grid (they should admit cleanly); the final stage
        # packs `burst` > api_concurrency arrivals per instant so shed
        # engagement is guaranteed by concurrency, not service-time
        # jitter. (`ramp` keeps a uniform burst; the sweep builds its
        # stages directly for the per-stage shape.)
        arrivals = []
        t = 0.0
        for idx, r in enumerate(rates):
            b = burst if idx == len(rates) - 1 else 1
            n = max(1, round(r * stage_duration_s))
            arrivals.extend(
                open_loop(r, n, burst=b, start=t, stage=idx)
            )
            t += stage_duration_s
        note(
            f"ramp {list(rates)} Hz x {stage_duration_s}s, top burst="
            f"{burst}, api_concurrency={api_concurrency}"
        )
        await harness.run_arrivals(arrivals, fire)

        stages = []
        shed_total = 0
        admitted_p99_max = 0.0
        for idx, rate in enumerate(rates):
            rep = harness.route_report("transactions", stage=idx)
            rep["offered_rate_hz"] = rate
            stages.append(rep)
            shed_total += rep["shed"]
            p99 = rep.get("latency_ms", {}).get("p99")
            if p99 is not None:
                admitted_p99_max = max(admitted_p99_max, p99)
        server_shed = ta.agent.metrics.counter(
            "corro_api_shed_total"
        ).get(route="/v1/transactions")
        shed_engaged = shed_total > 0
        note(
            f"shed client={shed_total} server={server_shed:g} "
            f"admitted_p99_max={admitted_p99_max}ms"
        )
        return {
            "api_concurrency": api_concurrency,
            "burst": burst,
            "stages": stages,
            "shed_total": shed_total,
            "server_shed_total": server_shed,
            "shed_accounting_consistent": server_shed == shed_total,
            "shed_engaged": shed_engaged,
            "admitted_p99_ms_max": admitted_p99_max,
            "admitted_p99_bounded": admitted_p99_max <= bounded_p99_ms,
            "bounded_p99_ms": bounded_p99_ms,
        }
    finally:
        await _stop_cluster(agents)


def intake_policy(
    *,
    nodes: int = 96,
    rounds: int = 96,
    write_prob: float = 0.08,
    intake_margin: int = 8,
    starved_intake: int = 1,
    seed: int = 0,
    progress=None,
    series_path: str | None = None,
) -> dict:
    """Scenario (c): the docs/SCALING.md collapse rule, measured.

    Runs the dense gossip engine twice on an identical sustained write
    schedule with the anti-entropy plane effectively disabled
    (``sync_interval`` past the run length) so broadcast intake is the
    ONLY delivery path — the isolation the 20k-node policy sweep used:
    once with ``rebroadcast_intake = write_rate + margin`` (the
    documented sizing rule) and once starved far below the write rate.
    The undelivered-version backlog (staleness mass, Σ per-node
    watermark gap) must stay bounded (tail slope ~flat, saw-tooth steady
    state) in the sized run and diverge (persistent positive slope,
    multi-x higher backlog) in the starved run.
    """
    import numpy as np

    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.obs.series import (
        MetricSeriesRecorder,
        record_process_sample,
        replay_series,
        series_values,
    )
    from corrosion_tpu.sim import simulate
    from corrosion_tpu.sim.engine import Schedule
    from corrosion_tpu.utils.metrics import MetricsRegistry

    def note(msg):
        if progress is not None:
            progress.write(f"[loadgen soak] {msg}\n")
            progress.flush()

    # Process self-observability rides the ONE sampling path every
    # endurance surface shares (obs/series.record_process_sample):
    # gauges set from live /proc reads, then a whole-registry snapshot
    # per section boundary. ``series_path`` keeps the
    # corro-metric-series/1 record as an artifact (`loadgen soak
    # --series-out`); by default it lands in a scratch dir.
    registry = MetricsRegistry()
    _scratch = None
    if series_path is None:
        _scratch = tempfile.TemporaryDirectory()
        series_path = os.path.join(_scratch.name, "soak.series.jsonl")
    recorder = MetricSeriesRecorder(
        series_path, source="loadgen-soak", mode="w"
    )
    t_start = time.monotonic()
    record_process_sample(recorder, registry, lag_s=0.0)

    # Sustained storm: no drain tail — the collapse rule is about steady
    # state under load, and a drain would let even a starved intake
    # eventually catch up.
    rng = np.random.default_rng(seed)
    writes = (rng.random((rounds, nodes)) < write_prob).astype(np.uint32)
    write_rate = float(writes.sum()) / rounds

    def run_with_intake(intake: int) -> dict:
        cfg, topo = _cfg(
            nodes, writers=list(range(nodes)),
            regions=[nodes // 4] * 4,
            # Broadcast-only: a sync wave would periodically rescue the
            # starved run and blur the intake signal.
            sync_interval=10 * rounds,
            fanout_near=3, fanout_far=3, queue=24,
            rebroadcast_intake=intake, n_cells=0,
        )
        sched = Schedule(writes=writes).make_samples(32)
        note(f"intake={intake} (write rate {write_rate:.1f}/round)")
        _, curves = simulate(cfg, topo, sched, seed=seed)
        stale = np.asarray(curves["staleness_sum"], np.float64)
        # Tail slope: least-squares over the last half of the run (wide
        # enough to smooth the bounded regime's saw-tooth) — bounded
        # means the backlog stopped growing, divergent means it still
        # climbs at end of run.
        tail = stale[-(rounds // 2):]
        x = np.arange(len(tail), dtype=np.float64)
        slope = float(np.polyfit(x, tail, 1)[0]) if len(tail) > 1 else 0.0
        return {
            "intake": intake,
            "staleness_last": float(stale[-1]),
            "staleness_peak": float(stale.max()),
            "tail_slope_per_round": round(slope, 3),
            "backlog_curve": [
                float(v) for v in stale[:: max(1, rounds // 36)]
            ],
        }

    sized = run_with_intake(int(round(write_rate)) + intake_margin)
    record_process_sample(
        recorder, registry, lag_s=time.monotonic() - t_start
    )
    starved = run_with_intake(starved_intake)
    # Bounded vs divergent, empirically: the sized run's end-of-run
    # backlog holds at a few rounds' worth of cluster write mass
    # (write_rate versions/round x nodes watermark-gap each — the
    # steady-state saw-tooth), while the starved run still climbs at end
    # of run (tail slope above the write rate) and sits multi-x above
    # the sized backlog.
    bounded_ceiling = 5.0 * write_rate * nodes
    divergence_ratio = (
        starved["staleness_last"] / max(sized["staleness_last"], 1.0)
    )
    record_process_sample(
        recorder, registry, lag_s=time.monotonic() - t_start
    )
    recorder.close()
    proc_samples = replay_series(series_path)["samples"]
    if _scratch is not None:
        _scratch.cleanup()
        series_path = None

    def _endpoints(name: str) -> tuple[float | None, float | None]:
        _, vals = series_values(proc_samples, name, family="gauges")
        return (vals[0], vals[-1]) if vals else (None, None)

    rss0, rss1 = _endpoints("corro_runtime_rss_bytes")
    fds0, fds1 = _endpoints("corro_runtime_open_fds")
    return {
        "kernel_nodes": nodes,
        "rounds": rounds,
        "write_rate_per_round": round(write_rate, 2),
        # Process self-observability (the satellite the hours-long
        # ROADMAP-5 soaks need): RSS/fd growth across the run, plus how
        # long the synchronous kernel sections held the event loop —
        # the soak's own loop-lag figure (the whole section IS lag when
        # run from an async caller). Start/end are the first/last
        # samples of the corro-metric-series/1 record above — the same
        # recorder+gauges path the agent runtime loop and the endurance
        # detectors consume, not a parallel ad-hoc probe.
        "process": {
            "start": {"rss_bytes": rss0, "open_fds": fds0},
            "end": {"rss_bytes": rss1, "open_fds": fds1},
            "rss_growth_bytes": (
                rss1 - rss0
                if rss1 is not None and rss0 is not None else None
            ),
            "loop_held_s": round(time.monotonic() - t_start, 3),
            "samples": len(proc_samples),
            "series_path": series_path,
        },
        "sized": sized,
        "starved": starved,
        "bounded_ceiling": bounded_ceiling,
        "divergence_ratio": round(divergence_ratio, 2),
        "collapse_rule_holds": (
            sized["staleness_last"] < bounded_ceiling
            and starved["tail_slope_per_round"] > write_rate
            and divergence_ratio > 3.0
        ),
    }


async def full_report(
    data_dir: str,
    *,
    subs: int = 200,
    writes: int = 120,
    write_rate: float = 40.0,
    scenario: str = "ci_smoke",
    include_soak: bool = False,
    progress=None,
    **sweep_kw,
) -> dict:
    """run + sweep (+ optionally soak) into one self-describing report —
    the loadgen-smoke CI entrypoint's measurement."""
    run = await fanout_storm(
        os.path.join(data_dir, "run"),
        subs=subs, writes=writes, write_rate=write_rate,
        progress=progress,
    )
    sweep = await saturation_sweep(
        os.path.join(data_dir, "sweep"), progress=progress, **sweep_kw
    )
    report = {
        **serving_context(scenario, 1, subs, writes, write_rate),
        "subs": subs,
        "run": run,
        "sweep": sweep,
    }
    if include_soak:
        report["soak"] = intake_policy(progress=progress)
    return report
