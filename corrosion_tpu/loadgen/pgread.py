"""Minimal asyncio PG-wire simple-query client.

Just enough protocol v3 for the load generator to put the PG server
(agent/pg.py) under the same open-loop read load as the HTTP routes:
startup + simple query ('Q') + DataRow counting. One connection per
client, reused across queries — the PG path is the pooled-read surface,
so connection reuse (not per-request connects) is the realistic shape.
"""

from __future__ import annotations

import asyncio
import struct


class PgQueryError(Exception):
    pass


class PgReadClient:
    def __init__(self, reader, writer) -> None:
        self.reader = reader
        self.writer = writer

    @classmethod
    async def connect(
        cls, host: str, port: int, user: str = "loadgen",
        database: str = "main",
    ) -> "PgReadClient":
        reader, writer = await asyncio.open_connection(host, port)
        params = (
            b"user\x00" + user.encode() + b"\x00"
            b"database\x00" + database.encode() + b"\x00\x00"
        )
        payload = struct.pack(">I", 196608) + params
        writer.write(struct.pack(">I", len(payload) + 4) + payload)
        await writer.drain()
        self = cls(reader, writer)
        msgs = await self._read_until(b"Z")
        if not any(t == b"R" for t, _ in msgs):
            raise PgQueryError("no AuthenticationOk in startup response")
        return self

    async def _read_msg(self):
        header = await self.reader.readexactly(5)
        (length,) = struct.unpack(">I", header[1:5])
        return header[0:1], await self.reader.readexactly(length - 4)

    async def _read_until(self, end_tag: bytes):
        out = []
        while True:
            tag, payload = await self._read_msg()
            out.append((tag, payload))
            if tag == end_tag:
                return out

    async def query(self, sql: str) -> int:
        """Simple-query flow; returns the DataRow count. An ErrorResponse
        raises (the flow still drains to ReadyForQuery first, so the
        connection stays usable)."""
        body = sql.encode() + b"\x00"
        self.writer.write(b"Q" + struct.pack(">I", len(body) + 4) + body)
        await self.writer.drain()
        msgs = await self._read_until(b"Z")
        errs = [p for t, p in msgs if t == b"E"]
        if errs:
            raise PgQueryError(errs[0].decode("utf-8", "replace"))
        return sum(1 for t, _ in msgs if t == b"D")

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass
