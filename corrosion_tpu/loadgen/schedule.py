"""Open-loop arrival schedules.

A closed-loop generator (request, wait, request) measures the server's
latency only while the server is keeping up: once it saturates, the
generator itself slows down and the recorded tail silently excludes
exactly the requests that would have queued — coordinated omission. An
open-loop schedule fixes every arrival time up front; the runner fires
each request at its scheduled instant whether or not earlier ones have
completed, and latency is measured from the *scheduled* arrival. A
saturated server then shows up as it should: as latency, shed, or
timeout — never as a quietly thinner sample.

Schedules here are plain lists of :class:`Arrival` (seconds from run
start + ramp stage index), built deterministically so two runs of the
same scenario fire the identical workload.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: offset from run start + ramp stage index."""

    t: float
    stage: int = 0


def open_loop(
    rate_hz: float,
    count: int,
    *,
    burst: int = 1,
    start: float = 0.0,
    stage: int = 0,
) -> list[Arrival]:
    """``count`` arrivals at ``rate_hz`` on a fixed grid from ``start``.

    ``burst`` groups arrivals: ``burst`` requests share one instant and
    instants are spaced ``burst / rate_hz`` apart, so the long-run rate
    is unchanged but at least ``burst`` requests are concurrently
    in-flight at each instant. The saturation sweep uses this to make
    load-shed engagement deterministic: a burst wider than the route's
    admission limit *must* shed, independent of service-time jitter.
    """
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be positive, got {rate_hz}")
    if burst < 1:
        raise ValueError(f"burst must be >= 1, got {burst}")
    out: list[Arrival] = []
    for i in range(count):
        slot = i // burst
        out.append(Arrival(t=start + slot * burst / rate_hz, stage=stage))
    return out


def ramp(
    stages: list[tuple[float, float]], *, burst: int = 1
) -> list[Arrival]:
    """Concatenated open-loop stages: ``[(rate_hz, duration_s), ...]``.

    Each stage contributes ``round(rate * duration)`` arrivals tagged
    with its index; the saturation sweep ramps the rate past the route's
    capacity and reads per-stage shed/latency from the tags.
    """
    out: list[Arrival] = []
    t = 0.0
    for idx, (rate, duration) in enumerate(stages):
        n = max(1, round(rate * duration))
        out.extend(open_loop(rate, n, burst=burst, start=t, stage=idx))
        t += duration
    return out
