"""Fan-out correctness oracle.

The subscription plane's contract (agent/subs.py, mirroring the
reference's Matcher): every committed transaction that matches a live
subscription's query is delivered to every attached stream **exactly
once**, with **monotonically increasing change ids** per stream, either
as a live change event or — for commits that raced the stream's attach —
inside the initial snapshot. The oracle checks that contract while the
load generator is deliberately trying to break it, so a loadgen run is a
robustness test, not just a benchmark.

Commits are registered by the write path as ``(key, payload)`` pairs
(each generated write uses a fresh primary key and a unique payload, so
identity is unambiguous); streams report snapshot rows and change events
as they arrive. A commit acked *after* a stream finished its snapshot
(the end-of-query frame) MUST eventually reach that stream; commits that
raced the attach may arrive via snapshot instead. Violations recorded:

- ``duplicate``: a stream saw the same committed row as a change event
  twice (replay overlap after reconnect, listener-queue double-publish);
- ``non_monotonic``: a change id on a stream failed to strictly
  increase;
- ``missing`` (at :meth:`finish`): an expected delivery never arrived
  within the drain window — a silently dropped event.

Delivery lag (commit-ack to event-receipt) feeds a shared
``utils.metrics.Histogram`` so fan-out percentiles ride the same bucket
machinery as every other latency surface.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from corrosion_tpu.utils.metrics import Histogram

# Fan-out lag buckets: 1 ms .. 30 s (finer low end than the default
# request buckets — loopback fan-out sits in single-digit ms).
LAG_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


@dataclass
class _Commit:
    key: object
    payload: object
    t_ack: float
    group: int | None = None
    # Causal-trace join keys (traced runs only): the client-minted trace
    # id this write's HTTP request carried, and wall-clock send/ack
    # times in the same epoch domain as the agent's span export — the
    # obs timeline correlator joins spans <-> commits <-> deliveries on
    # (trace_id, key). t_send_mono is the monotonic-clock send time
    # (same clock as t_ack): the correlator's reconciliation measures
    # the wall on the monotonic domain so the epoch-derived stage sum
    # has an INDEPENDENT measurement to answer to.
    trace_id: str | None = None
    t_send_wall: float | None = None
    t_ack_wall: float | None = None
    t_send_mono: float | None = None


@dataclass
class _Stream:
    sid: int
    group: int | None = None
    label: str = ""
    attached_t: float | None = None  # end-of-snapshot time; None = pending
    last_change_id: int | None = None
    seen_change: dict = field(default_factory=dict)  # (key, payload) -> cid
    seen_snapshot: set = field(default_factory=set)
    reconnects: int = 0


class FanoutOracle:
    """Tracks commits vs per-stream deliveries; see module docstring."""

    def __init__(self, registry=None, keep_deliveries: bool = False) -> None:
        """``keep_deliveries`` retains one record per observed delivery
        (stream, key, change id, wall time) for the obs timeline
        correlator — off by default, a 2k-stream storm's 40k deliveries
        should not be held unless a traced run asked for them."""
        self._commits: dict[tuple, _Commit] = {}
        self._streams: dict[int, _Stream] = {}
        self.keep_deliveries = keep_deliveries
        self.delivery_log: list[dict] = []
        # Deliveries observed BEFORE their commit registered: fan-out
        # regularly beats the writer's own HTTP ack (the matcher pushes
        # to listener queues before the execute response is written), so
        # lag resolves when commit() arrives — clamped at 0.
        self._early_deliveries: dict[tuple, list[float]] = {}
        self._next_sid = 0
        self.violations: list[str] = []
        self.lag_hist = (
            registry.histogram(
                "loadgen_fanout_lag_seconds",
                "commit-ack to subscription-event delivery lag",
                buckets=LAG_BUCKETS,
            )
            if registry is not None
            else Histogram(
                "loadgen_fanout_lag_seconds",
                "commit-ack to subscription-event delivery lag",
                buckets=LAG_BUCKETS,
            )
        )
        self.lag_max_s = 0.0
        self.delivered_changes = 0
        self.delivered_snapshot = 0

    # -- write side ----------------------------------------------------------

    def commit(
        self, key, payload, t_ack: float, group: int | None = None,
        trace_id: str | None = None, t_send_wall: float | None = None,
        t_ack_wall: float | None = None, t_send_mono: float | None = None,
    ) -> None:
        """Register an acked transaction. ``group`` partitions commits
        onto the subscription group whose query matches them (None =
        matches every stream). Traced runs also pass the write's
        ``trace_id`` and wall/monotonic send/ack times (see _Commit)."""
        k = (key, payload)
        if k in self._commits:
            raise ValueError(f"commit {k} registered twice by the harness")
        self._commits[k] = _Commit(
            key, payload, t_ack, group,
            trace_id=trace_id, t_send_wall=t_send_wall,
            t_ack_wall=t_ack_wall, t_send_mono=t_send_mono,
        )
        for t in self._early_deliveries.pop(k, ()):
            lag = max(0.0, t - t_ack)
            self.lag_hist.observe(lag)
            self.lag_max_s = max(self.lag_max_s, lag)

    def committed(self) -> dict:
        """Acked commits as ``{key: payload}`` — the ground-truth row
        set a converged cluster must contain (the host chaos harness's
        serial-merge analogue)."""
        return {c.key: c.payload for c in self._commits.values()}

    # -- subscription side ---------------------------------------------------

    def attach_stream(
        self, group: int | None = None, label: str = ""
    ) -> int:
        """Register a stream; returns its oracle id. The stream stays in
        "attaching" state (no delivery obligations yet) until
        :meth:`snapshot_done`."""
        sid = self._next_sid
        self._next_sid += 1
        self._streams[sid] = _Stream(sid=sid, group=group, label=label)
        return sid

    def snapshot_done(self, sid: int, t: float) -> None:
        """The stream received its end-of-query frame: from here on,
        every commit acked at or after ``t`` is an obligation."""
        st = self._streams[sid]
        if st.attached_t is None:
            st.attached_t = t

    def snapshot_row(
        self, sid: int, key, payload, t_wall: float | None = None
    ) -> None:
        """A row in the initial snapshot (or a snapshot-restart replay
        after deep reconnect). Set semantics: snapshot re-sends of the
        same row are not duplicates."""
        self._streams[sid].seen_snapshot.add((key, payload))
        self.delivered_snapshot += 1
        if self.keep_deliveries and t_wall is not None:
            self.delivery_log.append({
                "kind": "snapshot", "sid": sid, "key": key,
                "t_wall": t_wall,
            })

    def change(
        self, sid: int, kind: str, key, payload, change_id: int, t: float,
        t_wall: float | None = None,
    ) -> None:
        """A live change event on a stream."""
        st = self._streams[sid]
        if self.keep_deliveries and t_wall is not None:
            self.delivery_log.append({
                "kind": "change", "sid": sid, "key": key,
                "change_id": change_id, "t_wall": t_wall, "t_mono": t,
            })
        if st.last_change_id is not None and change_id <= st.last_change_id:
            self.violations.append(
                f"non_monotonic: stream {sid}{st.label and f' ({st.label})'} "
                f"change_id {change_id} after {st.last_change_id}"
            )
        st.last_change_id = change_id
        k = (key, payload)
        if k in st.seen_change:
            self.violations.append(
                f"duplicate: stream {sid}{st.label and f' ({st.label})'} "
                f"saw {k} as change twice (cid {st.seen_change[k]} then "
                f"{change_id})"
            )
            return
        st.seen_change[k] = change_id
        self.delivered_changes += 1
        c = self._commits.get(k)
        if c is not None:
            lag = max(0.0, t - c.t_ack)
            self.lag_hist.observe(lag)
            self.lag_max_s = max(self.lag_max_s, lag)
        else:
            self._early_deliveries.setdefault(k, []).append(t)

    def reconnected(self, sid: int) -> None:
        self._streams[sid].reconnects += 1

    # -- correlator export ---------------------------------------------------

    def delivery_records(self) -> dict:
        """The obs timeline correlator's input: every registered commit
        (with its trace id + wall send/ack times when the run was
        traced) and — with ``keep_deliveries`` — every observed delivery
        wall-timestamped. Keys must be JSON-scalar for the artifact (the
        loadgen scenarios use integer row ids)."""
        return {
            "writes": [
                {
                    "key": c.key,
                    "group": c.group,
                    "trace_id": c.trace_id,
                    "t_send_wall": c.t_send_wall,
                    "t_ack_wall": c.t_ack_wall,
                    "t_send_mono": c.t_send_mono,
                    # Monotonic ack time is exported unconditionally: the
                    # serving-cost join measures per-delivery lag against
                    # it even on untraced runs (the correlator still
                    # guards on t_send_mono for its reconciliation).
                    "t_ack_mono": c.t_ack,
                }
                for c in self._commits.values()
            ],
            "deliveries": list(self.delivery_log),
            # Per-stream identity + delivered mass: the serving-cost
            # report reconciles each subscription handle's ledger against
            # exactly these counts.
            "streams": [
                {
                    "sid": st.sid,
                    "group": st.group,
                    "label": st.label,
                    "delivered_changes": len(st.seen_change),
                    "delivered_snapshot": len(st.seen_snapshot),
                    "reconnects": st.reconnects,
                }
                for st in self._streams.values()
            ],
        }

    # -- verdict -------------------------------------------------------------

    def _expected(self, st: _Stream):
        """Commits this stream is obliged to deliver: matching group,
        acked after the stream's snapshot completed."""
        if st.attached_t is None:
            return
        for k, c in self._commits.items():
            if c.group is not None and st.group is not None \
                    and c.group != st.group:
                continue
            if c.t_ack >= st.attached_t:
                yield k

    def pending(self, limit: int | None = None) -> int:
        """Outstanding (stream, commit) obligations — the drain loop
        polls this to zero before declaring a scenario done. ``limit``
        short-circuits the count (the drain loop only needs "any?", and
        a 2k-stream storm makes the full scan non-trivial)."""
        n = 0
        for st in self._streams.values():
            for k in self._expected(st):
                if k not in st.seen_change and k not in st.seen_snapshot:
                    n += 1
                    if limit is not None and n >= limit:
                        return n
        return n

    def finish(self, max_examples: int = 8) -> dict:
        """Final verdict. Converts any still-missing obligation into a
        ``missing`` violation and returns the oracle block of the
        serving report."""
        missing = 0
        for st in self._streams.values():
            for k in self._expected(st):
                if k not in st.seen_change and k not in st.seen_snapshot:
                    missing += 1
                    if missing <= max_examples:
                        self.violations.append(
                            f"missing: stream {st.sid}"
                            f"{st.label and f' ({st.label})'} never saw {k}"
                        )
        if missing > max_examples:
            self.violations.append(
                f"missing: ... and {missing - max_examples} more"
            )
        lag_count = self.lag_hist.count()

        def q_ms(q: float) -> float:
            # Observations past the last bucket interpolate to +inf;
            # clamp to the exactly-tracked max so the report stays
            # strict-JSON and never overstates beyond what was measured.
            return round(
                min(self.lag_hist.quantile(q), self.lag_max_s) * 1000.0, 3
            )

        return {
            "streams": len(self._streams),
            "commits": len(self._commits),
            "delivered_changes": self.delivered_changes,
            "delivered_snapshot": self.delivered_snapshot,
            "reconnects": sum(
                s.reconnects for s in self._streams.values()
            ),
            "violations": len(self.violations),
            "violation_examples": self.violations[:max_examples],
            "missing": missing,
            "fanout_lag_ms": {
                "count": lag_count,
                "p50": q_ms(0.50) if lag_count else None,
                "p90": q_ms(0.90) if lag_count else None,
                "p99": q_ms(0.99) if lag_count else None,
                "max": round(self.lag_max_s * 1000.0, 3),
            },
        }
