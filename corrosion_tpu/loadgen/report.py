"""Serving-plane report emit path + the ``serving`` budget gate.

Every loadgen report funnels through the ONE self-describing emit path
(``telemetry.check_bench_invariants``, the PR 6 rule): platform, nodes,
device_count, config fingerprint — plus ``scenario`` for this report
class — are asserted at the emit site, so a load report can no more be
published without provenance than a kernel bench can.

``check_serving_budget`` mirrors ``benchlib.check_budget``'s shape for
the serving surface: dimension mismatches (platform / scenario /
subscription count) are breaches so a shrunk smoke config can't silently
loosen the gate, latency ceilings get the budget's tolerance multiplier,
and two keys are absolute: ``oracle_violations`` must be 0 (correctness
is never a tolerance question) and the sweep's ``shed_engaged`` must be
True (a sweep that never tripped admission control did not test it).
"""

from __future__ import annotations

from corrosion_tpu.sim import benchlib, telemetry

# Dimensions that must match the budget exactly (cf. benchlib gate dims).
SERVING_DIMS = ("platform", "scenario", "subs")


def emit_serving_report(report: dict) -> dict:
    """The serving plane's emit site: assert self-description (base
    provenance + ``scenario``) and return the report unchanged."""
    return telemetry.check_bench_invariants(
        report, extra_provenance=("scenario",)
    )


def serving_context(scenario: str, nodes: int, *fingerprint_parts) -> dict:
    """Provenance block for a serving report: ``nodes`` is the agent
    cluster size (the serving plane's scale axis), the rest comes from
    the shared benchlib context (platform, device_count, fingerprint)."""
    return {
        **benchlib.bench_context(scenario, nodes, *fingerprint_parts),
        "scenario": scenario,
        "nodes": nodes,
    }


_get = benchlib.get_path


def check_serving_budget(
    measured: dict, budget: dict
) -> tuple[bool, list[str]]:
    """Gate a serving report against the ``serving`` entry of
    bench_budget.json. Returns ``(ok, breaches)``.

    Budget keys:

    - ``tolerance``: multiplier on every ``*_ms`` ceiling.
    - dimension keys (``SERVING_DIMS``): must equal the measurement.
    - ``ceilings_ms``: dotted-path -> max milliseconds (e.g.
      ``"run.oracle.fanout_lag_ms.p99"``); a missing measurement is a
      breach (a silently vanished surface is how regressions hide).
    - ``oracle_violations_max`` (default 0): total oracle violations
      across scenarios, NOT tolerance-scaled.
    - ``require_shed_engaged`` (default True): the sweep must report
      ``shed_engaged`` true.
    """
    tol = float(budget.get("tolerance", benchlib.DEFAULT_TOLERANCE))
    breaches: list[str] = []
    for dim in SERVING_DIMS:
        if dim in budget and measured.get(dim) != budget[dim]:
            breaches.append(
                f"{dim}: measured at {measured.get(dim)!r} but the budget "
                f"was refreshed at {budget[dim]!r} — rerun with --update"
            )
    for path, limit in budget.get("ceilings_ms", {}).items():
        got = _get(measured, path)
        if got is None:
            breaches.append(f"{path}: missing from measurement")
        elif float(got) > float(limit) * tol:
            breaches.append(
                f"{path}: {float(got):.1f} ms > budget "
                f"{float(limit):.1f} ms x{tol}"
            )
    viol_max = int(budget.get("oracle_violations_max", 0))
    total_viol = sum(
        int(v)
        for v in (
            _get(measured, "run.oracle.violations"),
            _get(measured, "sweep.oracle.violations"),
        )
        if v is not None
    )
    if total_viol > viol_max:
        breaches.append(
            f"oracle violations: {total_viol} > {viol_max} — exactly-once "
            f"delivery or change-id monotonicity broke under load"
        )
    if budget.get("require_shed_engaged", True):
        if not _get(measured, "sweep.shed_engaged"):
            breaches.append(
                "sweep.shed_engaged: false — the ramp never tripped "
                "admission control, so the 503 fast-fail promise went "
                "untested"
            )
    return not breaches, breaches
