"""Serving-plane load subsystem: open-loop load generation against a live
agent cluster, with a built-in fan-out correctness oracle.

The agent plane speaks HTTP (/v1/transactions, /v1/queries, NDJSON
subscriptions), PG wire, and per-route admission control (RouteLimit,
agent/api.py) — this package is what exercises all of it above
single-test concurrency and turns the serving behavior into a measured,
CI-gated surface (docs/SERVING.md):

- ``schedule``: fixed **open-loop** arrival schedules. Arrivals fire on a
  wall-clock grid regardless of how fast earlier requests complete, and
  latency is measured from the *scheduled* arrival — so a saturated
  server cannot slow the generator down and hide its own queueing delay
  (the coordinated-omission failure mode of closed-loop harnesses).
- ``oracle``: the fan-out correctness oracle. Every committed transaction
  is registered; every live subscription stream must deliver each
  matching commit exactly once with monotonically increasing change ids.
  The harness is a robustness test first and a benchmark second.
- ``harness``: per-route open-loop drivers with latency histograms
  (``utils.metrics`` bucket machinery) and shed/error accounting split by
  cause (503 load-shed vs transport error vs timeout), plus the
  subscription pump that keeps thousands of NDJSON streams drained and
  reconnects through ``SubscriptionStream.reconnect``.
- ``pgread``: a minimal asyncio PG-wire simple-query client so the PG
  server sits under the same open-loop load as the HTTP routes.
- ``scenarios``: the three standing scenarios behind the ``loadgen`` CLI
  group — ``fanout_storm`` (run), ``saturation_sweep`` (sweep), and
  ``intake_policy`` (soak).
- ``report``: the one self-describing emit path (funnels through
  ``telemetry.check_bench_invariants``) plus the ``serving`` budget gate
  used by the loadgen-smoke CI job.
"""

from corrosion_tpu.loadgen.harness import LoadHarness, SubscriptionPump
from corrosion_tpu.loadgen.oracle import FanoutOracle
from corrosion_tpu.loadgen.schedule import Arrival, open_loop, ramp
from corrosion_tpu.loadgen.report import check_serving_budget, emit_serving_report

__all__ = [
    "Arrival",
    "FanoutOracle",
    "LoadHarness",
    "SubscriptionPump",
    "check_serving_budget",
    "emit_serving_report",
    "open_loop",
    "ramp",
]
