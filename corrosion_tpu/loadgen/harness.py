"""Open-loop load drivers + the subscription pump.

``LoadHarness`` owns the run clock, per-route latency histograms
(``utils.metrics`` bucket machinery — the same family the agent exports
on /metrics), and shed/error accounting split by cause: a 503 from
``RouteLimit`` is *shed* (admission control doing its job, fast-fail), a
connection failure is a *transport error*, and a request that neither
completes nor fails within its deadline is a *timeout*. The three are
different findings — a saturation sweep that lumped them together could
not distinguish "load-shed engaged as promised" from "the server fell
over".

``SubscriptionPump`` keeps one NDJSON subscription stream drained and
feeds every frame to the :class:`~corrosion_tpu.loadgen.oracle.
FanoutOracle`; when the server ends the stream (listener-queue overflow
eviction, agent restart) it resumes via
``SubscriptionStream.reconnect()`` from the last observed change id, so
an evicted laggard re-joins without duplicates or gaps — exactly the
contract the oracle then verifies.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from corrosion_tpu.client import ApiError, CorrosionApiClient
from corrosion_tpu.loadgen.oracle import FanoutOracle
from corrosion_tpu.loadgen.schedule import Arrival
from corrosion_tpu.utils.metrics import MetricsRegistry

OUTCOMES = ("ok", "shed", "error", "timeout")


@dataclass
class RouteStats:
    """Per-route open-loop accounting (one instance per route+stage)."""

    sent: int = 0
    ok: int = 0
    shed: int = 0
    error: int = 0
    timeout: int = 0
    errors_sample: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            "sent": self.sent, "ok": self.ok, "shed": self.shed,
            "error": self.error, "timeout": self.timeout,
        }
        if self.errors_sample:
            d["errors_sample"] = self.errors_sample[:4]
        return d


class LoadHarness:
    """Run clock + per-route accounting for one scenario execution."""

    def __init__(self, registry: MetricsRegistry | None = None) -> None:
        self.registry = registry or MetricsRegistry()
        self._hist = self.registry.histogram(
            "loadgen_route_seconds",
            "open-loop request latency from SCHEDULED arrival "
            "(includes generator queueing — coordinated-omission-free)",
        )
        self._stats: dict[tuple[str, int], RouteStats] = {}
        self._t0: float | None = None
        self._lat_max: dict[tuple[str, int], float] = {}

    def stats(self, route: str, stage: int = 0) -> RouteStats:
        key = (route, stage)
        if key not in self._stats:
            self._stats[key] = RouteStats()
        return self._stats[key]

    # -- open-loop core ------------------------------------------------------

    async def run_arrivals(self, arrivals: list[Arrival], fire) -> None:
        """Fire ``fire(arrival)`` at each scheduled instant without
        waiting for earlier calls (open-loop); awaits all completions
        before returning. The run clock starts at the first call, so
        latencies from :meth:`timed` line up with the schedule."""
        loop = asyncio.get_running_loop()
        if self._t0 is None:
            self._t0 = loop.time()
        t0 = self._t0
        tasks = []
        for a in arrivals:
            delay = t0 + a.t - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.ensure_future(fire(a)))
        if tasks:
            await asyncio.gather(*tasks)

    async def timed(
        self, route: str, arrival: Arrival, coro_fn, *,
        deadline_s: float = 15.0,
    ):
        """Run one request, classify its outcome, and record latency
        from the *scheduled* arrival. Returns the request's result, or
        None on shed/error/timeout."""
        loop = asyncio.get_running_loop()
        st = self.stats(route, arrival.stage)
        st.sent += 1
        result = None
        outcome = "ok"
        try:
            result = await asyncio.wait_for(coro_fn(), deadline_s)
        except ApiError as e:
            if e.status == 503:
                outcome = "shed"
            else:
                outcome = "error"
                st.errors_sample.append(f"HTTP {e.status}: {e.body[:80]}")
        except asyncio.TimeoutError:
            outcome = "timeout"
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            outcome = "error"
            st.errors_sample.append(repr(e)[:120])
        setattr(st, outcome, getattr(st, outcome) + 1)
        lat = loop.time() - ((self._t0 or 0.0) + arrival.t)
        # Shed MUST fail fast — its latency is part of the admission-
        # control promise, so it is recorded too (separate outcome label).
        self._hist.observe(
            lat, route=route, outcome=outcome, stage=str(arrival.stage)
        )
        key = (route, arrival.stage, outcome)
        self._lat_max[key] = max(self._lat_max.get(key, 0.0), lat)
        return result

    # -- report assembly -----------------------------------------------------

    def route_report(self, route: str, stage: int = 0) -> dict:
        """Stats + ok-latency percentiles for one route (and stage)."""
        st = self.stats(route, stage)
        out = st.to_dict()
        labels = {"route": route, "outcome": "ok", "stage": str(stage)}
        count = self._hist.count(**labels)
        lat_max = self._lat_max.get((route, stage, "ok"), 0.0)

        def q_ms(q: float) -> float:
            return round(
                min(self._hist.quantile(q, **labels), lat_max) * 1000.0, 3,
            )

        if count:
            out["latency_ms"] = {
                "p50": q_ms(0.50), "p90": q_ms(0.90), "p99": q_ms(0.99),
                "max": round(lat_max * 1000.0, 3),
            }
        if st.shed:
            # The other half of the admission promise: shed is FAST-fail.
            shed_max = self._lat_max.get((route, stage, "shed"), 0.0)
            out["shed_latency_ms"] = {
                "p99": round(
                    min(
                        self._hist.quantile(
                            0.99, route=route, outcome="shed",
                            stage=str(stage),
                        ),
                        shed_max,
                    ) * 1000.0, 3,
                ),
                "max": round(shed_max * 1000.0, 3),
            }
        return out

    def stages_of(self, route: str) -> list[int]:
        return sorted(s for (r, s) in self._stats if r == route)


class SubscriptionPump:
    """One live NDJSON subscription stream, drained into the oracle.

    Lifecycle: ``await start()`` subscribes and consumes the initial
    snapshot (sub_id header, columns, rows, end-of-query) synchronously —
    when it returns, the oracle knows this stream's obligations begin.
    The live phase runs as a background task; ``await stop()`` tears it
    down. A stream ended by the server (listener-queue overflow eviction
    or restart) resumes via ``reconnect()`` from the last change id.

    Events must be ``SELECT``s whose first cell is the row key and whose
    remaining cells serialize to the committed payload — the scenarios
    use ``SELECT id, text FROM tests ...`` so ``cells[0]`` is the key and
    ``cells[1]`` the payload.
    """

    def __init__(
        self,
        client: CorrosionApiClient,
        sql: str,
        oracle: FanoutOracle,
        *,
        group: int | None = None,
        label: str = "",
        reconnect: bool = True,
        reconnect_delay_s: float = 0.2,
        reconnect_retries: int = 25,
    ) -> None:
        self.client = client
        self.sql = sql
        self.oracle = oracle
        self.group = group
        self.label = label
        self.auto_reconnect = reconnect
        self.reconnect_delay_s = reconnect_delay_s
        self.reconnect_retries = reconnect_retries
        self.sid: int | None = None
        self.stream = None
        self._task: asyncio.Task | None = None
        self._stopping = False
        self.dead_reason: str | None = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.sid = self.oracle.attach_stream(
            group=self.group, label=self.label
        )
        self.stream = await self.client.subscribe(self.sql)
        await self._consume_snapshot(loop)
        self._task = asyncio.ensure_future(self._run())

    async def _consume_snapshot(self, loop) -> None:
        """Drain frames up to the end-of-query marker. Change events may
        legally arrive BEFORE eoq on a catch-up resume (the server
        replays the change log instead of a snapshot) — forward them."""
        async for ev in self.stream:
            if "row" in ev:
                _rowid, cells = ev["row"]
                self.oracle.snapshot_row(
                    self.sid, cells[0], tuple(cells[1:]),
                    t_wall=time.time(),
                )
            elif "change" in ev:
                self._on_change(ev, loop)
                # Catch-up resume: no eoq frame follows the replay.
                break
            elif "eoq" in ev:
                break
        self.oracle.snapshot_done(self.sid, loop.time())

    def _on_change(self, ev: dict, loop) -> None:
        kind, _rowid, cells, change_id = ev["change"]
        # Both clocks on purpose: loop.time() feeds the lag histogram
        # (monotonic, ack-relative); time.time() is the wall stamp the
        # timeline correlator joins against the agent's span export.
        self.oracle.change(
            self.sid, kind, cells[0], tuple(cells[1:]), change_id,
            loop.time(), t_wall=time.time(),
        )

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                async for ev in self.stream:
                    if "change" in ev:
                        self._on_change(ev, loop)
                    elif "row" in ev:
                        # Snapshot-restart replay after a deep reconnect.
                        _rowid, cells = ev["row"]
                        self.oracle.snapshot_row(
                            self.sid, cells[0], tuple(cells[1:])
                        )
            except (ConnectionError, OSError, asyncio.IncompleteReadError,
                    ValueError):
                pass
            if self._stopping or not self.auto_reconnect:
                return
            if not await self._try_reconnect():
                return

    async def _try_reconnect(self) -> bool:
        for _ in range(self.reconnect_retries):
            if self._stopping:
                return False
            try:
                await self.stream.reconnect()
            except (ApiError, ConnectionError, OSError) as e:
                self.dead_reason = repr(e)
                await asyncio.sleep(self.reconnect_delay_s)
                continue
            self.dead_reason = None  # corro-lint: disable=CT040 reason=single pump task owns dead_reason; it is report status, not control state
            self.oracle.reconnected(self.sid)
            return True
        return False

    def request_stop(self) -> None:
        """Synchronously mark the pump stopping and cut its stream, so a
        teardown path can pre-mark EVERY pump before awaiting the batched
        ``stop()``s — a pump whose ``async for`` breaks after the mark
        exits instead of spending reconnect retries against a stopping
        cluster."""
        self._stopping = True
        if self.stream is not None:
            self.stream.close()

    async def stop(self) -> None:
        self.request_stop()
        # Capture-and-swap before awaiting: a concurrent stop() (final
        # teardown racing a scenario's own stop) must not null _task
        # under the first caller's await — `self._task.cancel()` would
        # then be `None.cancel()`.
        task, self._task = self._task, None
        if task is not None:
            try:
                await asyncio.wait_for(task, 5.0)
            except asyncio.TimeoutError:
                task.cancel()
            except asyncio.CancelledError:
                task.cancel()
                raise  # we were cancelled: propagate, don't absorb


async def stop_pumps(pumps: list["SubscriptionPump"]) -> None:
    """Tear down a fleet of pumps: pre-mark EVERY pump stopping (so none
    spends reconnect retries against a stopping cluster), then await the
    stops in bounded batches — the one teardown shared by the loadgen
    scenarios and the host chaos harness."""
    for p in pumps:
        p.request_stop()
    for base in range(0, len(pumps), 256):
        await asyncio.gather(*(p.stop() for p in pumps[base:base + 256]))
