"""Configuration: TOML file + environment overlay.

Mirrors corro-types/src/config.rs: sections db/api/gossip/admin/telemetry/
log/consul (config.rs:10-25), env overrides with the ``__`` separator
(config.rs:185-191, e.g. CORRO_DB__PATH=/x overrides [db].path), and a
builder used by tests (config.rs:194-306). Hot reload re-applies schema
paths (command/reload.rs).
"""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field
from typing import Any

ENV_PREFIX = "CORRO_"


@dataclass
class DbConfig:
    path: str = "./corrosion.db"
    schema_paths: list[str] = field(default_factory=list)


@dataclass
class ApiConfig:
    addr: str = "127.0.0.1:0"


@dataclass
class GossipConfig:
    addr: str = "127.0.0.1:0"
    bootstrap: list[str] = field(default_factory=list)
    plaintext: bool = True
    max_transmissions: int = 4
    probe_interval_ms: int = 250
    sync_interval_ms: int = 500
    # TLS (config.rs GossipConfig.tls; flat here so the CORRO_GOSSIP__*
    # env overlay reaches every knob — a nested [gossip.tls] table in the
    # TOML maps onto these in Config.load).
    tls_cert_file: str | None = None
    tls_key_file: str | None = None
    tls_ca_file: str | None = None
    tls_insecure: bool = False
    tls_mtls: bool = False  # require + verify client certs
    tls_client_cert_file: str | None = None
    tls_client_key_file: str | None = None


@dataclass
class AdminConfig:
    uds_path: str = "./admin.sock"


@dataclass
class TelemetryConfig:
    prometheus_addr: str | None = None
    # OTLP/HTTP collector base URL (config.rs telemetry.open-telemetry;
    # spans batch-POST to <url>/v1/traces).
    otlp_endpoint: str | None = None


@dataclass
class LogConfig:
    format: str = "plaintext"  # plaintext | json (config.rs:318-326)
    colors: bool = False


@dataclass
class ConsulConfig:
    enabled: bool = False
    address: str = "127.0.0.1:8500"
    interval_ms: int = 1000


@dataclass
class Config:
    db: DbConfig = field(default_factory=DbConfig)
    api: ApiConfig = field(default_factory=ApiConfig)
    gossip: GossipConfig = field(default_factory=GossipConfig)
    admin: AdminConfig = field(default_factory=AdminConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    log: LogConfig = field(default_factory=LogConfig)
    consul: ConsulConfig = field(default_factory=ConsulConfig)

    @classmethod
    def load(cls, path: str | None = None, env: dict | None = None) -> "Config":
        data: dict[str, Any] = {}
        if path is not None:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        cfg = cls()
        for section, obj in (
            ("db", cfg.db), ("api", cfg.api), ("gossip", cfg.gossip),
            ("admin", cfg.admin), ("telemetry", cfg.telemetry),
            ("log", cfg.log), ("consul", cfg.consul),
        ):
            for k, v in data.get(section, {}).items():
                if k == "tls" and isinstance(v, dict):
                    # [gossip.tls] nested table → flat tls_* fields.
                    for tk, tv in v.items():
                        if isinstance(tv, dict):  # [gossip.tls.client]
                            for ck, cv in tv.items():
                                flat = f"tls_{tk}_{ck}"
                                if hasattr(obj, flat):
                                    setattr(obj, flat, cv)
                        elif hasattr(obj, f"tls_{tk}"):
                            setattr(obj, f"tls_{tk}", tv)
                    continue
                if hasattr(obj, k):
                    setattr(obj, k, v)
        cfg._apply_env(env if env is not None else dict(os.environ))
        return cfg

    def _apply_env(self, env: dict) -> None:
        """CORRO_<SECTION>__<FIELD>=value (config.rs:185-191)."""
        for key, value in env.items():
            if not key.startswith(ENV_PREFIX) or "__" not in key:
                continue
            section_name, _, field_name = key[len(ENV_PREFIX):].partition("__")
            obj = getattr(self, section_name.lower(), None)
            if obj is None:
                continue
            fname = field_name.lower()
            if not hasattr(obj, fname):
                continue
            current = getattr(obj, fname)
            setattr(obj, fname, _coerce(value, current))

    def schema_sql(self) -> str:
        parts = []
        for p in self.db.schema_paths:
            if os.path.isdir(p):
                for entry in sorted(os.listdir(p)):
                    if entry.endswith(".sql"):
                        with open(os.path.join(p, entry)) as f:
                            parts.append(f.read())
            elif os.path.exists(p):
                with open(p) as f:
                    parts.append(f.read())
        return "\n".join(parts)


def _coerce(value: str, current: Any) -> Any:
    if isinstance(current, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(current, int):
        return int(value)
    if isinstance(current, list):
        return [v.strip() for v in value.split(",") if v.strip()]
    return value


def parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def resolve_bootstrap(entries: list[str]) -> list[tuple[str, int]]:
    """Expand bootstrap entries into peer addresses.

    Plain ``host:port`` entries pass through. The reference's DNS resolver
    syntax ``name:port@dns[:dns_port]`` (resolve_bootstrap,
    corro-agent/src/agent.rs:1494-1586) resolves ``name`` and announces to
    EVERY address it maps to; stdlib resolution is used (the custom-server
    part of the syntax is accepted but the system resolver answers).
    Unresolvable names are skipped — bootstrap keeps retrying via the
    announce loop, matching the reference's tolerant startup.
    """
    import socket

    out: list[tuple[str, int]] = []
    for entry in entries:
        spec, _, _dns = entry.partition("@")
        host, port = parse_addr(spec)
        if _dns:
            try:
                infos = socket.getaddrinfo(
                    host, port, type=socket.SOCK_STREAM
                )
            except socket.gaierror:
                continue
            for info in infos:
                addr = (info[4][0], port)
                if addr not in out:
                    out.append(addr)
        else:
            out.append((host, port))
    return out
