"""Subscription engine — the Matcher (corro-types/src/pubsub.rs) rebuilt.

The reference's Matcher (pubsub.rs:510-1570, its largest component) parses a
SELECT, tracks which tables feed it, and on each batch of changes
incrementally re-evaluates the query, diffing against the previous result to
emit insert/update/delete QueryEvents with monotonically increasing change
ids; subscribers can catch up from any change id (`?from=`).

This implementation keeps the same contract with a different mechanism
suited to the host store:

- table dependencies are discovered with SQLite's authorizer hook during
  prepare (instead of a SQL AST walk with sqlite3-parser);
- row identity: for plain single-table selects the table's primary key is
  injected into the select list (the reference's PK-alias rewrite,
  pubsub.rs:566-661); other shapes (joins/aggregates) fall back to
  whole-row identity, which downgrades updates to delete+insert pairs but
  keeps the stream correct;
- the result snapshot and the change history live in each sub's own
  SQLite file (`query`/`changes`/`meta` tables — the reference's per-sub
  sub-db, pubsub.rs:806-841), so ``?from=`` catch-up replays across agent
  restarts and a laggard survives far deeper history than an in-memory
  ring; handles built without a directory (unit tests) fall back to an
  in-memory deque with the same change-id semantics.
"""

from __future__ import annotations

import asyncio
import json
import os
import sqlite3
import time
import uuid
from collections import deque

from corrosion_tpu.agent.store import Store
from corrosion_tpu.core.values import (
    CHANGE_DELETE,
    CHANGE_INSERT,
    CHANGE_UPDATE,
    Change,
    QueryEventChange,
    QueryEventColumns,
    QueryEventEndOfQuery,
    QueryEventRow,
    unpack_columns,
)

# In-memory fallback ring depth (no-db handles); durable handles retain
# MAX_DURABLE_HISTORY change rows in their sub-db before pruning.
MAX_CHANGE_HISTORY = 8192
MAX_DURABLE_HISTORY = 1 << 16


def _jsonable(v):
    if isinstance(v, bytes):
        return {"$b": v.hex()}
    return v


def _unjson(v):
    if isinstance(v, dict) and set(v.keys()) == {"$b"}:
        return bytes.fromhex(v["$b"])
    return v


def _key_to_json(key: tuple) -> str:
    return json.dumps([_jsonable(v) for v in key], separators=(",", ":"))


def _cells_to_json(cells) -> str:
    return json.dumps([_jsonable(v) for v in cells], separators=(",", ":"))


def _cells_from_json(s: str) -> tuple:
    return tuple(_unjson(v) for v in json.loads(s))


def _membership_is_local(select_list: str, tail: str) -> bool:
    """Candidate-only re-evaluation is sound only when a row's result
    VALUES and membership depend on that row alone: LIMIT windows, GROUP
    BY, and subqueries make membership global (a change to one PK can
    evict another row), and window functions / scalar subqueries in the
    select list make unchanged rows' values change — only a full diff
    notices either. Shared by the single-table and join injectors so the
    soundness rule cannot diverge."""
    import re

    return not re.search(
        r"(?i)\b(limit|group)\b|\(\s*select\b", tail
    ) and not re.search(r"(?i)\bover\s*\(|\(\s*select\b", select_list)


_PLAN_FEATURES = (
    ("window", r"(?i)\bover\s*\("),
    ("aggregate", r"(?i)\b(count|sum|avg|min|max|group_concat)\s*\("),
    ("group_by", r"(?i)\bgroup\s+by\b"),
    ("distinct", r"(?i)^\s*select\s+distinct\b"),
    ("subquery", r"(?i)\(\s*select\b"),
    ("limit", r"(?i)\blimit\b"),
    ("outer_join", r"(?i)\b(left|right|full|cross|natural)\s+(outer\s+)?join\b"),
    ("join", r"(?i)\bjoin\b"),
)


def classify_query(sql: str) -> tuple[str, list[str]]:
    """Syntactic feature sweep for the query-plan classifier: returns
    ``(class, features)`` where class is the dominant shape
    (window > aggregate > join > simple). Whether the shape is actually
    servable incrementally is decided by the PK injector — the handle's
    ``plan`` record combines both so the classification can never
    disagree with what the matcher really does."""
    import re

    feats = [name for name, pat in _PLAN_FEATURES if re.search(pat, sql)]
    if "window" in feats:
        cls = "window"
    elif "aggregate" in feats or "group_by" in feats:
        cls = "aggregate"
    elif "join" in feats or "outer_join" in feats:
        cls = "join"
    else:
        cls = "simple"
    return cls, feats


class SubCost:
    """Per-subscription cost ledger (one per MatcherHandle, allocated only
    when the cost plane is armed — ``MatcherHandle.cost`` stays ``None``
    otherwise and every hot-path site guards on that single check, the
    same zero-cost contract as ``prop_observe``).

    Counters cover the whole serving cost surface: candidate vs fallback
    evaluations, rows scanned, eval wall seconds, snapshot-diff rows,
    fan-out events/bytes, listener-queue depth high-water, and
    reconnect-replay rows. ``snapshot()`` is the ``corro-sub-cost/1``
    record body; ``load()`` re-adopts counters persisted in the sub-db
    so the ledger survives agent kill/relaunch like the endurance series
    recorder does."""

    COUNTERS = (
        "candidate_evals", "fallback_evals", "rows_scanned",
        "eval_seconds_candidate", "eval_seconds_fallback", "diff_rows",
        "fanout_events", "fanout_bytes", "queue_depth_hwm",
        "replays", "replay_rows",
    )

    __slots__ = COUNTERS + ("_label", "_hist", "_fb_counter")

    def __init__(self, sub_id: str, hist=None, fb_counter=None) -> None:
        for name in self.COUNTERS:
            setattr(self, name, 0)
        self.eval_seconds_candidate = 0.0
        self.eval_seconds_fallback = 0.0
        self._label = sub_id[:8]
        self._hist = hist
        self._fb_counter = fb_counter

    def note_eval(self, kind: str, rows: int, seconds: float) -> None:
        self.rows_scanned += rows
        if kind == "fallback":
            self.fallback_evals += 1
            self.eval_seconds_fallback += seconds
            if self._fb_counter is not None:
                self._fb_counter.inc(sub=self._label)
        else:
            self.candidate_evals += 1
            self.eval_seconds_candidate += seconds
        if self._hist is not None:
            self._hist.observe(seconds, kind=kind)

    def note_diff(self, n_events: int) -> None:
        self.diff_rows += n_events

    def note_fanout(self, events: int, nbytes: int, depth: int) -> None:
        self.fanout_events += events
        self.fanout_bytes += nbytes
        if depth > self.queue_depth_hwm:
            self.queue_depth_hwm = depth

    def note_replay(self, rows: int) -> None:
        self.replays += 1
        self.replay_rows += rows

    def snapshot(self) -> dict:
        out = {name: getattr(self, name) for name in self.COUNTERS}
        out["eval_seconds_total"] = (
            self.eval_seconds_candidate + self.eval_seconds_fallback
        )
        return out

    def load(self, d: dict) -> None:
        """Adopt persisted counters (additive: a restored handle resumes
        the ledger where the killed process last persisted it)."""
        for name in self.COUNTERS:
            v = d.get(name)
            if v is None:
                continue
            if name == "queue_depth_hwm":
                self.queue_depth_hwm = max(self.queue_depth_hwm, v)
            else:
                setattr(self, name, getattr(self, name) + v)


def normalize_sql(sql: str) -> str:
    """Canonical reuse key (pubsub.rs normalize_sql:2089, which parses and
    re-serializes via sqlparser). Token-level here: comments and
    whitespace drop, unquoted identifiers/keywords lowercase, trailing
    ';' strips — while string literals and quoted identifiers keep their
    case (the old lowercase-everything key deduped `x='A'` with `x='a'`
    onto ONE matcher, silently serving the second subscriber the wrong
    rows)."""
    from corrosion_tpu.agent import pgsql

    out = []
    for t in pgsql.tokenize(sql):
        if t.kind in ("ws", "comment"):
            continue
        out.append(t.text.lower() if t.kind == "ident" else t.text)
    while out and out[-1] == ";":
        out.pop()
    return " ".join(out)


_WRITE_ACTIONS = {
    sqlite3.SQLITE_INSERT, sqlite3.SQLITE_UPDATE, sqlite3.SQLITE_DELETE,
    sqlite3.SQLITE_CREATE_TABLE, sqlite3.SQLITE_DROP_TABLE,
    sqlite3.SQLITE_ALTER_TABLE, sqlite3.SQLITE_CREATE_INDEX,
    sqlite3.SQLITE_DROP_INDEX, sqlite3.SQLITE_PRAGMA,
}


def _clear_authorizer(conn: sqlite3.Connection) -> None:
    """``set_authorizer(None)`` only uninstalls the hook on Python >= 3.11
    (gh-90732); on older runtimes it is a silent no-op and the deny hook
    would poison every later statement on the connection ("not
    authorized") — overwrite with an allow-all hook instead."""
    import sys

    if sys.version_info >= (3, 11):
        conn.set_authorizer(None)
    else:
        conn.set_authorizer(lambda *_: sqlite3.SQLITE_OK)


def _referenced_tables(conn: sqlite3.Connection, sql: str) -> set[str]:
    """Tables a SELECT reads, via the authorizer hook during prepare.
    Rejects anything that would write — subscriptions are SELECT-only
    (the Matcher parses a SELECT, pubsub.rs:510-712)."""
    seen: set[str] = set()
    writes: list[int] = []

    def auth(action, arg1, arg2, dbname, trigger):
        if action == sqlite3.SQLITE_READ and arg1:
            seen.add(arg1)
        if action in _WRITE_ACTIONS:
            writes.append(action)
            return sqlite3.SQLITE_DENY
        return sqlite3.SQLITE_OK

    conn.set_authorizer(auth)
    try:
        conn.execute(f"EXPLAIN {sql}")
    except sqlite3.DatabaseError as e:
        if writes:
            raise ValueError("subscriptions must be SELECT statements") from e
        raise
    finally:
        _clear_authorizer(conn)
    if writes:
        raise ValueError("subscriptions must be SELECT statements")
    return {t for t in seen if not t.startswith("__")}


class MatcherHandle:
    """One materialized subscription; fan-out to any number of listeners
    (the broadcast::Sender per sub, api/public/pubsub.rs:117-180)."""

    def __init__(
        self, store: Store, sql: str, sub_id: str | None = None,
        start_change_id: int = 0, db_dir: str | None = None,
    ) -> None:
        self.id = sub_id or uuid.uuid4().hex
        self.sql = sql
        self.store = store
        self.tables = _referenced_tables(store.read_conn, sql)
        if not self.tables:
            raise ValueError("query reads no user tables")
        self._pk_prefix = 0
        self._pk_table: str | None = None
        # Join mode: [(table, alias, key_offset, n_pk_cols)] per joined
        # table; None = single-table or fallback identity. The per-segment
        # index (segment value -> full keys) keeps join deletes
        # O(candidates), not O(result set).
        self._pk_segments: list[tuple[str, str, int, int]] | None = None
        self._seg_index: list[dict[tuple, set[tuple]]] | None = None
        self._local_membership = False
        self._exec_sql = sql
        self._maybe_inject_pks()
        # EXPLAIN-style query-plan record (tentpole c): computed once at
        # subscribe time from the classifier sweep + the PK injector's
        # actual outcome, so "fallback_bound" is the matcher's ground
        # truth, not a regex guess. Static metadata — not ledger state.
        self.plan = self._classify_plan()
        # Cost ledger slot (tentpole a): None unless the cost plane is
        # armed via SubsManager.enable_costs — the pinned zero-cost
        # disabled mode (no per-sub allocation, bit-identical behavior).
        self.cost: SubCost | None = None
        self.columns: list[str] = []
        self.rows: dict[tuple, tuple] = {}  # identity key -> cells
        self.rowids: dict[tuple, int] = {}
        self._next_rowid = 1
        # Restored subs continue numbering where the persisted watermark
        # left off (Matcher::restore, pubsub.rs:735-771).
        self.change_id = start_change_id
        self.history: deque[QueryEventChange] = deque(maxlen=MAX_CHANGE_HISTORY)
        self._listeners: list[asyncio.Queue] = []
        # Listener queues that overflowed: their streams are LOSSY from
        # that point on, and the API layer must END them (the client then
        # resumes via ?from= and the durable log replays the gap) rather
        # than silently continue past a dropped event. dropped_events is
        # the observability counter behind corro_subs_dropped_events.
        self._overflowed: set[asyncio.Queue] = set()
        self.dropped_events = 0
        self._touched: list[tuple] = []
        # Fallback (full re-evaluation) cost control: once an evaluation
        # proves expensive, later change batches coalesce into one deferred
        # re-snapshot per FALLBACK_MIN_INTERVAL instead of re-scanning per
        # batch (see process()).
        self._last_full = 0.0
        self._full_expensive = False
        self._dirty = False
        self._flush_handle: asyncio.TimerHandle | None = None
        # In-flight off-loop re-snapshot (expensive shapes only) + the
        # snapshot-mutation generation that invalidates a stale scan.
        self._bg_task: asyncio.Task | None = None
        self._mutation_gen = 0
        self._db: sqlite3.Connection | None = None
        restored = False
        if db_dir is not None:
            os.makedirs(db_dir, exist_ok=True)
            self._db = sqlite3.connect(
                os.path.join(db_dir, f"{self.id}.sqlite"),
                check_same_thread=False,
            )
            self._db.isolation_level = None
            self._db.execute("PRAGMA journal_mode=WAL")
            # The sub-db is DERIVED state (rebuildable from the main db by
            # the restore-time reconcile diff), so commits skip fsync:
            # _persist_events runs on the event loop per ingest batch, and
            # a synchronous commit there would stall SWIM probes and sync
            # timeouts for the disk's flush latency.
            self._db.execute("PRAGMA synchronous=OFF")
            restored = self._restore_from_db()
        if restored:
            # Snapshot + watermark came from the sub-db; emit (and persist)
            # whatever drifted while we were down — a resuming subscriber
            # gets those as ordinary catch-up events instead of a snapshot
            # restart (Matcher::restore, pubsub.rs:735-771). process(None)'s
            # full evaluation also populates self.columns — one scan.
            self.process(None)
        else:
            self._run_initial()
            self._persist_snapshot()

    # -- durable sub-db (pubsub.rs:806-841) ----------------------------------

    def _restore_from_db(self) -> bool:
        """Load snapshot + history watermark from the sub-db; returns False
        when the db is fresh or belongs to a different query text."""
        db = self._db
        db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v TEXT)"
        )
        db.execute(
            "CREATE TABLE IF NOT EXISTS query ("
            " key TEXT PRIMARY KEY, rowid_ INTEGER NOT NULL,"
            " cells TEXT NOT NULL) WITHOUT ROWID"
        )
        db.execute(
            "CREATE TABLE IF NOT EXISTS changes ("
            " change_id INTEGER PRIMARY KEY, kind TEXT NOT NULL,"
            " rowid_ INTEGER NOT NULL, cells TEXT NOT NULL)"
        )
        row = db.execute("SELECT v FROM meta WHERE k = 'sql'").fetchone()
        if row is None or normalize_sql(row[0]) != normalize_sql(self.sql):
            db.execute("DELETE FROM meta")
            db.execute("DELETE FROM query")
            db.execute("DELETE FROM changes")
            db.execute(
                "INSERT INTO meta VALUES ('sql', ?)", (self.sql,)
            )
            return False
        wm = db.execute("SELECT v FROM meta WHERE k = 'change_id'").fetchone()
        if wm is not None:
            self.change_id = max(self.change_id, int(wm[0]))
        for key_s, rowid, cells_s in db.execute(
            "SELECT key, rowid_, cells FROM query"
        ).fetchall():
            key = tuple(_unjson(v) for v in json.loads(key_s))
            self.rows[key] = _cells_from_json(cells_s)
            self.rowids[key] = rowid
            self._next_rowid = max(self._next_rowid, rowid + 1)
        self._index_rebuild()
        return True

    def _persist_snapshot(self) -> None:
        if self._db is None:
            return
        db = self._db
        db.execute("BEGIN")
        db.execute("DELETE FROM query")
        db.executemany(
            "INSERT INTO query VALUES (?, ?, ?)",
            [
                (_key_to_json(k), self.rowids[k], _cells_to_json(c))
                for k, c in self.rows.items()
            ],
        )
        db.execute(
            "INSERT OR REPLACE INTO meta VALUES ('change_id', ?)",
            (str(self.change_id),),
        )
        db.execute("COMMIT")

    def _persist_events(
        self, events: list[QueryEventChange], touched: list[tuple]
    ) -> None:
        """Append events to the durable change log + upsert the touched
        snapshot rows, in one transaction; prune history past the cap."""
        if self._db is None or not events:
            return
        db = self._db
        db.execute("BEGIN")
        db.executemany(
            "INSERT OR REPLACE INTO changes VALUES (?, ?, ?, ?)",
            [
                (ev.change_id, ev.kind, ev.rowid, _cells_to_json(ev.cells))
                for ev in events
            ],
        )
        for key in touched:
            if key in self.rows:
                db.execute(
                    "INSERT OR REPLACE INTO query VALUES (?, ?, ?)",
                    (_key_to_json(key), self.rowids[key],
                     _cells_to_json(self.rows[key])),
                )
            else:
                db.execute(
                    "DELETE FROM query WHERE key = ?", (_key_to_json(key),)
                )
        db.execute(
            "INSERT OR REPLACE INTO meta VALUES ('change_id', ?)",
            (str(self.change_id),),
        )
        db.execute(
            "DELETE FROM changes WHERE change_id <= ?",
            (self.change_id - MAX_DURABLE_HISTORY,),
        )
        if self.cost is not None:
            # Piggyback the ledger on the batch transaction: a SIGKILL
            # loses at most the counters since the last published batch,
            # and relaunch adopts the rest (enable_cost).
            self._persist_cost(db)
        db.execute("COMMIT")

    def close(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        if self._bg_task is not None:
            self._bg_task.cancel()
            self._bg_task = None
            self._dirty = True
        if self._dirty:
            # A deferred re-snapshot must not die with the handle: the last
            # change batch before shutdown would stay unreported (and the
            # durable log would replay stale rows after restore). Direct
            # sync pass — the off-loop path must not re-arm during close.
            try:
                self._touched = []
                self._publish(self._full_pass())
            except Exception:
                pass
        if self._db is not None:
            try:
                if self.cost is not None:
                    self._persist_cost(self._db)
                self._db.close()
            except Exception:
                pass
            self._db = None

    # -- cost ledger + plan record -------------------------------------------

    def _classify_plan(self) -> dict:
        cls, feats = classify_query(self.sql)
        incremental = bool(self._pk_prefix and self._local_membership)
        return {
            "class": cls,
            "features": feats,
            "incremental": incremental,
            "fallback_bound": not incremental,
            "pk_identity": bool(self._pk_prefix),
            "join_segments": len(self._pk_segments or ()),
            "tables": sorted(self.tables),
        }

    def enable_cost(self, hist=None, fb_counter=None) -> None:
        """Arm the cost ledger (idempotent). Durable handles re-adopt the
        counters last persisted in their sub-db meta, so a killed and
        relaunched agent resumes the ledger instead of zeroing it."""
        if self.cost is not None:
            return
        self.cost = SubCost(self.id, hist=hist, fb_counter=fb_counter)
        if self._db is not None:
            row = self._db.execute(
                "SELECT v FROM meta WHERE k = 'cost'"
            ).fetchone()
            if row is not None:
                try:
                    self.cost.load(json.loads(row[0]))
                except (ValueError, TypeError):
                    pass

    def _persist_cost(self, db) -> None:
        db.execute(
            "INSERT OR REPLACE INTO meta VALUES ('cost', ?)",
            (json.dumps(self.cost.snapshot(), separators=(",", ":")),),
        )

    # -- query shape ---------------------------------------------------------

    def _maybe_inject_pks(self) -> None:
        """For `SELECT ... FROM <one crr table> ...`, prepend the table's PK
        columns as identity columns (hidden from emitted cells). For plain
        inner-join chains, prepend EVERY table's PK columns (the
        reference's Matcher aliases all tables' PKs, pubsub.rs:566-661) so
        a one-to-many join keeps per-result-row identity and candidate
        diffing works from any table's changed PKs."""
        import re

        m = re.match(
            r"(?is)^\s*select\s+(?!.*\bjoin\b)(.+?)\s+from\s+([A-Za-z_][\w]*)"
            r"(\s+(?:where|order|group|limit)\b.*)?\s*;?\s*$",
            self.sql,
        )
        if not m:
            self._maybe_inject_join_pks()
            return
        table = m.group(2)
        info = self.store.tables().get(table)
        if info is None:
            return
        select_list = m.group(1)
        tail = (m.group(3) or "").rstrip().rstrip(";")
        if re.search(r"(?i)\b(count|sum|avg|min|max|group_concat)\s*\(", select_list):
            return
        if re.match(r"(?i)\s*distinct\b", select_list):
            # Prepending PK columns to a DISTINCT list changes its meaning.
            return
        pk_cols = ", ".join(
            f'"{table}"."{c}" AS __pk{i}'
            for i, c in enumerate(info.pk_cols)
        )
        self._exec_sql = (
            f'SELECT {pk_cols}, {select_list} FROM "{table}"{tail}'
        )
        self._pk_prefix = len(info.pk_cols)
        self._pk_table = table
        self._local_membership = _membership_is_local(select_list, tail)

    def _maybe_inject_join_pks(self) -> None:
        """Inner-join chains: `SELECT ... FROM t1 [a] JOIN t2 [b] ON ...`.
        Row identity = concatenation of every table's PKs (unique per
        result row even for one-to-many joins); a change batch touching
        any joined table re-evaluates only result rows whose that-table PK
        segment matches a changed PK."""
        import re

        m = re.match(
            r"(?is)^\s*select\s+(.+?)\s+from\s+(.+?)"
            r"(\s+(?:where|order|group|limit)\b.*)?\s*;?\s*$",
            self.sql,
        )
        if not m:
            return
        select_list, from_clause = m.group(1), m.group(2)
        tail = (m.group(3) or "").rstrip().rstrip(";")
        # Only plain INNER JOIN chains: outer/cross/natural/USING change
        # membership semantics; subqueries and comma-joins fall back.
        if re.search(
            r"(?i)\b(left|right|full|cross|outer|natural|using)\b",
            from_clause,
        ) or "(" in from_clause or "," in from_clause:
            return
        if re.search(
            r"(?i)\b(count|sum|avg|min|max|group_concat)\s*\(", select_list
        ) or re.match(r"(?i)\s*distinct\b", select_list):
            return
        parts = re.split(r"(?i)\s+(?:inner\s+)?join\s+", from_clause)
        if len(parts) < 2:
            return

        def ref(s: str):
            mm = re.match(
                r"(?is)^\s*([A-Za-z_]\w*)(?:\s+(?:as\s+)?([A-Za-z_]\w*))?\s*$",
                s,
            )
            return (mm.group(1), mm.group(2) or mm.group(1)) if mm else None

        first = ref(parts[0])
        if first is None:
            return
        refs = [first]
        for seg in parts[1:]:
            mm = re.match(
                r"(?is)^\s*([A-Za-z_]\w*)(?:\s+(?:as\s+)?([A-Za-z_]\w*))?"
                r"\s+on\s+.+$",
                seg,
            )
            if mm is None:
                return
            refs.append((mm.group(1), mm.group(2) or mm.group(1)))
        infos = self.store.tables()
        if any(t not in infos for t, _ in refs):
            return
        segments: list[tuple[str, str, int, int]] = []
        alias_cols: list[str] = []
        off = 0
        for table, alias in refs:
            pk = infos[table].pk_cols
            for i, c in enumerate(pk):
                alias_cols.append(f'"{alias}"."{c}" AS __pk{off + i}')
            segments.append((table, alias, off, len(pk)))
            off += len(pk)
        self._exec_sql = (
            f"SELECT {', '.join(alias_cols)}, {select_list}"
            f" FROM {from_clause}{tail}"
        )
        self._pk_prefix = off
        self._pk_segments = segments
        self._seg_index = [dict() for _ in segments]
        self._local_membership = _membership_is_local(select_list, tail)

    def _evaluate(self) -> tuple[list[str], dict[tuple, tuple]]:
        cur = self.store.read_conn.execute(self._exec_sql)
        cols = [d[0] for d in cur.description][self._pk_prefix:]
        out: dict[tuple, tuple] = {}
        for row in cur.fetchall():
            if self._pk_prefix:
                key = tuple(row[: self._pk_prefix])
                cells = tuple(row[self._pk_prefix:])
            else:
                key = tuple(row)
                cells = tuple(row)
            out[key] = cells
        return cols, out

    def _run_initial(self) -> None:
        self.columns, self.rows = self._evaluate()
        for key in self.rows:
            self.rowids[key] = self._next_rowid
            self._next_rowid += 1
        self._index_rebuild()

    # -- per-segment key index (join mode) -----------------------------------

    def _index_rebuild(self) -> None:
        if self._seg_index is None:
            return
        self._seg_index = [dict() for _ in self._pk_segments]
        for key in self.rows:
            self._index_add(key)

    def _index_add(self, key: tuple) -> None:
        if self._seg_index is None:
            return
        for i, (_t, _a, off, npk) in enumerate(self._pk_segments):
            self._seg_index[i].setdefault(key[off:off + npk], set()).add(key)

    def _index_discard(self, key: tuple) -> None:
        if self._seg_index is None:
            return
        for i, (_t, _a, off, npk) in enumerate(self._pk_segments):
            seg = key[off:off + npk]
            bucket = self._seg_index[i].get(seg)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._seg_index[i][seg]

    # -- shared row mutation + event emission --------------------------------

    def _upsert(self, key, cells, events) -> None:
        if key not in self.rows:
            self.rowids.setdefault(key, self._next_rowid)
            self._next_rowid += 1
            self.rows[key] = cells
            self._index_add(key)
            events.append(self._emit(CHANGE_INSERT, key, cells))
        elif self.rows[key] != cells:
            self.rows[key] = cells
            events.append(self._emit(CHANGE_UPDATE, key, cells))

    def _delete_row(self, key, events) -> None:
        events.append(self._emit(CHANGE_DELETE, key, self.rows.pop(key)))
        self.rowids.pop(key, None)
        self._index_discard(key)

    # -- change path (handle_candidates, pubsub.rs:1303-1570) ----------------

    def interested(self, changes: list[Change]) -> bool:
        return any(ch.table in self.tables for ch in changes)

    # Candidate batches above this fall back to a full re-evaluation (one
    # scan beats thousands of point lookups).
    MAX_CANDIDATES = 512
    # Fallback cost guards: an evaluation materializing more rows than
    # MAX_FALLBACK_ROWS or taking longer than FALLBACK_EVAL_BUDGET seconds
    # marks the sub "expensive"; expensive subs re-snapshot at most once
    # per FALLBACK_MIN_INTERVAL, coalescing intervening change batches
    # (the reference's candidate path never full-scans, pubsub.rs:1303-
    # 1570 — shapes it can't cover incrementally must not be allowed to
    # stall the ingest loop per batch either).
    MAX_FALLBACK_ROWS = 10_000
    FALLBACK_EVAL_BUDGET = 0.05
    FALLBACK_MIN_INTERVAL = 2.0

    def process(
        self, changes: list[Change] | None = None, stages: list | None = None
    ) -> list[QueryEventChange]:
        """Diff against the store and emit change events.

        With PK identity and a change batch, only the candidate PKs are
        re-evaluated (the reference's handle_candidates: temp PK tables +
        rewritten per-table queries, pubsub.rs:1303-1570) — O(changed rows),
        not O(result set). Other shapes (joins, aggregates, no batch) fall
        back to full snapshot diffing, rate-limited once proven expensive
        (per-batch work stays bounded; events still arrive, one interval
        late at worst).

        ``stages`` (stage profiler, sampled traces only) collects
        ``(stage, t0_mono, t1_mono)`` tuples for candidate extraction /
        SQL exec / diff / fan-out enqueue; SubsManager.match_changes
        turns them into ``sub_match_stage`` spans. ``None`` — the
        default — costs nothing.
        """
        self._touched: list[tuple] = []
        # An overdue deferred re-snapshot flushes on ANY process() call —
        # the safety net for contexts with no event loop, where
        # _schedule_flush could not arm its timer.
        overdue = self._dirty and (
            time.monotonic() - self._last_full >= self.FALLBACK_MIN_INTERVAL
        )
        if overdue:
            candidates = None
        elif stages is None:
            candidates = self._candidate_keys(changes)
        else:
            t0 = time.monotonic()
            candidates = self._candidate_keys(changes)
            stages.append(("candidate_extract", t0, time.monotonic()))
        if candidates is None:
            if self._bg_task is not None:
                # A background re-snapshot is already scanning: coalesce.
                self._dirty = True
                return []
            if (
                not overdue
                and changes is not None
                and self._full_expensive
                and time.monotonic() - self._last_full
                < self.FALLBACK_MIN_INTERVAL
            ):
                self._dirty = True
                self._schedule_flush()
                return []
            if self._full_expensive and self._start_bg_full():
                # Expensive shapes re-snapshot OFF the event loop (a
                # worker thread on its own read connection): one
                # aggregate sub over a huge table must not stall the
                # match loop for its scan (pubsub.rs's candidate path
                # never full-scans; this bounds ours per batch).
                return []
            events = self._full_pass(stages)
        else:
            events = self._diff_candidates(candidates, stages)
        if stages is None:
            self._publish(events)
        else:
            t0 = time.monotonic()
            self._publish(events)
            stages.append(("fanout_enqueue", t0, time.monotonic()))
        return events

    def _publish(self, events: list[QueryEventChange]) -> None:
        # The deque stays populated either way: a bounded in-memory cache
        # for live introspection; durable handles additionally append to
        # the sub-db log that backs ?from= replay.
        self.history.extend(events)
        if self._db is not None:
            self._persist_events(events, self._touched)
        # Ledger-armed handles track enqueued event/byte mass and the
        # listener-queue high-water mark; sizes stays None when the cost
        # plane is off, so the disabled fan-out loop is untouched.
        cost = self.cost
        sizes = None
        if cost is not None and events and self._listeners:
            sizes = [len(_cells_to_json(ev.cells)) for ev in events]
            sent = sent_bytes = 0
        for i, ev in enumerate(events):
            for q in self._listeners:
                if q in self._overflowed:
                    # Once lossy, ALWAYS lossy: enqueuing later events
                    # past a dropped one would let the eviction flush
                    # deliver post-gap events, advancing the client's
                    # resume point PAST the drop — the ?from= replay
                    # (strictly change_id > from) would then skip it
                    # forever. Every event after the first drop is
                    # counted dropped and recovered by the replay.
                    self.dropped_events += 1
                    continue
                try:
                    q.put_nowait(ev)
                    if sizes is not None:
                        sent += 1
                        sent_bytes += sizes[i]
                except asyncio.QueueFull:
                    # A laggard that can't drain its queue must not
                    # silently miss events: mark the queue lossy so the
                    # stream layer evicts it — the client reconnects
                    # from its last change id and the durable log
                    # replays exactly what was dropped.
                    self._overflowed.add(q)
                    self.dropped_events += 1
        if sizes is not None:
            cost.note_fanout(
                sent, sent_bytes,
                max(q.qsize() for q in self._listeners),
            )

    def _start_bg_full(self) -> bool:
        """Launch the full re-evaluation on a worker thread with a fresh
        read connection; the diff and emission land back on the event
        loop. Returns False when no loop is running or the store has no
        on-disk path (unit-test contexts fall back to the sync path)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False
        path = getattr(self.store, "path", None)
        if not path or path == ":memory:":
            return False
        self._dirty = False
        sql = self._exec_sql
        pk_prefix = self._pk_prefix
        gen_at_start = self._mutation_gen

        def scan():
            # The store's SQL surface (corro_pack, CRDT helpers) must be
            # registered on the scan connection too — the sub's SQL may
            # call them.
            conn = self.store.open_read_connection()
            try:
                conn.execute("PRAGMA query_only=1")
                t0 = time.monotonic()
                cur = conn.execute(sql)
                cols = [d[0] for d in cur.description][pk_prefix:]
                out: dict[tuple, tuple] = {}
                for row in cur.fetchall():
                    if pk_prefix:
                        out[tuple(row[:pk_prefix])] = tuple(row[pk_prefix:])
                    else:
                        out[tuple(row)] = tuple(row)
                return cols, out, time.monotonic() - t0
            finally:
                conn.close()

        async def run():
            try:
                cols, new_rows, cost = await asyncio.to_thread(scan)
                if self._mutation_gen != gen_at_start:
                    # Candidate diffs advanced the snapshot while the
                    # scan ran; applying the stale scan would regress
                    # rows. Drop it and go again.
                    self._dirty = True
                    return
                self.columns = cols
                self._touched = []
                events = self._diff_full(new_rows)
                self._last_full = time.monotonic()
                self._full_expensive = (
                    len(new_rows) > self.MAX_FALLBACK_ROWS
                    or cost > self.FALLBACK_EVAL_BUDGET
                )
                if self.cost is not None:
                    # The measured scan cost used to be consumed for flow
                    # control then discarded; the ledger keeps it.
                    self.cost.note_eval("fallback", len(new_rows), cost)
                    self.cost.note_diff(len(events))
                self._publish(events)
            except asyncio.CancelledError:
                raise
            except Exception:
                import logging

                logging.getLogger("corrosion.subs").warning(
                    "background re-snapshot failed for sub %s",
                    self.id, exc_info=True,
                )
                # Rate-limit retries: without advancing the stamp the
                # rescheduled flush fires immediately and a persistent
                # failure becomes a hot spin.
                self._last_full = time.monotonic()
                self._dirty = True
            finally:
                self._bg_task = None
                if self._dirty:
                    self._schedule_flush()

        self._bg_task = loop.create_task(run())
        return True

    def _full_pass(self, stages: list | None = None) -> list[QueryEventChange]:
        """Full re-evaluation + snapshot diff, tracking its own cost."""
        t0 = time.monotonic()
        cols, new_rows = self._evaluate()
        t_eval = time.monotonic()
        self.columns = cols
        events = self._diff_full(new_rows)
        now = time.monotonic()
        if stages is not None:
            stages.append(("sql_exec", t0, t_eval))
            stages.append(("diff", t_eval, now))
        self._last_full = now
        self._full_expensive = (
            len(new_rows) > self.MAX_FALLBACK_ROWS
            or (now - t0) > self.FALLBACK_EVAL_BUDGET
        )
        if self.cost is not None:
            self.cost.note_eval("fallback", len(new_rows), now - t0)
            self.cost.note_diff(len(events))
        self._dirty = False
        return events

    def _schedule_flush(self) -> None:
        """Arm a one-shot timer so a deferred re-snapshot happens even if
        no further change batch arrives (outside an event loop — unit-test
        contexts — the next process() call flushes instead)."""
        if self._flush_handle is not None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        delay = max(
            0.0,
            self.FALLBACK_MIN_INTERVAL
            - (time.monotonic() - self._last_full),
        )
        self._flush_handle = loop.call_later(delay, self._flush_deferred)

    def _flush_deferred(self) -> None:
        self._flush_handle = None
        if self._dirty:
            self.process(None)

    def _candidate_keys(self, changes):
        """Distinct changed identity keys, or None when incremental
        evaluation does not apply (filter_matchable_change's role). Join
        mode returns ("join", {table: {pk_tuple}}) for the per-segment
        diff."""
        if changes is None or self._pk_prefix == 0 or not self._local_membership:
            return None
        if self._pk_segments is not None:
            seg_tables = {t for t, _, _, _ in self._pk_segments}
            by_table: dict[str, dict[tuple, None]] = {}
            for ch in changes:
                if ch.table not in seg_tables:
                    if ch.table in self.tables:
                        return None  # untracked dep changed: full pass
                    continue
                try:
                    by_table.setdefault(ch.table, {})[
                        unpack_columns(ch.pk)
                    ] = None
                except Exception:
                    return None
            if sum(len(v) for v in by_table.values()) > self.MAX_CANDIDATES:
                return None
            return ("join", {t: set(v) for t, v in by_table.items()})
        keys: dict[tuple, None] = {}
        for ch in changes:
            if ch.table != self._pk_table:
                if ch.table in self.tables:
                    return None  # another dep table changed: full pass
                continue
            try:
                keys[unpack_columns(ch.pk)] = None
            except Exception:
                return None
        if len(keys) > self.MAX_CANDIDATES:
            return None
        return list(keys)

    def _diff_candidates(self, keys, stages: list | None = None) -> list[QueryEventChange]:
        # Any candidate-path snapshot mutation invalidates an in-flight
        # background re-snapshot (its scan predates this change).
        self._mutation_gen += 1
        if isinstance(keys, tuple) and keys[0] == "join":
            return self._diff_join(keys[1], stages)
        if not keys:
            return []
        prof = self.cost is not None or stages is not None
        t0 = time.monotonic() if prof else 0.0
        npk = self._pk_prefix
        row_vals = ", ".join(
            "(" + ", ".join("?" for _ in range(npk)) + ")" for _ in keys
        )
        # The injected pk prefix is aliased __pk0..__pkN-1, addressable
        # through the wrapper for the candidate row-value filter.
        where = "(" + ", ".join(
            f'"__q"."__pk{i}"' for i in range(npk)
        ) + ") IN (VALUES " + row_vals + ")"
        sql = (
            "SELECT * FROM (" + self._exec_sql + ") AS __q WHERE " + where
        )
        params = [v for key in keys for v in key]
        cur = self.store.read_conn.execute(sql, params)
        fresh = {
            tuple(row[:npk]): tuple(row[npk:]) for row in cur.fetchall()
        }
        t1 = time.monotonic() if prof else 0.0
        events: list[QueryEventChange] = []
        for key in keys:
            cells = fresh.get(key)
            if cells is None:
                if key in self.rows:
                    self._delete_row(key, events)
            else:
                self._upsert(key, cells, events)
        if prof:
            t2 = time.monotonic()
            if stages is not None:
                stages.append(("sql_exec", t0, t1))
                stages.append(("diff", t1, t2))
            if self.cost is not None:
                self.cost.note_eval("candidate", len(keys), t1 - t0)
                self.cost.note_diff(len(events))
        return events

    def _diff_join(self, by_table: dict, stages: list | None = None) -> list[QueryEventChange]:
        """Candidate diff for join subscriptions (handle_candidates over
        multi-table PK temp tables, pubsub.rs:1303-1570): re-evaluate only
        result rows whose changed-table PK segment matches a candidate —
        a t2 update touches exactly the join rows built from that t2 row,
        not the whole result set."""
        if not by_table:
            return []
        prof = self.cost is not None or stages is not None
        t0 = time.monotonic() if prof else 0.0
        conds: list[str] = []
        params: list = []
        for table, _alias, off, npk in self._pk_segments:
            keys = by_table.get(table)
            if not keys:
                continue
            cols = ", ".join(f'"__q"."__pk{off + i}"' for i in range(npk))
            row_vals = ", ".join(
                "(" + ", ".join("?" for _ in range(npk)) + ")" for _ in keys
            )
            conds.append(f"({cols}) IN (VALUES {row_vals})")
            params.extend(v for key in keys for v in key)
        sql = (
            "SELECT * FROM (" + self._exec_sql + ") AS __q WHERE "
            + " OR ".join(conds)
        )
        npk_total = self._pk_prefix
        cur = self.store.read_conn.execute(sql, params)
        fresh = {
            tuple(row[:npk_total]): tuple(row[npk_total:])
            for row in cur.fetchall()
        }
        t1 = time.monotonic() if prof else 0.0
        # Affected existing rows via the per-segment index: O(candidates),
        # never a scan of the materialized result set.
        affected: set[tuple] = set()
        for i, (table, _alias, _off, _npk) in enumerate(self._pk_segments):
            for seg in by_table.get(table, ()):
                affected |= self._seg_index[i].get(seg, set())
        events: list[QueryEventChange] = []
        for key, cells in fresh.items():
            self._upsert(key, cells, events)
        for key in [k for k in affected if k not in fresh and k in self.rows]:
            self._delete_row(key, events)
        if prof:
            t2 = time.monotonic()
            if stages is not None:
                stages.append(("sql_exec", t0, t1))
                stages.append(("diff", t1, t2))
            if self.cost is not None:
                n_keys = sum(len(v) for v in by_table.values())
                self.cost.note_eval("candidate", n_keys, t1 - t0)
                self.cost.note_diff(len(events))
        return events

    def _diff_full(self, new_rows) -> list[QueryEventChange]:
        events: list[QueryEventChange] = []
        for key, cells in new_rows.items():
            if key not in self.rows:
                self.rowids.setdefault(key, self._next_rowid)
                self._next_rowid += 1
                events.append(self._emit(CHANGE_INSERT, key, cells))
            elif self.rows[key] != cells:
                events.append(self._emit(CHANGE_UPDATE, key, cells))
        for key, cells in self.rows.items():
            if key not in new_rows:
                events.append(self._emit(CHANGE_DELETE, key, cells))
                self.rowids.pop(key, None)
        self.rows = new_rows
        self._index_rebuild()
        return events

    def _emit(self, kind, key, cells) -> QueryEventChange:
        self.change_id += 1
        if self._db is not None:
            self._touched.append(key)
        return QueryEventChange(
            kind=kind,
            rowid=self.rowids.get(key, 0),
            cells=list(cells),
            change_id=self.change_id,
        )

    # -- listener fan-out ----------------------------------------------------

    def attach(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._listeners.append(q)
        return q

    def detach(self, q: asyncio.Queue) -> None:
        if q in self._listeners:
            self._listeners.remove(q)
        self._overflowed.discard(q)

    def lossy(self, q: asyncio.Queue) -> bool:
        """True once ``q`` has dropped an event (queue overflow): the
        stream serving it must end so the client resumes via ?from=."""
        return q in self._overflowed

    def backlog(self, from_change: int | None = None, skip_rows: bool = False):
        """Initial events for a new listener: either a snapshot (columns +
        rows + eoq) or catch-up from a change id (catch_up_sub,
        api/public/pubsub.rs:36-94)."""
        events: list = [{"sub_id": self.id}]
        replay: list[QueryEventChange] | None = None
        if from_change is not None:
            if self._db is not None:
                # Durable log: replay is valid iff nothing after
                # ``from_change`` has been pruned (the log retains
                # MAX_DURABLE_HISTORY rows and survives restarts).
                (oldest,) = self._db.execute(
                    "SELECT min(change_id) FROM changes"
                ).fetchone()
                if (
                    from_change >= self.change_id
                    or (oldest is not None and from_change + 1 >= oldest)
                ):
                    replay = [
                        QueryEventChange(
                            kind=kind, rowid=rowid,
                            cells=list(_cells_from_json(cells_s)),
                            change_id=cid,
                        )
                        for cid, kind, rowid, cells_s in self._db.execute(
                            "SELECT change_id, kind, rowid_, cells"
                            " FROM changes WHERE change_id > ?"
                            " ORDER BY change_id",
                            (from_change,),
                        ).fetchall()
                    ]
            else:
                oldest = self.history[0].change_id if self.history else None
                if oldest is not None and from_change + 1 >= oldest:
                    replay = [
                        ev for ev in self.history
                        if ev.change_id > from_change
                    ]
                elif oldest is None and from_change >= self.change_id:
                    replay = []
        if replay is None:
            # History truncated past the resume point (or no resume asked):
            # snapshot restart.
            events.append(QueryEventColumns(list(self.columns)))
            if not skip_rows:
                for key, cells in self.rows.items():
                    events.append(
                        QueryEventRow(self.rowids[key], list(cells))
                    )
            events.append(
                QueryEventEndOfQuery(time=time.time(), change_id=self.change_id)
            )
        else:
            # Exclusive: replay events AFTER the given change id
            # (doc/api/subscriptions.md resume semantics).
            events.append(QueryEventColumns(list(self.columns)))
            events.extend(replay)
            if self.cost is not None:
                self.cost.note_replay(len(replay))
        return [_WireEvent(e) if isinstance(e, dict) else e for e in events]


class _WireEvent:
    """Raw dict frames (sub_id header) alongside QueryEvents."""

    def __init__(self, obj: dict):
        self._obj = obj

    def to_json_obj(self) -> dict:
        return self._obj


class SubsManager:
    """Query-text-keyed matcher registry (SubsManager, pubsub.rs:77-214).

    Subscriptions persist to ``__corro_subs`` (id, sql, change_id watermark)
    and are recreated at boot (agent.rs:373-419 + Matcher::restore,
    pubsub.rs:735-771). Each sub's snapshot + change history lives in its
    own SQLite file under ``<data_dir>/subs/`` (the reference's per-sub
    sub-db), so ``?from=`` replays across restarts.
    """

    def __init__(self, store: Store, db_dir: str | None = None) -> None:
        self.store = store
        if db_dir is None:
            db_dir = os.path.join(
                os.path.dirname(os.path.abspath(store.path)), "subs"
            )
        self._db_dir = db_dir
        self._by_sql: dict[str, MatcherHandle] = {}
        self._by_id: dict[str, MatcherHandle] = {}
        # Causal-trace hook: set by the agent when write tracing is on.
        # match_changes then emits a `sub_fanout` child span inside each
        # traced write (ambient span present); unwired — the default —
        # the fan-out path costs one attribute check and nothing else.
        self.tracer = None
        # Cost plane (enable_costs): disarmed by default — handles carry
        # cost=None and no metric handles exist.
        self.costs_enabled = False
        self._cost_hist = None
        self._cost_fb = None
        self._cost_gauge = None
        self._ensure_table()

    def enable_costs(self, registry=None) -> None:
        """Arm the per-subscription cost ledger on every current and
        future handle. With a ``MetricsRegistry``, also publish the
        serving-cost aggregates — the per-sub fallback counter rides the
        registry's ``max_labelsets`` cap, so ephemeral-subscription
        storms fold into the ``other`` bucket instead of exploding
        /metrics cardinality."""
        self.costs_enabled = True
        if registry is not None:
            self._cost_hist = registry.histogram(
                "corro_subs_eval_seconds",
                "Matcher evaluation wall seconds (kind=candidate|fallback)",
            )
            self._cost_fb = registry.counter(
                "corro_subs_fallback_total",
                "Full-snapshot fallback evaluations (per-sub label, "
                "cardinality-capped)",
            )
            self._cost_gauge = registry.gauge(
                "corro_subs_fallback_bound",
                "Subscriptions the query-plan classifier marks "
                "fallback-bound (cannot be served incrementally)",
            )
        for h in self._by_id.values():
            h.enable_cost(self._cost_hist, self._cost_fb)
        self._refresh_fallback_gauge()

    def _refresh_fallback_gauge(self) -> None:
        if self._cost_gauge is not None:
            self._cost_gauge.set(
                sum(
                    1 for h in self._by_id.values()
                    if h.plan["fallback_bound"]
                )
            )

    def cost_snapshot(self, top: int | None = None) -> dict:
        """Live ledger snapshot (the `/v1/subs/costs` body and the
        ``corro-sub-cost/1`` artifact payload): one record per handle —
        plan record always, counters when the cost plane is armed —
        sorted by total eval seconds descending, plus ledger-wide
        totals."""
        subs = []
        totals = {
            "eval_seconds_total": 0.0, "eval_seconds_fallback": 0.0,
            "fallback_evals": 0, "candidate_evals": 0,
            "rows_scanned": 0, "fanout_events": 0, "fanout_bytes": 0,
            "replay_rows": 0, "fallback_bound_subs": 0,
        }
        for h in self._by_id.values():
            rec = {
                "sub_id": h.id,
                "sql": h.sql,
                "plan": dict(h.plan),
                "change_id": h.change_id,
                "listeners": len(h._listeners),
                "dropped_events": h.dropped_events,
            }
            if h.plan["fallback_bound"]:
                totals["fallback_bound_subs"] += 1
            if h.cost is not None:
                c = h.cost.snapshot()
                rec["cost"] = c
                totals["eval_seconds_total"] += c["eval_seconds_total"]
                totals["eval_seconds_fallback"] += c["eval_seconds_fallback"]
                for k in (
                    "fallback_evals", "candidate_evals", "rows_scanned",
                    "fanout_events", "fanout_bytes", "replay_rows",
                ):
                    totals[k] += c[k]
            subs.append(rec)
        subs.sort(
            key=lambda r: r.get("cost", {}).get("eval_seconds_total", 0.0),
            reverse=True,
        )
        if top is not None:
            subs = subs[:top]
        totals["fallback_share"] = (
            totals["eval_seconds_fallback"] / totals["eval_seconds_total"]
            if totals["eval_seconds_total"] > 0 else 0.0
        )
        return {
            "kind": "corro-sub-cost",
            "version": 1,
            "enabled": self.costs_enabled,
            "subs_total": len(self._by_id),
            "totals": totals,
            "subs": subs,
        }

    def _ensure_table(self) -> None:
        self.store.conn.execute(
            "CREATE TABLE IF NOT EXISTS __corro_subs ("
            " id TEXT PRIMARY KEY, sql TEXT NOT NULL,"
            " change_id INTEGER NOT NULL DEFAULT 0) WITHOUT ROWID"
        )

    def subscribe(self, sql: str) -> MatcherHandle:
        key = normalize_sql(sql)
        handle = self._by_sql.get(key)
        if handle is None:
            handle = MatcherHandle(self.store, sql, db_dir=self._db_dir)
            self._register(key, handle)
            with self.store._wlock("subs_persist"):
                self.store.conn.execute(
                    "INSERT OR REPLACE INTO __corro_subs VALUES (?, ?, ?)",
                    (handle.id, sql, handle.change_id),
                )
        return handle

    def _register(self, key: str, handle: MatcherHandle) -> None:
        self._by_sql[key] = handle
        self._by_id[handle.id] = handle
        if self.costs_enabled:
            handle.enable_cost(self._cost_hist, self._cost_fb)
            self._refresh_fallback_gauge()

    def restore(self) -> list[str]:
        """Recreate persisted subscriptions; returns restored ids. A query
        that no longer parses (schema changed under it) is dropped, like
        the reference pruning dead sub dbs at boot; transient failures
        (e.g. a locked database) keep the row so the next boot retries."""
        restored = []
        for sub_id, sql, change_id in self.store.conn.execute(
            "SELECT id, sql, change_id FROM __corro_subs"
        ).fetchall():
            if sub_id in self._by_id:
                continue
            try:
                handle = MatcherHandle(
                    self.store, sql, sub_id=sub_id, start_change_id=change_id,
                    db_dir=self._db_dir,
                )
            except Exception as e:
                msg = str(e).lower()
                invalid = isinstance(e, ValueError) or (
                    isinstance(e, sqlite3.Error)
                    and ("no such" in msg or "syntax error" in msg)
                )
                if invalid:
                    with self.store._wlock("subs_prune"):
                        self.store.conn.execute(
                            "DELETE FROM __corro_subs WHERE id = ?", (sub_id,)
                        )
                continue
            self._register(normalize_sql(sql), handle)
            restored.append(sub_id)
        return restored

    def get(self, sub_id: str) -> MatcherHandle | None:
        return self._by_id.get(sub_id)

    def match_changes(
        self, changes: list[Change]
    ) -> list[tuple[str, int]]:
        """filter_matchable_change + candidate dispatch (pubsub.rs:162-214,
        441). Returns the (sub_id, change_id) watermarks that advanced;
        callers persist them via ``persist_watermarks_sync`` — on the pool
        writer when one exists, so the event loop never waits on the store
        write lock."""
        span = None
        stages: list | None = None
        if self.tracer is not None:
            from corrosion_tpu.utils import tracing

            # Only inside an already-traced (and sampled) write: a bare
            # match call must not mint a noise root trace.
            if tracing.current_span() is not None:
                span = self.tracer.span("sub_fanout").__enter__()
                # Stage profiler rides the same deterministic sampling:
                # every handle appends (stage, t0, t1) tuples and the
                # aggregate becomes one sub_match_stage span per stage,
                # children of sub_fanout — joinable in obs timeline.
                stages = []
        dirty = []
        try:
            for handle in self._by_id.values():
                if handle.interested(changes) and handle.process(
                    changes, stages
                ):
                    dirty.append((handle.id, handle.change_id))
        finally:
            if span is not None:
                if stages:
                    self._emit_stage_spans(stages)
                span.set_attr("subs_matched", len(dirty))
                span.set_attr("subs_total", len(self._by_id))
                span.__exit__(None, None, None)
        return dirty

    def _emit_stage_spans(self, stages: list) -> None:
        """Fold per-handle stage timings into one span per stage name
        (candidate_extract / sql_exec / diff / fanout_enqueue). The span
        carries the stage's total duration and call count; its start is
        the first occurrence, converted from the monotonic clock to the
        tracer's epoch-ns domain."""
        base_ns = time.time_ns() - int(time.monotonic() * 1e9)
        agg: dict[str, tuple[float, float, int]] = {}
        for name, t0, t1 in stages:
            first, total, n = agg.get(name, (t0, 0.0, 0))
            agg[name] = (min(first, t0), total + (t1 - t0), n + 1)
        for name, (first, total, n) in agg.items():
            sp = self.tracer.span("sub_match_stage", stage=name, calls=n)
            sp.start_ns = base_ns + int(first * 1e9)
            sp.end_ns = sp.start_ns + int(total * 1e9)
            self.tracer._record(sp)

    def persist_watermarks_sync(self, dirty: list[tuple[str, int]]) -> None:
        if not dirty:
            return
        with self.store._wlock("subs_watermark"):
            self.store.conn.executemany(
                "UPDATE __corro_subs SET change_id = ? WHERE id = ?",
                [(cid, sid) for sid, cid in dirty],
            )

    def reinit_after_restore(self) -> None:
        """After an online restore the table reflects the BACKUP's origin
        (or is absent — backups strip it as node-local): recreate it and
        re-persist this node's live subscriptions + watermarks, then emit
        the diff between each sub's pre-restore snapshot and the restored
        data as ordinary change events (subscribers keep their streams)."""
        self._ensure_table()
        with self.store._wlock("subs_reinit"):
            self.store.conn.execute("DELETE FROM __corro_subs")
            self.store.conn.executemany(
                "INSERT OR REPLACE INTO __corro_subs VALUES (?, ?, ?)",
                [
                    (h.id, h.sql, h.change_id)
                    for h in self._by_id.values()
                ],
            )
        for h in self._by_id.values():
            h.process(None)

    def close(self) -> None:
        for h in self._by_id.values():
            h.close()
