"""Subscription engine — the Matcher (corro-types/src/pubsub.rs) rebuilt.

The reference's Matcher (pubsub.rs:510-1570, its largest component) parses a
SELECT, tracks which tables feed it, and on each batch of changes
incrementally re-evaluates the query, diffing against the previous result to
emit insert/update/delete QueryEvents with monotonically increasing change
ids; subscribers can catch up from any change id (`?from=`).

This implementation keeps the same contract with a different mechanism
suited to the host store:

- table dependencies are discovered with SQLite's authorizer hook during
  prepare (instead of a SQL AST walk with sqlite3-parser);
- row identity: for plain single-table selects the table's primary key is
  injected into the select list (the reference's PK-alias rewrite,
  pubsub.rs:566-661); other shapes (joins/aggregates) fall back to
  whole-row identity, which downgrades updates to delete+insert pairs but
  keeps the stream correct;
- the result snapshot and the change history (`query` and `changes` tables
  of the reference's per-sub SQLite db, pubsub.rs:806-841) live in memory,
  with the same change-id semantics.
"""

from __future__ import annotations

import asyncio
import sqlite3
import time
import uuid
from collections import deque

from corrosion_tpu.agent.store import Store
from corrosion_tpu.core.values import (
    CHANGE_DELETE,
    CHANGE_INSERT,
    CHANGE_UPDATE,
    Change,
    QueryEventChange,
    QueryEventColumns,
    QueryEventEndOfQuery,
    QueryEventRow,
    unpack_columns,
)

MAX_CHANGE_HISTORY = 8192


def normalize_sql(sql: str) -> str:
    """Whitespace/case-insensitive reuse key (pubsub.rs normalize_sql:2089)."""
    return " ".join(sql.strip().rstrip(";").split()).lower()


_WRITE_ACTIONS = {
    sqlite3.SQLITE_INSERT, sqlite3.SQLITE_UPDATE, sqlite3.SQLITE_DELETE,
    sqlite3.SQLITE_CREATE_TABLE, sqlite3.SQLITE_DROP_TABLE,
    sqlite3.SQLITE_ALTER_TABLE, sqlite3.SQLITE_CREATE_INDEX,
    sqlite3.SQLITE_DROP_INDEX, sqlite3.SQLITE_PRAGMA,
}


def _referenced_tables(conn: sqlite3.Connection, sql: str) -> set[str]:
    """Tables a SELECT reads, via the authorizer hook during prepare.
    Rejects anything that would write — subscriptions are SELECT-only
    (the Matcher parses a SELECT, pubsub.rs:510-712)."""
    seen: set[str] = set()
    writes: list[int] = []

    def auth(action, arg1, arg2, dbname, trigger):
        if action == sqlite3.SQLITE_READ and arg1:
            seen.add(arg1)
        if action in _WRITE_ACTIONS:
            writes.append(action)
            return sqlite3.SQLITE_DENY
        return sqlite3.SQLITE_OK

    conn.set_authorizer(auth)
    try:
        conn.execute(f"EXPLAIN {sql}")
    except sqlite3.DatabaseError as e:
        if writes:
            raise ValueError("subscriptions must be SELECT statements") from e
        raise
    finally:
        conn.set_authorizer(None)
    if writes:
        raise ValueError("subscriptions must be SELECT statements")
    return {t for t in seen if not t.startswith("__")}


class MatcherHandle:
    """One materialized subscription; fan-out to any number of listeners
    (the broadcast::Sender per sub, api/public/pubsub.rs:117-180)."""

    def __init__(
        self, store: Store, sql: str, sub_id: str | None = None,
        start_change_id: int = 0,
    ) -> None:
        self.id = sub_id or uuid.uuid4().hex
        self.sql = sql
        self.store = store
        self.tables = _referenced_tables(store.read_conn, sql)
        if not self.tables:
            raise ValueError("query reads no user tables")
        self._pk_prefix = 0
        self._pk_table: str | None = None
        self._local_membership = False
        self._exec_sql = sql
        self._maybe_inject_pks()
        self.columns: list[str] = []
        self.rows: dict[tuple, tuple] = {}  # identity key -> cells
        self.rowids: dict[tuple, int] = {}
        self._next_rowid = 1
        # Restored subs continue numbering where the persisted watermark
        # left off (Matcher::restore, pubsub.rs:735-771).
        self.change_id = start_change_id
        self.history: deque[QueryEventChange] = deque(maxlen=MAX_CHANGE_HISTORY)
        self._listeners: list[asyncio.Queue] = []
        self._run_initial()

    # -- query shape ---------------------------------------------------------

    def _maybe_inject_pks(self) -> None:
        """For `SELECT ... FROM <one crr table> ...`, prepend the table's PK
        columns as identity columns (hidden from emitted cells)."""
        import re

        m = re.match(
            r"(?is)^\s*select\s+(?!.*\bjoin\b)(.+?)\s+from\s+([A-Za-z_][\w]*)"
            r"(\s+(?:where|order|group|limit)\b.*)?\s*;?\s*$",
            self.sql,
        )
        if not m:
            return
        table = m.group(2)
        info = self.store.tables().get(table)
        if info is None:
            return
        select_list = m.group(1)
        tail = (m.group(3) or "").rstrip().rstrip(";")
        if re.search(r"(?i)\b(count|sum|avg|min|max|group_concat)\s*\(", select_list):
            return
        if re.match(r"(?i)\s*distinct\b", select_list):
            # Prepending PK columns to a DISTINCT list changes its meaning.
            return
        pk_cols = ", ".join(
            f'"{table}"."{c}" AS __pk{i}'
            for i, c in enumerate(info.pk_cols)
        )
        self._exec_sql = (
            f'SELECT {pk_cols}, {select_list} FROM "{table}"{tail}'
        )
        self._pk_prefix = len(info.pk_cols)
        self._pk_table = table
        # Candidate-only re-evaluation is sound only when a row's result
        # VALUES and membership depend on that row alone: LIMIT windows,
        # GROUP BY, and subqueries make membership global (a change to one
        # PK can evict another row), and window functions / scalar
        # subqueries in the select list make unchanged rows' values change
        # — only a full diff notices either.
        self._local_membership = not re.search(
            r"(?i)\b(limit|group)\b|\(\s*select\b", tail
        ) and not re.search(
            r"(?i)\bover\s*\(|\(\s*select\b", select_list
        )

    def _evaluate(self) -> tuple[list[str], dict[tuple, tuple]]:
        cur = self.store.read_conn.execute(self._exec_sql)
        cols = [d[0] for d in cur.description][self._pk_prefix:]
        out: dict[tuple, tuple] = {}
        for row in cur.fetchall():
            if self._pk_prefix:
                key = tuple(row[: self._pk_prefix])
                cells = tuple(row[self._pk_prefix:])
            else:
                key = tuple(row)
                cells = tuple(row)
            out[key] = cells
        return cols, out

    def _run_initial(self) -> None:
        self.columns, self.rows = self._evaluate()
        for key in self.rows:
            self.rowids[key] = self._next_rowid
            self._next_rowid += 1

    # -- change path (handle_candidates, pubsub.rs:1303-1570) ----------------

    def interested(self, changes: list[Change]) -> bool:
        return any(ch.table in self.tables for ch in changes)

    # Candidate batches above this fall back to a full re-evaluation (one
    # scan beats thousands of point lookups).
    MAX_CANDIDATES = 512

    def process(
        self, changes: list[Change] | None = None
    ) -> list[QueryEventChange]:
        """Diff against the store and emit change events.

        With PK identity and a change batch, only the candidate PKs are
        re-evaluated (the reference's handle_candidates: temp PK tables +
        rewritten per-table queries, pubsub.rs:1303-1570) — O(changed rows),
        not O(result set). Other shapes (joins, aggregates, no batch) fall
        back to full snapshot diffing.
        """
        candidates = self._candidate_keys(changes)
        if candidates is None:
            _, new_rows = self._evaluate()
            events = self._diff_full(new_rows)
        else:
            events = self._diff_candidates(candidates)
        for ev in events:
            self.history.append(ev)
            for q in self._listeners:
                try:
                    q.put_nowait(ev)
                except asyncio.QueueFull:
                    pass
        return events

    def _candidate_keys(self, changes) -> list[tuple] | None:
        """Distinct changed identity keys, or None when incremental
        evaluation does not apply (filter_matchable_change's role)."""
        if changes is None or self._pk_prefix == 0 or not self._local_membership:
            return None
        keys: dict[tuple, None] = {}
        for ch in changes:
            if ch.table != self._pk_table:
                if ch.table in self.tables:
                    return None  # another dep table changed: full pass
                continue
            try:
                keys[unpack_columns(ch.pk)] = None
            except Exception:
                return None
        if len(keys) > self.MAX_CANDIDATES:
            return None
        return list(keys)

    def _diff_candidates(self, keys: list[tuple]) -> list[QueryEventChange]:
        if not keys:
            return []
        npk = self._pk_prefix
        row_vals = ", ".join(
            "(" + ", ".join("?" for _ in range(npk)) + ")" for _ in keys
        )
        # The injected pk prefix is aliased __pk0..__pkN-1, addressable
        # through the wrapper for the candidate row-value filter.
        where = "(" + ", ".join(
            f'"__q"."__pk{i}"' for i in range(npk)
        ) + ") IN (VALUES " + row_vals + ")"
        sql = (
            "SELECT * FROM (" + self._exec_sql + ") AS __q WHERE " + where
        )
        params = [v for key in keys for v in key]
        cur = self.store.read_conn.execute(sql, params)
        fresh = {
            tuple(row[:npk]): tuple(row[npk:]) for row in cur.fetchall()
        }
        events: list[QueryEventChange] = []
        for key in keys:
            cells = fresh.get(key)
            if cells is None:
                if key in self.rows:
                    events.append(
                        self._emit(CHANGE_DELETE, key, self.rows.pop(key))
                    )
                    self.rowids.pop(key, None)
            elif key not in self.rows:
                self.rowids.setdefault(key, self._next_rowid)
                self._next_rowid += 1
                self.rows[key] = cells
                events.append(self._emit(CHANGE_INSERT, key, cells))
            elif self.rows[key] != cells:
                self.rows[key] = cells
                events.append(self._emit(CHANGE_UPDATE, key, cells))
        return events

    def _diff_full(self, new_rows) -> list[QueryEventChange]:
        events: list[QueryEventChange] = []
        for key, cells in new_rows.items():
            if key not in self.rows:
                self.rowids.setdefault(key, self._next_rowid)
                self._next_rowid += 1
                events.append(self._emit(CHANGE_INSERT, key, cells))
            elif self.rows[key] != cells:
                events.append(self._emit(CHANGE_UPDATE, key, cells))
        for key, cells in self.rows.items():
            if key not in new_rows:
                events.append(self._emit(CHANGE_DELETE, key, cells))
                self.rowids.pop(key, None)
        self.rows = new_rows
        return events

    def _emit(self, kind, key, cells) -> QueryEventChange:
        self.change_id += 1
        return QueryEventChange(
            kind=kind,
            rowid=self.rowids.get(key, 0),
            cells=list(cells),
            change_id=self.change_id,
        )

    # -- listener fan-out ----------------------------------------------------

    def attach(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._listeners.append(q)
        return q

    def detach(self, q: asyncio.Queue) -> None:
        if q in self._listeners:
            self._listeners.remove(q)

    def backlog(self, from_change: int | None = None, skip_rows: bool = False):
        """Initial events for a new listener: either a snapshot (columns +
        rows + eoq) or catch-up from a change id (catch_up_sub,
        api/public/pubsub.rs:36-94)."""
        events: list = [{"sub_id": self.id}]
        if from_change is not None:
            oldest = self.history[0].change_id if self.history else None
            if oldest is not None and from_change + 1 < oldest:
                # History truncated: restart with a snapshot.
                from_change = None
            elif oldest is None and from_change < self.change_id:
                # No history but the watermark moved past the resume point
                # (e.g. restored after a restart): snapshot restart.
                from_change = None
        if from_change is None:
            events.append(QueryEventColumns(list(self.columns)))
            if not skip_rows:
                for key, cells in self.rows.items():
                    events.append(
                        QueryEventRow(self.rowids[key], list(cells))
                    )
            events.append(
                QueryEventEndOfQuery(time=time.time(), change_id=self.change_id)
            )
        else:
            # Exclusive: replay events AFTER the given change id
            # (doc/api/subscriptions.md resume semantics).
            events.append(QueryEventColumns(list(self.columns)))
            for ev in self.history:
                if ev.change_id > from_change:
                    events.append(ev)
        return [_WireEvent(e) if isinstance(e, dict) else e for e in events]


class _WireEvent:
    """Raw dict frames (sub_id header) alongside QueryEvents."""

    def __init__(self, obj: dict):
        self._obj = obj

    def to_json_obj(self) -> dict:
        return self._obj


class SubsManager:
    """Query-text-keyed matcher registry (SubsManager, pubsub.rs:77-214).

    Subscriptions persist to ``__corro_subs`` (id, sql, change_id watermark)
    and are recreated at boot (agent.rs:373-419 + Matcher::restore,
    pubsub.rs:735-771). Event history is in-memory only; a subscriber
    resuming past the restored watermark gets a snapshot restart.
    """

    def __init__(self, store: Store) -> None:
        self.store = store
        self._by_sql: dict[str, MatcherHandle] = {}
        self._by_id: dict[str, MatcherHandle] = {}
        self._ensure_table()

    def _ensure_table(self) -> None:
        self.store.conn.execute(
            "CREATE TABLE IF NOT EXISTS __corro_subs ("
            " id TEXT PRIMARY KEY, sql TEXT NOT NULL,"
            " change_id INTEGER NOT NULL DEFAULT 0) WITHOUT ROWID"
        )

    def subscribe(self, sql: str) -> MatcherHandle:
        key = normalize_sql(sql)
        handle = self._by_sql.get(key)
        if handle is None:
            handle = MatcherHandle(self.store, sql)
            self._register(key, handle)
            with self.store._wlock("subs_persist"):
                self.store.conn.execute(
                    "INSERT OR REPLACE INTO __corro_subs VALUES (?, ?, ?)",
                    (handle.id, sql, handle.change_id),
                )
        return handle

    def _register(self, key: str, handle: MatcherHandle) -> None:
        self._by_sql[key] = handle
        self._by_id[handle.id] = handle

    def restore(self) -> list[str]:
        """Recreate persisted subscriptions; returns restored ids. A query
        that no longer parses (schema changed under it) is dropped, like
        the reference pruning dead sub dbs at boot; transient failures
        (e.g. a locked database) keep the row so the next boot retries."""
        restored = []
        for sub_id, sql, change_id in self.store.conn.execute(
            "SELECT id, sql, change_id FROM __corro_subs"
        ).fetchall():
            if sub_id in self._by_id:
                continue
            try:
                handle = MatcherHandle(
                    self.store, sql, sub_id=sub_id, start_change_id=change_id
                )
            except Exception as e:
                msg = str(e).lower()
                invalid = isinstance(e, ValueError) or (
                    isinstance(e, sqlite3.Error)
                    and ("no such" in msg or "syntax error" in msg)
                )
                if invalid:
                    with self.store._wlock("subs_prune"):
                        self.store.conn.execute(
                            "DELETE FROM __corro_subs WHERE id = ?", (sub_id,)
                        )
                continue
            self._register(normalize_sql(sql), handle)
            restored.append(sub_id)
        return restored

    def get(self, sub_id: str) -> MatcherHandle | None:
        return self._by_id.get(sub_id)

    def match_changes(
        self, changes: list[Change]
    ) -> list[tuple[str, int]]:
        """filter_matchable_change + candidate dispatch (pubsub.rs:162-214,
        441). Returns the (sub_id, change_id) watermarks that advanced;
        callers persist them via ``persist_watermarks_sync`` — on the pool
        writer when one exists, so the event loop never waits on the store
        write lock."""
        dirty = []
        for handle in self._by_id.values():
            if handle.interested(changes) and handle.process(changes):
                dirty.append((handle.id, handle.change_id))
        return dirty

    def persist_watermarks_sync(self, dirty: list[tuple[str, int]]) -> None:
        if not dirty:
            return
        with self.store._wlock("subs_watermark"):
            self.store.conn.executemany(
                "UPDATE __corro_subs SET change_id = ? WHERE id = ?",
                [(cid, sid) for sid, cid in dirty],
            )

    def reinit_after_restore(self) -> None:
        """After an online restore the table reflects the BACKUP's origin
        (or is absent — backups strip it as node-local): recreate it and
        re-persist this node's live subscriptions + watermarks."""
        self._ensure_table()
        with self.store._wlock("subs_reinit"):
            self.store.conn.execute("DELETE FROM __corro_subs")
            self.store.conn.executemany(
                "INSERT OR REPLACE INTO __corro_subs VALUES (?, ?, ?)",
                [
                    (h.id, h.sql, h.change_id)
                    for h in self._by_id.values()
                ],
            )
