"""Admin RPC over a unix socket.

The corro-admin analogue (corro-admin/src/lib.rs:35-243): length-delimited
JSON command frames on a UDS. Commands: ping, sync (generate), locks
(top-N), cluster (membership states), reload (re-apply schema paths).
Responses stream as JSON frames ending with {"done": true}.
"""

from __future__ import annotations

import asyncio
import os
from typing import TYPE_CHECKING

from corrosion_tpu.agent.agent import _state_to_wire
from corrosion_tpu.agent.transport import Session, encode_frame, read_frame
from corrosion_tpu.core.bookkeeping import generate_sync

if TYPE_CHECKING:
    from corrosion_tpu.agent.agent import Agent


async def start_admin(agent: "Agent", uds_path: str) -> asyncio.AbstractServer:
    if os.path.exists(uds_path):
        os.unlink(uds_path)

    async def on_conn(reader, writer):
        session = Session(reader, writer)
        try:
            while True:
                msg = await read_frame(reader)
                if msg is None:
                    break
                try:
                    await _handle(agent, session, msg)
                except (ConnectionError, asyncio.CancelledError):
                    raise
                except Exception as e:  # command failed: report, stay up
                    await session.send({"error": str(e), "done": True})
        except ConnectionError:
            pass
        except asyncio.CancelledError:
            raise  # server shutdown: cleanup runs, cancellation flows
        finally:
            session.close()

    server = await asyncio.start_unix_server(on_conn, uds_path)
    agent._admin_server = server
    return server


async def _handle(agent: "Agent", session: Session, msg: dict) -> None:
    cmd = msg.get("c")
    if cmd == "ping":
        await session.send({"pong": True, "actor_id": agent.actor_id})
    elif cmd == "sync":
        state = generate_sync(agent.bookie, agent.actor_id)
        await session.send(
            {"sync": _state_to_wire(state), "need_len": state.need_len()}
        )
    elif cmd == "locks":
        await session.send(
            {"locks": agent.lock_registry.snapshot(msg.get("top", 10))}
        )
    elif cmd == "cluster":
        members = [
            {
                "actor_id": m.actor_id,
                "addr": list(m.addr),
                "state": m.state,
                "incarnation": m.incarnation,
                "ring": m.ring,
            }
            for m in agent.members.states.values()
        ]
        members.append(
            {
                "actor_id": agent.actor_id,
                "addr": list(agent.gossip_addr),
                "state": "alive",
                "incarnation": agent.swim.incarnation if agent.swim else 0,
                "ring": 0,
            }
        )
        await session.send({"members": members})
    elif cmd == "reload":
        sql = msg.get("schema_sql", "")
        changed = agent.store.apply_schema(sql) if sql else []
        if "api_concurrency" in msg:
            agent.cfg.api_concurrency = int(msg["api_concurrency"])
        if "migration_concurrency" in msg:
            agent.cfg.migration_concurrency = int(msg["migration_concurrency"])
        from corrosion_tpu.agent.api import rebuild_api_limits

        rebuild_api_limits(agent)  # config hot-reload reaches admission
        await session.send({"reloaded": changed})
    elif cmd == "restore":
        actor = await agent.restore_online(
            msg["path"], self_actor_id=bool(msg.get("self_actor_id"))
        )
        await session.send({"restored": True, "actor_id": actor})
    elif cmd == "metrics":
        await session.send({"metrics": agent.metrics.snapshot()})
    elif cmd == "trace":
        await session.send(
            {"spans": agent.tracer.recent(
                limit=msg.get("limit", 100), name=msg.get("name")
            )}
        )
    else:
        await session.send({"error": f"unknown command {cmd!r}"})
    await session.send({"done": True})


class AdminClient:
    """Client side of the admin protocol (corrosion/src/command/admin.rs)."""

    def __init__(self, uds_path: str):
        self.uds_path = uds_path

    async def call(self, command: dict) -> list[dict]:
        reader, writer = await asyncio.open_unix_connection(self.uds_path)
        try:
            writer.write(encode_frame(command))
            await writer.drain()
            frames = []
            while True:
                msg = await asyncio.wait_for(read_frame(reader), 10.0)
                if msg is None or msg.get("done"):
                    break
                frames.append(msg)
            return frames
        finally:
            writer.close()
