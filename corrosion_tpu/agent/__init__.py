"""Host agent: the product surface around the TPU kernels.

Python/SQLite equivalent of the reference's corro-agent + corro-types host
runtime: a CRDT SQLite store (cr-sqlite's role), version bookkeeping, gossip
broadcast + anti-entropy sync over a TCP transport, an HTTP API with
streaming subscriptions, and the background loops that tie them together.
"""

from corrosion_tpu.agent.store import Store, StoreError, SchemaError  # noqa: F401
