"""SplitPool: 1 serialized writer + a read-connection pool + 3-tier write
priority queues.

The reference's SplitPool (corro-types/src/agent.rs:353-578) holds one
read-write connection behind three bounded priority queues (low 1024 /
normal 512 / high 256) plus a global write semaphore, and a 20-connection
read-only pool. This is its asyncio shape around our Store:

- Writes are closures executed one at a time on a dedicated writer thread,
  admitted through three bounded queues drained strictly high → normal →
  low (``write_priority`` ≈ the API write path, ``write_normal`` ≈ change
  ingest, ``write_low`` ≈ background compaction/empties).
- Reads run on a pool of ``read_conns`` extra read-only connections
  (WAL snapshot isolation) under a semaphore, in worker threads, so big
  queries never block the event loop or the writer.
"""

from __future__ import annotations

import asyncio
import sqlite3
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable

from corrosion_tpu import native
from corrosion_tpu.agent.store import Store
from corrosion_tpu.core.values import Statement

HIGH, NORMAL, LOW = 0, 1, 2
QUEUE_DEPTHS = {HIGH: 256, NORMAL: 512, LOW: 1024}  # agent.rs:399-421


@dataclass
class _Job:
    fn: Callable[[], Any]
    future: asyncio.Future


class SplitPool:
    """Async facade over a Store: serialized prioritized writes + pooled
    snapshot reads.

    **Backpressure contract** (deterministic; the write-storm tests pin
    it): when a priority class's bounded queue is full, ``write`` BLOCKS
    the caller in ``Queue.put`` until the writer drains a slot — it
    never sheds, drops, or reorders within a class. Load-shed is the API
    layer's job (``RouteLimit`` 503s *before* work is accepted); once a
    write is admitted past admission control it is executed, in FIFO
    order within its class, with queue-full pressure propagating to the
    producer as await-time. ``queue_depths`` overrides the per-class
    bounds (tests shrink them to make the blocking observable).
    """

    def __init__(
        self, store: Store, read_conns: int = 20,
        queue_depths: dict[int, int] | None = None,
    ) -> None:
        self.store = store
        self.metrics = None  # optional MetricsRegistry (agent wires it)
        self._exec_hist = None  # resolved lazily from the registry
        self._queue_hist = None
        self._queues = {
            p: asyncio.Queue(maxsize=d)
            # Merge, don't replace: a partial override must leave the
            # other priority classes at their defaults, not KeyError at
            # the first write to an un-listed class.
            for p, d in {**QUEUE_DEPTHS, **(queue_depths or {})}.items()
        }
        self._kick = asyncio.Event()
        self._writer_task: asyncio.Task | None = None
        self._read_sem = asyncio.Semaphore(read_conns)
        self._read_pool: list[sqlite3.Connection] = []
        self._read_lock = threading.Lock()
        self._n_read = read_conns
        self._gen = 0  # bumped by flush_read_conns; stale conns retire
        self._conn_gen: dict[sqlite3.Connection, int] = {}
        self._current: _Job | None = None  # job the writer is executing
        self._closed = False
        # Dedicated single writer thread (not asyncio.to_thread): close()
        # must be able to WAIT for an in-flight job — cancelling the
        # awaiting task leaves the thread running, and closing the store's
        # connection under a mid-transaction job segfaults in sqlite3.
        self._writer_exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="splitpool-writer"
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._writer_task = asyncio.get_running_loop().create_task(
            self._writer_loop()
        )

    async def close(self) -> None:
        self._closed = True
        if self._writer_task is not None:
            self._kick.set()
            self._writer_task.cancel()
            try:
                await self._writer_task
            except asyncio.CancelledError:
                pass
        # Fail the in-flight and still-queued jobs so their awaiting
        # callers never hang.
        if self._current is not None and not self._current.future.done():
            self._current.future.set_exception(RuntimeError("pool closed"))
        while (job := self._pop()) is not None:
            if not job.future.done():
                job.future.set_exception(RuntimeError("pool closed"))
        # Drain the writer THREAD: an in-flight job keeps executing after
        # its awaiting task is cancelled, and the store connection must
        # not be closed under it.
        await asyncio.to_thread(self._writer_exec.shutdown, True)
        with self._read_lock:
            for c in self._read_pool:
                c.close()
            self._read_pool.clear()

    # -- writes --------------------------------------------------------------

    async def write(
        self, fn: Callable[[], Any], priority: int = NORMAL
    ) -> Any:
        """Run ``fn`` (a closure over the Store) on the writer, serialized
        with all other writes, admitted by priority class. Queue-full
        blocks right here (deterministic backpressure, never a shed or a
        drop — see the class docstring)."""
        if self._closed:
            raise RuntimeError("pool closed")
        loop = asyncio.get_running_loop()
        job = _Job(fn=fn, future=loop.create_future())
        t0 = time.perf_counter()
        await self._queues[priority].put(job)  # bounded: backpressure
        if self._closed and not job.future.done():
            # close() drained the queues while we were blocked in put():
            # nothing will ever run this job — fail it, don't hang.
            job.future.set_exception(RuntimeError("pool closed"))
        self._kick.set()
        try:
            return await job.future
        finally:
            if self.metrics is not None:
                # Queue-to-done wall time (the reference splits queue vs
                # execution; the writer runs one job at a time, so queue
                # wait dominates the difference). Histogram handle cached:
                # this is the ingest hot path and a registry lookup takes
                # the registry lock per call.
                h = self._exec_hist
                if h is None:
                    h = self._exec_hist = self.metrics.histogram(
                        "corro_sqlite_pool_execution_seconds",
                        "writer job wall time incl. queue wait",
                    )
                h.observe(time.perf_counter() - t0)

    async def write_priority(self, fn: Callable[[], Any]) -> Any:
        return await self.write(fn, HIGH)

    async def write_low(self, fn: Callable[[], Any]) -> Any:
        return await self.write(fn, LOW)

    # corro-lint: disable=CT040 reason=single writer-loop task owns _current; close() only reads it to fail the in-flight future
    async def _writer_loop(self) -> None:
        while not self._closed:
            job = self._pop()
            if job is None:
                self._kick.clear()
                await self._kick.wait()
                continue
            self._current = job
            try:
                result = await asyncio.get_running_loop().run_in_executor(
                    self._writer_exec, job.fn
                )
            except asyncio.CancelledError:
                # close() cancelled us mid-job: fail the caller before the
                # cancellation unwinds, or it would await forever.
                if not job.future.done():
                    job.future.set_exception(RuntimeError("pool closed"))
                raise
            except Exception as e:  # propagate to the caller only
                if not job.future.done():
                    job.future.set_exception(e)
                continue
            finally:
                self._current = None
            if not job.future.done():
                job.future.set_result(result)

    def queue_depths(self) -> dict[str, int]:
        """Queued writer jobs per priority class (for metrics)."""
        return {
            "high": self._queues[HIGH].qsize(),
            "normal": self._queues[NORMAL].qsize(),
            "low": self._queues[LOW].qsize(),
        }

    def _pop(self) -> _Job | None:
        for p in (HIGH, NORMAL, LOW):
            try:
                return self._queues[p].get_nowait()
            except asyncio.QueueEmpty:
                continue
        return None

    # -- reads ---------------------------------------------------------------

    async def query(self, stmt: Statement) -> tuple[list[str], list[tuple]]:
        """Pooled snapshot read (the 20-conn read pool role)."""
        t0 = time.perf_counter()
        async with self._read_sem:
            if self.metrics is not None:
                h = self._queue_hist
                if h is None:
                    h = self._queue_hist = self.metrics.histogram(
                        "corro_sqlite_pool_queue_seconds",
                        "wait for a read-pool slot",
                    )
                h.observe(time.perf_counter() - t0)
            return await asyncio.to_thread(self._query_sync, stmt)

    def _query_sync(self, stmt: Statement) -> tuple[list[str], list[tuple]]:
        conn = self._take_conn()
        try:
            from corrosion_tpu.agent.store import _bind

            cur = conn.execute(stmt.sql, _bind(stmt))
            cols = [d[0] for d in cur.description] if cur.description else []
            return cols, cur.fetchall()
        finally:
            self._put_conn(conn)

    def quiesce_reads(self):
        """Async context manager acquiring every read slot: no pooled read
        runs until it exits (used around online restore, where same-process
        readers are not excluded by the fcntl file locks)."""
        sem, n = self._read_sem, self._n_read

        class _Quiesce:
            async def __aenter__(self):
                for _ in range(n):
                    await sem.acquire()
                return self

            async def __aexit__(self, *exc):
                for _ in range(n):
                    sem.release()
                return False

        return _Quiesce()

    def _take_conn(self) -> sqlite3.Connection:
        with self._read_lock:
            if self._read_pool:
                return self._read_pool.pop()
            gen = self._gen
        conn = sqlite3.connect(self.store.path, check_same_thread=False)
        conn.isolation_level = None
        conn.execute("PRAGMA query_only=1")
        # Same SQL surface as the store's own read connection.
        from corrosion_tpu.agent.store import _sql_pack

        conn.create_function("corro_pack", -1, _sql_pack, deterministic=True)
        native.load_crdt_extension(conn)
        with self._read_lock:
            self._conn_gen[conn] = gen
        return conn

    def flush_read_conns(self) -> None:
        """Retire all pooled read connections (after an online restore their
        page caches are stale); checked-out connections retire on return via
        the generation stamp. Fresh ones are opened on demand."""
        with self._read_lock:
            self._gen += 1
            for c in self._read_pool:
                self._conn_gen.pop(c, None)
                c.close()
            self._read_pool.clear()

    def _put_conn(self, conn: sqlite3.Connection) -> None:
        with self._read_lock:
            fresh = self._conn_gen.get(conn, -1) == self._gen
            if fresh and len(self._read_pool) < self._n_read and not self._closed:
                self._read_pool.append(conn)
                return
            self._conn_gen.pop(conn, None)
        conn.close()
