"""Backup / restore.

Mirrors `corrosion backup` / `corrosion restore` (reference
corrosion/src/main.rs:154-288): backup = `VACUUM INTO` a snapshot and strip
node-local state so the file can seed a *different* node; restore = swap
the db file into place (offline here — the reference's online variant takes
SQLite's C file locks, sqlite3-restore/lib.rs:15-57, which only matters for
a live process) optionally re-adopting the backup's actor id.
"""

from __future__ import annotations

import os
import shutil
import sqlite3

# Node-local tables a backup must not carry into another node
# (main.rs:176-216 strips members + local bookkeeping rewrite).
NODE_LOCAL_TABLES = ("__corro_members",)


def backup(db_path: str, out_path: str) -> None:
    if os.path.exists(out_path):
        raise FileExistsError(out_path)
    src = sqlite3.connect(db_path)
    try:
        src.execute("VACUUM INTO ?", (out_path,))
    finally:
        src.close()
    snap = sqlite3.connect(out_path)
    try:
        for tbl in NODE_LOCAL_TABLES:
            snap.execute(f"DROP TABLE IF EXISTS {tbl}")
        # The snapshot must not reuse the origin's identity by default: a
        # restored node adopts it only with --self-actor-id (main.rs:220-288).
        snap.execute("COMMIT") if snap.in_transaction else None
        snap.execute("VACUUM")
    finally:
        snap.close()


def restore(
    backup_path: str, db_path: str, self_actor_id: bool = False
) -> bytes:
    """Swap the backup into place; returns the site_id now in effect.

    With self_actor_id=False a fresh identity is assigned so the restored
    node replicates as a new actor (the safe default); True keeps the
    backup's identity (re-adoption)."""
    tmp = db_path + ".restore"
    shutil.copyfile(backup_path, tmp)
    conn = sqlite3.connect(tmp)
    try:
        if not self_actor_id:
            new_site = os.urandom(16)
            conn.execute(
                "UPDATE __corro_meta SET value = ? WHERE key = 'site_id'",
                (new_site,),
            )
            conn.commit()
        (site_id,) = conn.execute(
            "SELECT value FROM __corro_meta WHERE key='site_id'"
        ).fetchone()
    finally:
        conn.close()
    for suffix in ("", "-wal", "-shm"):
        p = db_path + suffix
        if suffix and os.path.exists(p):
            os.unlink(p)
    os.replace(tmp, db_path)
    return bytes(site_id)
