"""Backup / restore.

Mirrors `corrosion backup` / `corrosion restore` (reference
corrosion/src/main.rs:154-288): backup = `VACUUM INTO` a snapshot and strip
node-local state so the file can seed a *different* node; restore = swap
the db file into place (offline here — the reference's online variant takes
SQLite's C file locks, sqlite3-restore/lib.rs:15-57, which only matters for
a live process) optionally re-adopting the backup's actor id.
"""

from __future__ import annotations

import fcntl
import os
import shutil
import sqlite3

# SQLite's file-locking byte offsets (the C ABI contract sqlite3-restore
# manipulates, lib.rs:15-30): a PENDING byte, a RESERVED byte, and a
# 510-byte SHARED range at 1 GiB, plus the WAL-index lock bytes 120-128 in
# the -shm file.
PENDING_BYTE = 0x40000000
RESERVED_BYTE = PENDING_BYTE + 1
SHARED_FIRST = PENDING_BYTE + 2
SHARED_SIZE = 510
SHM_LOCK_OFF = 120
SHM_LOCK_LEN = 8

# Node-local tables a backup must not carry into another node
# (main.rs:176-216 strips members + local bookkeeping rewrite).
# Subscriptions are per-node state too: a restored node must keep ITS
# subscriptions, not adopt the backup origin's.
NODE_LOCAL_TABLES = ("__corro_members", "__corro_subs")


def backup(db_path: str, out_path: str) -> None:
    if os.path.exists(out_path):
        raise FileExistsError(out_path)
    src = sqlite3.connect(db_path)
    try:
        src.execute("VACUUM INTO ?", (out_path,))
    finally:
        src.close()
    snap = sqlite3.connect(out_path)
    try:
        for tbl in NODE_LOCAL_TABLES:
            snap.execute(f"DROP TABLE IF EXISTS {tbl}")
        # The snapshot must not reuse the origin's identity by default: a
        # restored node adopts it only with --self-actor-id (main.rs:220-288).
        snap.execute("COMMIT") if snap.in_transaction else None
        snap.execute("VACUUM")
    finally:
        snap.close()


def _prepare_restore_file(
    backup_path: str, db_path: str, self_actor_id: bool
) -> tuple[str, bytes]:
    """Copy the backup next to the target and fix its identity; returns
    (tmp_path, site_id that will be in effect)."""
    tmp = db_path + ".restore"
    shutil.copyfile(backup_path, tmp)
    conn = sqlite3.connect(tmp)
    try:
        if not self_actor_id:
            new_site = os.urandom(16)
            conn.execute(
                "UPDATE __corro_meta SET value = ? WHERE key = 'site_id'",
                (new_site,),
            )
            conn.commit()
        (site_id,) = conn.execute(
            "SELECT value FROM __corro_meta WHERE key='site_id'"
        ).fetchone()
    finally:
        conn.close()
    return tmp, bytes(site_id)


def online_restore(
    backup_path: str, db_path: str, self_actor_id: bool = False
) -> bytes:
    """Replace a LIVE database's content under SQLite's own file locks.

    The sqlite3-restore analogue (lib.rs:57+): take the PENDING, RESERVED
    and SHARED lock bytes on the main file (excluding every other reader
    and writer at the SQLite protocol level), take the WAL-index lock bytes
    on the -shm file, then overwrite the file's *content in place* — same
    inode, so connections already holding file descriptors keep working and
    observe the restored database on their next transaction (SQLite re-reads
    the header when the change counter moves). The -wal file is truncated so
    no stale frames overlay the new content.
    """
    tmp, site_id = _prepare_restore_file(backup_path, db_path, self_actor_id)
    fd = os.open(db_path, os.O_RDWR)
    shm_fd = None
    try:
        # Lock order mirrors the reference: PENDING → RESERVED → SHARED.
        fcntl.lockf(fd, fcntl.LOCK_EX, 1, PENDING_BYTE, os.SEEK_SET)
        fcntl.lockf(fd, fcntl.LOCK_EX, 1, RESERVED_BYTE, os.SEEK_SET)
        fcntl.lockf(fd, fcntl.LOCK_EX, SHARED_SIZE, SHARED_FIRST, os.SEEK_SET)
        shm_path = db_path + "-shm"
        if os.path.exists(shm_path):
            shm_fd = os.open(shm_path, os.O_RDWR)
            fcntl.lockf(
                shm_fd, fcntl.LOCK_EX, SHM_LOCK_LEN, SHM_LOCK_OFF, os.SEEK_SET
            )
        # Same-inode content replacement, chunked (a single os.write caps
        # out near 2 GiB on Linux and reports a short count).
        os.ftruncate(fd, 0)
        os.lseek(fd, 0, os.SEEK_SET)
        with open(tmp, "rb") as src:
            while chunk := src.read(1 << 24):
                view = memoryview(chunk)
                while view:
                    view = view[os.write(fd, view):]
        os.fsync(fd)
        wal_path = db_path + "-wal"
        if os.path.exists(wal_path):
            with open(wal_path, "r+b") as wal:
                wal.truncate(0)
        os.unlink(tmp)
    finally:
        if shm_fd is not None:
            fcntl.lockf(
                shm_fd, fcntl.LOCK_UN, SHM_LOCK_LEN, SHM_LOCK_OFF, os.SEEK_SET
            )
            os.close(shm_fd)
        fcntl.lockf(fd, fcntl.LOCK_UN, SHARED_SIZE, SHARED_FIRST, os.SEEK_SET)
        fcntl.lockf(fd, fcntl.LOCK_UN, 1, RESERVED_BYTE, os.SEEK_SET)
        fcntl.lockf(fd, fcntl.LOCK_UN, 1, PENDING_BYTE, os.SEEK_SET)
        os.close(fd)
    return site_id


def restore(
    backup_path: str, db_path: str, self_actor_id: bool = False
) -> bytes:
    """Swap the backup into place; returns the site_id now in effect.

    With self_actor_id=False a fresh identity is assigned so the restored
    node replicates as a new actor (the safe default); True keeps the
    backup's identity (re-adoption)."""
    tmp, site_id = _prepare_restore_file(backup_path, db_path, self_actor_id)
    for suffix in ("-wal", "-shm"):
        p = db_path + suffix
        if os.path.exists(p):
            os.unlink(p)
    os.replace(tmp, db_path)
    return site_id
