"""In-process multi-agent test harness.

The corro-tests analogue (crates/corro-tests/src/lib.rs:11-66): launch a real
agent on ephemeral localhost ports with a tempdir and the canonical test
schema, hand back agent + client. All multi-node tests run real TCP over
loopback, like the reference's integration tests (SURVEY.md §4).
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass

from corrosion_tpu.agent.agent import Agent, AgentConfig
from corrosion_tpu.client import CorrosionApiClient

# corro-tests/src/lib.rs:11-26
TEST_SCHEMA = """
CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE testsblob (id BLOB NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
"""


@dataclass
class TestAgent:
    agent: Agent
    client: CorrosionApiClient

    @property
    def gossip_addr(self) -> tuple[str, int]:
        return self.agent.gossip_addr

    async def stop(self) -> None:
        await self.agent.stop()

    async def hard_kill(self) -> None:
        """SIGKILL semantics: no graceful leave, no final flushes — see
        Agent.abort. The TestAgent keeps its cfg and last-known addrs so
        :func:`relaunch_test_agent` can resurrect it in place."""
        await self.agent.abort()


async def _launch_from_cfg(cfg: AgentConfig, subs: bool = True) -> TestAgent:
    agent = Agent(cfg)
    if subs:
        from corrosion_tpu.agent.subs import SubsManager

        agent.subs = SubsManager(agent.store)
    await agent.start()
    host, port = agent.api_addr
    return TestAgent(agent=agent, client=CorrosionApiClient(host, port))


async def launch_test_agent(
    data_dir: str,
    bootstrap: list[tuple[str, int]] | None = None,
    schema: str = TEST_SCHEMA,
    subs: bool = True,
    **cfg_overrides,
) -> TestAgent:
    cfg = AgentConfig(
        data_dir=data_dir,
        bootstrap=list(bootstrap or []),
        schema_sql=schema,
        **cfg_overrides,
    )
    return await _launch_from_cfg(cfg, subs=subs)


async def hard_kill(ta: TestAgent) -> None:
    """Module-level alias for :meth:`TestAgent.hard_kill` (crash-recovery
    scenarios read better as ``await hard_kill(victim)``)."""
    await ta.agent.abort()


async def relaunch_test_agent(
    ta: TestAgent,
    bootstrap: list[tuple[str, int]] | None = None,
    subs: bool = True,
    **cfg_overrides,
) -> TestAgent:
    """Restart a (hard-)killed agent on the SAME data_dir, gossip port,
    and API port — the crash-recovery path every chaos scenario needs:
    clients and subscription pumps reconnect to the address they already
    hold, and the store/bookkeeping rehydrate from whatever the previous
    life persisted. ``bootstrap`` defaults to the previous life's list
    (pass a live peer when the original seed may itself be dead)."""
    import dataclasses

    old = ta.agent.cfg
    gossip = ta.agent.gossip_addr
    api = ta.agent.api_addr
    cfg = dataclasses.replace(
        old,
        gossip_port=gossip[1] if gossip else old.gossip_port,
        api_port=api[1] if api else old.api_port,
        bootstrap=(
            [tuple(a) for a in bootstrap]
            if bootstrap is not None else list(old.bootstrap)
        ),
        **cfg_overrides,
    )
    return await _launch_from_cfg(cfg, subs=subs)


async def launch_test_cluster(
    data_dir: str,
    n: int,
    wait_membership: bool = True,
    membership_timeout: float = 20.0,
    cfg_for=None,
    **cfg_overrides,
) -> list[TestAgent]:
    """``n`` agents over loopback, chained via bootstrap through the
    first — the cluster-launch loop the loadgen scenarios, the fidelity
    harness, and the CLI all share. With ``wait_membership`` (default)
    it returns only once every agent believes the other ``n - 1`` alive,
    so callers can start measuring immediately. Launched agents are
    stopped on a launch/poll failure (no orphaned listeners).

    ``cfg_for`` (``index -> dict``) merges per-agent config over the
    shared ``cfg_overrides`` — e.g. a distinct ``trace_export_path`` per
    agent so traced clusters don't interleave span files."""
    agents: list[TestAgent] = []
    try:
        for i in range(n):
            per_agent = dict(cfg_overrides)
            if cfg_for is not None:
                per_agent.update(cfg_for(i))
            agents.append(await launch_test_agent(
                os.path.join(data_dir, f"agent{i}"),
                bootstrap=[agents[0].gossip_addr] if agents else None,
                **per_agent,
            ))
        if wait_membership and n > 1:
            await poll_until(
                lambda: asyncio.sleep(0, all(
                    len(a.agent.members.alive()) == n - 1 for a in agents
                )),
                timeout=membership_timeout,
            )
    except BaseException:
        await stop_cluster(agents)
        raise
    return agents


async def stop_cluster(agents) -> None:
    """Best-effort stop of every agent (teardown must not mask the
    test's own failure)."""
    for ta in agents:
        try:
            await ta.stop()
        except Exception:
            pass


async def poll_until(cond, timeout: float = 15.0, interval: float = 0.1):
    """Await an async predicate until truthy or timeout (the polling loops
    the reference tests use for convergence checks)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = await cond()
        if value:
            return value
        await asyncio.sleep(interval)
    raise TimeoutError("condition not met within timeout")
