"""PG SQL lexer + token-level dialect translation.

The reference parses PG SQL with a real parser (sqlparser — corro-pg/src/
lib.rs:306, 325-327) before rewriting it for SQLite. The regex passes this
module replaces were blind to comments and could be confused by quoted
text; here a small hand-written lexer produces a token stream —
strings/identifiers/comments/dollar-quotes/parameters are single tokens —
and every translation (session shims, boolean/ILIKE dialect, ``::`` casts,
E-string decoding, ``$N`` placeholders, pg_catalog routing, statement
splitting) walks tokens, so content inside literals and comments can never
be rewritten or mis-split.

Lexical grammar follows PostgreSQL's: ``--`` line comments, nested
``/* */`` block comments, ``'...'`` strings with doubled-quote escapes,
``E'...'`` strings with backslash escapes, ``$tag$...$tag$`` dollar
quoting, ``"..."`` identifiers, ``$N`` parameters, and ``::`` as a single
operator token.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

__all__ = [
    "Tok", "tokenize", "render", "split_statements", "translate",
    "translate_placeholders", "strip_catalog_prefix", "mentions_catalog",
]


@dataclass
class Tok:
    kind: str  # ws comment str estr qident ident num param op
    text: str


_IDENT_START = set("abcdefghijklmnopqrstuvwxyz" "ABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_IDENT_CONT = _IDENT_START | set("0123456789$")
_DOLLAR_TAG = re.compile(r"\$(?:[A-Za-z_][A-Za-z_0-9]*)?\$")


def tokenize(sql: str) -> list[Tok]:
    toks: list[Tok] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        # Whitespace runs.
        if ch.isspace():
            j = i + 1
            while j < n and sql[j].isspace():
                j += 1
            toks.append(Tok("ws", sql[i:j]))
            i = j
            continue
        # Line comment.
        if ch == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            j = n if j < 0 else j + 1
            toks.append(Tok("comment", sql[i:j]))
            i = j
            continue
        # Block comment (nested, per PG).
        if ch == "/" and sql.startswith("/*", i):
            depth = 1
            j = i + 2
            while j < n and depth:
                if sql.startswith("/*", j):
                    depth += 1
                    j += 2
                elif sql.startswith("*/", j):
                    depth -= 1
                    j += 2
                else:
                    j += 1
            toks.append(Tok("comment", sql[i:j]))
            i = j
            continue
        # Standard string literal; doubled quotes stay inside ONE token.
        if ch == "'":
            toks.append(Tok("str", sql[i:(i := _scan_quoted(sql, i, "'"))]))
            continue
        # Quoted identifier.
        if ch == '"':
            toks.append(Tok("qident", sql[i:(i := _scan_quoted(sql, i, '"'))]))
            continue
        # Dollar-quoted string or $N parameter.
        if ch == "$":
            m = _DOLLAR_TAG.match(sql, i)
            if m:
                tag = m.group(0)
                close = sql.find(tag, m.end())
                j = n if close < 0 else close + len(tag)
                toks.append(Tok("str", sql[i:j]))
                i = j
                continue
            m = re.match(r"\$\d+", sql[i:])
            if m:
                toks.append(Tok("param", m.group(0)))
                i += m.end()
                continue
            toks.append(Tok("op", "$"))
            i += 1
            continue
        # SQLite-style ?N placeholder: translate_placeholders runs BEFORE
        # translate in the prepared-statement path, so the cast pass must
        # see ?N as a single parameter token ("$1::int8" → "?1::int8" →
        # CAST(?1 AS INTEGER)).
        if ch == "?":
            m = re.match(r"\?\d*", sql[i:])
            toks.append(Tok("param", m.group(0)))
            i += m.end()
            continue
        # E'...' escape string / identifier / keyword.
        if ch in _IDENT_START:
            if ch in "eE" and i + 1 < n and sql[i + 1] == "'":
                j = _scan_estring(sql, i + 1)
                toks.append(Tok("estr", sql[i:j]))
                i = j
                continue
            j = i + 1
            while j < n and sql[j] in _IDENT_CONT:
                j += 1
            toks.append(Tok("ident", sql[i:j]))
            i = j
            continue
        # Number (digits, decimal point, exponent).
        if ch.isdigit() or (
            ch == "." and i + 1 < n and sql[i + 1].isdigit()
        ):
            j = i
            while j < n and (sql[j].isdigit() or sql[j] == "."):
                j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    j = k
                    while j < n and sql[j].isdigit():
                        j += 1
            toks.append(Tok("num", sql[i:j]))
            i = j
            continue
        # '::' is one operator token; everything else single chars.
        if ch == ":" and sql.startswith("::", i):
            toks.append(Tok("op", "::"))
            i += 2
            continue
        toks.append(Tok("op", ch))
        i += 1
    return toks


def _scan_quoted(sql: str, i: int, q: str) -> int:
    """Scan a quoted run starting at ``i``; doubled quotes continue it."""
    n = len(sql)
    j = i + 1
    while j < n:
        if sql[j] == q:
            if j + 1 < n and sql[j + 1] == q:
                j += 2
                continue
            return j + 1
        j += 1
    return n


def _scan_estring(sql: str, i: int) -> int:
    """Scan the quoted body of an E-string (backslash escapes)."""
    n = len(sql)
    j = i + 1
    while j < n:
        if sql[j] == "\\" and j + 1 < n:
            j += 2
            continue
        if sql[j] == "'":
            if j + 1 < n and sql[j + 1] == "'":
                j += 2
                continue
            return j + 1
        j += 1
    return n


def render(toks: list[Tok]) -> str:
    return "".join(t.text for t in toks)


def split_statements(sql: str) -> list[str]:
    """Top-level ';' split — token-aware, so ';' inside strings, quoted
    identifiers, comments, and dollar-quoted blocks never splits."""
    parts: list[list[Tok]] = [[]]
    for t in tokenize(sql):
        if t.kind == "op" and t.text == ";":
            parts.append([])
        else:
            parts[-1].append(t)
    out = []
    for p in parts:
        s = render(p).strip()
        if s:
            out.append(s)
    return out


# -- translation passes -------------------------------------------------------

# PG type name → SQLite CAST target (affinity groups).
PG_TYPE_MAP = {
    "int2": "INTEGER", "int4": "INTEGER", "int8": "INTEGER",
    "smallint": "INTEGER", "integer": "INTEGER", "int": "INTEGER",
    "bigint": "INTEGER", "serial": "INTEGER", "bigserial": "INTEGER",
    "oid": "INTEGER", "bool": "INTEGER", "boolean": "INTEGER",
    "float4": "REAL", "float8": "REAL", "real": "REAL",
    "numeric": "REAL", "decimal": "REAL", "double": "REAL",
    "text": "TEXT", "varchar": "TEXT", "char": "TEXT", "bpchar": "TEXT",
    "character": "TEXT",
    "name": "TEXT", "uuid": "TEXT", "json": "TEXT", "jsonb": "TEXT",
    "regclass": "TEXT", "regtype": "TEXT",
    "bytea": "BLOB",
}

_SESSION_FN = {
    "version": "'corrosion-tpu (PostgreSQL 14 compatible)'",
    "current_database": "'corrosion'",
    "current_schema": "'public'",
    "pg_backend_pid": "1",
}
_SESSION_IDENT = {
    "current_user": "'corrosion'",
    "session_user": "'corrosion'",
}
_DIALECT_IDENT = {"true": "1", "false": "0", "ilike": "LIKE"}

_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
    "\\": "\\", "'": "'", '"': '"',
}


def _sig(toks: list[Tok], i: int, step: int) -> int:
    """Next significant (non-ws/comment) token index from i+step, or -1."""
    j = i + step
    while 0 <= j < len(toks):
        if toks[j].kind not in ("ws", "comment"):
            return j
        j += step
    return -1


def _pass_idents(toks: list[Tok]) -> list[Tok]:
    """Session shims + boolean/ILIKE dialect, on identifier tokens only."""
    out = list(toks)
    for i, t in enumerate(out):
        if t.kind != "ident":
            continue
        low = t.text.lower()
        if low in _SESSION_FN:
            j = _sig(out, i, 1)
            if j >= 0 and out[j].text == "(":
                k = _sig(out, j, 1)
                if k >= 0 and out[k].text == ")":
                    out[i] = Tok("num", _SESSION_FN[low])
                    for idx in range(i + 1, k + 1):
                        out[idx] = Tok("ws", "")
            continue
        if low in _SESSION_IDENT:
            # Not a column reference when qualified (t.current_user).
            p = _sig(out, i, -1)
            if p >= 0 and out[p].text == ".":
                continue
            out[i] = Tok("str", _SESSION_IDENT[low])
            continue
        if low in _DIALECT_IDENT:
            p = _sig(out, i, -1)
            if p >= 0 and out[p].text == ".":
                continue
            out[i] = Tok(t.kind, _DIALECT_IDENT[low])
    return [t for t in out if t.text != ""]


def _pass_estrings(toks: list[Tok]) -> list[Tok]:
    """E'...' → standard literal with escapes decoded (SQLite has no
    backslash escapes)."""
    out = []
    for t in toks:
        if t.kind != "estr":
            out.append(t)
            continue
        body = t.text[2:-1] if t.text.endswith("'") else t.text[2:]
        decoded = []
        j = 0
        while j < len(body):
            if body[j] == "\\" and j + 1 < len(body):
                decoded.append(_ESCAPES.get(body[j + 1], body[j + 1]))
                j += 2
            elif body[j] == "'" and j + 1 < len(body) and body[j + 1] == "'":
                decoded.append("'")
                j += 2
            else:
                decoded.append(body[j])
                j += 1
        out.append(Tok("str", "'" + "".join(decoded).replace("'", "''") + "'"))
    return out


_VALUE_KINDS = {"str", "estr", "qident", "ident", "num", "param"}

# Reserved words that can precede '(' without being a function call — a
# parenthesized cast value must not swallow them.
_RESERVED = {
    "select", "from", "where", "and", "or", "not", "in", "as", "on", "by",
    "group", "order", "limit", "offset", "join", "inner", "left", "right",
    "full", "cross", "outer", "values", "set", "case", "when", "then",
    "else", "end", "distinct", "all", "union", "except", "intersect",
    "having", "insert", "update", "delete", "returning", "like", "ilike",
    "between", "is", "null", "exists", "any", "some", "using", "into",
}


def _value_span(toks: list[Tok], end: int) -> int:
    """Start index of the value expression ending at ``end`` (inclusive):
    a parenthesized run (plus a preceding function name), or a dotted
    identifier chain, or a single value token."""
    t = toks[end]
    if t.text == ")":
        depth = 0
        j = end
        while j >= 0:
            if toks[j].text == ")":
                depth += 1
            elif toks[j].text == "(":
                depth -= 1
                if depth == 0:
                    break
            j -= 1
        if j < 0:
            return end
        p = _sig(toks, j, -1)
        # f(x)::t casts the call result; CAST(...) from a previous pass
        # keeps its keyword attached the same way. Reserved words before
        # '(' are clause keywords, not callables.
        if p >= 0 and toks[p].kind in ("ident", "qident") and (
            toks[p].text.lower() not in _RESERVED
        ):
            return p
        return j
    if t.text == "]":
        # Bracketed run: ARRAY[...] literal or a subscripted value x[i] —
        # include the matching '[' and the value it subscripts (a bare ']'
        # treated as a one-token value mangled ARRAY casts).
        depth = 0
        j = end
        while j >= 0:
            if toks[j].text == "]":
                depth += 1
            elif toks[j].text == "[":
                depth -= 1
                if depth == 0:
                    break
            j -= 1
        if j < 0:
            return end
        p = _sig(toks, j, -1)
        if p >= 0 and (
            toks[p].text in (")", "]") or toks[p].kind in _VALUE_KINDS
        ) and not (
            toks[p].kind in ("ident", "qident")
            and toks[p].text.lower() in _RESERVED
        ):
            return _value_span(toks, p)
        return j
    if t.kind in _VALUE_KINDS:
        start = end
        while True:
            p = _sig(toks, start, -1)
            if p < 0 or toks[p].text != ".":
                return start
            q = _sig(toks, p, -1)
            if q < 0 or toks[q].kind not in ("ident", "qident"):
                return start
            start = q
    return end


def _pass_casts(toks: list[Tok]) -> list[Tok]:
    """``value::type`` → ``CAST(value AS affinity)``; unknown types drop
    the cast and keep the value. Left-to-right, repeated — so nested casts
    compose: x::int::text → CAST(CAST(x AS INTEGER) AS TEXT). Terminates:
    every iteration removes one '::' (the malformed branch included)."""
    while True:
        idx = next(
            (i for i, t in enumerate(toks)
             if t.kind == "op" and t.text == "::"),
            None,
        )
        if idx is None:
            return toks
        prev = _sig(toks, idx, -1)
        nxt = _sig(toks, idx, 1)
        if prev < 0 or nxt < 0 or toks[nxt].kind != "ident":
            # Malformed; drop the operator so we can't loop forever.
            toks = toks[:idx] + toks[idx + 1:]
            continue
        type_end = nxt
        typ = toks[nxt].text.lower()
        # Multi-word type names: consume the suffix so it can never dangle
        # after the rewrite (x::double precision must not leave a bare
        # "precision" behind).
        j = _sig(toks, nxt, 1)
        if j >= 0 and toks[j].kind == "ident":
            suf = toks[j].text.lower()
            if typ == "double" and suf == "precision":
                type_end = j
            elif suf == "varying" and typ in ("character", "bit"):
                type_end = j
                typ = "varchar" if typ == "character" else "bit varying"
        # Optional length suffix: varchar(32), timestamp(3).
        j = _sig(toks, type_end, 1)
        if j >= 0 and toks[j].text == "(":
            k = _sig(toks, j, 1)
            m = _sig(toks, k, 1) if k >= 0 else -1
            if k >= 0 and toks[k].kind == "num" and m >= 0 and toks[m].text == ")":
                type_end = m
        # with/without time zone AFTER any length paren ("timestamp(3)
        # with time zone" is the common PG spelling).
        j = _sig(toks, type_end, 1)
        if (
            j >= 0 and toks[j].kind == "ident"
            and toks[j].text.lower() in ("with", "without")
            and typ in ("timestamp", "time")
        ):
            k = _sig(toks, j, 1)
            m = _sig(toks, k, 1) if k >= 0 else -1
            if (
                k >= 0 and toks[k].text.lower() == "time"
                and m >= 0 and toks[m].text.lower() == "zone"
            ):
                type_end = m
        # Array type suffix: type[] / type[n] / type[2][3] has no SQLite
        # affinity — consume ALL bracket groups and drop the cast (keep
        # the value).
        is_array_type = False
        while True:
            j = _sig(toks, type_end, 1)
            if j < 0 or toks[j].text != "[":
                break
            k = _sig(toks, j, 1)
            if k >= 0 and toks[k].text == "]":
                type_end, is_array_type = k, True
            elif k >= 0 and toks[k].kind == "num":
                m = _sig(toks, k, 1)
                if m >= 0 and toks[m].text == "]":
                    type_end, is_array_type = m, True
                else:
                    break
            else:
                break
        start = _value_span(toks, prev)
        value = toks[start:prev + 1]
        target = None if is_array_type else PG_TYPE_MAP.get(typ)
        if target is None:
            repl = value
        else:
            repl = (
                [Tok("ident", "CAST"), Tok("op", "(")]
                + value
                + [Tok("ws", " "), Tok("ident", "AS"), Tok("ws", " "),
                   Tok("ident", target), Tok("op", ")")]
            )
        toks = toks[:start] + repl + toks[type_end + 1:]


def _pass_params(toks: list[Tok]) -> list[Tok]:
    return [
        Tok("param", "?" + t.text[1:]) if t.kind == "param" else t
        for t in toks
    ]


def translate_placeholders(sql: str) -> str:
    """PG ``$N`` → SQLite ``?N`` (parameters are single tokens, so text
    inside literals/comments is untouched)."""
    return render(_pass_params(tokenize(sql)))


def strip_catalog_prefix(sql: str) -> str:
    """Drop ``pg_catalog.`` qualifiers (catalog snapshot tables are
    unqualified TEMP tables)."""
    toks = tokenize(sql)
    out: list[Tok] = []
    i = 0
    while i < len(toks):
        t = toks[i]
        if t.kind == "ident" and t.text.lower() == "pg_catalog":
            j = _sig(toks, i, 1)
            if j >= 0 and toks[j].text == ".":
                i = j + 1
                continue
        out.append(t)
        i += 1
    return render(out)


_CATALOG_TABLES = {
    "pg_type", "pg_class", "pg_namespace", "pg_database", "pg_range",
    "pg_attribute", "pg_tables",
}


def mentions_catalog(sql: str) -> bool:
    return any(
        t.kind == "ident" and t.text.lower() in _CATALOG_TABLES
        for t in tokenize(sql)
    )


def translate(sql: str) -> str:
    """Full PG → SQLite surface translation of one statement (corro-pg's
    parse_query rewrite, lib.rs:306-472): comments stripped, session shims,
    boolean/ILIKE dialect, ``::`` casts, E-strings. ``BEGIN``/``COMMIT``/
    ``SET``/``SHOW`` become empty (the agent manages transactions)."""
    # Comments become a space (not nothing: `x--c<newline>FROM` must not
    # fuse into one identifier).
    toks = [
        Tok("ws", " ") if t.kind == "comment" else t for t in tokenize(sql)
    ]
    sig = [t for t in toks if t.kind != "ws"]
    while sig and sig[-1].text == ";":
        sig.pop()
    if sig and sig[0].kind == "ident":
        head = sig[0].text.upper()
        stmt = " ".join(t.text.upper() for t in sig)
        if stmt in ("BEGIN", "COMMIT", "ROLLBACK", "START TRANSACTION"):
            return ""
        if head in ("SET", "SHOW"):
            return ""
    toks = _pass_idents(toks)
    toks = _pass_estrings(toks)
    toks = _pass_casts(toks)
    return render(toks).strip().rstrip(";").strip()
