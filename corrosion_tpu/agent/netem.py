"""Deterministic network impairment for the host gossip transport.

The kernel plane got a declarative chaos language in ``sim/faults.py``;
this is its host-plane sibling: a :class:`HostFaultPlan`
(``corro-host-fault-plan/1``, JSON round-trip like the kernel's
``corro-fault-plan/1``) of typed impairment components over the agent
transport's three planes — the SWIM **probe** datagrams, one-shot
**bcast** changeset frames, and **sync** session streams
(agent/transport.py's plane split of the reference's QUIC multiplexing).
``agent.transport.Transport`` consults an armed :class:`NetemShim` at
every outbound operation; with no shim installed the hooks are a single
``is None`` branch — the impaired and unimpaired paths share every byte
of frame encoding (pinned by tests).

Component kinds (windows are ``[start_s, stop_s)`` seconds relative to
:meth:`NetemShim.arm`; ``stop_s=None`` = end of run):

- ``delay``: one-way latency ``delay_ms`` ± uniform ``jitter_ms`` on the
  matched planes/links — 40 ms each way ≈ an 80 ms-RTT WAN. On UDP the
  delay is a scheduled late send (so unequal jitter reorders packets,
  like a real WAN); on streams it paces the send call, which is what the
  sync plane's adaptive chunker and stall guard actually observe.
- ``loss``: silent drop with ``prob`` (planes ``probe``/``bcast`` only:
  a TCP byte stream does not lose application frames — loss there
  manifests as delay, which ``delay`` models).
- ``dup``: duplicate datagram delivery with ``prob`` (``probe`` only —
  that's where the wire can duplicate; SWIM seq matching must absorb
  it).
- ``reorder``: with ``prob``, hold a probe datagram back ``extra_ms``
  so it lands after its successors (UDP only).
- ``blackhole``: the matched ``src``→``dst`` direction stops completely.
  Datagrams vanish; stream operations stall ``stall_s`` (a dropped SYN
  burning the dial timeout) and then fail — the path that feeds the
  per-peer circuit breaker.
- ``partition`` / ``flap``: link cut between name sides ``a`` and ``b``
  (``b`` empty = everyone not in ``a``), symmetric unless ``one_way``
  (cuts only a→b, the asymmetric case — sim/faults semantics). ``flap``
  toggles every ``period_s`` inside its window, first half-cycle cut.

**Determinism.** Every probabilistic decision is a pure function of
``(seed, src, dst, plane, event_index, component)`` via sha256 — no RNG
state, no call-order coupling. The shim records an impairment trace
(event index, link, plane, active components, resulting decision);
:func:`replay_schedule` recomputes each recorded decision from the plan
+ seed alone and must reproduce it exactly — the mechanical form of
"replaying the same seed reproduces the identical fault schedule".

Link names: components match symbolic node names (``n0``, ``n1``, ...).
Each agent's shim knows its own name (``local``) and resolves peer
gossip addresses registered via :meth:`register_peer`; unresolved
addresses (inbound ephemeral ports, pre-registration traffic) match only
wildcard components and never sit inside a partition side.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field

PLAN_SCHEMA = "corro-host-fault-plan/1"

PLANES = ("probe", "bcast", "sync")

KINDS = ("delay", "loss", "dup", "reorder", "blackhole", "partition", "flap")

# Probability-bearing kinds (planes restricted to the lossy planes).
_PROB_KINDS = ("loss", "dup", "reorder")


@dataclass(frozen=True)
class HostFault:
    """One impairment component. Only the fields its ``kind`` reads
    matter; the rest keep defaults (and serialize compactly)."""

    kind: str
    start_s: float = 0.0
    stop_s: float | None = None  # None = until the run ends
    planes: tuple = ()  # () = every plane the kind supports
    src: tuple = ()  # directional kinds: sender node names (() = any)
    dst: tuple = ()  # directional kinds: receiver node names (() = any)
    delay_ms: float = 0.0
    jitter_ms: float = 0.0
    prob: float = 1.0  # loss / dup / reorder
    extra_ms: float = 50.0  # reorder hold-back
    stall_s: float = 0.3  # blackhole/partition: dial stall before failing
    a: tuple = ()  # partition/flap side A
    b: tuple = ()  # () = every node not in a
    one_way: bool = False  # cut a->b only
    period_s: float = 0.0  # flap half-cycle

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown host fault kind {self.kind!r}; one of {KINDS}"
            )
        if self.start_s < 0 or (
            self.stop_s is not None and self.stop_s <= self.start_s
        ):
            raise ValueError(
                f"{self.kind}: need 0 <= start_s < stop_s, got "
                f"[{self.start_s}, {self.stop_s})"
            )
        for p in self.planes:
            if p not in PLANES:
                raise ValueError(
                    f"{self.kind}: unknown plane {p!r}; one of {PLANES}"
                )
        if self.kind in _PROB_KINDS:
            if not (0.0 < self.prob <= 1.0):
                raise ValueError(
                    f"{self.kind}: prob must be in (0, 1], got {self.prob}"
                )
            lossy = (
                ("probe", "bcast") if self.kind == "loss" else ("probe",)
            )
            bad = [p for p in self.planes if p not in lossy]
            if bad:
                raise ValueError(
                    f"{self.kind}: planes {bad} unsupported — a TCP stream "
                    f"does not lose/duplicate frames (model it as delay); "
                    f"allowed: {lossy}"
                )
        if self.kind == "delay" and self.delay_ms <= 0:
            raise ValueError("delay: delay_ms must be > 0")
        if self.kind == "delay" and self.jitter_ms > self.delay_ms:
            raise ValueError(
                "delay: jitter_ms > delay_ms would mean negative latency"
            )
        if self.kind in ("partition", "flap") and not self.a:
            raise ValueError(f"{self.kind}: side `a` must name >= 1 node")
        if self.kind == "flap" and self.period_s <= 0:
            raise ValueError("flap: period_s must be > 0")

    def effective_planes(self, kind_default: tuple = PLANES) -> tuple:
        if self.planes:
            return self.planes
        if self.kind == "loss":
            return ("probe", "bcast")
        if self.kind in ("dup", "reorder"):
            return ("probe",)
        return kind_default

    def active_at(self, t: float) -> bool:
        if t < self.start_s:
            return False
        if self.stop_s is not None and t >= self.stop_s:
            return False
        if self.kind == "flap":
            # First half-cycle inside the window is the cut phase.
            return int((t - self.start_s) / self.period_s) % 2 == 0
        return True

    def cuts(self, src: str, dst: str) -> bool:
        """Partition/flap: does this component cut the src->dst link?"""
        def in_b(x: str) -> bool:
            # Unresolved peers ("?") never belong to a side: a component
            # cannot cut traffic whose endpoint it cannot name.
            if x == "?":
                return False
            return x in self.b if self.b else x not in self.a

        if src in self.a and in_b(dst):
            return True
        if not self.one_way and in_b(src) and dst in self.a:
            return True
        return False

    def matches_dir(self, src: str, dst: str) -> bool:
        """Directional kinds: does (src -> dst) match the link filter?"""
        return (not self.src or src in self.src) and (
            not self.dst or dst in self.dst
        )

    def to_dict(self) -> dict:
        d: dict = {"kind": self.kind, "start_s": self.start_s}
        if self.stop_s is not None:
            d["stop_s"] = self.stop_s
        if self.planes:
            d["planes"] = list(self.planes)
        if self.src:
            d["src"] = list(self.src)
        if self.dst:
            d["dst"] = list(self.dst)
        if self.kind == "delay":
            d["delay_ms"] = self.delay_ms
            if self.jitter_ms:
                d["jitter_ms"] = self.jitter_ms
        if self.kind in _PROB_KINDS:
            d["prob"] = self.prob
        if self.kind == "reorder":
            d["extra_ms"] = self.extra_ms
        if self.kind in ("blackhole", "partition", "flap"):
            d["stall_s"] = self.stall_s
        if self.kind in ("partition", "flap"):
            d["a"] = list(self.a)
            if self.b:
                d["b"] = list(self.b)
            if self.one_way:
                d["one_way"] = True
        if self.kind == "flap":
            d["period_s"] = self.period_s
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "HostFault":
        return cls(
            kind=d["kind"],
            start_s=float(d.get("start_s", 0.0)),
            stop_s=(
                None if d.get("stop_s") is None else float(d["stop_s"])
            ),
            planes=tuple(d.get("planes", ())),
            src=tuple(d.get("src", ())),
            dst=tuple(d.get("dst", ())),
            # No defaulting games: a delay component whose JSON lacks a
            # positive delay_ms must FAIL validation, not quietly become
            # a near-zero impairment that reports green.
            delay_ms=float(d.get("delay_ms", 0.0)),
            jitter_ms=float(d.get("jitter_ms", 0.0)),
            prob=float(d.get("prob", 1.0)),
            extra_ms=float(d.get("extra_ms", 50.0)),
            stall_s=float(d.get("stall_s", 0.3)),
            a=tuple(d.get("a", ())),
            b=tuple(d.get("b", ())),
            one_way=bool(d.get("one_way", False)),
            period_s=float(d.get("period_s", 0.0)),
        )


@dataclass(frozen=True)
class HostFaultPlan:
    faults: tuple = ()
    name: str = ""

    @property
    def empty(self) -> bool:
        return not self.faults

    def horizon_s(self) -> float:
        """First instant with every windowed component over (0 when the
        plan is empty or purely always-on)."""
        stops = [
            f.stop_s for f in self.faults
            if not (f.start_s == 0.0 and f.stop_s is None)
        ]
        if any(s is None for s in stops):
            return float("inf")
        return max((float(s) for s in stops), default=0.0)

    def to_json_obj(self) -> dict:
        return {
            "schema": PLAN_SCHEMA,
            "name": self.name,
            "faults": [f.to_dict() for f in self.faults],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_obj(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, src) -> "HostFaultPlan":
        d = json.loads(src) if isinstance(src, str) else src
        if d.get("schema") != PLAN_SCHEMA:
            raise ValueError(
                f"not a {PLAN_SCHEMA} document: schema={d.get('schema')!r}"
            )
        return cls(
            name=d.get("name", ""),
            faults=tuple(HostFault.from_dict(f) for f in d.get("faults", ())),
        )


@dataclass
class UdpVerdict:
    """Impairment decision for one outbound datagram."""

    drop: bool = False
    dup: bool = False
    delay_s: float = 0.0


@dataclass
class StreamVerdict:
    """Impairment decision for one stream operation (frame send, session
    open, session send). ``block_s`` set = the link is cut: stall that
    long, then fail (the dropped-SYN shape the circuit breaker exists
    for). ``drop`` = the frame silently vanishes (bcast loss)."""

    block_s: float | None = None
    drop: bool = False
    delay_s: float = 0.0


_NOOP_UDP = UdpVerdict()
_NOOP_STREAM = StreamVerdict()


class NetemShim:
    """Seeded per-link/per-plane impairment schedule (module docstring).

    ``clock`` is injectable for deterministic unit tests. Before
    :meth:`arm` only always-on components (``start_s == 0``,
    ``stop_s is None``) apply, so a scheduled partition can never fire
    while the harness is still launching the cluster; ``arm`` pins the
    window origin to "storm start".
    """

    TRACE_CAP = 20000

    def __init__(
        self,
        plan,
        seed: int = 0,
        local: str = "?",
        clock=time.monotonic,
    ) -> None:
        self.plan = (
            plan if isinstance(plan, HostFaultPlan)
            else HostFaultPlan.from_json(plan)
        )
        self.seed = int(seed)
        self.local = local
        self._clock = clock
        self._t0 = clock()
        self._armed = False
        self._peers: dict[tuple, str] = {}
        self._n: dict[tuple, int] = {}
        self.trace: list[dict] = []
        self.trace_overflow = 0
        self.stats = {
            "events": 0, "dropped": 0, "duplicated": 0, "delayed": 0,
            "blocked": 0,
        }

    @property
    def enabled(self) -> bool:
        return not self.plan.empty

    # -- wiring ---------------------------------------------------------------

    def register_peer(self, addr, name: str) -> None:
        self._peers[tuple(addr)] = name

    def arm(self, at: float | None = None) -> None:
        """Start the fault windows. ``at`` (a prior ``clock()`` reading)
        lets a restarted agent's fresh shim share the original origin so
        its windows line up with the rest of the cluster."""
        self._t0 = self._clock() if at is None else at
        self._armed = True

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def _peer(self, addr) -> str:
        try:
            return self._peers.get(tuple(addr), "?")
        except TypeError:
            return "?"

    # -- deterministic draws --------------------------------------------------

    def _u(self, plane: str, dst: str, n: int, salt: str) -> float:
        """Uniform in [0, 1): a pure function of the decision key — no
        RNG state, so the schedule replays from (plan, seed) alone."""
        h = hashlib.sha256(
            f"{self.seed}|{self.local}>{dst}|{plane}|{n}|{salt}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def _active(self, t: float):
        for i, f in enumerate(self.plan.faults):
            if not self._armed and not (
                f.start_s == 0.0 and f.stop_s is None
            ):
                continue  # scheduled windows wait for arm()
            if f.active_at(t):
                yield i, f

    # -- decision core --------------------------------------------------------

    def _verdict(self, plane: str, dst: str, n: int, t: float):
        """Compute (active component idxs, drop, dup, block_s, delay_s)
        for one event. Pure given (plan, seed, plane, dst, n, active
        set) — the replay contract."""
        idxs: list[int] = []
        drop = dup = False
        block: float | None = None
        delay = 0.0
        for i, f in self._active(t):
            if plane not in f.effective_planes():
                continue
            if f.kind in ("partition", "flap"):
                if f.cuts(self.local, dst):
                    idxs.append(i)
                    block = max(block or 0.0, f.stall_s)
                continue
            if not f.matches_dir(self.local, dst):
                continue
            if f.kind == "blackhole":
                idxs.append(i)
                block = max(block or 0.0, f.stall_s)
            elif f.kind == "delay":
                idxs.append(i)
                u = self._u(plane, dst, n, f"delay{i}")
                delay += max(
                    0.0, f.delay_ms + (2.0 * u - 1.0) * f.jitter_ms
                ) / 1000.0
            elif f.kind == "loss":
                idxs.append(i)
                if self._u(plane, dst, n, f"loss{i}") < f.prob:
                    drop = True
            elif f.kind == "dup":
                idxs.append(i)
                if self._u(plane, dst, n, f"dup{i}") < f.prob:
                    dup = True
            elif f.kind == "reorder":
                idxs.append(i)
                if self._u(plane, dst, n, f"reorder{i}") < f.prob:
                    delay += f.extra_ms / 1000.0
        return idxs, drop, dup, block, delay

    def _record(self, plane, dst, n, t, idxs, drop, dup, block, delay):
        self.stats["events"] += 1
        if drop or (block is not None and plane == "probe"):
            self.stats["dropped"] += 1
        if dup:
            self.stats["duplicated"] += 1
        if delay > 0:
            self.stats["delayed"] += 1
        if block is not None and plane != "probe":
            self.stats["blocked"] += 1
        if len(self.trace) >= self.TRACE_CAP:
            self.trace_overflow += 1
            return
        self.trace.append({
            "n": n, "plane": plane, "src": self.local, "dst": dst,
            "f": idxs, "drop": drop, "dup": dup,
            "block_s": block,
            "delay_ms": round(delay * 1000.0, 3),
            "t": round(t, 3),
        })

    def _next_n(self, plane: str, dst: str) -> int:
        key = (plane, dst)
        n = self._n.get(key, 0)
        self._n[key] = n + 1
        return n

    def udp_fault(self, addr) -> UdpVerdict:
        """Decision for one outbound SWIM datagram. A cut link (blackhole
        or partition) drops datagrams silently — UDP has no dial to
        stall."""
        t = self.elapsed()
        dst = self._peer(addr)
        n = self._next_n("probe", dst)
        idxs, drop, dup, block, delay = self._verdict("probe", dst, n, t)
        if not idxs:
            return _NOOP_UDP
        if block is not None:
            drop = True
        self._record("probe", dst, n, t, idxs, drop, dup, block, delay)
        return UdpVerdict(drop=drop, dup=dup, delay_s=delay)

    def stream_fault(self, plane: str, addr) -> StreamVerdict:
        """Decision for one stream operation on ``plane`` ("bcast" frame
        send or "sync" open/send) toward ``addr``."""
        t = self.elapsed()
        dst = self._peer(addr)
        n = self._next_n(plane, dst)
        idxs, drop, dup, block, delay = self._verdict(plane, dst, n, t)
        if not idxs:
            return _NOOP_STREAM
        self._record(plane, dst, n, t, idxs, drop, dup, block, delay)
        return StreamVerdict(block_s=block, drop=drop, delay_s=delay)

    # -- replay ---------------------------------------------------------------

    def fingerprint(self) -> str:
        """Stable hash over the decision-relevant part of the trace
        (wall times excluded — they jitter; decisions must not)."""
        return trace_fingerprint(self.trace)

    def replay_event(self, entry: dict):
        """Recompute one recorded decision from the plan + seed alone.
        Returns the (drop, dup, block_s, delay_ms) tuple the schedule
        dictates for that event."""
        plane, dst, n = entry["plane"], entry["dst"], entry["n"]
        drop = dup = False
        block: float | None = None
        delay = 0.0
        for i in entry["f"]:
            f = self.plan.faults[i]
            if f.kind in ("partition", "flap", "blackhole"):
                block = max(block or 0.0, f.stall_s)
            elif f.kind == "delay":
                u = self._u(plane, dst, n, f"delay{i}")
                delay += max(
                    0.0, f.delay_ms + (2.0 * u - 1.0) * f.jitter_ms
                ) / 1000.0
            elif f.kind == "loss":
                if self._u(plane, dst, n, f"loss{i}") < f.prob:
                    drop = True
            elif f.kind == "dup":
                if self._u(plane, dst, n, f"dup{i}") < f.prob:
                    dup = True
            elif f.kind == "reorder":
                if self._u(plane, dst, n, f"reorder{i}") < f.prob:
                    delay += f.extra_ms / 1000.0
        if block is not None and plane == "probe":
            drop = True
        return drop, dup, block, round(delay * 1000.0, 3)


def trace_fingerprint(trace: list[dict]) -> str:
    canon = [
        [e["n"], e["plane"], e["src"], e["dst"], list(e["f"]),
         bool(e["drop"]), bool(e["dup"]), e["block_s"], e["delay_ms"]]
        for e in trace
    ]
    return hashlib.sha256(
        json.dumps(canon, separators=(",", ":")).encode()
    ).hexdigest()


def replay_schedule(
    plan, seed: int, local: str, trace: list[dict]
) -> tuple[bool, list[str]]:
    """Mechanical schedule-replay check: every recorded decision must be
    reproduced exactly by the pure (plan, seed) function. Returns
    ``(ok, mismatches)``."""
    shim = NetemShim(plan, seed=seed, local=local)
    mismatches: list[str] = []
    for e in trace:
        try:
            if e["src"] != local:
                mismatches.append(
                    f"event n={e['n']}: src {e['src']!r} != shim local "
                    f"{local!r}"
                )
                continue
            drop, dup, block, delay_ms = shim.replay_event(e)
            got = (
                bool(e["drop"]), bool(e["dup"]), e["block_s"], e["delay_ms"]
            )
        except (KeyError, IndexError, TypeError) as err:
            # A tampered/corrupt entry (component index outside the
            # plan, missing keys) is a mismatch to DIAGNOSE, not a
            # traceback.
            mismatches.append(
                f"structurally invalid trace entry {e!r}: {err!r}"
            )
            continue
        want = (drop, dup, block, delay_ms)
        if got != want:
            mismatches.append(
                f"event n={e['n']} {e['plane']} {e['src']}->{e['dst']}: "
                f"recorded {got} != replayed {want}"
            )
    return not mismatches, mismatches
