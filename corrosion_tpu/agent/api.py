"""HTTP public API over asyncio streams.

The reference serves axum routes /v1/transactions, /v1/queries,
/v1/migrations, /v1/subscriptions (corro-agent/src/agent.rs:833-931,
api/public/mod.rs). Python's stdlib has no async HTTP server, so this is a
deliberately small HTTP/1.1 implementation: enough for JSON request bodies,
JSON responses, and chunked NDJSON streaming for queries and subscriptions
(the reference streams QueryEvents as newline-delimited JSON,
api/public/pubsub.rs).
"""

from __future__ import annotations

import asyncio
import json
from typing import TYPE_CHECKING
from urllib.parse import parse_qs, urlparse

from corrosion_tpu.core.values import Statement

if TYPE_CHECKING:
    from corrosion_tpu.agent.agent import Agent

MAX_BODY = 64 * 1024 * 1024
# Header-section caps: an abusive or buggy client must not be able to
# buffer unbounded memory on the server by streaming headers forever.
# asyncio's stream limit (64 KiB) already bounds any SINGLE line; these
# bound the count and the section total, answered with 431.
MAX_HEADER_COUNT = 128
MAX_HEADER_BYTES = 32 * 1024


class HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class RouteLimit:
    """Admission control per route: the reference wraps every /v1 route in
    a concurrency limit + load-shed (128 per route, 4 for migrations;
    agent.rs:836-902). Handlers run on one event loop, so a plain counter
    suffices; over-limit requests shed immediately with 503.

    When a ``MetricsRegistry`` is wired (``rebuild_api_limits``), shed
    decisions and the live admission count are visible on /metrics as
    ``corro_api_shed_total{route=...}`` / ``corro_api_inflight{route=...}``
    — so a load generator's client-side 503 accounting can be
    cross-checked against the server's own."""

    def __init__(self, limit: int, route: str = "", metrics=None):
        self.limit = limit
        self.active = 0
        self.route = route
        self._shed = (
            metrics.counter(
                "corro_api_shed_total",
                "requests shed (503) by per-route admission control",
            )
            if metrics is not None else None
        )
        self._inflight = (
            metrics.gauge(
                "corro_api_inflight",
                "requests currently holding a per-route admission slot",
            )
            if metrics is not None else None
        )

    def __enter__(self):
        if self.active >= self.limit:
            if self._shed is not None:
                self._shed.inc(route=self.route)
            raise HttpError(503, "concurrency limit reached (load shed)")
        self.active += 1
        if self._inflight is not None:
            # add(), not set(self.active): after a config hot-reload
            # (rebuild_api_limits) old and new RouteLimit instances
            # briefly coexist on the same gauge label — deltas keep the
            # published value equal to TOTAL in-flight across both,
            # where a set() from a draining old instance would clobber
            # the new one's count.
            self._inflight.add(1, route=self.route)
        return self

    def __exit__(self, *exc):
        self.active -= 1
        if self._inflight is not None:
            self._inflight.add(-1, route=self.route)


async def _read_request(reader: asyncio.StreamReader):
    try:
        line = await reader.readline()
    except ValueError:
        # asyncio stream-limit overrun: a request line longer than the
        # 64 KiB buffer. The read side is no longer line-synchronized,
        # so the caller closes the connection after responding.
        raise HttpError(431, "request line too long")
    if not line:
        return None
    try:
        method, target, _version = line.decode().split()
    except ValueError:
        raise HttpError(400, "bad request line")
    headers = {}
    header_bytes = 0
    while True:
        try:
            h = await reader.readline()
        except ValueError:
            raise HttpError(431, "header line too long")
        if h in (b"\r\n", b"\n", b""):
            break
        header_bytes += len(h)
        if (
            len(headers) >= MAX_HEADER_COUNT
            or header_bytes > MAX_HEADER_BYTES
        ):
            raise HttpError(431, "too many request headers")
        k, _, v = h.decode().partition(":")
        headers[k.strip().lower()] = v.strip()
    body = b""
    try:
        n = int(headers.get("content-length", 0))
    except ValueError:
        raise HttpError(400, "bad content-length")
    if n < 0:
        raise HttpError(400, "bad content-length")
    if n:
        if n > MAX_BODY:
            raise HttpError(413, "body too large")
        body = await reader.readexactly(n)
    return method, target, headers, body


def _resp(writer, status: int, body: bytes, content_type="application/json"):
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              413: "Payload Too Large",
              431: "Request Header Fields Too Large",
              500: "Internal Server Error",
              501: "Not Implemented",
              503: "Service Unavailable"}.get(status, "?")
    writer.write(
        f"HTTP/1.1 {status} {reason}\r\n"
        f"content-type: {content_type}\r\n"
        f"content-length: {len(body)}\r\n"
        "connection: keep-alive\r\n\r\n".encode() + body
    )


def _json_resp(writer, status: int, obj) -> None:
    _resp(writer, status, json.dumps(obj).encode())


async def _start_stream(writer, content_type="application/json"):
    writer.write(
        "HTTP/1.1 200 OK\r\n"
        f"content-type: {content_type}\r\n"
        "transfer-encoding: chunked\r\n"
        "connection: close\r\n\r\n".encode()
    )
    await writer.drain()


async def _stream_chunk(writer, data: bytes) -> None:
    writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
    await writer.drain()


async def _end_stream(writer) -> None:
    writer.write(b"0\r\n\r\n")
    await writer.drain()


async def serve_api(agent: "Agent") -> tuple[str, int]:
    async def on_conn(reader, writer):
        try:
            while True:
                try:
                    req = await _read_request(reader)
                except HttpError as e:
                    _json_resp(writer, e.status, {"error": e.message})
                    await writer.drain()
                    if e.status in (431, 413):
                        # Bounded best-effort input drain before close,
                        # ONLY for the desync statuses whose request
                        # bytes are known-unread: closing with unread
                        # input RSTs the connection and can destroy the
                        # error response before the client reads it.
                        # Other errors (400s from a clean read) must not
                        # pay a 0.2 s lingering read per connection.
                        # Hard-capped — this must never become the
                        # unbounded read it guards against.
                        try:
                            for _ in range(16):
                                chunk = await asyncio.wait_for(
                                    reader.read(65536), 0.2
                                )
                                if not chunk:
                                    break
                        except (asyncio.TimeoutError, ConnectionError,
                                ValueError):
                            pass
                    break
                if req is None:
                    break
                method, target, headers, body = req
                url = urlparse(target)
                try:
                    keep = await _route(
                        agent, reader, writer, method, url.path,
                        parse_qs(url.query), body, headers,
                    )
                except HttpError as e:
                    _json_resp(writer, e.status, {"error": e.message})
                    keep = True
                except Exception as e:  # 500 (load-shed analogue is upstream)
                    _json_resp(writer, 500, {"error": repr(e)})
                    keep = True
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    rebuild_api_limits(agent)
    server = await asyncio.start_server(
        on_conn, agent.cfg.api_host, agent.cfg.api_port
    )
    agent._api_server = server
    sock = server.sockets[0].getsockname()
    return sock[0], sock[1]


def rebuild_api_limits(agent) -> None:
    """(Re)build the per-route admission limits from the current config —
    called at serve time and by config hot-reload so a changed
    api_concurrency takes effect without restart. In-flight requests keep
    their old limiter; new requests see the new one."""
    n = agent.cfg.api_concurrency
    metrics = getattr(agent, "metrics", None)

    def rl(route: str, limit: int) -> RouteLimit:
        return RouteLimit(limit, route=route, metrics=metrics)

    agent._api_limits = {
        "/v1/transactions": rl("/v1/transactions", n),
        "/v1/queries": rl("/v1/queries", n),
        "/v1/migrations": rl(
            "/v1/migrations", agent.cfg.migration_concurrency
        ),
        "/v1/subscriptions": rl("/v1/subscriptions", n),
    }


async def _route(
    agent, reader, writer, method, path, query, body, headers=None
) -> bool:
    """Dispatch; returns False when the connection was turned into a stream
    (and must close when the stream ends)."""
    route_key = "/".join(path.split("/")[:3])  # /v1/<route>
    limit = agent._api_limits.get(route_key)
    if limit is None:
        return await _dispatch(
            agent, reader, writer, method, path, query, body, lambda: None,
            headers,
        )
    # The limit bounds request SETUP, not stream lifetime: the reference's
    # ConcurrencyLimitLayer releases its permit when the handler returns
    # the response, before the body streams — a long-lived subscription
    # must not pin an admission slot (the 129th subscriber would shed).
    # Streaming branches call ``release`` once setup is done; the finally
    # covers every other path (idempotent via the once-guard).
    limit.__enter__()
    released = False

    def release() -> None:
        nonlocal released
        if not released:
            released = True
            limit.__exit__(None, None, None)

    try:
        return await _dispatch(
            agent, reader, writer, method, path, query, body, release,
            headers,
        )
    finally:
        release()


async def _dispatch(
    agent, reader, writer, method, path, query, body, release, headers=None
) -> bool:
    if method == "POST" and path == "/v1/transactions":
        stmts = [Statement.parse(o) for o in _json_body(body)]
        # Causal write tracing (opt-in, AgentConfig.trace_writes): every
        # write gets a trace id HERE, at ingest — continuing the client's
        # W3C `traceparent` header when one came in, so an end-to-end
        # journey joins on the caller's trace id. The root `api_write`
        # span covers request handling through the response body build;
        # the commit/fan-out children open inside execute_async. The
        # default path allocates no spans (pinned by tests).
        span = (
            agent.tracer.maybe_span(
                "api_write",
                traceparent=(headers or {}).get("traceparent"),
                route=path,
            )
            if getattr(agent, "_trace_writes", False) else None
        )
        if span is None:
            resp = await agent.execute_async(stmts)
        else:
            with span:
                resp = await agent.execute_async(stmts)
        _json_resp(writer, 200, resp.to_json_obj())
        return True
    if method == "POST" and path == "/v1/queries":
        stmt = Statement.parse(_json_body(body))
        # Pooled snapshot read (SplitPool read pool): large results never
        # stall the gossip loops.
        cols, rows = await agent.pool.query(stmt)
        await _start_stream(writer)
        await _stream_chunk(
            writer, json.dumps({"columns": cols}).encode() + b"\n"
        )
        for i, row in enumerate(rows):
            await _stream_chunk(
                writer,
                json.dumps({"row": [i + 1, _jsonable(row)]}).encode() + b"\n",
            )
        await _stream_chunk(writer, b'{"eoq":{}}\n')
        await _end_stream(writer)
        return False
    if method == "POST" and path == "/v1/migrations":
        stmts = _json_body(body)
        changed = agent.store.apply_schema(
            "\n".join(stmts if isinstance(stmts, list) else [stmts])
        )
        _json_resp(writer, 200, {"changed": changed})
        return True
    if method == "POST" and path == "/v1/subscriptions":
        if agent.subs is None:
            raise HttpError(501, "subscriptions not enabled")
        stmt = Statement.parse(_json_body(body))
        handle = agent.subs.subscribe(stmt.sql)
        release()  # setup done; the stream must not hold an admission slot
        await _stream_sub(agent, reader, writer, handle, from_change=None,
                          skip_rows=query.get("skip_rows") == ["true"])
        return False
    if method == "GET" and path == "/v1/subs/costs":
        # Live cost-ledger snapshot (docs/SERVING.md "Query-cost plane"):
        # top-K subscriptions by total eval seconds plus ledger-wide
        # totals. Works with the plane disarmed too — plan records are
        # always present; counters appear once enable_costs armed it.
        if agent.subs is None:
            raise HttpError(501, "subscriptions not enabled")
        top_q = query.get("top")
        try:
            top = int(top_q[0]) if top_q else None
        except ValueError as e:
            raise HttpError(400, f"bad top= value: {top_q[0]!r}") from e
        if top is not None and top < 0:
            raise HttpError(400, "top= must be >= 0")
        _json_resp(writer, 200, agent.subs.cost_snapshot(top=top))
        return True
    if method == "GET" and path.startswith("/v1/subscriptions/"):
        if agent.subs is None:
            raise HttpError(501, "subscriptions not enabled")
        sub_id = path.rsplit("/", 1)[1]
        handle = agent.subs.get(sub_id)
        if handle is None:
            raise HttpError(404, f"no such subscription {sub_id}")
        frm = query.get("from")
        release()  # setup done; the stream must not hold an admission slot
        await _stream_sub(
            agent, reader, writer, handle,
            from_change=int(frm[0]) if frm else None,
            skip_rows=query.get("skip_rows") == ["true"],
        )
        return False
    raise HttpError(404, f"no route for {method} {path}")


async def _stream_sub(
    agent, reader, writer, handle, from_change, skip_rows
) -> None:
    """NDJSON QueryEvent stream (api/public/pubsub.rs:36-180)."""
    await _start_stream(writer)
    queue = handle.attach()
    # Disconnect watch: an idle stream never writes, so a vanished client
    # would otherwise hold the handler (and its admission-control slot)
    # forever. Clients send nothing after the request, so any read
    # completion — EOF included — means the peer is gone. Deliberate
    # trade-off: a client that half-closes its write side (SHUT_WR) while
    # still reading gets its stream ended — admission-control slots must
    # not leak, and the SDK never half-closes; reconnect via ?from= covers
    # the exotic client.
    eof = asyncio.ensure_future(reader.read(1))
    try:
        for ev in handle.backlog(from_change=from_change, skip_rows=skip_rows):
            await _stream_chunk(
                writer, json.dumps(_json_safe(ev.to_json_obj())).encode() + b"\n"
            )
        while not agent.tripwire.tripped and not eof.done():
            if handle.lossy(queue):
                # The listener queue overflowed: events were dropped, so
                # continuing would silently violate exactly-once
                # delivery. Flush what IS queued (all older than the
                # drop), then end the stream — the client reconnects
                # with ?from= and the durable log replays the gap.
                while True:
                    try:
                        ev = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    await _stream_chunk(
                        writer,
                        json.dumps(
                            _json_safe(ev.to_json_obj())
                        ).encode() + b"\n",
                    )
                break
            try:
                ev = await asyncio.wait_for(queue.get(), timeout=0.5)
            except asyncio.TimeoutError:
                continue
            await _stream_chunk(
                writer, json.dumps(_json_safe(ev.to_json_obj())).encode() + b"\n"
            )
    finally:
        eof.cancel()
        handle.detach(queue)
        try:
            await _end_stream(writer)
        except (ConnectionError, OSError):
            pass


def _json_body(body: bytes):
    if not body:
        raise HttpError(400, "empty body")
    try:
        return json.loads(body)
    except json.JSONDecodeError as e:
        raise HttpError(400, f"bad json: {e}")


def _jsonable(row):
    return [
        v.hex() if isinstance(v, bytes) else v for v in row
    ]


def _json_safe(obj):
    """Recursive bytes -> hex for event payloads (BLOB cells)."""
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj
