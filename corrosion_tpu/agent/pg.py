"""PostgreSQL wire-protocol server — the corro-pg analogue.

The reference serves the pgwire protocol, translating PG SQL to SQLite and
executing against the agent DB with full bookkeeping + broadcast parity
(corro-pg/src/lib.rs:474-1769). This implementation speaks protocol v3's
startup + simple-query flow (plus SSLRequest refusal and Terminate):
SELECTs run on the store's read connection; writes run through
Agent.execute so version allocation, bookkeeping, and dissemination are
identical to the HTTP path (the parity that matters, lib.rs write path).

Everything is typed as text on the wire (like psql's default rendering).
Both protocol flows are served: the simple-query flow ('Q') and the
extended flow (Parse/Bind/Describe/Execute/Close/Sync/Flush — what libpq's
PQexecParams and most drivers send), with PG's ``$N`` placeholders
translated to SQLite ``?N``. Text parameter/result format only; a client
requesting binary gets a clean protocol error.
"""

from __future__ import annotations

import asyncio
import logging
import re
import sqlite3
import struct
from typing import TYPE_CHECKING

from corrosion_tpu.core.values import Statement

if TYPE_CHECKING:
    from corrosion_tpu.agent.agent import Agent

SSL_REQUEST = 80877103
PROTOCOL_V3 = 196608
TEXT_OID = 25
BOOL_OID = 16
BYTEA_OID = 17
INT2_OID, INT4_OID, INT8_OID = 21, 23, 20
FLOAT4_OID, FLOAT8_OID = 700, 701

# Parameter OIDs we coerce from text (ints/floats/bool); everything else
# stays a string and relies on SQLite column affinity.
_INT_OIDS = {20, 21, 23, 26}
_FLOAT_OIDS = {700, 701, 1700}
_BOOL_OID = BOOL_OID


# SQLSTATE mapping for SQLite error text (the role of corro-pg's
# sql_state.rs, 1336 LoC of codes; these are the ones SQLite can actually
# produce through this server).
_SQLSTATE_PATTERNS = [
    (re.compile(r"(?i)no such table"), "42P01"),  # undefined_table
    (re.compile(r"(?i)no such column"), "42703"),  # undefined_column
    (re.compile(r"(?i)syntax error"), "42601"),  # syntax_error
    (re.compile(r"(?i)ambiguous column"), "42702"),  # ambiguous_column
    (re.compile(r"(?i)UNIQUE constraint failed"), "23505"),  # unique_violation
    (re.compile(r"(?i)NOT NULL constraint failed"), "23502"),  # not_null
    (re.compile(r"(?i)CHECK constraint failed"), "23514"),  # check_violation
    (re.compile(r"(?i)FOREIGN KEY constraint failed"), "23503"),  # fk
    (re.compile(r"(?i)datatype mismatch"), "22P02"),  # invalid_text_rep
    (re.compile(r"(?i)attempt to write a readonly"), "25006"),  # read_only
    (re.compile(r"(?i)database is locked"), "55P03"),  # lock_not_available
    (re.compile(r"(?i)too many terms|parser stack overflow"), "54001"),
]


def sqlstate_for(message: str) -> str:
    """Best-fit SQLSTATE for an engine error message (sql_state.rs role)."""
    for pat, code in _SQLSTATE_PATTERNS:
        if pat.search(message):
            return code
    return "XX000"


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _error(message: str, code: str = "XX000") -> bytes:
    fields = b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    return _msg(b"E", fields)


def _infer_oids(rows: list, n_cols: int) -> list[int]:
    """Column type oids from the first non-NULL value per column (SQLite is
    dynamically typed; drivers want real oids for type mapping)."""
    oids = [TEXT_OID] * n_cols
    for c in range(n_cols):
        for row in rows:
            v = row[c]
            if v is None:
                continue
            if isinstance(v, bool):
                oids[c] = BOOL_OID
            elif isinstance(v, int):
                oids[c] = INT8_OID
            elif isinstance(v, float):
                oids[c] = FLOAT8_OID
            elif isinstance(v, bytes):
                oids[c] = BYTEA_OID
            break
    return oids


def _row_description(
    cols: list[str], oids: list[int] | None = None,
    fmts: list[int] | None = None,
) -> bytes:
    body = struct.pack(">H", len(cols))
    for i, name in enumerate(cols):
        oid = oids[i] if oids else TEXT_OID
        fmt = fmts[i] if fmts else 0
        body += _cstr(name)
        body += struct.pack(">IhIhih", 0, 0, oid, -1, -1, fmt)
    return _msg(b"T", body)


def _encode_binary(v, oid: int) -> bytes:
    """Binary result encoding per oid (the formats real drivers request)."""
    if oid == INT8_OID and isinstance(v, int):
        return struct.pack(">q", v)
    if oid == INT4_OID and isinstance(v, int):
        return struct.pack(">i", v)
    if oid == INT2_OID and isinstance(v, int):
        return struct.pack(">h", v)
    if oid == FLOAT8_OID and isinstance(v, (int, float)):
        return struct.pack(">d", float(v))
    if oid == FLOAT4_OID and isinstance(v, (int, float)):
        return struct.pack(">f", float(v))
    if oid == BOOL_OID:
        return b"\x01" if v else b"\x00"
    if isinstance(v, bytes):
        return v  # bytea binary = raw bytes
    # text/varchar binary representation == utf-8 text
    return str(v).encode()


def _text_cell(v) -> bytes:
    if isinstance(v, bytes):
        return ("\\x" + v.hex()).encode()
    if isinstance(v, bool):
        return b"t" if v else b"f"
    return str(v).encode()


def _data_row(
    row, rfmts: list[int] | None = None, oids: list[int] | None = None
) -> bytes:
    body = struct.pack(">H", len(row))
    for i, v in enumerate(row):
        if v is None:
            body += struct.pack(">i", -1)
            continue
        fmt = rfmts[i] if rfmts else 0
        if fmt == 1:
            raw = _encode_binary(v, oids[i] if oids else TEXT_OID)
        else:
            raw = _text_cell(v)
        body += struct.pack(">i", len(raw)) + raw
    return body and _msg(b"D", body)


def _command_complete(tag: str) -> bytes:
    return _msg(b"C", _cstr(tag))


def _ready() -> bytes:
    return _msg(b"Z", b"I")


def _is_query(sql: str) -> bool:
    head = sql.lstrip().split(None, 1)
    word = head[0].upper() if head else ""
    return word in ("SELECT", "WITH", "EXPLAIN", "PRAGMA", "VALUES", "SHOW")


def translate_pg_sql(sql: str) -> str:
    """PG->SQLite surface translation (corro-pg's parse_query,
    lib.rs:306-472 via sqlparser; here: the dialect constructs drivers and
    hand-written PG SQL actually emit — session shims, ``::`` casts,
    boolean literals, ILIKE, E'...' escape strings)."""
    s = sql.strip().rstrip(";")
    upper = s.upper()
    if upper in ("BEGIN", "COMMIT", "ROLLBACK", "START TRANSACTION"):
        return ""  # the agent wraps writes in its own transaction
    if upper.startswith("SET ") or upper.startswith("SHOW "):
        return ""
    # Session-introspection shims clients issue at connect time — applied
    # only OUTSIDE string/identifier literals (an INSERT of the literal
    # 'current_user' must pass through untouched).
    s = _sub_unquoted(s, _SESSION_SHIMS)
    s = _sub_unquoted(s, _DIALECT_SUBS)
    s = _translate_casts(s)
    s = _translate_estrings(s)
    return s


# PG type name → SQLite CAST target (affinity groups).
_PG_TYPE_MAP = {
    "int2": "INTEGER", "int4": "INTEGER", "int8": "INTEGER",
    "smallint": "INTEGER", "integer": "INTEGER", "int": "INTEGER",
    "bigint": "INTEGER", "serial": "INTEGER", "bigserial": "INTEGER",
    "oid": "INTEGER", "bool": "INTEGER", "boolean": "INTEGER",
    "float4": "REAL", "float8": "REAL", "real": "REAL",
    "numeric": "REAL", "decimal": "REAL",
    "text": "TEXT", "varchar": "TEXT", "char": "TEXT", "bpchar": "TEXT",
    "name": "TEXT", "uuid": "TEXT", "json": "TEXT", "jsonb": "TEXT",
    "regclass": "TEXT", "regtype": "TEXT",
    "bytea": "BLOB",
}

_DIALECT_SUBS = [
    # Boolean literals → SQLite integers (corro-pg translates via sqlparser).
    (re.compile(r"(?i)\btrue\b"), "1"),
    (re.compile(r"(?i)\bfalse\b"), "0"),
    # SQLite LIKE is already case-insensitive for ASCII.
    (re.compile(r"(?i)\bilike\b"), "LIKE"),
]

# `token::type` where token is a quote-terminated literal, number,
# placeholder, identifier, or closing paren. Paren-closed expressions keep
# their value and drop the cast (SQLite's dynamic typing absorbs it);
# simple tokens become CAST(token AS affinity).
_CAST_RE = re.compile(
    r"(\)|\?\d*|[A-Za-z_][\w.]*|\d+(?:\.\d+)?)\s*::\s*"
    r"([A-Za-z_][\w]*)(?:\s*\(\s*\d+\s*\))?"
)


def _translate_casts(sql: str) -> str:
    def repl(m: re.Match) -> str:
        token, typ = m.group(1), m.group(2).lower()
        target = _PG_TYPE_MAP.get(typ)
        if token == ")" or target is None:
            return token  # drop the cast, keep the value
        return f"CAST({token} AS {target})"

    # Merge adjacent quoted segments first: a doubled-quote literal
    # ('it''s') scans as two adjacent quoted runs, and a cast applied to
    # it must wrap the WHOLE literal, not the final fragment.
    parts: list[tuple[bool, str]] = []
    for quoted, seg in _split_quoted(sql):
        if quoted and parts and parts[-1][0]:
            parts[-1] = (True, parts[-1][1] + seg)
        else:
            parts.append((quoted, seg))
    out = []
    for quoted, seg in parts:
        if quoted:
            # A cast can follow a string literal: 'x'::text — handled by
            # peeking in the NEXT unquoted segment (the '::type' prefix).
            out.append(seg)
        else:
            # Cast applied to the preceding quoted literal.
            m = re.match(r"\s*::\s*([A-Za-z_][\w]*)(?:\s*\(\s*\d+\s*\))?", seg)
            if m and out and out[-1].startswith(("'", '"')):
                typ = m.group(1).lower()
                target = _PG_TYPE_MAP.get(typ)
                lit = out.pop()
                if target is None:
                    out.append(lit)
                else:
                    out.append(f"CAST({lit} AS {target})")
                seg = seg[m.end():]
            out.append(_CAST_RE.sub(repl, seg))
    return "".join(out)


_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
    "\\": "\\", "'": "'", '"': '"',
}


def _translate_estrings(sql: str) -> str:
    """PG E'...' escape strings → standard SQL literals (SQLite has no
    backslash escapes; a passed-through E-string would keep literal
    backslashes)."""
    parts = _split_quoted(sql)
    out: list[str] = []
    for i, (quoted, seg) in enumerate(parts):
        if (
            quoted
            and seg.startswith("'")
            and out
            and out[-1]
            and out[-1][-1] in "eE"
            and (len(out[-1]) < 2 or not (
                out[-1][-2].isalnum() or out[-1][-2] == "_"
            ))
        ):
            body = seg[1:-1] if seg.endswith("'") and len(seg) > 1 else seg[1:]
            decoded = []
            j = 0
            while j < len(body):
                if body[j] == "\\" and j + 1 < len(body):
                    decoded.append(_ESCAPES.get(body[j + 1], body[j + 1]))
                    j += 2
                else:
                    decoded.append(body[j])
                    j += 1
            out[-1] = out[-1][:-1]  # drop the E prefix
            out.append("'" + "".join(decoded).replace("'", "''") + "'")
        else:
            out.append(seg)
    return "".join(out)


_SESSION_SHIMS = [
    (re.compile(r"(?i)\bversion\s*\(\s*\)"),
     "'corrosion-tpu (PostgreSQL 14 compatible)'"),
    (re.compile(r"(?i)\bcurrent_database\s*\(\s*\)"), "'corrosion'"),
    (re.compile(r"(?i)\bcurrent_schema\s*\(\s*\)"), "'public'"),
    (re.compile(r"(?i)\bpg_backend_pid\s*\(\s*\)"), "1"),
    (re.compile(r"(?i)\b(current_user|session_user)\b"), "'corrosion'"),
]


# A dollar-quote opener: $$ or $tag$ (tags are identifiers, so a $N
# parameter placeholder never matches).
_DOLLAR_TAG = re.compile(r"\$(?:[A-Za-z_][A-Za-z_0-9]*)?\$")


def _split_quoted(sql: str) -> list[tuple[bool, str]]:
    """Split SQL into (is_quoted, segment) runs; quoted segments include
    their delimiters. A doubled quote ('it''s') splits into two adjacent
    quoted segments — the literal's content never lands in an unquoted
    run, which is the property the callers rely on. Recognizes PG
    dollar-quoted blocks ($$...$$ / $tag$...$tag$) and backslash escapes
    inside E'...' literals, so shim/placeholder rewriting never corrupts
    their contents."""
    out: list[tuple[bool, str]] = []
    buf: list[str] = []
    i, n = 0, len(sql)

    def flush() -> None:
        if buf:
            out.append((False, "".join(buf)))
            buf.clear()

    while i < n:
        ch = sql[i]
        if ch in ("'", '"'):
            # E'...' (the E stays in the unquoted run) honors backslash
            # escapes; plain literals treat backslash as data.
            esc = (
                ch == "'"
                and buf
                and buf[-1] in "eE"
                and (len(buf) < 2 or not (buf[-2].isalnum() or buf[-2] == "_"))
            )
            flush()
            j = i + 1
            while j < n and sql[j] != ch:
                j += 2 if esc and sql[j] == "\\" else 1
            end = min(j + 1, n)
            out.append((True, sql[i:end]))
            i = end
            continue
        if ch == "$":
            m = _DOLLAR_TAG.match(sql, i)
            if m:
                tag = m.group(0)
                close = sql.find(tag, m.end())
                end = n if close < 0 else close + len(tag)
                flush()
                out.append((True, sql[i:end]))
                i = end
                continue
        buf.append(ch)
        i += 1
    flush()
    return out


def _sub_unquoted(sql: str, subs) -> str:
    parts = []
    for quoted, seg in _split_quoted(sql):
        if not quoted:
            for pat, repl in subs:
                seg = pat.sub(repl, seg)
        parts.append(seg)
    return "".join(parts)


def _mentions_catalog(sql: str) -> bool:
    return any(
        _CATALOG_RE.search(seg)
        for quoted, seg in _split_quoted(sql)
        if not quoted
    )


# -- pg_catalog (the reference's vtabs: corro-pg/src/vtab/{pg_type 405,
# pg_class 113, pg_namespace 108, pg_database 166, pg_range} LoC) ----------

_CATALOG_RE = re.compile(
    r"(?i)\b(?:pg_catalog\.)?"
    r"(pg_type|pg_class|pg_namespace|pg_database|pg_range|pg_attribute"
    r"|pg_tables)\b"
)

# (oid, typname, typlen): the types the wire layer speaks.
_PG_TYPES = [
    (16, "bool", 1), (17, "bytea", -1), (20, "int8", 8), (21, "int2", 2),
    (23, "int4", 4), (25, "text", -1), (700, "float4", 4),
    (701, "float8", 8), (1043, "varchar", -1), (1700, "numeric", -1),
]
_NS_CATALOG, _NS_PUBLIC = 11, 2200
_FIRST_REL_OID = 16384


def catalog_conn(agent: "Agent") -> sqlite3.Connection:
    """A pg_catalog snapshot derived from the live schema, built as TEMP
    tables on a fresh read connection to the real database — so catalog
    queries can also join user tables, like the reference's virtual tables
    (which live on every connection).

    Per-query construction keeps it automatically in sync with migrations;
    introspection traffic (psql \\d, ORM table listing at connect) is rare
    enough that rebuild cost is irrelevant.
    """
    c = sqlite3.connect(agent.store.path)
    c.executescript(
        """
        CREATE TEMP TABLE pg_type (oid INT, typname TEXT, typlen INT,
          typtype TEXT, typnamespace INT);
        CREATE TEMP TABLE pg_namespace (oid INT, nspname TEXT);
        CREATE TEMP TABLE pg_database (oid INT, datname TEXT);
        CREATE TEMP TABLE pg_class (oid INT, relname TEXT, relnamespace INT,
          relkind TEXT);
        CREATE TEMP TABLE pg_attribute (attrelid INT, attname TEXT,
          atttypid INT, attnum INT, attnotnull INT, attisdropped INT);
        CREATE TEMP TABLE pg_range (rngtypid INT, rngsubtype INT);
        CREATE TEMP TABLE pg_tables (schemaname TEXT, tablename TEXT);
        """
    )
    c.executemany(
        "INSERT INTO pg_type VALUES (?, ?, ?, 'b', ?)",
        [(o, n, l, _NS_CATALOG) for o, n, l in _PG_TYPES],
    )
    c.executemany(
        "INSERT INTO pg_namespace VALUES (?, ?)",
        [(_NS_CATALOG, "pg_catalog"), (_NS_PUBLIC, "public")],
    )
    c.execute("INSERT INTO pg_database VALUES (1, 'corrosion')")
    oid = _FIRST_REL_OID
    for name, info in sorted(agent.store.tables().items()):
        c.execute(
            "INSERT INTO pg_class VALUES (?, ?, ?, 'r')",
            (oid, name, _NS_PUBLIC),
        )
        c.execute("INSERT INTO pg_tables VALUES ('public', ?)", (name,))
        decl = {
            row[1]: (row[2] or "") for row in c.execute(
                f'PRAGMA table_info("{name}")'
            )
        }
        for attnum, col in enumerate(
            [*info.pk_cols, *info.data_cols], start=1
        ):
            c.execute(
                "INSERT INTO pg_attribute VALUES (?, ?, ?, ?, ?, 0)",
                (oid, col, _affinity_oid(decl.get(col, "")), attnum,
                 int(col in info.pk_cols)),
            )
        oid += 1
    return c


def _affinity_oid(decl_type: str) -> int:
    """SQLite declared type → pg_type oid, by SQLite's affinity rules."""
    t = decl_type.upper()
    if "INT" in t:
        return 20  # int8
    if "CHAR" in t or "CLOB" in t or "TEXT" in t:
        return 25
    if "BLOB" in t or not t:
        return 17
    if "REAL" in t or "FLOA" in t or "DOUB" in t:
        return 701
    return 1700  # NUMERIC affinity


async def _run_query(
    agent: "Agent", sql: str, params: list | None = None
) -> tuple[list[str], list]:
    """Route a read: queries touching pg_catalog names (outside string
    literals) go to the catalog-snapshot connection — which also sees the
    user tables — everything else to the agent's read pool."""
    if _mentions_catalog(sql):
        def run():
            c = catalog_conn(agent)
            try:
                cur = c.execute(
                    _sub_unquoted(sql, _CATALOG_PREFIX_STRIP),
                    tuple(params or ()),
                )
                cols = (
                    [d[0] for d in cur.description] if cur.description else []
                )
                return cols, cur.fetchall()
            finally:
                c.close()

        return await asyncio.to_thread(run)
    return await agent.pool.query(Statement(sql, params=params))


_CATALOG_PREFIX_STRIP = [(re.compile(r"(?i)\bpg_catalog\."), "")]


_PLACEHOLDER_SUB = [(re.compile(r"\$(\d+)"), r"?\1")]


def translate_placeholders(sql: str) -> str:
    """PG ``$N`` → SQLite ``?N``, outside string/identifier literals
    (one quote scanner — ``_split_quoted`` — serves shims, catalog
    routing, and placeholder translation alike)."""
    return _sub_unquoted(sql, _PLACEHOLDER_SUB)


class _Prepared:
    def __init__(self, sql: str, param_oids: list[int]):
        self.raw = sql
        self.translated = translate_pg_sql(translate_placeholders(sql))
        self.param_oids = param_oids


class _Portal:
    def __init__(
        self, prepared: _Prepared, params: list,
        rfmts: list[int] | None = None,
    ):
        self.prepared = prepared
        self.params = params
        self.rfmts = rfmts or []
        self.described: tuple[list[str], list[tuple]] | None = None

    def col_fmts(self, n_cols: int) -> list[int]:
        """Expand Bind's result-format list per protocol: empty = all text,
        one entry = applies to every column, else per column."""
        if not self.rfmts:
            return [0] * n_cols
        if len(self.rfmts) == 1:
            return [self.rfmts[0]] * n_cols
        return (self.rfmts + [0] * n_cols)[:n_cols]


class _PgError(Exception):
    def __init__(self, message: str, code: str = "XX000"):
        super().__init__(message)
        self.code = code


async def serve_pg(agent: "Agent", host: str = "127.0.0.1", port: int = 0):
    async def on_conn(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        prepared: dict[str, _Prepared] = {}
        portals: dict[str, _Portal] = {}
        in_error = False  # extended-protocol error state: skip until Sync
        try:
            await _handshake(reader, writer)
            writer.write(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
            for k, v in (
                ("server_version", "14.0 (corrosion-tpu)"),
                ("server_encoding", "UTF8"),
                ("client_encoding", "UTF8"),
            ):
                writer.write(_msg(b"S", _cstr(k) + _cstr(v)))
            writer.write(_msg(b"K", struct.pack(">II", 1, 0)))  # BackendKeyData
            writer.write(_ready())
            await writer.drain()
            while True:
                header = await reader.readexactly(5)
                tag, length = header[0:1], struct.unpack(">I", header[1:5])[0]
                payload = await reader.readexactly(length - 4)
                if tag == b"X":
                    break
                if tag == b"Q":
                    in_error = False
                    await _simple_query(agent, writer, payload[:-1].decode())
                elif tag == b"S":  # Sync: end of extended batch
                    in_error = False
                    portals.clear()
                    writer.write(_ready())
                elif tag == b"H":  # Flush
                    pass
                elif in_error:
                    pass  # discard until Sync (protocol error recovery)
                elif tag in (b"P", b"B", b"D", b"E", b"C"):
                    try:
                        await _extended(
                            agent, writer, tag, payload, prepared, portals
                        )
                    except _PgError as e:
                        writer.write(_error(str(e), e.code))
                        in_error = True
                    except Exception as e:
                        writer.write(_error(str(e), sqlstate_for(str(e))))
                        in_error = True
                else:
                    writer.write(
                        _error(f"unsupported message {tag!r}", "0A000")
                    )
                    writer.write(_ready())
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(on_conn, host, port)
    sock = server.sockets[0].getsockname()
    return server, (sock[0], sock[1])


def _read_cstr(buf: bytes, off: int) -> tuple[str, int]:
    end = buf.index(b"\x00", off)
    return buf[off:end].decode(), end + 1


async def _extended(
    agent: "Agent", writer, tag: bytes, payload: bytes,
    prepared: dict[str, _Prepared], portals: dict[str, _Portal],
) -> None:
    """One extended-protocol message (the pgwire flows of corro-pg's
    on_query/on_describe handlers, lib.rs:474-1769)."""
    if tag == b"P":  # Parse: name, query, param oids
        name, off = _read_cstr(payload, 0)
        query, off = _read_cstr(payload, off)
        (n_oids,) = struct.unpack_from(">H", payload, off)
        off += 2
        oids = [
            struct.unpack_from(">I", payload, off + 4 * i)[0]
            for i in range(n_oids)
        ]
        prepared[name] = _Prepared(query, oids)
        writer.write(_msg(b"1", b""))  # ParseComplete
        return

    if tag == b"B":  # Bind: portal, stmt, formats, params, result formats
        portal_name, off = _read_cstr(payload, 0)
        stmt_name, off = _read_cstr(payload, off)
        stmt = prepared.get(stmt_name)
        if stmt is None:
            raise _PgError(f"unknown prepared statement {stmt_name!r}", "26000")
        (n_fmt,) = struct.unpack_from(">H", payload, off)
        off += 2
        fmts = [
            struct.unpack_from(">H", payload, off + 2 * i)[0]
            for i in range(n_fmt)
        ]
        off += 2 * n_fmt
        (n_params,) = struct.unpack_from(">H", payload, off)
        off += 2
        params: list = []
        for i in range(n_params):
            (plen,) = struct.unpack_from(">i", payload, off)
            off += 4
            if plen < 0:
                params.append(None)
                continue
            raw = payload[off : off + plen]
            off += plen
            fmt = fmts[i] if i < len(fmts) else (fmts[0] if len(fmts) == 1 else 0)
            oid = stmt.param_oids[i] if i < len(stmt.param_oids) else 0
            if fmt != 0:
                params.append(_decode_binary_param(raw, oid))
            else:
                params.append(_coerce_param(raw.decode(), oid))
        (n_rfmt,) = struct.unpack_from(">H", payload, off)
        off += 2
        rfmts = [
            struct.unpack_from(">H", payload, off + 2 * i)[0]
            for i in range(n_rfmt)
        ]
        portals[portal_name] = _Portal(stmt, params, rfmts)
        writer.write(_msg(b"2", b""))  # BindComplete
        return

    if tag == b"D":  # Describe: 'S' statement | 'P' portal
        kind, name = payload[0:1], _read_cstr(payload, 1)[0]
        if kind == b"S":
            stmt = prepared.get(name)
            if stmt is None:
                raise _PgError(f"unknown prepared statement {name!r}", "26000")
            body = struct.pack(">H", len(stmt.param_oids))
            for oid in stmt.param_oids:
                body += struct.pack(">I", oid or TEXT_OID)
            writer.write(_msg(b"t", body))  # ParameterDescription
            # Off-loop: the probe may build a catalog snapshot (fresh
            # connection + temp tables) — not event-loop work.
            cols = await asyncio.to_thread(_try_describe, agent, stmt)
            writer.write(_row_description(cols) if cols else _msg(b"n", b""))
            return
        portal = portals.get(name)
        if portal is None:
            raise _PgError(f"unknown portal {name!r}", "34000")
        if _is_query(portal.prepared.translated):
            cols, rows = await _run_query(
                agent, portal.prepared.translated, portal.params
            )
            portal.described = (cols, rows)
            writer.write(
                _row_description(
                    cols, _infer_oids(rows, len(cols)),
                    portal.col_fmts(len(cols)),
                )
            )
        else:
            writer.write(_msg(b"n", b""))  # NoData
        return

    if tag == b"E":  # Execute: portal, max rows (portal suspension unsupported)
        name, off = _read_cstr(payload, 0)
        portal = portals.get(name)
        if portal is None:
            raise _PgError(f"unknown portal {name!r}", "34000")
        sql = portal.prepared.translated
        if not sql:
            writer.write(_command_complete("SET"))
            return
        if _is_query(sql):
            if portal.described is not None:
                cols, rows = portal.described
            else:
                cols, rows = await _run_query(agent, sql, portal.params)
            oids = _infer_oids(rows, len(cols))
            fmts = portal.col_fmts(len(cols))
            for row in rows:
                writer.write(_data_row(row, fmts, oids))
            writer.write(_command_complete(f"SELECT {len(rows)}"))
        else:
            resp = await agent.execute_async(
                [Statement(sql, params=portal.params)]
            )
            bad = [r for r in resp.results if r.error]
            if bad:
                raise _PgError(bad[0].error, sqlstate_for(bad[0].error))
            n = sum(r.rows_affected or 0 for r in resp.results)
            word = sql.split(None, 1)[0].upper()
            tag_word = f"INSERT 0 {n}" if word == "INSERT" else f"{word} {n}"
            writer.write(_command_complete(tag_word))
        return

    if tag == b"C":  # Close statement/portal
        kind, name = payload[0:1], _read_cstr(payload, 1)[0]
        (prepared if kind == b"S" else portals).pop(name, None)
        writer.write(_msg(b"3", b""))  # CloseComplete
        return


def _decode_binary_param(raw: bytes, oid: int):
    """Binary Bind parameter decode (the formats drivers actually send:
    PQexecParams with paramFormats=1, psycopg binary adapters)."""
    try:
        if oid == INT2_OID:
            return struct.unpack(">h", raw)[0]
        if oid == INT4_OID or oid == 26:  # oid type rides int4's format
            return struct.unpack(">i", raw)[0]
        if oid == INT8_OID:
            return struct.unpack(">q", raw)[0]
        if oid == FLOAT4_OID:
            return struct.unpack(">f", raw)[0]
        if oid == FLOAT8_OID:
            return struct.unpack(">d", raw)[0]
        if oid == BOOL_OID:
            return raw != b"\x00"
        if oid == BYTEA_OID or oid == 0:
            return raw
    except struct.error as e:
        raise _PgError(
            f"invalid binary parameter for oid {oid}", "22P03"
        ) from e
    try:
        return raw.decode()  # text-family binary repr == utf-8 text
    except UnicodeDecodeError:
        return raw


def _coerce_param(text: str, oid: int):
    try:
        if oid in _INT_OIDS:
            return int(text)
        if oid in _FLOAT_OIDS:
            return float(text)
        if oid == _BOOL_OID:
            return text in ("t", "true", "1", "on", "y", "yes")
    except ValueError:
        pass
    return text


def _try_describe(agent: "Agent", stmt: _Prepared) -> list[str] | None:
    """Result columns for Describe(statement): probe with a LIMIT-0 wrapper
    and NULL params; None (→ NoData) when the probe cannot run."""
    if not _is_query(stmt.translated):
        return None
    n_params = max(
        (int(m) for m in re.findall(r"\?(\d+)", stmt.translated)), default=0
    )
    try:
        if _mentions_catalog(stmt.translated):
            c = catalog_conn(agent)
            try:
                cur = c.execute(
                    "SELECT * FROM ("
                    + _sub_unquoted(stmt.translated, _CATALOG_PREFIX_STRIP)
                    + ") LIMIT 0",
                    tuple([None] * n_params),
                )
                return (
                    [d[0] for d in cur.description]
                    if cur.description else None
                )
            finally:
                c.close()
        # Fresh connection: this probe runs in a to_thread worker, and the
        # store's shared read_conn belongs to the event loop.
        c = sqlite3.connect(agent.store.path)
        try:
            cur = c.execute(
                f"SELECT * FROM ({stmt.translated}) LIMIT 0",
                tuple([None] * n_params),
            )
            return [d[0] for d in cur.description] if cur.description else None
        finally:
            c.close()
    except Exception:
        # NoData is the protocol fallback; keep a debug trail so a broken
        # probe doesn't silently degrade every prepared query.
        logging.getLogger(__name__).debug(
            "describe probe failed", exc_info=True
        )
        return None


async def _handshake(reader, writer) -> None:
    while True:
        (length,) = struct.unpack(">I", await reader.readexactly(4))
        payload = await reader.readexactly(length - 4)
        (code,) = struct.unpack(">I", payload[:4])
        if code == SSL_REQUEST:
            writer.write(b"N")  # no TLS
            await writer.drain()
            continue
        if code != PROTOCOL_V3:
            raise ConnectionError(f"unsupported protocol {code}")
        return


def _split_statements(sql: str) -> list[str]:
    """Split on top-level semicolons only — ';' inside '…'/"…" string or
    identifier literals (with doubled-quote escapes) must not split."""
    parts: list[str] = []
    cur: list[str] = []
    quote: str | None = None
    for ch in sql:
        if quote is not None:
            cur.append(ch)
            if ch == quote:
                quote = None  # doubled quotes re-enter on the next char
        elif ch in ("'", '"'):
            quote = ch
            cur.append(ch)
        elif ch == ";":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p for p in (s.strip() for s in parts) if p]


async def _simple_query(agent: "Agent", writer, sql: str) -> None:
    for part in _split_statements(sql):
        translated = translate_pg_sql(part)
        if not translated:
            writer.write(_command_complete("SET"))
            continue
        try:
            if _is_query(translated):
                cols, rows = await _run_query(agent, translated)
                writer.write(
                    _row_description(cols, _infer_oids(rows, len(cols)))
                )
                for row in rows:
                    writer.write(_data_row(row))
                writer.write(_command_complete(f"SELECT {len(rows)}"))
            else:
                resp = await agent.execute_async([Statement(translated)])
                err = next((r.error for r in resp.results if r.error), None)
                if err:
                    raise _PgError(err, sqlstate_for(err))
                n = sum(r.rows_affected for r in resp.results)
                word = translated.split(None, 1)[0].upper()
                tag = f"INSERT 0 {n}" if word == "INSERT" else f"{word} {n}"
                writer.write(_command_complete(tag))
        except _PgError as e:
            writer.write(_error(str(e), e.code))
            break
        except Exception as e:
            writer.write(_error(str(e), sqlstate_for(str(e))))
            break
    writer.write(_ready())
