"""PostgreSQL wire-protocol server — the corro-pg analogue.

The reference serves the pgwire protocol, translating PG SQL to SQLite and
executing against the agent DB with full bookkeeping + broadcast parity
(corro-pg/src/lib.rs:474-1769). This implementation speaks protocol v3's
startup + simple-query flow (plus SSLRequest refusal and Terminate):
SELECTs run on the store's read connection; writes run through
Agent.execute so version allocation, bookkeeping, and dissemination are
identical to the HTTP path (the parity that matters, lib.rs write path).

Everything is typed as text on the wire (like psql's default rendering);
the extended query protocol (parse/bind) is not implemented — psql's simple
protocol and most drivers' simple modes work.
"""

from __future__ import annotations

import asyncio
import struct
from typing import TYPE_CHECKING

from corrosion_tpu.core.values import Statement

if TYPE_CHECKING:
    from corrosion_tpu.agent.agent import Agent

SSL_REQUEST = 80877103
PROTOCOL_V3 = 196608
TEXT_OID = 25


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _error(message: str, code: str = "XX000") -> bytes:
    fields = b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    return _msg(b"E", fields)


def _row_description(cols: list[str]) -> bytes:
    body = struct.pack(">H", len(cols))
    for name in cols:
        body += _cstr(name)
        body += struct.pack(">IhIhih", 0, 0, TEXT_OID, -1, -1, 0)
    return _msg(b"T", body)


def _data_row(row) -> bytes:
    body = struct.pack(">H", len(row))
    for v in row:
        if v is None:
            body += struct.pack(">i", -1)
        else:
            if isinstance(v, bytes):
                text = "\\x" + v.hex()
            elif isinstance(v, bool):
                text = "t" if v else "f"
            else:
                text = str(v)
            raw = text.encode()
            body += struct.pack(">i", len(raw)) + raw
    return body and _msg(b"D", body)


def _command_complete(tag: str) -> bytes:
    return _msg(b"C", _cstr(tag))


def _ready() -> bytes:
    return _msg(b"Z", b"I")


def _is_query(sql: str) -> bool:
    head = sql.lstrip().split(None, 1)
    word = head[0].upper() if head else ""
    return word in ("SELECT", "WITH", "EXPLAIN", "PRAGMA", "VALUES", "SHOW")


def translate_pg_sql(sql: str) -> str:
    """Small PG->SQLite surface translation (corro-pg's parse_query,
    lib.rs:306-472, collapses to the dialect overlaps that matter here)."""
    s = sql.strip().rstrip(";")
    upper = s.upper()
    if upper in ("BEGIN", "COMMIT", "ROLLBACK", "START TRANSACTION"):
        return ""  # the agent wraps writes in its own transaction
    if upper.startswith("SET ") or upper.startswith("SHOW "):
        return ""
    if upper == "SELECT VERSION()":
        return "SELECT 'corrosion-tpu (PostgreSQL 14 compatible)' AS version"
    return s


async def serve_pg(agent: "Agent", host: str = "127.0.0.1", port: int = 0):
    async def on_conn(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            await _handshake(reader, writer)
            writer.write(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
            for k, v in (
                ("server_version", "14.0 (corrosion-tpu)"),
                ("server_encoding", "UTF8"),
                ("client_encoding", "UTF8"),
            ):
                writer.write(_msg(b"S", _cstr(k) + _cstr(v)))
            writer.write(_ready())
            await writer.drain()
            while True:
                header = await reader.readexactly(5)
                tag, length = header[0:1], struct.unpack(">I", header[1:5])[0]
                payload = await reader.readexactly(length - 4)
                if tag == b"X":
                    break
                if tag == b"Q":
                    await _simple_query(
                        agent, writer, payload[:-1].decode()
                    )
                else:
                    writer.write(
                        _error(f"unsupported message {tag!r}", "0A000")
                    )
                    writer.write(_ready())
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(on_conn, host, port)
    sock = server.sockets[0].getsockname()
    return server, (sock[0], sock[1])


async def _handshake(reader, writer) -> None:
    while True:
        (length,) = struct.unpack(">I", await reader.readexactly(4))
        payload = await reader.readexactly(length - 4)
        (code,) = struct.unpack(">I", payload[:4])
        if code == SSL_REQUEST:
            writer.write(b"N")  # no TLS
            await writer.drain()
            continue
        if code != PROTOCOL_V3:
            raise ConnectionError(f"unsupported protocol {code}")
        return


def _split_statements(sql: str) -> list[str]:
    """Split on top-level semicolons only — ';' inside '…'/"…" string or
    identifier literals (with doubled-quote escapes) must not split."""
    parts: list[str] = []
    cur: list[str] = []
    quote: str | None = None
    for ch in sql:
        if quote is not None:
            cur.append(ch)
            if ch == quote:
                quote = None  # doubled quotes re-enter on the next char
        elif ch in ("'", '"'):
            quote = ch
            cur.append(ch)
        elif ch == ";":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p for p in (s.strip() for s in parts) if p]


async def _simple_query(agent: "Agent", writer, sql: str) -> None:
    for part in _split_statements(sql):
        translated = translate_pg_sql(part)
        if not translated:
            writer.write(_command_complete("SET"))
            continue
        try:
            if _is_query(translated):
                cols, rows = await agent.pool.query(Statement(translated))
                writer.write(_row_description(cols))
                for row in rows:
                    writer.write(_data_row(row))
                writer.write(_command_complete(f"SELECT {len(rows)}"))
            else:
                resp = await agent.execute_async([Statement(translated)])
                n = sum(r.rows_affected for r in resp.results)
                word = translated.split(None, 1)[0].upper()
                tag = f"INSERT 0 {n}" if word == "INSERT" else f"{word} {n}"
                writer.write(_command_complete(tag))
        except Exception as e:
            writer.write(_error(str(e)))
            break
    writer.write(_ready())
