"""PostgreSQL wire-protocol server — the corro-pg analogue.

The reference serves the pgwire protocol, translating PG SQL to SQLite and
executing against the agent DB with full bookkeeping + broadcast parity
(corro-pg/src/lib.rs:474-1769). This implementation speaks protocol v3's
startup + simple-query flow (plus SSLRequest refusal and Terminate):
SELECTs run on the store's read connection; writes run through
Agent.execute so version allocation, bookkeeping, and dissemination are
identical to the HTTP path (the parity that matters, lib.rs write path).

Both protocol flows are served: the simple-query flow ('Q') and the
extended flow (Parse/Bind/Describe/Execute/Close/Sync/Flush — what libpq's
PQexecParams and most drivers send), with PG's ``$N`` placeholders
translated to SQLite ``?N``. Parameters and results support both wire
formats: text (psql's default rendering) and binary (format code 1) for
the core scalar types (int2/4/8, float4/8, bool, bytea, text). SQL
translation is token-level (agent/pgsql.py's lexer), mirroring corro-pg's
parse-before-rewrite approach.
"""

from __future__ import annotations

import asyncio
import logging
import re
import sqlite3
import struct
from typing import TYPE_CHECKING

from corrosion_tpu.agent import pgsql
from corrosion_tpu.core.values import Statement

if TYPE_CHECKING:
    from corrosion_tpu.agent.agent import Agent

SSL_REQUEST = 80877103
PROTOCOL_V3 = 196608
TEXT_OID = 25
BOOL_OID = 16
BYTEA_OID = 17
INT2_OID, INT4_OID, INT8_OID = 21, 23, 20
FLOAT4_OID, FLOAT8_OID = 700, 701

# Parameter OIDs we coerce from text (ints/floats/bool); everything else
# stays a string and relies on SQLite column affinity.
_INT_OIDS = {20, 21, 23, 26}
_FLOAT_OIDS = {700, 701, 1700}
_BOOL_OID = BOOL_OID


# SQLSTATE mapping for SQLite error text (the role of corro-pg's
# sql_state.rs, 1336 LoC of codes; these are the ones SQLite can actually
# produce through this server).
_SQLSTATE_PATTERNS = [
    (re.compile(r"(?i)no such table"), "42P01"),  # undefined_table
    (re.compile(r"(?i)no such column"), "42703"),  # undefined_column
    (re.compile(r"(?i)syntax error"), "42601"),  # syntax_error
    (re.compile(r"(?i)ambiguous column"), "42702"),  # ambiguous_column
    (re.compile(r"(?i)UNIQUE constraint failed"), "23505"),  # unique_violation
    (re.compile(r"(?i)NOT NULL constraint failed"), "23502"),  # not_null
    (re.compile(r"(?i)CHECK constraint failed"), "23514"),  # check_violation
    (re.compile(r"(?i)FOREIGN KEY constraint failed"), "23503"),  # fk
    (re.compile(r"(?i)datatype mismatch"), "22P02"),  # invalid_text_rep
    (re.compile(r"(?i)attempt to write a readonly"), "25006"),  # read_only
    (re.compile(r"(?i)database is locked"), "55P03"),  # lock_not_available
    (re.compile(r"(?i)too many terms|parser stack overflow"), "54001"),
]


def sqlstate_for(message: str) -> str:
    """Best-fit SQLSTATE for an engine error message (sql_state.rs role)."""
    for pat, code in _SQLSTATE_PATTERNS:
        if pat.search(message):
            return code
    return "XX000"


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _error(message: str, code: str = "XX000") -> bytes:
    fields = b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    return _msg(b"E", fields)


def _infer_oids(rows: list, n_cols: int) -> list[int]:
    """Column type oids from the first non-NULL value per column (SQLite is
    dynamically typed; drivers want real oids for type mapping)."""
    oids = [TEXT_OID] * n_cols
    for c in range(n_cols):
        for row in rows:
            v = row[c]
            if v is None:
                continue
            if isinstance(v, bool):
                oids[c] = BOOL_OID
            elif isinstance(v, int):
                oids[c] = INT8_OID
            elif isinstance(v, float):
                oids[c] = FLOAT8_OID
            elif isinstance(v, bytes):
                oids[c] = BYTEA_OID
            break
    return oids


def _row_description(
    cols: list[str], oids: list[int] | None = None,
    fmts: list[int] | None = None,
) -> bytes:
    body = struct.pack(">H", len(cols))
    for i, name in enumerate(cols):
        oid = oids[i] if oids else TEXT_OID
        fmt = fmts[i] if fmts else 0
        body += _cstr(name)
        body += struct.pack(">IhIhih", 0, 0, oid, -1, -1, fmt)
    return _msg(b"T", body)


def _encode_binary(v, oid: int) -> bytes:
    """Binary result encoding per oid (the formats real drivers request)."""
    if oid == INT8_OID and isinstance(v, int):
        return struct.pack(">q", v)
    if oid == INT4_OID and isinstance(v, int):
        return struct.pack(">i", v)
    if oid == INT2_OID and isinstance(v, int):
        return struct.pack(">h", v)
    if oid == FLOAT8_OID and isinstance(v, (int, float)):
        return struct.pack(">d", float(v))
    if oid == FLOAT4_OID and isinstance(v, (int, float)):
        return struct.pack(">f", float(v))
    if oid == BOOL_OID:
        return b"\x01" if v else b"\x00"
    if isinstance(v, bytes):
        return v  # bytea binary = raw bytes
    # text/varchar binary representation == utf-8 text
    return str(v).encode()


def _text_cell(v) -> bytes:
    if isinstance(v, bytes):
        return ("\\x" + v.hex()).encode()
    if isinstance(v, bool):
        return b"t" if v else b"f"
    return str(v).encode()


def _data_row(
    row, rfmts: list[int] | None = None, oids: list[int] | None = None
) -> bytes:
    body = struct.pack(">H", len(row))
    for i, v in enumerate(row):
        if v is None:
            body += struct.pack(">i", -1)
            continue
        fmt = rfmts[i] if rfmts else 0
        if fmt == 1:
            raw = _encode_binary(v, oids[i] if oids else TEXT_OID)
        else:
            raw = _text_cell(v)
        body += struct.pack(">i", len(raw)) + raw
    return body and _msg(b"D", body)


def _command_complete(tag: str) -> bytes:
    return _msg(b"C", _cstr(tag))


def _ready(status: bytes = b"I") -> bytes:
    return _msg(b"Z", status)


def _write_verb_tokens(sql: str) -> list:
    """Write keywords appearing as real statement verbs: identifier
    tokens (never inside strings/comments) whose next significant token
    is NOT ``(`` — ``replace(x, 'a', 'b')`` is the SQL function, not the
    REPLACE statement, and must not drag a read-only query onto the
    write path (it bypasses the read pool AND mislabels the
    CommandComplete tag)."""
    toks = pgsql.tokenize(sql)
    verbs = []
    for i, t in enumerate(toks):
        if t.kind != "ident" or t.text.lower() not in (
            "insert", "update", "delete", "replace"
        ):
            continue
        j = pgsql._sig(toks, i, 1)
        if j >= 0 and toks[j].text == "(":
            continue  # function-call form, e.g. replace(col, 'a', 'b')
        verbs.append(t)
    return verbs


def _contains_write_tokens(sql: str) -> bool:
    """Any write keyword as a real statement verb (not inside strings/
    comments, not a function call) — the shape check for CTEs feeding
    writes (WITH ... INSERT ...), which a head-word test misroutes to
    the read pool, bypassing version assignment."""
    return bool(_write_verb_tokens(sql))


def _is_query(sql: str) -> bool:
    head = sql.lstrip().split(None, 1)
    word = head[0].upper() if head else ""
    if word == "WITH":
        return not _contains_write_tokens(sql)
    return word in ("SELECT", "EXPLAIN", "PRAGMA", "VALUES", "SHOW")


# Explicit-transaction control + features the server deliberately does
# not speak (corro-pg supports txns, lib.rs:518-720; COPY/LISTEN have no
# analogue here and must fail with a clean SQLSTATE instead of a parse
# error deep in SQLite).
_TXN_BEGIN = ("BEGIN", "START")
_TXN_COMMIT = ("COMMIT", "END")
_TXN_ROLLBACK = ("ROLLBACK", "ABORT")
_UNSUPPORTED_WORDS = {
    "COPY": "COPY is not supported",
    "LISTEN": "LISTEN/NOTIFY is not supported",
    "UNLISTEN": "LISTEN/NOTIFY is not supported",
    "NOTIFY": "LISTEN/NOTIFY is not supported",
    "DECLARE": "server-side cursors are not supported",
    "FETCH": "server-side cursors are not supported",
    "MOVE": "server-side cursors are not supported",
}


class _Txn:
    """Per-connection explicit-transaction state.

    Statements inside BEGIN..COMMIT queue up (validated with EXPLAIN at
    queue time) and apply ATOMICALLY through one agent batch at COMMIT —
    the agent's multi-statement execute is transactional end-to-end.
    Divergences from a held server-side txn, documented: reads inside
    the block see the pre-transaction snapshot (not own writes), and
    runtime constraint violations surface at COMMIT rather than at the
    offending statement. After any in-block error the connection enters
    the failed state: every statement until ROLLBACK/COMMIT gets
    SQLSTATE 25P02, and COMMIT of a failed block reports ROLLBACK —
    exactly libpq's recovery flow."""

    def __init__(self) -> None:
        self.mode = "idle"  # idle | txn | failed
        self.queue: list[Statement] = []
        self.has_ddl = False  # queued DDL: later EXPLAIN probes can't see it

    @property
    def status(self) -> bytes:
        return {"idle": b"I", "txn": b"T", "failed": b"E"}[self.mode]

    def begin(self) -> None:
        self.mode = "txn"
        self.queue = []
        self.has_ddl = False

    def reset(self) -> None:
        self.mode = "idle"
        self.queue = []
        self.has_ddl = False

    def fail(self) -> None:
        if self.mode == "txn":
            self.mode = "failed"


_ABORTED_MSG = (
    "current transaction is aborted, commands ignored until end of "
    "transaction block"
)


def translate_pg_sql(sql: str) -> str:
    """PG->SQLite surface translation (corro-pg's parse_query,
    lib.rs:306-472 via sqlparser). Token-level — see agent/pgsql.py for
    the lexer: strings, comments, dollar-quotes, and identifiers are
    single tokens, so nothing inside them can be rewritten."""
    return pgsql.translate(sql)


def _mentions_catalog(sql: str) -> bool:
    return pgsql.mentions_catalog(sql)


# -- pg_catalog (the reference's vtabs: corro-pg/src/vtab/{pg_type 405,
# pg_class 113, pg_namespace 108, pg_database 166, pg_range} LoC) ----------

# (oid, typname, typlen): the types the wire layer speaks.
_PG_TYPES = [
    (16, "bool", 1), (17, "bytea", -1), (20, "int8", 8), (21, "int2", 2),
    (23, "int4", 4), (25, "text", -1), (700, "float4", 4),
    (701, "float8", 8), (1043, "varchar", -1), (1700, "numeric", -1),
]
_NS_CATALOG, _NS_PUBLIC = 11, 2200
_FIRST_REL_OID = 16384


def catalog_conn(agent: "Agent") -> sqlite3.Connection:
    """A pg_catalog snapshot derived from the live schema, built as TEMP
    tables on a fresh read connection to the real database — so catalog
    queries can also join user tables, like the reference's virtual tables
    (which live on every connection).

    Per-query construction keeps it automatically in sync with migrations;
    introspection traffic (psql \\d, ORM table listing at connect) is rare
    enough that rebuild cost is irrelevant.
    """
    c = sqlite3.connect(agent.store.path)
    c.executescript(
        """
        CREATE TEMP TABLE pg_type (oid INT, typname TEXT, typlen INT,
          typtype TEXT, typnamespace INT);
        CREATE TEMP TABLE pg_namespace (oid INT, nspname TEXT);
        CREATE TEMP TABLE pg_database (oid INT, datname TEXT);
        CREATE TEMP TABLE pg_class (oid INT, relname TEXT, relnamespace INT,
          relkind TEXT);
        CREATE TEMP TABLE pg_attribute (attrelid INT, attname TEXT,
          atttypid INT, attnum INT, attnotnull INT, attisdropped INT);
        CREATE TEMP TABLE pg_range (rngtypid INT, rngsubtype INT);
        CREATE TEMP TABLE pg_tables (schemaname TEXT, tablename TEXT);
        """
    )
    c.executemany(
        "INSERT INTO pg_type VALUES (?, ?, ?, 'b', ?)",
        [(o, n, l, _NS_CATALOG) for o, n, l in _PG_TYPES],
    )
    c.executemany(
        "INSERT INTO pg_namespace VALUES (?, ?)",
        [(_NS_CATALOG, "pg_catalog"), (_NS_PUBLIC, "public")],
    )
    c.execute("INSERT INTO pg_database VALUES (1, 'corrosion')")
    oid = _FIRST_REL_OID
    for name, info in sorted(agent.store.tables().items()):
        c.execute(
            "INSERT INTO pg_class VALUES (?, ?, ?, 'r')",
            (oid, name, _NS_PUBLIC),
        )
        c.execute("INSERT INTO pg_tables VALUES ('public', ?)", (name,))
        decl = {
            row[1]: (row[2] or "") for row in c.execute(
                f'PRAGMA table_info("{name}")'
            )
        }
        for attnum, col in enumerate(
            [*info.pk_cols, *info.data_cols], start=1
        ):
            c.execute(
                "INSERT INTO pg_attribute VALUES (?, ?, ?, ?, ?, 0)",
                (oid, col, _affinity_oid(decl.get(col, "")), attnum,
                 int(col in info.pk_cols)),
            )
        oid += 1
    return c


def _affinity_oid(decl_type: str) -> int:
    """SQLite declared type → pg_type oid, by SQLite's affinity rules."""
    t = decl_type.upper()
    if "INT" in t:
        return 20  # int8
    if "CHAR" in t or "CLOB" in t or "TEXT" in t:
        return 25
    if "BLOB" in t or not t:
        return 17
    if "REAL" in t or "FLOA" in t or "DOUB" in t:
        return 701
    return 1700  # NUMERIC affinity


async def _run_query(
    agent: "Agent", sql: str, params: list | None = None
) -> tuple[list[str], list]:
    """Route a read: queries touching pg_catalog names (outside string
    literals) go to the catalog-snapshot connection — which also sees the
    user tables — everything else to the agent's read pool."""
    if _mentions_catalog(sql):
        def run():
            c = catalog_conn(agent)
            try:
                cur = c.execute(
                    pgsql.strip_catalog_prefix(sql),
                    tuple(params or ()),
                )
                cols = (
                    [d[0] for d in cur.description] if cur.description else []
                )
                return cols, cur.fetchall()
            finally:
                c.close()

        return await asyncio.to_thread(run)
    return await agent.pool.query(Statement(sql, params=params))


def translate_placeholders(sql: str) -> str:
    """PG ``$N`` → SQLite ``?N``, outside string/identifier literals and
    comments (token-level, agent/pgsql.py)."""
    return pgsql.translate_placeholders(sql)


class _Prepared:
    def __init__(self, sql: str, param_oids: list[int]):
        self.raw = sql
        self.translated = translate_pg_sql(translate_placeholders(sql))
        self.param_oids = param_oids


class _Portal:
    def __init__(
        self, prepared: _Prepared, params: list,
        rfmts: list[int] | None = None,
    ):
        self.prepared = prepared
        self.params = params
        self.rfmts = rfmts or []
        self.described: tuple[list[str], list[tuple]] | None = None

    def col_fmts(self, n_cols: int) -> list[int]:
        """Expand Bind's result-format list per protocol: empty = all text,
        one entry = applies to every column, else per column."""
        if not self.rfmts:
            return [0] * n_cols
        if len(self.rfmts) == 1:
            return [self.rfmts[0]] * n_cols
        return (self.rfmts + [0] * n_cols)[:n_cols]


class _PgError(Exception):
    def __init__(self, message: str, code: str = "XX000"):
        super().__init__(message)
        self.code = code


async def serve_pg(agent: "Agent", host: str = "127.0.0.1", port: int = 0):
    async def on_conn(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        prepared: dict[str, _Prepared] = {}
        portals: dict[str, _Portal] = {}
        txn = _Txn()
        in_error = False  # extended-protocol error state: skip until Sync
        try:
            await _handshake(reader, writer)
            writer.write(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
            for k, v in (
                ("server_version", "14.0 (corrosion-tpu)"),
                ("server_encoding", "UTF8"),
                ("client_encoding", "UTF8"),
            ):
                writer.write(_msg(b"S", _cstr(k) + _cstr(v)))
            writer.write(_msg(b"K", struct.pack(">II", 1, 0)))  # BackendKeyData
            writer.write(_ready())
            await writer.drain()
            while True:
                header = await reader.readexactly(5)
                tag, length = header[0:1], struct.unpack(">I", header[1:5])[0]
                payload = await reader.readexactly(length - 4)
                if tag == b"X":
                    break
                if tag == b"Q":
                    in_error = False
                    await _simple_query(
                        agent, writer, payload[:-1].decode(), txn
                    )
                elif tag == b"S":  # Sync: end of extended batch
                    in_error = False
                    portals.clear()
                    writer.write(_ready(txn.status))
                elif tag == b"H":  # Flush
                    pass
                elif in_error:
                    pass  # discard until Sync (protocol error recovery)
                elif tag in (b"P", b"B", b"D", b"E", b"C"):
                    try:
                        await _extended(
                            agent, writer, tag, payload, prepared,
                            portals, txn,
                        )
                    except _PgError as e:
                        txn.fail()
                        writer.write(_error(str(e), e.code))
                        in_error = True
                    except Exception as e:
                        txn.fail()
                        writer.write(_error(str(e), sqlstate_for(str(e))))
                        in_error = True
                else:
                    writer.write(
                        _error(f"unsupported message {tag!r}", "0A000")
                    )
                    writer.write(_ready(txn.status))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(on_conn, host, port)
    sock = server.sockets[0].getsockname()
    return server, (sock[0], sock[1])


def _read_cstr(buf: bytes, off: int) -> tuple[str, int]:
    end = buf.index(b"\x00", off)
    return buf[off:end].decode(), end + 1


async def _extended(
    agent: "Agent", writer, tag: bytes, payload: bytes,
    prepared: dict[str, _Prepared], portals: dict[str, _Portal],
    txn: _Txn,
) -> None:
    """One extended-protocol message (the pgwire flows of corro-pg's
    on_query/on_describe handlers, lib.rs:474-1769)."""
    if txn.mode == "failed" and tag in (b"P", b"B", b"D"):
        # An aborted transaction refuses Parse/Bind/Describe outright
        # (real PostgreSQL performs no query work in this state;
        # Describe(portal) here would otherwise execute the query).
        raise _PgError(_ABORTED_MSG, "25P02")
    if tag == b"P":  # Parse: name, query, param oids
        name, off = _read_cstr(payload, 0)
        query, off = _read_cstr(payload, off)
        (n_oids,) = struct.unpack_from(">H", payload, off)
        off += 2
        oids = [
            struct.unpack_from(">I", payload, off + 4 * i)[0]
            for i in range(n_oids)
        ]
        prepared[name] = _Prepared(query, oids)
        writer.write(_msg(b"1", b""))  # ParseComplete
        return

    if tag == b"B":  # Bind: portal, stmt, formats, params, result formats
        portal_name, off = _read_cstr(payload, 0)
        stmt_name, off = _read_cstr(payload, off)
        stmt = prepared.get(stmt_name)
        if stmt is None:
            raise _PgError(f"unknown prepared statement {stmt_name!r}", "26000")
        (n_fmt,) = struct.unpack_from(">H", payload, off)
        off += 2
        fmts = [
            struct.unpack_from(">H", payload, off + 2 * i)[0]
            for i in range(n_fmt)
        ]
        off += 2 * n_fmt
        (n_params,) = struct.unpack_from(">H", payload, off)
        off += 2
        params: list = []
        for i in range(n_params):
            (plen,) = struct.unpack_from(">i", payload, off)
            off += 4
            if plen < 0:
                params.append(None)
                continue
            raw = payload[off : off + plen]
            off += plen
            fmt = fmts[i] if i < len(fmts) else (fmts[0] if len(fmts) == 1 else 0)
            oid = stmt.param_oids[i] if i < len(stmt.param_oids) else 0
            if fmt != 0:
                params.append(_decode_binary_param(raw, oid))
            else:
                params.append(_coerce_param(raw.decode(), oid))
        (n_rfmt,) = struct.unpack_from(">H", payload, off)
        off += 2
        rfmts = [
            struct.unpack_from(">H", payload, off + 2 * i)[0]
            for i in range(n_rfmt)
        ]
        portals[portal_name] = _Portal(stmt, params, rfmts)
        writer.write(_msg(b"2", b""))  # BindComplete
        return

    if tag == b"D":  # Describe: 'S' statement | 'P' portal
        kind, name = payload[0:1], _read_cstr(payload, 1)[0]
        if kind == b"S":
            stmt = prepared.get(name)
            if stmt is None:
                raise _PgError(f"unknown prepared statement {name!r}", "26000")
            body = struct.pack(">H", len(stmt.param_oids))
            for oid in stmt.param_oids:
                body += struct.pack(">I", oid or TEXT_OID)
            writer.write(_msg(b"t", body))  # ParameterDescription
            # Off-loop: the probe may build a catalog snapshot (fresh
            # connection + temp tables) — not event-loop work.
            cols = await asyncio.to_thread(_try_describe, agent, stmt)
            writer.write(_row_description(cols) if cols else _msg(b"n", b""))
            return
        portal = portals.get(name)
        if portal is None:
            raise _PgError(f"unknown portal {name!r}", "34000")
        if _is_query(portal.prepared.translated):
            cols, rows = await _run_query(
                agent, portal.prepared.translated, portal.params
            )
            portal.described = (cols, rows)
            writer.write(
                _row_description(
                    cols, _infer_oids(rows, len(cols)),
                    portal.col_fmts(len(cols)),
                )
            )
        else:
            writer.write(_msg(b"n", b""))  # NoData
        return

    if tag == b"E":  # Execute: portal, max rows (portal suspension unsupported)
        name, off = _read_cstr(payload, 0)
        portal = portals.get(name)
        if portal is None:
            raise _PgError(f"unknown portal {name!r}", "34000")
        raw_word = _head_word(portal.prepared.raw)
        if txn.mode == "failed" and raw_word not in (
            *_TXN_COMMIT, *_TXN_ROLLBACK
        ):
            raise _PgError(_ABORTED_MSG, "25P02")
        if raw_word in _UNSUPPORTED_WORDS:
            raise _PgError(_UNSUPPORTED_WORDS[raw_word], "0A000")
        if raw_word in (*_TXN_BEGIN, *_TXN_COMMIT, *_TXN_ROLLBACK):
            await _txn_control(agent, writer, raw_word, txn)
            return
        sql = portal.prepared.translated
        if not sql:
            writer.write(_command_complete("SET"))
            return
        if txn.mode == "txn" and not _is_query(sql):
            writer.write(_command_complete(
                _queue_deferred_write(agent, txn, sql, portal.params)
            ))
            return
        if _is_query(sql):
            if portal.described is not None:
                cols, rows = portal.described
            else:
                cols, rows = await _run_query(agent, sql, portal.params)
            oids = _infer_oids(rows, len(cols))
            fmts = portal.col_fmts(len(cols))
            for row in rows:
                writer.write(_data_row(row, fmts, oids))
            writer.write(_command_complete(f"SELECT {len(rows)}"))
        else:
            resp = await agent.execute_async(
                [Statement(sql, params=portal.params)]
            )
            bad = [r for r in resp.results if r.error]
            if bad:
                raise _PgError(bad[0].error, sqlstate_for(bad[0].error))
            n = sum(r.rows_affected or 0 for r in resp.results)
            writer.write(
                _command_complete(_command_tag(_dml_word(sql), n, sql))
            )
        return

    if tag == b"C":  # Close statement/portal
        kind, name = payload[0:1], _read_cstr(payload, 1)[0]
        (prepared if kind == b"S" else portals).pop(name, None)
        writer.write(_msg(b"3", b""))  # CloseComplete
        return


def _decode_binary_param(raw: bytes, oid: int):
    """Binary Bind parameter decode (the formats drivers actually send:
    PQexecParams with paramFormats=1, psycopg binary adapters)."""
    try:
        if oid == INT2_OID:
            return struct.unpack(">h", raw)[0]
        if oid == INT4_OID or oid == 26:  # oid type rides int4's format
            return struct.unpack(">i", raw)[0]
        if oid == INT8_OID:
            return struct.unpack(">q", raw)[0]
        if oid == FLOAT4_OID:
            return struct.unpack(">f", raw)[0]
        if oid == FLOAT8_OID:
            return struct.unpack(">d", raw)[0]
        if oid == BOOL_OID:
            return raw != b"\x00"
        if oid == BYTEA_OID or oid == 0:
            return raw
    except struct.error as e:
        raise _PgError(
            f"invalid binary parameter for oid {oid}", "22P03"
        ) from e
    try:
        return raw.decode()  # text-family binary repr == utf-8 text
    except UnicodeDecodeError:
        return raw


def _coerce_param(text: str, oid: int):
    try:
        if oid in _INT_OIDS:
            return int(text)
        if oid in _FLOAT_OIDS:
            return float(text)
        if oid == _BOOL_OID:
            return text in ("t", "true", "1", "on", "y", "yes")
    except ValueError:
        pass
    return text


def _try_describe(agent: "Agent", stmt: _Prepared) -> list[str] | None:
    """Result columns for Describe(statement): probe with a LIMIT-0 wrapper
    and NULL params; None (→ NoData) when the probe cannot run."""
    if not _is_query(stmt.translated):
        return None
    n_params = max(
        (int(m) for m in re.findall(r"\?(\d+)", stmt.translated)), default=0
    )
    try:
        if _mentions_catalog(stmt.translated):
            c = catalog_conn(agent)
            try:
                cur = c.execute(
                    "SELECT * FROM ("
                    + pgsql.strip_catalog_prefix(stmt.translated)
                    + ") LIMIT 0",
                    tuple([None] * n_params),
                )
                return (
                    [d[0] for d in cur.description]
                    if cur.description else None
                )
            finally:
                c.close()
        # Fresh connection: this probe runs in a to_thread worker, and the
        # store's shared read_conn belongs to the event loop. query_only
        # makes the probe structurally incapable of executing a write
        # smuggled through a shape the lexer missed.
        c = sqlite3.connect(agent.store.path)
        c.execute("PRAGMA query_only=1")
        try:
            cur = c.execute(
                f"SELECT * FROM ({stmt.translated}) LIMIT 0",
                tuple([None] * n_params),
            )
            return [d[0] for d in cur.description] if cur.description else None
        finally:
            c.close()
    except Exception:
        # NoData is the protocol fallback; keep a debug trail so a broken
        # probe doesn't silently degrade every prepared query.
        logging.getLogger(__name__).debug(
            "describe probe failed", exc_info=True
        )
        return None


async def _handshake(reader, writer) -> None:
    while True:
        (length,) = struct.unpack(">I", await reader.readexactly(4))
        payload = await reader.readexactly(length - 4)
        (code,) = struct.unpack(">I", payload[:4])
        if code == SSL_REQUEST:
            writer.write(b"N")  # no TLS
            await writer.drain()
            continue
        if code != PROTOCOL_V3:
            raise ConnectionError(f"unsupported protocol {code}")
        return


def _split_statements(sql: str) -> list[str]:
    """Split on top-level semicolons only — token-aware (';' inside
    strings, quoted identifiers, comments, and dollar-quoted blocks never
    splits)."""
    return pgsql.split_statements(sql)


def _head_word(sql: str) -> str:
    head = sql.lstrip().split(None, 1)
    return head[0].upper().rstrip(";") if head else ""


def _nominal_insert_count(sql: str) -> int:
    """Rows a queued `INSERT ... VALUES (...), (...)` will insert — the
    CommandComplete tag for deferred in-transaction writes. Shapes whose
    count depends on data (INSERT .. SELECT) report 0 ("unknown") rather
    than asserting a false exact count."""
    toks = pgsql.tokenize(sql)
    depth = 0
    groups = 0
    seen_values = False
    for t in toks:
        if t.kind == "ident" and t.text.lower() == "values" and depth == 0:
            seen_values = True
        elif t.text == "(":
            if depth == 0 and seen_values:
                groups += 1
            depth += 1
        elif t.text == ")":
            depth -= 1
    return groups


def _dml_word(sql: str) -> str:
    """The top-level DML verb for the CommandComplete tag: a WITH-headed
    write reports its underlying INSERT/UPDATE/DELETE like PostgreSQL.
    Function-call uses of the verb words (``replace(...)`` inside a CTE
    body) are skipped, so the tag names the real top-level verb."""
    word = sql.split(None, 1)[0].upper() if sql.split(None, 1) else ""
    if word != "WITH":
        return word
    for t in _write_verb_tokens(sql):
        return t.text.upper()
    return word


def _command_tag(word: str, n: int, sql: str = "") -> str:
    if word in ("CREATE", "DROP", "ALTER"):
        # DDL tags carry the object kind, never a count ("CREATE TABLE").
        parts = sql.split(None, 2)
        kind = parts[1].upper() if len(parts) > 1 else "TABLE"
        return f"{word} {kind}"
    return f"INSERT 0 {n}" if word == "INSERT" else f"{word} {n}"


def _queue_deferred_write(
    agent: "Agent", txn: _Txn, sql: str, params=None
) -> str:
    """Validate (when the schema is still probeable) + queue a write for
    the COMMIT batch; returns the CommandComplete tag."""
    word = _dml_word(sql)
    head = sql.split(None, 1)[0].upper() if sql.split(None, 1) else ""
    is_ddl = head in ("CREATE", "ALTER", "DROP")
    if not txn.has_ddl:
        # EXPLAIN sees the pre-transaction schema: once the block queued
        # DDL, later statements may legitimately reference it — defer
        # ALL their errors to COMMIT instead of spuriously failing the
        # standard migration pattern (CREATE TABLE; INSERT INTO it).
        _validate_statement(agent, sql)
    if is_ddl:
        txn.has_ddl = True
    txn.queue.append(Statement(sql, params=params))
    n = _nominal_insert_count(sql) if word == "INSERT" else 0
    return _command_tag(word, n, sql)


async def _txn_control(
    agent: "Agent", writer, word: str, txn: _Txn
) -> None:
    if word in _TXN_BEGIN:
        if txn.mode == "idle":
            txn.begin()
        writer.write(_command_complete("BEGIN"))
        return
    if word in _TXN_ROLLBACK:
        txn.reset()
        writer.write(_command_complete("ROLLBACK"))
        return
    # COMMIT/END: a failed block rolls back (libpq's recovery flow).
    if txn.mode == "failed":
        txn.reset()
        writer.write(_command_complete("ROLLBACK"))
        return
    queued, txn.queue = txn.queue, []
    txn.mode = "idle"
    if queued:
        resp = await agent.execute_async(queued)
        err = next((r.error for r in resp.results if r.error), None)
        if err:
            raise _PgError(err, sqlstate_for(err))
    writer.write(_command_complete("COMMIT"))


async def _one_statement(
    agent: "Agent", writer, part: str, txn: _Txn
) -> None:
    """Execute one statement under the connection's transaction state.
    Raises _PgError on failure (caller marks the txn failed)."""
    word = _head_word(part)
    if txn.mode == "failed" and word not in (
        *_TXN_COMMIT, *_TXN_ROLLBACK
    ):
        raise _PgError(_ABORTED_MSG, "25P02")
    if word in _UNSUPPORTED_WORDS:
        raise _PgError(_UNSUPPORTED_WORDS[word], "0A000")
    if word in (*_TXN_BEGIN, *_TXN_COMMIT, *_TXN_ROLLBACK):
        await _txn_control(agent, writer, word, txn)
        return
    translated = translate_pg_sql(part)
    if not translated:
        writer.write(_command_complete("SET"))
        return
    if _is_query(translated):
        cols, rows = await _run_query(agent, translated)
        writer.write(_row_description(cols, _infer_oids(rows, len(cols))))
        for row in rows:
            writer.write(_data_row(row))
        writer.write(_command_complete(f"SELECT {len(rows)}"))
        return
    if txn.mode == "txn":
        # Deferred write: prepare-time errors fail the block at the
        # offending statement; application is atomic at COMMIT.
        writer.write(_command_complete(
            _queue_deferred_write(agent, txn, translated)
        ))
        return
    resp = await agent.execute_async([Statement(translated)])
    err = next((r.error for r in resp.results if r.error), None)
    if err:
        raise _PgError(err, sqlstate_for(err))
    n = sum(r.rows_affected for r in resp.results)
    writer.write(
        _command_complete(_command_tag(_dml_word(translated), n, translated))
    )


def _validate_statement(agent: "Agent", sql: str) -> None:
    """Prepare (EXPLAIN) without executing: syntax + schema errors
    surface at queue time; runtime constraint violations defer to
    COMMIT (documented divergence of the deferred-batch txn)."""
    import sqlite3 as _sq

    try:
        agent.store.read_conn.execute(f"EXPLAIN {sql}")
    except _sq.Error as e:
        raise _PgError(str(e), sqlstate_for(str(e)))


async def _simple_query(
    agent: "Agent", writer, sql: str, txn: _Txn
) -> None:
    for part in _split_statements(sql):
        try:
            await _one_statement(agent, writer, part, txn)
        except _PgError as e:
            txn.fail()
            writer.write(_error(str(e), e.code))
            break
        except Exception as e:
            txn.fail()
            writer.write(_error(str(e), sqlstate_for(str(e))))
            break
    writer.write(_ready(txn.status))
