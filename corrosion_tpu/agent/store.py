"""CRDT SQLite store — the host-side replacement for cr-sqlite's C engine.

The reference vendors cr-sqlite as a prebuilt C extension
(corro-types/src/sqlite.rs:20-26) providing per-table clock tables, the
`crsql_changes` virtual table, and LWW + causal-length merge
(doc/crdts.md:11-28). This module implements the same replication contract
natively over stock SQLite:

- ``apply_schema`` marks user tables as CRRs: a ``{t}__crdt_rows`` causal-
  length table, a ``{t}__crdt_clock`` per-cell version table, and AFTER
  INSERT/UPDATE/DELETE triggers that record every local cell write into the
  ``__crdt_changes`` log (the `crsql_changes` analogue) with
  (col_version, db_version, seq, site_id, cl).
- ``execute_transaction`` wraps user statements with db_version/seq
  allocation, mirroring the write path of api_v1_transactions
  (corro-agent/src/api/public/mod.rs:33-142: crsql_next_db_version, MAX(seq),
  read-back of the changeset).
- ``apply_changes`` merges remote changes with exact cr-sqlite precedence:
  causal length first (bigger cl wins; even = deleted), then col_version,
  then value order (`value_cmp_key` — "biggest value wins",
  doc/crdts.md:15-16). Equivalent to `INSERT INTO crsql_changes` per change
  (agent.rs:2192-2214) and returns the applied count
  (`crsql_rows_impacted`, agent.rs:2215-2231).

The merge math itself also exists as the batched TPU kernel (ops/crdt.py);
this store materializes per-node state for the product surface (queries,
subscriptions) and the in-process cluster tests.
"""

from __future__ import annotations

import sqlite3
import threading
from dataclasses import dataclass

from corrosion_tpu import native
from corrosion_tpu.core.intervals import RangeSet
from corrosion_tpu.core.values import (
    Change,
    Statement,
    ExecResult,
    SqliteValue,
    pack_columns,
    unpack_columns,
    value_le,
)


class StoreError(Exception):
    pass


class SchemaError(StoreError):
    pass


INTERNAL_PREFIXES = ("__corro_", "__crdt_", "sqlite_")


@dataclass(frozen=True)
class TableInfo:
    name: str
    pk_cols: tuple[str, ...]
    data_cols: tuple[str, ...]
    create_sql: str


def _q(ident: str) -> str:
    """Quote an SQL identifier."""
    return '"' + ident.replace('"', '""') + '"'


def _qs(text: str) -> str:
    """Escape a string for single-quoted SQL literal position."""
    return text.replace("'", "''")


def _info_from_meta(
    name: str, meta: dict[str, tuple], create_sql: str
) -> TableInfo:
    rows = sorted(meta.values(), key=lambda r: r[0])
    pk = tuple(r[1] for r in sorted(rows, key=lambda r: r[5]) if r[5] > 0)
    data = tuple(r[1] for r in rows if r[5] == 0)
    if not pk:
        raise SchemaError(
            f"table {name} has no primary key — every CRR needs one "
            "(schema.rs requires non-null PKs)"
        )
    return TableInfo(name=name, pk_cols=pk, data_cols=data, create_sql=create_sql)


class Store:
    """One node's materialized database + CRDT change tracking.

    Thread-safety: a single writer lock serializes write transactions over
    the write connection (the SplitPool's one-writer discipline,
    corro-types/src/agent.rs:353-547); reads run on a separate connection so
    WAL gives them a committed snapshot, never a writer's in-flight state.
    """

    def __init__(self, path: str, site_id: bytes) -> None:
        if len(site_id) != 16:
            raise StoreError("site_id must be 16 bytes")
        self.path = path
        self.site_id = site_id
        self._write_lock = threading.Lock()
        self.lock_registry = None  # optional utils.locks.LockRegistry
        self._retired_read_conns: list[sqlite3.Connection] = []
        self._open_connections()
        self._tables: dict[str, TableInfo] = {}
        self._migrate()
        # Adopt the PERSISTED identity: on a pre-existing database the
        # INSERT OR IGNORE in _migrate keeps the original site_id, and the
        # triggers stamp changes with the meta row — a restarted node must
        # read its own local writes back with that id, not the fresh one
        # the caller passed (ActorId = crsql_site_id(), agent.rs:115-120).
        self._adopt_persisted_site_id()
        self._load_schema()

    def _open_connections(self) -> None:
        self.conn = sqlite3.connect(self.path, check_same_thread=False)
        # Explicit transaction control (BEGIN IMMEDIATE below); the library's
        # implicit-transaction mode would fight it.
        self.conn.isolation_level = None
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.conn.execute("PRAGMA synchronous=NORMAL")
        # setup_conn pragmas (corro-types/src/sqlite.rs:107-118)
        self.conn.create_function("corro_pack", -1, _sql_pack, deterministic=True)
        # Native CRDT helpers (crdt_value_cmp, …) — the cr-sqlite loading
        # seam (init_cr_conn, corro-types/src/sqlite.rs:87-105). When the
        # built extension is absent the pure-Python merge path is used.
        self.native_crdt = native.load_crdt_extension(self.conn)
        # Dedicated read connection (the read pool's role): WAL snapshot
        # isolation from in-flight write transactions.
        self.read_conn = self.open_read_connection()

    def open_read_connection(self) -> sqlite3.Connection:
        """A fresh snapshot-read connection with the store's SQL surface
        (corro_pack + native CRDT helpers) registered — for worker threads
        that must not share the event loop's connections."""
        conn = sqlite3.connect(self.path, check_same_thread=False)
        conn.isolation_level = None
        conn.create_function(
            "corro_pack", -1, _sql_pack, deterministic=True
        )
        native.load_crdt_extension(conn)
        return conn

    def _adopt_persisted_site_id(self) -> None:
        (db_site,) = self.conn.execute(
            "SELECT value FROM __corro_meta WHERE key='site_id'"
        ).fetchone()
        self.site_id = bytes(db_site)

    def reload_after_restore(self) -> None:
        """Re-adopt identity + schema after an online restore swapped the
        database content (sqlite3-restore's seam). SQLite page caches do
        not track external same-inode rewrites in WAL mode, so the store's
        own connections are reopened. The old READ connection is retired,
        not closed: event-loop code (subscription evaluation, pg describe)
        may be mid-query on it from another thread, and closing a live
        connection under a cursor raises in the reader — the retired
        handle drains naturally and is closed with the store."""
        with self._wlock("reload_after_restore"):
            self.conn.close()
            self._retired_read_conns.append(self.read_conn)
            self._open_connections()
            self._adopt_persisted_site_id()
            self._tables = {}
            self._load_schema()

    def close(self) -> None:
        self.conn.close()
        self.read_conn.close()
        for c in self._retired_read_conns:
            try:
                c.close()
            except Exception:
                pass
        self._retired_read_conns.clear()

    def _wlock(self, label: str):
        """Writer lock, registered for lock diagnostics when a registry is
        attached (CountedTokioRwLock's role, corro-types/agent.rs:593-650)."""
        if self.lock_registry is not None:
            return self.lock_registry.acquire(self._write_lock, label)
        return self._write_lock

    # -- internal tables (migrate framework, sqlite.rs:120-168) -------------

    def _migrate(self) -> None:
        c = self.conn
        with self._wlock("migrate"):
            c.execute(
                "CREATE TABLE IF NOT EXISTS __corro_meta "
                "(key TEXT PRIMARY KEY, value) WITHOUT ROWID"
            )
            for k, v in (
                ("db_version", 0),
                ("seq", -1),
                ("apply_remote", 0),
            ):
                c.execute(
                    "INSERT OR IGNORE INTO __corro_meta VALUES (?, ?)", (k, v)
                )
            c.execute(
                "INSERT OR IGNORE INTO __corro_meta VALUES ('site_id', ?)",
                (self.site_id,),
            )
            c.execute(
                "CREATE TABLE IF NOT EXISTS __crdt_changes ("
                " tbl TEXT NOT NULL, pk BLOB NOT NULL, cid TEXT NOT NULL,"
                " val, col_version INTEGER NOT NULL,"
                " db_version INTEGER NOT NULL, seq INTEGER NOT NULL,"
                " site_id BLOB NOT NULL, cl INTEGER NOT NULL)"
            )
            # Upgrade path: an earlier schema created this index non-unique;
            # IF NOT EXISTS would silently keep it and break the
            # INSERT OR REPLACE dedup in _log_change.
            idx_sql = c.execute(
                "SELECT sql FROM sqlite_master WHERE type='index'"
                " AND name='__crdt_changes_site_dbv'"
            ).fetchone()
            if idx_sql is not None and "UNIQUE" not in (idx_sql[0] or ""):
                c.execute(
                    "DELETE FROM __crdt_changes WHERE rowid NOT IN ("
                    " SELECT MIN(rowid) FROM __crdt_changes"
                    " GROUP BY site_id, db_version, seq)"
                )
                c.execute("DROP INDEX __crdt_changes_site_dbv")
            c.execute(
                "CREATE UNIQUE INDEX IF NOT EXISTS __crdt_changes_site_dbv"
                " ON __crdt_changes (site_id, db_version, seq)"
            )
            c.execute(
                "CREATE TABLE IF NOT EXISTS __corro_schema ("
                " tbl_name TEXT PRIMARY KEY, create_sql TEXT NOT NULL"
                ") WITHOUT ROWID"
            )
            # Replication bookkeeping persisted for restart rehydration
            # (agent.rs:147-268; tables at corro-types/src/agent.rs:232-314).
            c.execute(
                "CREATE TABLE IF NOT EXISTS __corro_bookkeeping ("
                " actor_id BLOB NOT NULL, start_version INTEGER NOT NULL,"
                " end_version INTEGER, db_version INTEGER,"
                " last_seq INTEGER, ts INTEGER,"
                " PRIMARY KEY (actor_id, start_version)) WITHOUT ROWID"
            )
            c.execute(
                "CREATE TABLE IF NOT EXISTS __corro_buffered_changes ("
                " actor_id BLOB NOT NULL, version INTEGER NOT NULL,"
                " tbl TEXT NOT NULL, pk BLOB NOT NULL, cid TEXT NOT NULL,"
                " val, col_version INTEGER NOT NULL,"
                " db_version INTEGER NOT NULL, seq INTEGER NOT NULL,"
                " site_id BLOB NOT NULL, cl INTEGER NOT NULL,"
                " PRIMARY KEY (actor_id, version, seq)) WITHOUT ROWID"
            )
            c.execute(
                "CREATE TABLE IF NOT EXISTS __corro_seq_bookkeeping ("
                " actor_id BLOB NOT NULL, version INTEGER NOT NULL,"
                " start_seq INTEGER NOT NULL, end_seq INTEGER NOT NULL,"
                " last_seq INTEGER NOT NULL, ts INTEGER NOT NULL,"
                " PRIMARY KEY (actor_id, version, start_seq)) WITHOUT ROWID"
            )
            # SWIM member states persisted for restart rejoin + operator
            # introspection (diff_member_states upserts into
            # __corro_members every 60 s, broadcast/mod.rs:570-702; loaded
            # back at setup, agent.rs:772-831).
            c.execute(
                "CREATE TABLE IF NOT EXISTS __corro_members ("
                " actor_id TEXT PRIMARY KEY, addr TEXT NOT NULL,"
                " state TEXT NOT NULL, incarnation INTEGER NOT NULL,"
                " updated_at REAL NOT NULL) WITHOUT ROWID"
            )
            # A crash between apply_changes' COMMIT and its flag reset would
            # otherwise leave apply_remote=1 persisted, silently muting all
            # local-change triggers on restart.
            c.execute(
                "UPDATE __corro_meta SET value = 0 WHERE key='apply_remote'"
            )

    def _load_schema(self) -> None:
        for name, sql in self.conn.execute(
            "SELECT tbl_name, create_sql FROM __corro_schema"
        ):
            self._tables[name] = self._introspect(name, sql)

    # -- schema management (schema.rs apply_schema, :266-628) ----------------

    def _introspect(self, name: str, create_sql: str) -> TableInfo:
        rows = list(self.conn.execute(f"PRAGMA table_info({_q(name)})"))
        pk = tuple(r[1] for r in sorted(rows, key=lambda r: r[5]) if r[5] > 0)
        data = tuple(r[1] for r in rows if r[5] == 0)
        if not pk:
            raise SchemaError(
                f"table {name} has no primary key — every CRR needs one "
                "(schema.rs requires non-null PKs)"
            )
        return TableInfo(name=name, pk_cols=pk, data_cols=data, create_sql=create_sql)

    def apply_schema(self, schema_sql: str) -> list[str]:
        """Parse DDL, diff vs the current schema, apply additive changes and
        CRR-ify new tables. Destructive changes (dropped tables/columns,
        changed PKs) are rejected (schema.rs:266-628 forbids them).

        Returns the list of new/changed table names.
        """
        tmp = sqlite3.connect(":memory:")
        try:
            tmp.executescript(schema_sql)
            desired: dict[str, str] = {
                name: sql
                for name, sql in tmp.execute(
                    "SELECT name, sql FROM sqlite_master"
                    " WHERE type='table' AND name NOT LIKE 'sqlite_%'"
                )
            }
            colmeta: dict[str, dict[str, tuple]] = {
                name: {
                    r[1]: r  # (cid, name, type, notnull, dflt, pk)
                    for r in tmp.execute(f"PRAGMA table_info({_q(name)})")
                }
                for name in desired
            }
        except sqlite3.Error as e:
            raise SchemaError(f"bad schema sql: {e}") from e
        finally:
            tmp.close()

        changed: list[str] = []
        for name in self._tables:
            if name not in desired:
                raise SchemaError(f"cannot drop table {name} (destructive)")

        # One explicit transaction so a rejected/broken schema leaves no
        # partial DDL behind (apply_schema is all-or-nothing in the
        # reference too, schema.rs:266-628).
        with self._wlock("apply_schema"):
            c = self.conn
            c.execute("BEGIN IMMEDIATE")
            staged: dict[str, TableInfo] = {}
            try:
                for name, sql in desired.items():
                    if name.startswith(INTERNAL_PREFIXES):
                        raise SchemaError(f"reserved table name {name}")
                    meta = colmeta[name]
                    new_info = _info_from_meta(name, meta, sql)
                    if name not in self._tables:
                        c.execute(sql)
                        self._create_crr(c, new_info)
                        c.execute(
                            "INSERT OR REPLACE INTO __corro_schema VALUES (?, ?)",
                            (name, sql),
                        )
                        staged[name] = new_info
                        changed.append(name)
                    else:
                        old = self._tables[name]
                        if new_info.pk_cols != old.pk_cols:
                            raise SchemaError(
                                f"cannot change primary key of {name}"
                            )
                        dropped = set(old.data_cols) - set(new_info.data_cols)
                        if dropped:
                            raise SchemaError(
                                f"cannot drop columns {sorted(dropped)} of {name}"
                            )
                        added = [
                            col for col in new_info.data_cols
                            if col not in old.data_cols
                        ]
                        if added:
                            for col in added:
                                r = meta[col]
                                type_ = r[2] or ""
                                dflt = (
                                    f" DEFAULT {r[4]}" if r[4] is not None else ""
                                )
                                c.execute(
                                    f"ALTER TABLE {_q(name)} ADD COLUMN"
                                    f" {_q(col)} {type_}{dflt}"
                                )
                            self._drop_triggers(c, old)
                            self._create_triggers(c, new_info)
                            c.execute(
                                "UPDATE __corro_schema SET create_sql=?"
                                " WHERE tbl_name=?",
                                (sql, name),
                            )
                            staged[name] = new_info
                            changed.append(name)
                c.execute("COMMIT")
            except Exception:
                c.execute("ROLLBACK")
                raise
            self._tables.update(staged)
        return changed

    # -- CRR machinery (crsql_as_crr analogue) -------------------------------

    def _create_crr(self, c: sqlite3.Connection, info: TableInfo) -> None:
        t = info.name
        c.execute(
            f"CREATE TABLE IF NOT EXISTS {_q(t + '__crdt_rows')} ("
            " pk BLOB PRIMARY KEY, cl INTEGER NOT NULL) WITHOUT ROWID"
        )
        c.execute(
            f"CREATE TABLE IF NOT EXISTS {_q(t + '__crdt_clock')} ("
            " pk BLOB NOT NULL, cid TEXT NOT NULL,"
            " col_version INTEGER NOT NULL, db_version INTEGER NOT NULL,"
            " seq INTEGER NOT NULL, site_id BLOB,"
            " PRIMARY KEY (pk, cid)) WITHOUT ROWID"
        )
        # Compaction probes scan (site_id, db_version); the reference
        # creates the same index for find_cleared_db_versions
        # (agent.rs:3238-3239).
        c.execute(
            f"CREATE INDEX IF NOT EXISTS {_q(t + '__crdt_clock_site_dbv')}"
            f" ON {_q(t + '__crdt_clock')} (site_id, db_version)"
        )
        self._create_triggers(c, info)

    def _drop_triggers(self, c: sqlite3.Connection, info: TableInfo) -> None:
        t = info.name
        for suffix in (
            ["ins", "del"] + [f"upd_{col}" for col in info.data_cols]
        ):
            c.execute(f"DROP TRIGGER IF EXISTS {_q(t + '__crdt_' + suffix)}")

    def _create_triggers(self, c: sqlite3.Connection, info: TableInfo) -> None:
        t = info.name
        pk_expr = "corro_pack(" + ", ".join(
            f"NEW.{_q(col)}" for col in info.pk_cols
        ) + ")"
        old_pk_expr = "corro_pack(" + ", ".join(
            f"OLD.{_q(col)}" for col in info.pk_cols
        ) + ")"
        dbv = "(SELECT value FROM __corro_meta WHERE key='db_version')"
        seq = "(SELECT value FROM __corro_meta WHERE key='seq')"
        local_guard = (
            "WHEN (SELECT value FROM __corro_meta WHERE key='apply_remote') = 0"
        )
        rows_t = _q(t + "__crdt_rows")
        clock_t = _q(t + "__crdt_clock")

        def cell_sql(col: str, new_pk: str) -> str:
            qc = _q(col)
            lc = _qs(col)
            lt = _qs(t)
            return (
                "UPDATE __corro_meta SET value = value + 1 WHERE key='seq';\n"
                f"INSERT INTO {clock_t} (pk, cid, col_version, db_version, seq, site_id)"
                f" VALUES ({new_pk}, '{lc}', 1, {dbv}, {seq}, NULL)"
                " ON CONFLICT (pk, cid) DO UPDATE SET"
                "  col_version = col_version + 1,"
                "  db_version = excluded.db_version,"
                "  seq = excluded.seq, site_id = NULL;\n"
                "INSERT INTO __crdt_changes"
                " (tbl, pk, cid, val, col_version, db_version, seq, site_id, cl)"
                f" SELECT '{lt}', {new_pk}, '{lc}', NEW.{qc},"
                f"  (SELECT col_version FROM {clock_t} WHERE pk = {new_pk} AND cid = '{lc}'),"
                f"  {dbv}, {seq},"
                "  (SELECT value FROM __corro_meta WHERE key='site_id'),"
                f"  (SELECT cl FROM {rows_t} WHERE pk = {new_pk});\n"
            )

        # INSERT: resurrect-or-create the row's causal length, then record
        # every data column (or a pk-only marker). A resurrection retires the
        # delete sentinel: its version stops being referenced by any clock
        # row and becomes compactable (find_cleared_db_versions semantics,
        # agent.rs:1250-1299).
        body = (
            f"INSERT INTO {rows_t} (pk, cl) VALUES ({pk_expr}, 1)"
            " ON CONFLICT (pk) DO UPDATE SET"
            "  cl = CASE WHEN cl % 2 = 0 THEN cl + 1 ELSE cl END;\n"
            f"DELETE FROM {clock_t} WHERE pk = {pk_expr}"
            f" AND cid = '{Change.DELETE_CID}';\n"
        )
        if info.data_cols:
            for col in info.data_cols:
                body += cell_sql(col, pk_expr)
        else:
            # PK-only rows keep a sentinel clock entry so their creating
            # version stays "live" for compaction purposes: cr-sqlite models
            # this with a __crsql_pko clock row — without it the version
            # would look overwritten immediately and peers that missed the
            # broadcast would never receive the row.
            body += (
                "UPDATE __corro_meta SET value = value + 1 WHERE key='seq';\n"
                f"INSERT INTO {clock_t} (pk, cid, col_version, db_version, seq, site_id)"
                f" VALUES ({pk_expr}, '{Change.PKONLY_CID}', 1, {dbv}, {seq}, NULL)"
                " ON CONFLICT (pk, cid) DO UPDATE SET"
                "  db_version = excluded.db_version,"
                "  seq = excluded.seq, site_id = NULL;\n"
                "INSERT INTO __crdt_changes"
                " (tbl, pk, cid, val, col_version, db_version, seq, site_id, cl)"
                f" SELECT '{_qs(t)}', {pk_expr}, '{Change.PKONLY_CID}', NULL, 1,"
                f" {dbv}, {seq},"
                " (SELECT value FROM __corro_meta WHERE key='site_id'),"
                f" (SELECT cl FROM {rows_t} WHERE pk = {pk_expr});\n"
            )
        c.execute(
            f"CREATE TRIGGER {_q(t + '__crdt_ins')} AFTER INSERT ON {_q(t)}"
            f" {local_guard} BEGIN\n{body}END"
        )

        # UPDATE: one trigger per data column, firing only on real change.
        for col in info.data_cols:
            qc = _q(col)
            c.execute(
                f"CREATE TRIGGER {_q(t + '__crdt_upd_' + col)}"
                f" AFTER UPDATE OF {qc} ON {_q(t)}"
                f" {local_guard} AND (NEW.{qc} IS NOT OLD.{qc})"
                f" BEGIN\n{cell_sql(col, pk_expr)}END"
            )

        # DELETE: causal length goes even, cell clocks clear, and a delete
        # sentinel clock row keeps the tombstone's db_version live — cr-sqlite
        # keeps a __crsql_del clock entry for exactly this reason: if the
        # delete's version were compacted away, a peer that missed the delete
        # broadcast would get "cleared" from sync and keep the row forever.
        c.execute(
            f"CREATE TRIGGER {_q(t + '__crdt_del')} AFTER DELETE ON {_q(t)}"
            f" {local_guard} BEGIN\n"
            f"UPDATE {rows_t} SET cl = cl + 1 WHERE pk = {old_pk_expr} AND cl % 2 = 1;\n"
            f"DELETE FROM {clock_t} WHERE pk = {old_pk_expr};\n"
            "UPDATE __corro_meta SET value = value + 1 WHERE key='seq';\n"
            f"INSERT INTO {clock_t} (pk, cid, col_version, db_version, seq, site_id)"
            f" VALUES ({old_pk_expr}, '{Change.DELETE_CID}', 1, {dbv}, {seq}, NULL);\n"
            "INSERT INTO __crdt_changes"
            " (tbl, pk, cid, val, col_version, db_version, seq, site_id, cl)"
            f" SELECT '{_qs(t)}', {old_pk_expr}, '{Change.DELETE_CID}', NULL, 1,"
            f" {dbv}, {seq},"
            " (SELECT value FROM __corro_meta WHERE key='site_id'),"
            f" (SELECT cl FROM {rows_t} WHERE pk = {old_pk_expr});\n"
            "END"
        )

    # -- reads ---------------------------------------------------------------

    def query(self, stmt: Statement) -> tuple[list[str], list[tuple]]:
        cur = self.read_conn.execute(stmt.sql, _bind(stmt))
        cols = [d[0] for d in cur.description] if cur.description else []
        return cols, cur.fetchall()

    def db_version(self) -> int:
        (v,) = self.conn.execute(
            "SELECT value FROM __corro_meta WHERE key='db_version'"
        ).fetchone()
        return v

    def tables(self) -> dict[str, TableInfo]:
        return dict(self._tables)

    # -- local writes (make_broadcastable_changes, public/mod.rs:33-191) -----

    def execute_transaction(
        self, statements: list[Statement]
    ) -> tuple[list[ExecResult], int, int, list[Change]]:
        """Run statements in one write txn; allocate a db_version; read back
        the changeset. Returns (results, db_version, last_seq, changes);
        db_version is 0 and changes empty when nothing was recorded."""
        c = self.conn
        with self._wlock("execute_transaction"):
            try:
                c.execute("BEGIN IMMEDIATE")
                c.execute(
                    "UPDATE __corro_meta SET value = value + 1"
                    " WHERE key='db_version'"
                )
                c.execute("UPDATE __corro_meta SET value = -1 WHERE key='seq'")
                dbv = self.db_version()
                results = []
                for st in statements:
                    cur = c.execute(st.sql, _bind(st))
                    results.append(
                        ExecResult(rows_affected=max(cur.rowcount, 0))
                    )
                changes = self._read_changes(dbv)
                if not changes:
                    # No CRR rows touched: give the db_version back
                    # (the has_changes check, public/mod.rs:67-80).
                    c.execute(
                        "UPDATE __corro_meta SET value = value - 1"
                        " WHERE key='db_version'"
                    )
                    dbv = 0
                c.execute("COMMIT")
            except Exception:
                c.execute("ROLLBACK")
                raise
        last_seq = max((ch.seq for ch in changes), default=0)
        return results, dbv, last_seq, changes

    def _read_changes(self, dbv: int) -> list[Change]:
        rows = self.conn.execute(
            "SELECT tbl, pk, cid, val, col_version, db_version, seq, site_id, cl"
            " FROM __crdt_changes WHERE db_version = ? AND site_id = ?"
            " ORDER BY seq",
            (dbv, self.site_id),
        ).fetchall()
        return [Change.from_tuple(r) for r in rows]

    def changes_for(
        self, site_id: bytes, db_version: int,
        seqs: tuple[int, int] | None = None,
    ) -> list[Change]:
        """Serve a changeset for sync (handle_known_version's read,
        peer.rs:358-562), optionally restricted to a seq range."""
        sql = (
            "SELECT tbl, pk, cid, val, col_version, db_version, seq, site_id, cl"
            " FROM __crdt_changes WHERE site_id = ? AND db_version = ?"
        )
        args: list = [site_id, db_version]
        if seqs is not None:
            sql += " AND seq BETWEEN ? AND ?"
            args += [seqs[0], seqs[1]]
        sql += " ORDER BY seq"
        # Read connection, not the writer: sync serving runs on the event
        # loop while the pool's writer thread may hold an open BEGIN
        # IMMEDIATE on ``conn`` — joining that in-flight transaction could
        # serve uncommitted state. WAL gives this snapshot committed
        # versions only, which is exactly what booked.current describes.
        return [
            Change.from_tuple(r)
            for r in self.read_conn.execute(sql, args).fetchall()
        ]

    # -- compaction (clear_overwritten_versions, agent.rs:995-1299) ----------

    def find_cleared_versions(self, site_id: bytes) -> set[int]:
        """db_versions of ``site_id`` that no live clock row references —
        every cell they wrote has been overwritten by a newer version
        (find_cleared_db_versions, agent.rs:1250-1299). Delete/pk-only
        sentinel clock rows keep tombstone versions live until superseded.
        Local writes store NULL in clock site_id (like crsql ordinal 0), so
        the probe uses ``IS ?``.
        """
        if not self._tables:
            return set()
        probe = None if site_id == self.site_id else site_id
        parts: list[str] = []
        params: list = [site_id]
        for name in self._tables:
            clock_t = _q(name + "__crdt_clock")
            parts.append(
                f"SELECT DISTINCT db_version FROM {clock_t} WHERE site_id IS ?"
            )
            params.append(probe)
        sql = (
            "SELECT DISTINCT db_version FROM __corro_bookkeeping"
            " WHERE actor_id = ? AND db_version IS NOT NULL"
            " EXCEPT SELECT db_version FROM ("
            + " UNION ".join(parts)
            + ")"
        )
        return {row[0] for row in self.read_conn.execute(sql, params)}

    def store_empty_changeset(
        self, actor_id: bytes, start: int, end: int
    ) -> int:
        """Collapse [start, end] into one cleared (db_version-less)
        bookkeeping range row, merging overlapping/adjacent rows — the
        range-collapsing DELETE+INSERT of store_empty_changeset
        (agent.rs:1588-1664) — then prune the change log and partial
        buffers those versions owned. Returns the number of range rows
        written (1, or 0 if the merge produced nothing new)."""
        c = self.conn
        with self._wlock("store_empty_changeset"):
            try:
                c.execute("BEGIN IMMEDIATE")
                # Overlap/adjacency predicate (store_empty_changeset's
                # DELETE, agent.rs:1598-1614, with its straddle-the-start
                # hole closed): current singles (end_version NULL) inside
                # the range, and cleared ranges (end_version set) that
                # overlap or touch [start-1, end+1] — contained, straddling
                # either end, containing, or exactly adjacent.
                pred = (
                    " actor_id = ? AND ("
                    "  (end_version IS NULL AND start_version BETWEEN ? AND ?)"
                    "  OR (end_version IS NOT NULL AND start_version <= ?"
                    "      AND end_version >= ?))"
                )
                args = (actor_id, start, end, end + 1, start - 1)
                rows = c.execute(
                    "SELECT start_version, end_version, db_version"
                    " FROM __corro_bookkeeping WHERE" + pred,
                    args,
                ).fetchall()
                merged = RangeSet([(start, end)])
                for sv, ev, _dbv in rows:
                    merged.insert(sv, ev if ev is not None else sv)
                if len(merged) > 1:
                    # Failsafe mirrored from the reference: deleting
                    # non-contiguous ranges means bookkeeping is corrupt.
                    raise StoreError(
                        f"store_empty_changeset would merge non-contiguous"
                        f" ranges: {list(merged)}"
                    )
                c.execute(
                    "DELETE FROM __corro_bookkeeping WHERE" + pred, args
                )
                inserted = 0
                for s, e in merged:
                    c.execute(
                        "INSERT INTO __corro_bookkeeping (actor_id,"
                        " start_version, end_version, db_version, last_seq, ts)"
                        " VALUES (?, ?, ?, NULL, NULL, NULL)",
                        (actor_id, s, e),
                    )
                    inserted += 1
                # Prune: the change log rows for the cleared db_versions (the
                # actual space reclaim — the crsql vtab does this implicitly
                # because overwritten clock rows vanish), and any stale
                # partial buffers within the cleared span.
                dbvs = [r[2] for r in rows if r[2] is not None]
                if dbvs:
                    qs = ",".join("?" for _ in dbvs)
                    c.execute(
                        f"DELETE FROM __crdt_changes WHERE site_id = ?"
                        f" AND db_version IN ({qs})",
                        (actor_id, *dbvs),
                    )
                c.execute(
                    "DELETE FROM __corro_buffered_changes"
                    " WHERE actor_id = ? AND version BETWEEN ? AND ?",
                    (actor_id, start, end),
                )
                c.execute(
                    "DELETE FROM __corro_seq_bookkeeping"
                    " WHERE actor_id = ? AND version BETWEEN ? AND ?",
                    (actor_id, start, end),
                )
                c.execute("COMMIT")
            except Exception:
                c.execute("ROLLBACK")
                raise
        return inserted

    # -- remote merge (process_multiple_changes, agent.rs:1809-2060) ---------

    def apply_changes(self, changes: list[Change]) -> int:
        """Merge remote changes in one txn; returns the applied count."""
        c = self.conn
        applied = 0
        with self._wlock("apply_changes"):
            try:
                c.execute("BEGIN IMMEDIATE")
                c.execute(
                    "UPDATE __corro_meta SET value = 1 WHERE key='apply_remote'"
                )
                for ch in changes:
                    if self._apply_one(c, ch):
                        applied += 1
                c.execute("COMMIT")
            except Exception:
                c.execute("ROLLBACK")
                raise
            finally:
                c.execute(
                    "UPDATE __corro_meta SET value = 0 WHERE key='apply_remote'"
                )
        return applied

    def _apply_one(self, c: sqlite3.Connection, ch: Change) -> bool:
        info = self._tables.get(ch.table)
        if info is None:
            return False  # unknown table (schema lag): drop, sync re-serves
        rows_t = _q(ch.table + "__crdt_rows")
        clock_t = _q(ch.table + "__crdt_clock")
        # One point read for both the causal length and the cell's clock
        # (the apply path runs per change; two separate SELECTs measurably
        # dominated the receiver side of the host bench). The joined
        # col_version is only valid in the same-epoch branch — the
        # adoption branch wipes the clock table first.
        row = c.execute(
            f"SELECT r.cl, cc.col_version FROM {rows_t} r"
            f" LEFT JOIN {clock_t} cc ON cc.pk = r.pk AND cc.cid = ?"
            " WHERE r.pk = ?",
            (ch.cid, ch.pk),
        ).fetchone()
        local_cl = row[0] if row else 0
        local_cv_joined = row[1] if row else None

        if ch.cl < local_cl:
            return False  # stale causal epoch
        if ch.cl > local_cl:
            # Adopt the newer epoch.
            c.execute(
                f"INSERT INTO {rows_t} (pk, cl) VALUES (?, ?)"
                " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                (ch.pk, ch.cl),
            )
            c.execute(f"DELETE FROM {clock_t} WHERE pk = ?", (ch.pk,))
            if ch.cl % 2 == 0:
                self._delete_row(c, info, ch.pk)
                # Tombstone sentinel: keeps the delete's db_version live in
                # the clock so compaction can't clear it (see _create_crr).
                self._upsert_clock_sentinel(c, clock_t, Change.DELETE_CID, ch)
                self._log_change(c, ch)
                return True
            self._ensure_row(c, info, ch.pk)
            if ch.cid in (Change.DELETE_CID, Change.PKONLY_CID):
                if ch.cid == Change.PKONLY_CID:
                    self._upsert_clock_sentinel(
                        c, clock_t, Change.PKONLY_CID, ch
                    )
                self._log_change(c, ch)
                return True
            # fall through: apply (and log) the cell in the fresh epoch
        else:
            if ch.cl % 2 == 0:
                return False  # duplicate delete
            if ch.cid == Change.DELETE_CID:
                return False  # delete sentinel for an epoch we've superseded
            if ch.cid == Change.PKONLY_CID:
                self._ensure_row(c, info, ch.pk)
                self._upsert_clock_sentinel(c, clock_t, Change.PKONLY_CID, ch)
                self._log_change(c, ch)
                return True

        if ch.cid not in info.data_cols:
            return False  # column we don't know (additive schema lag)

        if ch.cl > local_cl:
            # Epoch adoption wiped the clock above: no LWW compare.
            local_cv_joined = None
        if local_cv_joined is not None:
            local_cv = local_cv_joined
            if ch.col_version < local_cv:
                return False
            if ch.col_version == local_cv:
                if self.native_crdt:
                    # In-DB tie-break: the local value never leaves SQLite.
                    where = " AND ".join(
                        f"{_q(k)} = ?" for k in info.pk_cols
                    )
                    row = c.execute(
                        f"SELECT crdt_value_cmp(?, {_q(ch.cid)}) <= 0"
                        f" FROM {_q(info.name)} WHERE {where}",
                        (ch.val, *unpack_columns(ch.pk)),
                    ).fetchone()
                    # Missing row ⇒ local cell is NULL: only a NULL ties.
                    lose = bool(row[0]) if row is not None else ch.val is None
                else:
                    local_val = self._cell_value(c, info, ch.pk, ch.cid)
                    lose = value_le(ch.val, local_val)
                if lose:
                    return False  # we win or tie exactly (idempotent)
        self._ensure_row(c, info, ch.pk)
        c.execute(
            f"UPDATE {_q(info.name)} SET {_q(ch.cid)} = ? WHERE "
            + " AND ".join(f"{_q(k)} = ?" for k in info.pk_cols),
            (ch.val, *unpack_columns(ch.pk)),
        )
        c.execute(
            f"INSERT INTO {clock_t} (pk, cid, col_version, db_version, seq, site_id)"
            " VALUES (?, ?, ?, ?, ?, ?)"
            " ON CONFLICT (pk, cid) DO UPDATE SET"
            "  col_version = excluded.col_version,"
            "  db_version = excluded.db_version,"
            "  seq = excluded.seq, site_id = excluded.site_id",
            (ch.pk, ch.cid, ch.col_version, ch.db_version, ch.seq, ch.site_id),
        )
        self._log_change(c, ch)
        return True

    def _upsert_clock_sentinel(
        self, c: sqlite3.Connection, clock_t: str, cid: str, ch: Change
    ) -> None:
        c.execute(
            f"INSERT INTO {clock_t} (pk, cid, col_version, db_version, seq, site_id)"
            " VALUES (?, ?, 1, ?, ?, ?)"
            " ON CONFLICT (pk, cid) DO UPDATE SET"
            "  db_version = excluded.db_version,"
            "  seq = excluded.seq, site_id = excluded.site_id",
            (ch.pk, cid, ch.db_version, ch.seq, ch.site_id),
        )

    def _log_change(self, c: sqlite3.Connection, ch: Change) -> None:
        # Keep the winning change re-servable for third-party sync
        # (the crsql_changes vtab serves merged state by (site, db_version)).
        c.execute(
            "INSERT OR REPLACE INTO __crdt_changes"
            " (tbl, pk, cid, val, col_version, db_version, seq, site_id, cl)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
            ch.to_tuple(),
        )

    def _ensure_row(self, c: sqlite3.Connection, info: TableInfo, pk: bytes) -> None:
        cols = ", ".join(_q(k) for k in info.pk_cols)
        ph = ", ".join("?" for _ in info.pk_cols)
        c.execute(
            f"INSERT OR IGNORE INTO {_q(info.name)} ({cols}) VALUES ({ph})",
            unpack_columns(pk),
        )

    def _delete_row(self, c: sqlite3.Connection, info: TableInfo, pk: bytes) -> None:
        c.execute(
            f"DELETE FROM {_q(info.name)} WHERE "
            + " AND ".join(f"{_q(k)} = ?" for k in info.pk_cols),
            unpack_columns(pk),
        )

    def _cell_value(
        self, c: sqlite3.Connection, info: TableInfo, pk: bytes, cid: str
    ) -> SqliteValue:
        row = c.execute(
            f"SELECT {_q(cid)} FROM {_q(info.name)} WHERE "
            + " AND ".join(f"{_q(k)} = ?" for k in info.pk_cols),
            unpack_columns(pk),
        ).fetchone()
        return row[0] if row else None


def _bind(st: Statement):
    if st.named_params is not None:
        return st.named_params
    return st.params or ()


def _sql_pack(*values: SqliteValue) -> bytes:
    return pack_columns(values)
