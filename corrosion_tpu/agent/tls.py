"""TLS utilities: certificate generation + ssl contexts for the gossip plane.

The reference secures QUIC gossip with rustls and generates certificates
with rcgen (`corrosion tls ca/server/client generate`,
corrosion/src/command/tls.rs:1-94; server/client configs incl. the mTLS
client verifier, corro-agent/src/api/peer.rs:132-313). Here the TCP gossip
plane is wrapped with stdlib ``ssl`` and certificates come from the
``cryptography`` package (the rcgen role):

- ``generate_ca(dir)``            → ca_cert.pem + ca_key.pem (self-signed)
- ``generate_server_cert(...)``   → cert.pem + key.pem signed by the CA,
                                    SAN = the gossip addr's host
- ``generate_client_cert(...)``   → client-auth cert for mTLS
- ``server_ssl_context(...)``     → accepts gossip connections; optionally
                                    requires + verifies client certs (mTLS)
- ``client_ssl_context(...)``     → verifies the server against the CA;
                                    ``insecure=True`` mirrors the
                                    reference's `insecure = true` config
                                    (skip name/chain verification)
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from dataclasses import dataclass

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

CA_CERT = "ca_cert.pem"
CA_KEY = "ca_key.pem"
SERVER_CERT = "cert.pem"
SERVER_KEY = "key.pem"
CLIENT_CERT = "client_cert.pem"
CLIENT_KEY = "client_key.pem"


@dataclass(frozen=True)
class CertPaths:
    cert: str
    key: str


def _write_key_cert(
    directory: str, key, cert, key_name: str, cert_name: str
) -> CertPaths:
    os.makedirs(directory, exist_ok=True)
    key_path = os.path.join(directory, key_name)
    cert_path = os.path.join(directory, cert_name)
    with open(key_path, "wb") as f:
        os.fchmod(f.fileno(), 0o600)
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return CertPaths(cert=cert_path, key=key_path)


def _name(common_name: str) -> x509.Name:
    return x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    )


def _validity():
    now = datetime.datetime.now(datetime.timezone.utc)
    return now - datetime.timedelta(hours=1), now + datetime.timedelta(
        days=3650
    )


def generate_ca(directory: str) -> CertPaths:
    """Self-signed CA (tls.rs `generate_ca`)."""
    key = ec.generate_private_key(ec.SECP256R1())
    not_before, not_after = _validity()
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name("corrosion-tpu CA"))
        .issuer_name(_name("corrosion-tpu CA"))
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            True,
        )
        .sign(key, hashes.SHA256())
    )
    return _write_key_cert(directory, key, cert, CA_KEY, CA_CERT)


def _load_ca(ca_dir: str):
    with open(os.path.join(ca_dir, CA_KEY), "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), None)
    with open(os.path.join(ca_dir, CA_CERT), "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    return ca_key, ca_cert


def _signed_cert(ca_dir: str, common_name: str, eku, sans=None):
    ca_key, ca_cert = _load_ca(ca_dir)
    key = ec.generate_private_key(ec.SECP256R1())
    not_before, not_after = _validity()
    builder = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), True)
        .add_extension(x509.ExtendedKeyUsage([eku]), False)
    )
    if sans:
        alt_names = []
        for san in sans:
            try:
                alt_names.append(
                    x509.IPAddress(ipaddress.ip_address(san))
                )
            except ValueError:
                alt_names.append(x509.DNSName(san))
        builder = builder.add_extension(
            x509.SubjectAlternativeName(alt_names), False
        )
    return key, builder.sign(ca_key, hashes.SHA256())


def generate_server_cert(
    directory: str, ca_dir: str, host: str
) -> CertPaths:
    """Server cert for the gossip addr's host (tls.rs `generate_server_cert`
    uses config.gossip.addr's IP as the SAN)."""
    key, cert = _signed_cert(
        ca_dir,
        host,
        ExtendedKeyUsageOID.SERVER_AUTH,
        sans=[host],
    )
    return _write_key_cert(directory, key, cert, SERVER_KEY, SERVER_CERT)


def generate_client_cert(directory: str, ca_dir: str) -> CertPaths:
    """Client-auth cert for mTLS (tls.rs `generate_client_cert`)."""
    key, cert = _signed_cert(
        ca_dir, "corrosion-tpu client", ExtendedKeyUsageOID.CLIENT_AUTH
    )
    return _write_key_cert(directory, key, cert, CLIENT_KEY, CLIENT_CERT)


def server_ssl_context(
    cert: str, key: str, ca_cert: str | None = None,
    require_client_cert: bool = False,
) -> ssl.SSLContext:
    """Gossip-server context (peer.rs:132-213). ``require_client_cert``
    enables the mTLS client verifier against the CA."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3
    ctx.load_cert_chain(cert, key)
    if require_client_cert:
        if ca_cert is None:
            raise ValueError("mTLS requires the CA certificate")
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(ca_cert)
    return ctx


def client_ssl_context(
    ca_cert: str | None = None,
    cert: str | None = None,
    key: str | None = None,
    insecure: bool = False,
) -> ssl.SSLContext:
    """Gossip-client context (peer.rs:221-313); pass cert+key for mTLS.
    ``insecure`` skips chain/name verification (config `insecure = true`).

    Fails closed: verification without a CA would leave an empty trust
    store whose every handshake error the transport swallows as a generic
    connection failure — a silent never-syncs outage — so it is rejected
    here at build time instead.
    """
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3
    if insecure:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    elif ca_cert is not None:
        ctx.load_verify_locations(ca_cert)
    else:
        raise ValueError(
            "client TLS without a CA certificate: pass ca_cert (the "
            "cluster CA) or insecure=True"
        )
    if cert and key:
        ctx.load_cert_chain(cert, key)
    return ctx
