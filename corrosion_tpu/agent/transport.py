"""Gossip transport: UDP datagrams + length-delimited frames over TCP.

The reference multiplexes three planes over QUIC (SURVEY.md §5: datagrams =
SWIM, uni streams = broadcast, bi streams = sync) with a cached
connection-per-addr pool (corro-agent/src/transport.rs:26-63). Python's
stdlib has no QUIC, so the host agent keeps the same plane split:

- an **unreliable datagram plane** for SWIM packets (send_datagram — one
  UDP socket bound beside the TCP gossip port, ≤1178 B per packet like
  foca's max_packet_size, broadcast/mod.rs:710). UDP sends never connect
  and never block, so a black-holing peer cannot stall the probe loop;
  oversized or UDP-less sends fall back to the stream plane transparently.
- one-shot stream frames for broadcast changesets (send_frame, pooled
  connections, reconnect-once semantics like transport.rs:75-89);
- a request/stream exchange for sync sessions (open_session), the
  bi-stream analogue of peer.rs:925-1527.

Per-addr **circuit breaker**: a peer whose sends keep failing (or whose
connect black-holes past the timeout) trips open after
``BREAKER_THRESHOLD`` consecutive failures and fails fast for an
exponentially growing cooldown — the transport-level complement of the
reference's reconnect-once + backoff (transport.rs:75-89). Without it a
SYN-dropping peer costs every caller the full connect timeout.

Frames are 4-byte big-endian length + a kind byte + body (datagrams carry
kind + body without the length prefix — the packet delimits itself). Kind 1
is the compact binary codec (the speedy-encoding role of
corro-types/src/broadcast.rs), encoded by the native runtime
(corrosion_tpu/_native) when built; kind 0 is JSON with bytes values as
{"$b": hex}, the encode fallback without a C toolchain. Decoding accepts
both kinds on every peer — a pure-Python binary decoder below keeps mixed
native/non-native clusters fully interoperable.
"""

from __future__ import annotations

import asyncio
import json
import struct
import time
from typing import Any, Callable, Awaitable

from corrosion_tpu import native as _native

MAX_FRAME = 32 * 1024 * 1024
MAX_DATAGRAM = 1178  # foca max_packet_size (broadcast/mod.rs:710)

FRAME_JSON = 0
FRAME_BIN = 1

# Causal-trace wire header: broadcast changeset frames may carry a W3C
# traceparent under this key (the SyncTraceContextV1 role for the
# broadcast plane, sync.rs:32-67) so a write's dissemination chain
# reconstructs across hops — each relay re-stamps the frame with ITS
# ingest span's traceparent, parenting the next hop's span on this one.
# Absent on untraced/unsampled writes; relays without tracing forward it
# untouched (the chain skips them but stays connected by trace id).
TRACE_KEY = "trace"


def attach_trace(frame: dict, traceparent: str | None) -> dict:
    """Stamp (or re-stamp) a frame's trace header in place; a None
    traceparent leaves the frame untouched."""
    if traceparent is not None:
        frame[TRACE_KEY] = traceparent
    return frame


def extract_trace(frame: dict) -> str | None:
    """The frame's traceparent header, or None. Malformed values are
    dropped here (one validation point) so ingest never parents a span
    on garbage a peer sent."""
    tp = frame.get(TRACE_KEY)
    if isinstance(tp, str):
        from corrosion_tpu.utils.tracing import parse_traceparent

        if parse_traceparent(tp) is not None:
            return tp
    return None

# Circuit breaker: consecutive failures before tripping, and the cooldown
# schedule (doubles per further failure, capped).
BREAKER_THRESHOLD = 3
BREAKER_BASE_S = 1.0
BREAKER_MAX_S = 30.0


def encode_value(o: Any) -> Any:
    if isinstance(o, bytes):
        return {"$b": o.hex()}
    if isinstance(o, (list, tuple)):
        return [encode_value(x) for x in o]
    if isinstance(o, dict):
        return {k: encode_value(v) for k, v in o.items()}
    return o


def decode_value(o: Any) -> Any:
    if isinstance(o, dict):
        if set(o.keys()) == {"$b"}:
            return bytes.fromhex(o["$b"])
        return {k: decode_value(v) for k, v in o.items()}
    if isinstance(o, list):
        return [decode_value(x) for x in o]
    return o


def encode_frame(msg: dict) -> bytes:
    if _native.native is not None:
        body = bytes([FRAME_BIN]) + _native.native.encode(msg)
    else:
        body = bytes([FRAME_JSON]) + json.dumps(
            encode_value(msg), separators=(",", ":")
        ).encode()
    return struct.pack(">I", len(body)) + body


# Binary wire tags (native/corro_native.c W_*; keep in sync).
_W_NULL, _W_FALSE, _W_TRUE, _W_INT = 0, 1, 2, 3
_W_FLOAT, _W_STR, _W_BYTES, _W_LIST, _W_DICT = 4, 5, 6, 7, 8


def _py_read_varint(b: bytes, i: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        if i >= len(b) or shift > 63:
            raise ValueError("truncated wire varint")
        byte = b[i]
        i += 1
        n |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return n, i
        shift += 7


def _py_wire_decode(b: bytes, i: int = 0, depth: int = 0) -> tuple[Any, int]:
    """Pure-Python decoder for the binary wire format (parity with the C
    decoder; used when the native module is not built)."""
    if depth > 64 or i >= len(b):
        raise ValueError("bad wire value")
    tag = b[i]
    i += 1
    if tag == _W_NULL:
        return None, i
    if tag == _W_FALSE:
        return False, i
    if tag == _W_TRUE:
        return True, i
    if tag == _W_INT:
        z, i = _py_read_varint(b, i)
        return (z >> 1) ^ -(z & 1), i
    if tag == _W_FLOAT:
        if i + 8 > len(b):
            raise ValueError("truncated wire float")
        return struct.unpack_from(">d", b, i)[0], i + 8
    if tag in (_W_STR, _W_BYTES):
        n, i = _py_read_varint(b, i)
        if i + n > len(b):
            raise ValueError("truncated wire string")
        raw = b[i : i + n]
        return (raw.decode("utf-8") if tag == _W_STR else raw), i + n
    if tag == _W_LIST:
        n, i = _py_read_varint(b, i)
        out = []
        for _ in range(n):
            v, i = _py_wire_decode(b, i, depth + 1)
            out.append(v)
        return out, i
    if tag == _W_DICT:
        n, i = _py_read_varint(b, i)
        d: dict = {}
        for _ in range(n):
            kn, i = _py_read_varint(b, i)
            if i + kn > len(b):
                raise ValueError("truncated wire key")
            key = b[i : i + kn].decode("utf-8")
            i += kn
            d[key], i = _py_wire_decode(b, i, depth + 1)
        return d, i
    raise ValueError(f"bad wire tag {tag}")


def decode_frame_body(body: bytes) -> dict:
    if not body:
        raise ValueError("empty frame")
    kind, payload = body[0], body[1:]
    if kind == FRAME_BIN:
        if _native.native is not None:
            return _native.native.decode(payload)
        obj, end = _py_wire_decode(payload)
        if end != len(payload):
            raise ValueError("trailing bytes after wire value")
        return obj
    if kind == FRAME_JSON:
        return decode_value(json.loads(payload))
    raise ValueError(f"unknown frame kind {kind}")


async def read_frame(reader: asyncio.StreamReader) -> dict | None:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    (n,) = struct.unpack(">I", header)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return decode_frame_body(body)


class Breaker:
    """Per-peer circuit breaker state (see module docstring). The
    threshold/cooldown schedule is instance-configurable so chaos
    harnesses can compress the cooldown into test time; defaults are
    the module constants."""

    __slots__ = ("fails", "open_until", "threshold", "base_s", "max_s")

    def __init__(
        self,
        threshold: int = BREAKER_THRESHOLD,
        base_s: float = BREAKER_BASE_S,
        max_s: float = BREAKER_MAX_S,
    ) -> None:
        self.fails = 0
        self.open_until = 0.0
        self.threshold = threshold
        self.base_s = base_s
        self.max_s = max_s

    def available(self) -> bool:
        return time.monotonic() >= self.open_until

    def ok(self) -> bool:
        """Reset on success. Returns True when this closed a previously
        tripped breaker (a recovery — the observability counterpart of
        the trip edge)."""
        recovered = self.fails >= self.threshold
        self.fails = 0
        self.open_until = 0.0
        return recovered

    def fail(self) -> bool:
        """Record a failure. Returns True on an available→open edge (a
        trip) — including a re-trip after a cooldown expired — so the
        caller can count trips without re-deriving the transition."""
        tripped = self.fails + 1 >= self.threshold and self.available()
        self.fails += 1
        if self.fails >= self.threshold:
            over = self.fails - self.threshold
            cooldown = min(self.base_s * (2.0 ** over), self.max_s)
            self.open_until = time.monotonic() + cooldown
        return tripped


class _DatagramPlane(asyncio.DatagramProtocol):
    """Inbound side of the UDP gossip socket; frames dispatch to the same
    handler as stream frames, with a reply-less session."""

    # In-flight dispatch cap: past this, inbound packets drop (the
    # unreliable plane's legitimate response to a flood).
    MAX_PENDING = 1024

    def __init__(self, handler, owner: "Transport | None" = None) -> None:
        self._handler = handler
        self._owner = owner
        self.transport: asyncio.DatagramTransport | None = None
        # Strong refs: the event loop only weak-refs tasks, and a GC'd
        # dispatch task would silently swallow a ping/ack.
        self._pending: set[asyncio.Task] = set()

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        if self._owner is not None:
            self._owner._count("datagrams_recv")
            self._owner._count("bytes_recv", len(data))
        if len(self._pending) >= self.MAX_PENDING:
            return  # flood: drop like any saturated datagram socket
        try:
            msg = decode_frame_body(data)
        except (ValueError, UnicodeDecodeError):
            return  # malformed packet: drop (unreliable plane)
        task = asyncio.ensure_future(self._dispatch(msg))
        self._pending.add(task)
        task.add_done_callback(self._pending.discard)

    async def _dispatch(self, msg: dict) -> None:
        try:
            await self._handler(DatagramSession(), msg)
        except Exception:
            pass  # handler errors must not kill the UDP protocol


class DatagramSession:
    """Session stand-in for datagram-delivered frames: replies flow via
    explicit peer addresses (SWIM carries from_addr), never the session."""

    async def send(self, msg: dict) -> None:
        raise ConnectionError("datagram session cannot stream replies")

    async def recv(self, timeout: float = 0.0) -> None:
        return None

    def close(self) -> None:
        pass


class Transport:
    """Pooled one-shot sender + datagram plane + session opener + server.

    Optional TLS (agent/tls.py): pass an ``ssl.SSLContext`` for the server
    (inbound gossip) and/or client (outbound) side — the rustls configs of
    peer.rs:132-313. mTLS comes from the contexts themselves. With TLS the
    datagram plane is disabled (plaintext UDP would downgrade the gossip
    plane; QUIC datagrams in the reference are encrypted) and SWIM rides
    the TLS stream path.
    """

    # Outbound datagram sockets, addr-hashed (the reference's 8 QUIC
    # client endpoints, transport.rs:54-57): spreads kernel socket-buffer
    # pressure across sockets under gossip bursts.
    N_CLIENT_ENDPOINTS = 8

    def __init__(
        self,
        ssl_server=None,
        ssl_client=None,
        connect_timeout: float = 3.0,
        send_timeout: float = 5.0,
        metrics=None,
        netem=None,
        breaker_threshold: int = BREAKER_THRESHOLD,
        breaker_base_s: float = BREAKER_BASE_S,
        breaker_max_s: float = BREAKER_MAX_S,
    ) -> None:
        self._pool: dict[tuple[str, int], tuple[asyncio.StreamReader, asyncio.StreamWriter]] = {}
        # Per-addr dial serialization: two tasks missing the pool at
        # once must not both dial — the loser's socket would be
        # overwritten in the pool and leak (never closed by _drop).
        self._dial_locks: dict[tuple[str, int], asyncio.Lock] = {}
        self._locks: dict[tuple[str, int], asyncio.Lock] = {}
        self._breakers: dict[tuple[str, int], Breaker] = {}
        # ACCEPTED connections, tracked so close() kills them too. An
        # asyncio server's close() only stops LISTENING; in-process the
        # event loop would keep serving already-accepted peers of a
        # "dead" agent forever — peers' pooled sends would keep
        # succeeding against a corpse, which no real process death
        # allows (and which kept the circuit breaker from ever seeing
        # the crash in the chaos harness).
        self._accepted: set[asyncio.StreamWriter] = set()
        self._server: asyncio.AbstractServer | None = None
        self._udp: asyncio.DatagramTransport | None = None
        self._client_udp: list[asyncio.DatagramTransport] = []
        self._ssl_server = ssl_server
        self._ssl_client = ssl_client
        self.connect_timeout = connect_timeout
        # Blocking-send abort (the reference aborts a sync send blocked
        # > 5 s, peer.rs:352-355; same guard here for any frame send).
        self.send_timeout = send_timeout
        # Deterministic impairment shim (agent/netem.py); None = the
        # bit-identical unimpaired path (a single branch per operation).
        self._netem = netem
        self._breaker_threshold = breaker_threshold
        self._breaker_base_s = breaker_base_s
        self._breaker_max_s = breaker_max_s
        # Aggregate transport metrics (Transport::emit_metrics,
        # transport.rs:225+): frames/datagrams/bytes both ways, pooled
        # connections, open breakers.
        self._m = None
        if metrics is not None:
            self.bind_metrics(metrics)

    def bind_metrics(self, registry) -> None:
        self._m = {
            "frames_sent": registry.counter(
                "corro_peer_streams_sent", "stream frames sent"
            ),
            "frames_recv": registry.counter(
                "corro_peer_streams_recv", "stream frames received"
            ),
            "datagrams_sent": registry.counter(
                "corro_peer_datagrams_sent", "UDP datagrams sent"
            ),
            "datagrams_recv": registry.counter(
                "corro_peer_datagrams_recv", "UDP datagrams received"
            ),
            "bytes_sent": registry.counter(
                "corro_peer_bytes_sent", "wire bytes sent (frames+datagrams)"
            ),
            "bytes_recv": registry.counter(
                "corro_peer_bytes_recv", "wire bytes received"
            ),
            "send_failures": registry.counter(
                "corro_peer_send_failures", "failed frame sends"
            ),
            "conns": registry.gauge(
                "corro_peer_connections", "pooled outbound connections"
            ),
            "breakers_open": registry.gauge(
                "corro_peer_breakers_open", "peers with an open circuit breaker"
            ),
            # Trip/recovery EDGES, per peer: the open-breaker gauge shows
            # the steady state but a trip that opens and cools down
            # between scrapes was invisible — the host chaos harness
            # asserts on these to prove the defense actually fired.
            "breaker_trips": registry.counter(
                "corro_peer_breaker_trips_total",
                "circuit-breaker open transitions, by peer addr",
            ),
            "breaker_recoveries": registry.counter(
                "corro_peer_breaker_recoveries_total",
                "circuit-breaker recoveries (first success after a trip)",
            ),
        }

    def _count(self, key: str, n: int = 1) -> None:
        if self._m is not None:
            self._m[key].inc(n)

    def _sample_gauges(self) -> None:
        if self._m is not None:
            self._m["conns"].set(len(self._pool))
            self._m["breakers_open"].set(
                sum(1 for b in self._breakers.values() if not b.available())
            )

    # -- circuit breaker -----------------------------------------------------

    def breaker(self, addr: tuple[str, int]) -> Breaker:
        br = self._breakers.get(addr)
        if br is None:
            br = self._breakers[addr] = Breaker(
                threshold=self._breaker_threshold,
                base_s=self._breaker_base_s,
                max_s=self._breaker_max_s,
            )
        return br

    def _breaker_fail(self, addr: tuple[str, int], br: Breaker) -> None:
        """One failed operation: breaker bookkeeping + the failure/trip
        counters (shared by frame sends and session opens)."""
        if br.fail() and self._m is not None:
            self._m["breaker_trips"].inc(addr=f"{addr[0]}:{addr[1]}")
        self._count("send_failures")
        self._sample_gauges()

    def _breaker_ok(self, addr: tuple[str, int], br: Breaker) -> None:
        if br.ok() and self._m is not None:
            self._m["breaker_recoveries"].inc(addr=f"{addr[0]}:{addr[1]}")

    # -- outbound ------------------------------------------------------------

    def send_datagram(self, addr: tuple[str, int], msg: dict) -> bool:
        """Unreliable, non-blocking single-packet send (the SWIM plane,
        Transport::send_datagram, transport.rs:66-90) over one of the
        addr-hashed client endpoints. Returns False when the packet
        exceeds MAX_DATAGRAM or the UDP sockets are absent — callers
        needing delivery-or-fallback use ``send_packet``."""
        if self._udp is None or not self._client_udp:
            return False
        body = encode_frame(msg)[4:]  # kind + payload; packet self-delimits
        if len(body) > MAX_DATAGRAM:
            return False
        sock = self._client_udp[hash(addr) % len(self._client_udp)]
        if self._netem is not None:
            v = self._netem.udp_fault(addr)
            if v.drop:
                # Lost in the (simulated) network: the sender cannot
                # tell, exactly like a real dropped datagram.
                return True
            if v.delay_s > 0.0 or v.dup:
                return self._udp_send_impaired(sock, body, addr, v)
        try:
            sock.sendto(body, addr)
            self._count("datagrams_sent")
            self._count("bytes_sent", len(body))
            return True
        except OSError:
            return False

    def _udp_send_impaired(self, sock, body, addr, v) -> bool:
        """Delayed/duplicated datagram emission: late sends are
        scheduled, so unequal jitter across packets reorders them on the
        wire like a real WAN path would. Counters tick at the ACTUAL
        send, never for scheduled copies that die with the socket."""
        copies = 2 if v.dup else 1
        if v.delay_s > 0.0:
            def emit() -> None:
                # A delayed send may fire after the transport closed
                # (agent stop/crash mid-jitter): a late datagram into a
                # closed socket is just a lost packet, never an error.
                if sock.is_closing():
                    return
                try:
                    sock.sendto(body, addr)
                except Exception:
                    return
                self._count("datagrams_sent")
                self._count("bytes_sent", len(body))

            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return False
            for _ in range(copies):
                loop.call_later(v.delay_s, emit)
            return True  # in flight; a WAN sender can't know its fate
        sent = False
        for _ in range(copies):
            try:
                sock.sendto(body, addr)
            except OSError:
                continue  # same contract as the unimpaired path
            sent = True
            self._count("datagrams_sent")
            self._count("bytes_sent", len(body))
        return sent

    async def send_packet(self, addr: tuple[str, int], msg: dict) -> bool:
        """SWIM packet send: datagram when possible, stream fallback for
        oversized packets (bootstrap `known` dumps) or UDP-less/TLS mode."""
        if self.send_datagram(addr, msg):
            return True
        return await self.send_frame(addr, msg)

    async def send_frame(self, addr: tuple[str, int], msg: dict) -> bool:
        """Fire-and-forget frame (uni-stream analogue). One retry with a
        fresh connection on failure (transport.rs:75-89); fails fast while
        the peer's circuit breaker is open."""
        br = self.breaker(addr)
        if not br.available():
            return False
        if self._netem is not None:
            v = self._netem.stream_fault("bcast", addr)
            if v.drop:
                return True  # frame vanished in the impaired network
            if v.block_s is not None:
                # Cut link: burn the dial stall, then take the normal
                # failure path — exactly what feeds the breaker.
                await asyncio.sleep(v.block_s)
                self._breaker_fail(addr, br)
                return False
            if v.delay_s > 0.0:
                await asyncio.sleep(v.delay_s)
        lock = self._locks.setdefault(addr, asyncio.Lock())
        async with lock:
            if not br.available():
                return False  # tripped while we waited on the lock
            for attempt in (0, 1):
                try:
                    _, writer = await self._conn(addr, fresh=attempt > 0)
                    frame = encode_frame(msg)
                    writer.write(frame)
                    await asyncio.wait_for(writer.drain(), self.send_timeout)
                    self._breaker_ok(addr, br)
                    self._count("frames_sent")
                    self._count("bytes_sent", len(frame))
                    self._sample_gauges()
                    return True
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    self._drop(addr)
        self._breaker_fail(addr, br)
        return False

    async def open_session(
        self, addr: tuple[str, int], first: dict, timeout: float = 10.0
    ) -> "Session | None":
        """Dedicated connection for a sync exchange (bi-stream analogue)."""
        br = self.breaker(addr)
        if not br.available():
            return None
        if self._netem is not None:
            v = self._netem.stream_fault("sync", addr)
            if v.block_s is not None:
                await asyncio.sleep(v.block_s)
                self._breaker_fail(addr, br)
                return None
            if v.delay_s > 0.0:
                await asyncio.sleep(v.delay_s)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*addr, ssl=self._ssl_client), timeout
            )
            frame = encode_frame(first)
            writer.write(frame)
            await writer.drain()
            self._breaker_ok(addr, br)
            self._count("frames_sent")
            self._count("bytes_sent", len(frame))
            return Session(
                reader, writer, counter=self._count,
                netem=self._netem, peer=addr,
            )
        except (ConnectionError, OSError, asyncio.TimeoutError):
            self._breaker_fail(addr, br)
            return None

    async def _conn(self, addr, fresh=False):
        if fresh:
            self._drop(addr)
        lock = self._dial_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            if addr not in self._pool:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*addr, ssl=self._ssl_client),
                    self.connect_timeout,
                )
                self._pool[addr] = (reader, writer)
            return self._pool[addr]

    def _drop(self, addr) -> None:
        pair = self._pool.pop(addr, None)
        if pair:
            try:
                pair[1].close()
            except Exception:
                pass

    # -- inbound -------------------------------------------------------------

    async def serve(
        self,
        host: str,
        port: int,
        handler: Callable[["Session", dict], Awaitable[None]],
    ) -> tuple[str, int]:
        """Accept connections; dispatch each inbound frame to ``handler``.
        The handler may keep the session for a streaming exchange. Also
        binds the UDP datagram plane on the same port (plaintext mode
        only); if the UDP bind fails, gossip degrades to stream-only."""

        async def on_conn(reader, writer):
            # Inbound sessions stream sync replies back to the dialer;
            # their peer is an ephemeral client port the shim cannot
            # name, so netem impairment on them matches wildcard-link
            # components only (documented in agent/netem.py).
            session = Session(
                reader, writer, counter=self._count,
                netem=self._netem,
                peer=writer.get_extra_info("peername"),
            )
            self._accepted.add(writer)
            try:
                while True:
                    msg = await read_frame(reader)
                    if msg is None:
                        break
                    self._count("frames_recv")
                    await handler(session, msg)
            except ConnectionError:
                pass
            except asyncio.CancelledError:
                raise  # server shutdown: cleanup runs, cancellation flows
            except ValueError:
                pass  # malformed frame: drop the connection cleanly
            finally:
                self._accepted.discard(writer)
                session.close()

        self._server = await asyncio.start_server(
            on_conn, host, port, ssl=self._ssl_server
        )
        sock = self._server.sockets[0].getsockname()
        if self._ssl_server is None:
            try:
                loop = asyncio.get_running_loop()
                self._udp, _ = await loop.create_datagram_endpoint(
                    lambda: _DatagramPlane(handler, self),
                    local_addr=(sock[0], sock[1]),
                )
                # Addr-hashed outbound endpoints (transport.rs:54-57's 8
                # client endpoints). SWIM replies target the peer's
                # ADVERTISED addr (from_addr in the packet), never the
                # packet's source, so ephemeral-port client sockets are
                # send-only.
                for _ in range(self.N_CLIENT_ENDPOINTS):
                    t, _p = await loop.create_datagram_endpoint(
                        asyncio.DatagramProtocol,
                        local_addr=(sock[0], 0),
                    )
                    self._client_udp.append(t)
            except OSError:
                # Atomic: a failed client-endpoint bind must not leave a
                # recv-only gossip socket behind (or leak it past close()).
                if self._udp is not None:
                    self._udp.close()
                self._udp = None  # corro-lint: disable=CT040 reason=serve() runs once at startup; the OSError unwind must null the shared handle it just closed
                for t in self._client_udp:
                    t.close()
                self._client_udp = []
        return sock[0], sock[1]

    def close(self) -> None:
        for addr in list(self._pool):
            self._drop(addr)
        for w in list(self._accepted):
            try:
                w.close()
            except Exception:
                pass
        self._accepted.clear()
        if self._udp is not None:
            self._udp.close()
        for t in self._client_udp:
            t.close()
        self._client_udp = []
        if self._server is not None:
            self._server.close()


class Session:
    """One connection usable for framed request/stream exchanges. The
    optional counter keeps sync-session traffic visible to the transport
    metrics (emit_metrics parity — sync dominates wire bytes during
    catch-up)."""

    def __init__(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
        counter=None, netem=None, peer=None,
    ):
        self.reader = reader
        self.writer = writer
        self._count = counter or (lambda key, n=1: None)
        # Session sends are the sync plane's wire surface (the only
        # streaming exchange): the netem shim paces them with "sync"
        # delay components — which is exactly what the adaptive chunker
        # and the blocking-send stall guard observe — and a cut link
        # fails them after the stall.
        self._netem = netem
        self._peer = peer

    async def send(self, msg: dict) -> int:
        if self._netem is not None and self._peer is not None:
            v = self._netem.stream_fault("sync", self._peer)
            if v.block_s is not None:
                await asyncio.sleep(v.block_s)
                raise ConnectionError("netem: sync link cut")
            if v.delay_s > 0.0:
                await asyncio.sleep(v.delay_s)
        frame = encode_frame(msg)
        self.writer.write(frame)
        await self.writer.drain()
        self._count("frames_sent")
        self._count("bytes_sent", len(frame))
        return len(frame)

    async def recv(self, timeout: float = 30.0) -> dict | None:
        try:
            msg = await asyncio.wait_for(read_frame(self.reader), timeout)
        except asyncio.TimeoutError:
            return None
        if msg is not None:
            self._count("frames_recv")
        return msg

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass
