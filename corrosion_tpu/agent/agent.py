"""Agent: setup/run, change-ingest pipeline, background loops.

The host counterpart of corro-agent/src/agent.rs (setup :105-336, run
:354-970): owns the Store, the Bookie, the HLC, the transport, SWIM
membership, the broadcast pending queue, and the sync loop; exposes the
write path used by the HTTP API (make_broadcastable_changes,
api/public/mod.rs:33-191) and the ingest path for remote changesets
(process_multiple_changes, agent.rs:1809-2060) including partial-version
buffering (process_incomplete_version :2063-2151,
process_fully_buffered_changes :1667-1806).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field

from corrosion_tpu.agent.membership import Members, Swim
from corrosion_tpu.agent.store import Store
from corrosion_tpu.agent.transport import (
    Session,
    Transport,
    attach_trace,
    extract_trace,
)
from corrosion_tpu.core.bookkeeping import (
    Bookie,
    CLEARED,
    Current,
    FullNeed,
    Partial,
    PartialNeed,
    generate_sync,
)
from corrosion_tpu.core.changes import AdaptiveChunker, chunk_changes
from corrosion_tpu.core.hlc import HLC, ts_physical_ms
from corrosion_tpu.core.intervals import RangeSet
from corrosion_tpu.core.values import Change, ExecResponse, ExecResult, Statement
from corrosion_tpu.utils.locks import LockRegistry
from corrosion_tpu.utils.metrics import MetricsRegistry
from corrosion_tpu.utils.spawn import TaskRegistry
from corrosion_tpu.utils.tracing import Tracer
from corrosion_tpu.utils.tracing import current_span as tracing_current_span
from corrosion_tpu.utils.tripwire import Tripwire


@dataclass
class AgentConfig:
    data_dir: str
    gossip_host: str = "127.0.0.1"
    gossip_port: int = 0
    api_host: str = "127.0.0.1"
    api_port: int = 0
    bootstrap: list[tuple[str, int]] = field(default_factory=list)
    # Raw bootstrap specs ("host:port" or "name:port@dns") re-resolved by
    # the announcer loop until peers appear — DNS may not be published yet
    # at startup (resolve_bootstrap, agent.rs:1494-1586 + announcer
    # backoff, agent.rs:726-768).
    bootstrap_raw: list[str] = field(default_factory=list)
    schema_sql: str = ""
    probe_interval: float = 0.25
    broadcast_interval: float = 0.05  # flush tick (500 ms in the reference)
    # Pending-broadcast byte budget (the reference cuts its broadcast
    # buffer at 64 KiB, broadcast/mod.rs:357): over budget, oldest
    # retransmission backlog sheds first; never-sent local frames survive
    # to 8x this before shedding — so a member-less agent under sustained
    # write load holds bounded memory (see _pending_push).
    broadcast_buffer_bytes: int = 64 * 1024
    sync_interval: float = 0.5  # backoff floor 1 s in the reference
    fanout: int = 3  # num_indirect_probes analogue
    max_transmissions: int = 4
    sync_peers: int = 3  # 3-10 by need desc / ring asc (agent.rs:2383-2423)
    # Concurrent sync-session scheduling (parallel_sync, peer.rs:1108-1223):
    # need blocks requested per wave per session, and the server's per-wave
    # version budget (fairness across concurrent sessions).
    sync_wave_needs: int = 10
    sync_serve_budget: int = 512
    # Adaptive chunk sizing + stall abort (peer.rs:352-355, 638-653).
    sync_chunk_max_bytes: int = 8 * 1024
    sync_chunk_min_bytes: int = 1024
    sync_adapt_threshold: float = 0.5
    sync_stall_timeout: float = 5.0
    ingest_batch: int = 1000  # handle_changes batching (agent.rs:2450-2518)
    ingest_linger: float = 0.05
    # Admission control: per-route concurrency + load-shed (128 per route,
    # 4 for migrations; agent.rs:836-902).
    api_concurrency: int = 128
    migration_concurrency: int = 4
    admin_uds: str = ""  # unix socket path for admin RPC ("" = disabled)
    # Compaction cadence. The reference runs clear_overwritten_versions
    # every 300 s and batches empties for 120 s (agent.rs:86, :2520);
    # scaled down to in-process test time.
    compact_interval: float = 5.0
    empties_flush_interval: float = 0.5
    # Orphaned-partial reconcile cadence (clear_buffered_meta_loop runs
    # every 300 s in the reference, agent.rs:2575-2619; scaled to
    # in-process test time like compact_interval).
    buffered_meta_interval: float = 10.0
    # Row-count sampling cadence (collect_metrics runs every 10 s in the
    # reference, agent.rs:1138-1187). Full COUNT(*) scans ride the read
    # pool, but at millions of log rows even pooled scans are not free —
    # the cadence is its own knob, not derived from compact_interval.
    metrics_interval: float = 10.0
    # WAL truncation cadence (the reference checkpoints + times WAL
    # truncation in its db_cleanup loop, agent.rs:956-967, 1413-1435).
    wal_checkpoint_interval: float = 15.0
    # Member-state persistence cadence (diff_member_states every 60 s,
    # broadcast/mod.rs:570-702); persisted members seed rejoin at restart.
    member_persist_interval: float = 60.0
    # Gossip transport dial/send guards + circuit-breaker schedule
    # (transport.py module constants by default; chaos scenarios compress
    # them into test time).
    connect_timeout: float = 3.0
    send_timeout: float = 5.0
    breaker_threshold: int = 3
    breaker_base_s: float = 1.0
    breaker_max_s: float = 30.0
    # Announcer-loop backoff (agent.rs:726-768): how fast an agent with
    # an EMPTY alive set re-announces to its bootstrap seeds — both at
    # startup (DNS lag) and after a partition/suspicion cascade emptied
    # the membership.
    announce_backoff_min_s: float = 1.0
    announce_backoff_max_s: float = 30.0
    # Deterministic WAN impairment (agent/netem.py, docs/CHAOS.md "Host
    # plane"): a corro-host-fault-plan/1 dict installs a NetemShim on the
    # gossip transport. None = no shim, bit-identical transport path.
    netem_plan: dict | None = None
    netem_seed: int = 0
    netem_node: str = ""  # this node's name in the plan's link space
    tls: "AgentTls | None" = None  # gossip-plane TLS (None = plaintext)
    prometheus_addr: str = ""  # host:port for /metrics ("" = disabled)
    trace_export_path: str = ""  # JSON-lines span export ("" = in-memory)
    # OTLP/HTTP collector base URL (spans POST to <url>/v1/traces as
    # OTLP/JSON, batched — main.rs:64-117's exporter). "" = disabled.
    otlp_endpoint: str = ""
    # Causal write tracing (docs/OBSERVABILITY.md "Causal tracing"): give
    # every /v1/transactions write a trace id at API ingest and propagate
    # it through commit, inter-node rebroadcast (a traceparent header in
    # the bcast frame), and subscription fan-out. OFF by default: the
    # write path allocates no spans at all unless enabled (pinned by
    # tests), so the serving bench is untouched.
    trace_writes: bool = False
    # Trace-id-keyed sampling rate for write spans (tracing.trace_sampled)
    # — deterministic per trace id, so every hop of a kept trace keeps it
    # and a 2k-subscription storm can thin its span volume consistently.
    trace_sample: float = 1.0
    # Endurance plane (docs/OBSERVABILITY.md "Endurance plane"): stream
    # one whole-registry snapshot per runtime-metrics tick to a
    # corro-metric-series/1 JSONL (obs/series.py). None = not installed;
    # the loop takes ONE `is None` branch and is otherwise bit-identical
    # (pinned). Relaunch in the same process reattaches (mode="a"), so
    # kill_restart soaks keep one continuous, reset-annotated series.
    metric_series_path: str | None = None
    metric_series_max_bytes: int | None = None
    # Runtime-metrics/series sampling cadence; soak lanes compress it
    # into test time like every other interval knob.
    runtime_metrics_interval: float = 1.0
    # Serving query-cost plane (docs/SERVING.md "Query-cost plane"): arm
    # the per-subscription cost ledger (SubsManager.enable_costs) at
    # startup. OFF by default — handles carry ``cost=None``, the matcher
    # hot path takes single ``is None`` branches, and behavior is
    # bit-identical (pinned), the same contract as trace_writes and
    # metric_series_path.
    sub_costs: bool = False


@dataclass
class AgentTls:
    """Gossip-plane TLS material (peer.rs:132-313; agent/tls.py builds the
    contexts). ``mtls`` requires client certs on inbound and presents
    ``client_cert``/``client_key`` on outbound."""

    cert: str
    key: str
    ca: str | None = None
    client_cert: str | None = None
    client_key: str | None = None
    mtls: bool = False
    insecure: bool = False


class _StreakLogger:
    """Failure logging for periodic loops: WARNING on the first failure of
    a streak, DEBUG on repeats — a permanently failing loop stays visible
    without log spam (the reference warns on loop errors)."""

    def __init__(self, msg: str) -> None:
        self._log = logging.getLogger(__name__)
        self._msg = msg
        self._failing = False

    def ok(self) -> None:
        self._failing = False

    def fail(self) -> None:
        self._log.log(
            logging.DEBUG if self._failing else logging.WARNING,
            self._msg,
            exc_info=True,
        )
        self._failing = True


@dataclass
class PendingBroadcast:
    """An entry in the broadcast pending queue (broadcast/mod.rs:716-738)."""

    frame: dict
    tx_left: int
    size: int = 0  # encoded-size estimate, counted against the byte budget


class Agent:
    def __init__(self, cfg: AgentConfig) -> None:
        self.cfg = cfg
        os.makedirs(cfg.data_dir, exist_ok=True)
        site_id = os.urandom(16)
        self.store = Store(os.path.join(cfg.data_dir, "state.db"), site_id)
        self.actor_id = self.store.site_id.hex()
        self.bookie = Bookie()
        self.hlc = HLC()
        self.netem = None
        if cfg.netem_plan:
            from corrosion_tpu.agent.netem import NetemShim

            shim = NetemShim(
                cfg.netem_plan, seed=cfg.netem_seed,
                local=cfg.netem_node or self.actor_id[:8],
            )
            # An empty plan installs nothing: the transport keeps its
            # bit-identical unimpaired path.
            self.netem = shim if shim.enabled else None
        transport_kw = dict(
            connect_timeout=cfg.connect_timeout,
            send_timeout=cfg.send_timeout,
            breaker_threshold=cfg.breaker_threshold,
            breaker_base_s=cfg.breaker_base_s,
            breaker_max_s=cfg.breaker_max_s,
            netem=self.netem,
        )
        if cfg.tls is not None:
            from corrosion_tpu.agent import tls as tls_mod

            self.transport = Transport(
                ssl_server=tls_mod.server_ssl_context(
                    cfg.tls.cert, cfg.tls.key, cfg.tls.ca,
                    require_client_cert=cfg.tls.mtls,
                ),
                ssl_client=tls_mod.client_ssl_context(
                    cfg.tls.ca, cfg.tls.client_cert, cfg.tls.client_key,
                    insecure=cfg.tls.insecure,
                ),
                **transport_kw,
            )
        else:
            self.transport = Transport(**transport_kw)
        self.members = Members(self.actor_id)
        self.tasks = TaskRegistry()
        self.tripwire = Tripwire()
        self.lock_registry = LockRegistry()
        self.metrics = MetricsRegistry()
        # Aggregate transport metrics (Transport::emit_metrics parity).
        self.transport.bind_metrics(self.metrics)
        _added = self.metrics.counter(
            "corro_gossip_member_added", "members learned (first sighting)"
        )
        _removed = self.metrics.counter(
            "corro_gossip_member_removed", "members forgotten (down GC)"
        )
        # Zero-seed: a churn-free life must still EXPOSE the series (a 0
        # on the scrape and in every metric-series snapshot), so the
        # endurance plane's probe-false-alarm budget arms on a clean
        # soak instead of silently never evaluating.
        _added.inc(0)
        _removed.inc(0)
        self.members.on_added = lambda _aid: _added.inc()
        self.members.on_removed = lambda _aid: _removed.inc()
        self.tracer = Tracer(
            service=f"corrosion-{self.actor_id[:8]}",
            export_path=cfg.trace_export_path or None,
            otlp_endpoint=cfg.otlp_endpoint or None,
            sample=cfg.trace_sample,
        )
        self._trace_writes = cfg.trace_writes
        self._prom_server = None
        self._series_recorder = None  # endurance plane, installed lazily
        self.pool = None  # SplitPool, started with the event loop
        # Hot-path metric handles, resolved once.
        self._m_recv_lag = self.metrics.histogram(
            "corro_broadcast_recv_lag_seconds",
            "HLC age of received changesets (agent.rs:1238-1240)",
        )
        self._m_applied = self.metrics.counter(
            "corro_changes_applied", "changesets applied to the store"
        )
        self._m_buffered = self.metrics.counter(
            "corro_changes_buffered", "partial changesets buffered"
        )
        self.store.lock_registry = self.lock_registry
        self._admin_server = None
        self.gossip_addr: tuple[str, int] | None = None
        self.api_addr: tuple[str, int] | None = None
        self.swim: Swim | None = None
        self._pending: list[PendingBroadcast] = []
        self._pending_bytes = 0
        self._m_bcast_pending_bytes = self.metrics.gauge(
            "corro_broadcast_pending_bytes",
            "bytes queued in the pending-broadcast buffer",
        )
        self._m_bcast_dropped = self.metrics.counter(
            "corro_broadcast_dropped",
            "pending broadcasts dropped over the byte budget (sync heals)",
        )
        # Cleared version ranges awaiting persistence, batched like
        # write_empties_loop (agent.rs:2522-2571).
        self._empties: dict[str, RangeSet] = {}
        self._m_cleared = self.metrics.counter(
            "corro_versions_cleared",
            "versions compacted to Cleared (clear_overwritten_versions)",
        )
        self._m_bcast_recv = self.metrics.counter(
            "corro_broadcast_recv_count",
            "broadcast changeset frames received",
        )
        self._m_committed = self.metrics.counter(
            "corro_changes_committed",
            "local write transactions committed",
        )
        # Sync-plane series pre-registered so an idle agent still exposes
        # them at 0 (doc/telemetry/prometheus.md parity).
        self._m_sync_sent = self.metrics.counter(
            "corro_sync_changes_sent", "changes served through sync"
        )
        self._m_sync_sent_bytes = self.metrics.counter(
            "corro_sync_chunk_sent_bytes",
            "wire bytes of sync change chunks served",
        )
        self.metrics.counter(
            "corro_sync_attempts_count", "sync sessions attempted"
        )
        self.metrics.counter(
            "corro_sync_client_member", "sync sessions established, by peer"
        )
        self.metrics.counter(
            "corro_sync_changes_recv", "changes received through sync"
        )
        # Defensive-machinery visibility (docs/CHAOS.md "Host plane"):
        # the stall abort, adaptive chunk halving, and announcer backoff
        # all fire silently without these — and the chaos harness's
        # "prove the defense engaged" assertions read exactly them.
        # (Breaker trip/recovery edges live in transport.bind_metrics.)
        self._m_stall_aborts = self.metrics.counter(
            "corro_sync_stall_aborts_total",
            "sync sessions aborted by the blocking-send stall guard "
            "(peer.rs:352-355)",
        )
        self._m_chunk_halvings = self.metrics.counter(
            "corro_sync_chunk_halvings_total",
            "adaptive sync chunk-size halvings (peer.rs:638-653)",
        )
        self.metrics.counter(
            "corro_peer_backoff_retries_total",
            "backoff waits taken by the bootstrap announcer loop",
        )
        self._ingest: asyncio.Queue = asyncio.Queue(maxsize=4096)
        self._addr_of: dict[str, tuple[str, int]] = {}
        self._api_server = None
        self.subs = None  # SubsManager, attached by api/subs wiring
        # Optional (actor_id, version, hlc_ts) hook on every committed
        # local write — the trace-recording seam for kernel replay.
        self.on_local_write = None
        self._rehydrate()
        if cfg.schema_sql:
            self.store.apply_schema(cfg.schema_sql)

    # -- setup (agent.rs:105-336) -------------------------------------------

    def _rehydrate(self) -> None:
        """Rebuild BookedVersions from __corro_bookkeeping +
        __corro_seq_bookkeeping (agent.rs:147-268)."""
        for actor, sv, ev, dbv, last_seq, ts in self.store.conn.execute(
            "SELECT actor_id, start_version, end_version, db_version,"
            " last_seq, ts FROM __corro_bookkeeping"
        ):
            booked = self.bookie.for_actor(bytes(actor).hex())
            if dbv is None:
                booked.insert_many(sv, ev if ev is not None else sv, CLEARED)
            else:
                booked.insert(
                    sv, Current(db_version=dbv, last_seq=last_seq, ts=ts or 0)
                )
        for actor, ver, ss, es, last_seq, ts in self.store.conn.execute(
            "SELECT actor_id, version, start_seq, end_seq, last_seq, ts"
            " FROM __corro_seq_bookkeeping"
        ):
            booked = self.bookie.for_actor(bytes(actor).hex())
            known = booked.get(ver)
            if isinstance(known, Partial):
                known.seqs.insert(ss, es)
            else:
                booked.insert(
                    ver,
                    Partial(seqs=RangeSet([(ss, es)]), last_seq=last_seq, ts=ts),
                )

    async def start(self) -> None:
        from corrosion_tpu.agent.pool import SplitPool

        self.pool = SplitPool(self.store)
        self.pool.metrics = self.metrics
        self.pool.start()
        self.gossip_addr = await self.transport.serve(
            self.cfg.gossip_host, self.cfg.gossip_port, self._on_gossip
        )
        # SWIM rides the unreliable datagram plane (foca over QUIC
        # datagrams, broadcast/mod.rs:710 + transport.rs:66-90): UDP sends
        # never connect, so a black-holing peer cannot stall the probe
        # cadence. Oversized packets / TLS mode fall back to streams.
        self.swim = Swim(
            self.members,
            self.gossip_addr,
            self.transport.send_packet,
            probe_interval=self.cfg.probe_interval,
            max_transmissions=self.cfg.max_transmissions,
        )
        # Identity freshness across restarts (actor.rs:169-194's renew-on-
        # rejoin): the own-incarnation row persisted at shutdown seeds the
        # next life one higher, so ALIVE@n+1 beats any durable DOWN@n a
        # graceful leave taught the cluster.
        row = self.store.conn.execute(  # corro-lint: disable=CT042 reason=boot path; the loop serves no sessions until start() returns
            "SELECT incarnation FROM __corro_members WHERE actor_id = ?",
            (self.actor_id,),
        ).fetchone()
        if row is not None:
            self.swim.incarnation = int(row[0]) + 1
        from corrosion_tpu.agent.api import serve_api

        self.api_addr = await serve_api(self)
        if self.subs is not None:
            # Restore persisted subscriptions (agent.rs:373-419).
            self.subs.restore()
            if self._trace_writes:
                # Fan-out spans ride the same tracer as the write path;
                # left unwired (the default) match_changes costs nothing.
                self.subs.tracer = self.tracer
            if self.cfg.sub_costs:
                # Arm the per-subscription cost ledger AFTER restore so
                # durable handles re-adopt their persisted counters
                # (kill/relaunch continues the ledger, like the series
                # recorder's mode="a" reattach).
                self.subs.enable_costs(self.metrics)
        # Rejoin via persisted member states (agent.rs:772-831): a restarted
        # node reaches its old cluster even when the bootstrap seeds are
        # gone. The failure detector prunes any that died while we were
        # down.
        self._members_persisted: dict[str, tuple] = {}
        # Serializes diff-persist passes: stop()'s final pass can run
        # concurrently with the loop's, and an interleaved snapshot swap
        # would regress _members_persisted behind rows already written.
        self._members_persist_lock = asyncio.Lock()
        restored_members = self._load_members()
        for m in restored_members[:10]:
            await self.swim.announce(m.addr)
        self.tasks.spawn(self._swim_loop(), name="swim_loop")
        self.tasks.spawn(
            self._members_persist_loop(), name="diff_member_states"
        )
        self.tasks.spawn(self._broadcast_loop(), name="broadcast_loop")
        self.tasks.spawn(self._ingest_loop(), name="handle_changes")
        self.tasks.spawn(self._sync_loop(), name="sync_loop")
        self.tasks.spawn(
            self._compact_loop(), name="clear_overwritten_versions"
        )
        self.tasks.spawn(self._empties_loop(), name="write_empties_loop")
        self.tasks.spawn(
            self._buffered_meta_loop(), name="clear_buffered_meta_loop"
        )
        self.tasks.spawn(self._metrics_loop(), name="metrics_loop")
        self.tasks.spawn(
            self._runtime_metrics_loop(), name="runtime_metrics"
        )
        self.tasks.spawn(self._wal_checkpoint_loop(), name="db_cleanup")
        if self.cfg.admin_uds:
            from corrosion_tpu.agent.admin import start_admin

            await start_admin(self, self.cfg.admin_uds)
        if self.cfg.prometheus_addr:
            from corrosion_tpu.agent.config import parse_addr
            from corrosion_tpu.utils.metrics import serve_prometheus

            host, port = parse_addr(self.cfg.prometheus_addr)
            self._prom_server, self.prometheus_addr = await serve_prometheus(
                self.metrics, host, port
            )
        # Static config/build series (doc/telemetry/prometheus.md).
        self.metrics.gauge(
            "corro_build_info", "build identity"
        ).set(1, version="corrosion-tpu")
        self.metrics.gauge(
            "corro_gossip_config_max_transmissions",
            "configured broadcast retransmission budget",
        ).set(self.cfg.max_transmissions)
        self.metrics.gauge(
            "corro_gossip_config_num_indirect_probes",
            "configured indirect probe count",
        ).set(self.swim.indirect_probes)
        self.metrics.gauge(
            "corro_broadcast_buffer_capacity",
            "pending-broadcast buffer byte budget",
        ).set(self.cfg.broadcast_buffer_bytes)
        for addr in self.cfg.bootstrap:
            await self.swim.announce(tuple(addr))
        if self.cfg.bootstrap_raw or self.cfg.bootstrap:
            self.tasks.spawn(
                self._bootstrap_loop(), name="bootstrap_announcer"
            )

    async def _bootstrap_loop(self) -> None:
        """Announcer loop (agent.rs:726-768): re-resolve + re-announce
        the bootstrap seeds with backoff WHENEVER the alive member set is
        empty — at startup (a seed name may not be DNS-published yet) and
        again after a partition or suspicion cascade empties the
        membership. The SWIM plane never probes members it believes
        down, so a fully isolated node can only re-enter the cluster by
        announcing its way back in; the announce reply carries the
        cluster's belief about the announcer so it can refute a stale
        DOWN with a higher incarnation (membership.on_message)."""
        from corrosion_tpu.agent.config import resolve_bootstrap
        from corrosion_tpu.utils.backoff import Backoff

        retries = self.metrics.counter("corro_peer_backoff_retries_total")
        backoff = Backoff(
            min_wait=self.cfg.announce_backoff_min_s,
            max_wait=self.cfg.announce_backoff_max_s,
            on_wait=lambda _w: retries.inc(),
        )
        while not self.tripwire.tripped:
            if self.members.alive():
                backoff.reset()
                await asyncio.sleep(1.0)
                continue
            addrs = [tuple(a) for a in self.cfg.bootstrap]
            if self.cfg.bootstrap_raw:
                addrs.extend(resolve_bootstrap(self.cfg.bootstrap_raw))
            for addr in addrs:
                if addr != self.gossip_addr:
                    await self.swim.announce(addr)
            await asyncio.sleep(next(backoff))

    async def stop(self) -> None:
        # Graceful departure first, while the transport is still up
        # (foca.leave_cluster, broadcast/mod.rs:306): peers learn DOWN now
        # instead of after a probe-timeout + suspect window.
        if self.swim is not None:
            try:
                await asyncio.wait_for(self.swim.leave_cluster(), 1.0)
            except Exception:
                pass
        self.tripwire.trip()
        await self.tasks.cancel_all()
        await self.tasks.wait_for_all_pending_handles(cap=5.0)
        # Drain unpersisted cleared ranges (write_empties_loop drains its
        # queue before shutdown, agent.rs:2558-2570).
        if self._empties:
            try:
                await self._flush_empties()
            except Exception:
                pass
        # Final member-state flush: a node cleanly restarted within the
        # persist interval must still find its cluster in __corro_members.
        if getattr(self, "_members_persisted", None) is not None:
            try:
                await self._persist_members_once()
            except Exception:
                pass
        await self._close_resources()

    async def _close_resources(self) -> None:
        """The ungraceful tail shared by stop() and abort(): release
        every in-process resource (sockets, sqlite handles, threads) so
        the same data_dir can relaunch immediately. Anything added here
        closes on BOTH paths; graceful-only work (leave, flushes) stays
        in stop()."""
        self.transport.close()
        if self.subs is not None:
            self.subs.close()
        for srv in (self._api_server, self._admin_server, self._prom_server):
            if srv is not None:
                srv.close()
        if self.pool is not None:
            await self.pool.close()
        if self._series_recorder is not None:
            # Refcounted release (obs/series.py): closing on BOTH the
            # stop() and abort() paths means a same-process relaunch
            # reopens the series mode="a" and the record continues.
            self._series_recorder.close()
            self._series_recorder = None
        self.tracer.close()
        self.store.close()

    async def abort(self) -> None:
        """Crash-style shutdown — the in-process stand-in for SIGKILL
        (agent/testing.hard_kill). Deliberately NOT stop(): no graceful
        SWIM leave (peers must detect the death), no empties drain, no
        final member-state flush — the restarted life gets only what a
        dead process would have left behind: the store's committed WAL
        state and whatever the periodic loops happened to persist."""
        self.tripwire.trip()
        await self.tasks.cancel_all()
        await self._close_resources()

    # -- write path (make_broadcastable_changes) ------------------------------

    def execute(self, statements: list[Statement]) -> ExecResponse:
        """Synchronous local write (tests, tooling): store txn inline."""
        t0 = time.monotonic()
        results, dbv, last_seq, changes = self.store.execute_transaction(
            statements
        )
        resp, persist, frames = self._finish_local_write(
            results, dbv, last_seq, changes, t0
        )
        if persist is not None:
            persist()
        for frame in frames:
            self._queue_broadcast(frame)
        return resp

    async def execute_async(self, statements: list[Statement]) -> ExecResponse:
        """API-path local write: the SQLite transaction runs on the
        SplitPool's writer at HIGH priority (pool.write_priority ≈
        `pool.write_priority()` at public/mod.rs:41), keeping the event
        loop free; bookkeeping/subs/broadcast stay loop-confined.

        With causal write tracing on, a ``commit`` span (child of the API
        layer's ``api_write`` root when one is ambient) covers the store
        transaction through bookkeeping persistence; its traceparent is
        stamped onto every broadcast frame so remote hops chain onto it.
        The default path allocates no spans."""
        t0 = time.monotonic()
        # Child of the ambient api_write root ONLY: when the root was
        # dropped (sampling said no, or a non-API caller), minting a
        # fresh root here would re-roll the sampling decision on a new
        # random id — orphan commit/fan-out/hop trees for writes the
        # sampler already dropped, defeating the thinning. With an
        # ambient parent, maybe_span re-checks the SAME trace id, so
        # the whole tree keeps or drops together.
        span = (
            self.tracer.maybe_span("commit")
            if self._trace_writes and tracing_current_span() is not None
            else None
        )
        if span is None:
            return await self._execute_async_inner(statements, t0, None)
        with span:
            return await self._execute_async_inner(statements, t0, span)

    async def _execute_async_inner(
        self, statements, t0, span
    ) -> ExecResponse:
        if self.pool is not None:
            results, dbv, last_seq, changes = await self.pool.write_priority(
                lambda: self.store.execute_transaction(statements)
            )
        else:
            results, dbv, last_seq, changes = self.store.execute_transaction(
                statements
            )
        resp, persist, frames = self._finish_local_write(
            results, dbv, last_seq, changes, t0, span=span
        )
        if persist is not None:
            # Persist BEFORE dissemination: a frame on the wire whose
            # version is not in __corro_bookkeeping could be re-allocated
            # after a crash-restart — peers would dedupe the reused number
            # and silently diverge.
            await self._store_write(persist)
        for frame in frames:
            self._queue_broadcast(frame)
        return resp

    def _finish_local_write(
        self, results, dbv, last_seq, changes, t0, span=None
    ):
        """Loop-confined bookkeeping; returns (response, persist_closure,
        broadcast_frames). The closure is store-only work the caller runs on
        the pool writer (or inline for the sync path) — and MUST complete
        before the frames are queued for dissemination."""
        persist = None
        frames: list[dict] = []
        if dbv and changes:
            ts = self.hlc.new_timestamp()
            booked = self.bookie.for_actor(self.actor_id)
            version = (booked.last() or 0) + 1
            booked.insert(
                version, Current(db_version=dbv, last_seq=last_seq, ts=ts)
            )
            self._m_committed.inc()
            if span is not None:
                span.set_attr("actor", self.actor_id[:8])
                span.set_attr("version", version)
                span.set_attr("changes", len(changes))
            if self.on_local_write is not None:
                # Trace hook: real write traffic recorded for kernel replay
                # (sim/trace.py; SURVEY §7 step 7's dispatch-seam bridge).
                self.on_local_write(self.actor_id, version, ts)
            dirty = (
                self.subs.match_changes(changes)
                if self.subs is not None else []
            )
            actor = self.actor_id

            def persist() -> None:
                self._persist_bookkeeping(actor, version, dbv, last_seq, ts)
                if self.subs is not None:
                    self.subs.persist_watermarks_sync(dirty)

            # Chunk for dissemination (public/mod.rs:128-187); queued by
            # the caller after the bookkeeping row is durable. Traced
            # writes stamp the commit span's traceparent on every frame
            # (transport.TRACE_KEY) so the first gossip hop parents on it.
            tp = span.traceparent if span is not None else None
            frames = [
                attach_trace(
                    self._changeset_frame(
                        self.actor_id, version, chunk, (s, e), last_seq, ts
                    ),
                    tp,
                )
                for chunk, (s, e) in chunk_changes(changes, last_seq)
            ]
        return (
            ExecResponse(results=results, time=time.monotonic() - t0),
            persist,
            frames,
        )

    async def restore_online(
        self, backup_path: str, self_actor_id: bool = False
    ) -> str:
        """Swap in a backup while running (`corrosion restore` against a
        live node; sqlite3-restore's role). The content swap runs on the
        SplitPool writer — serialized with every other write — then the
        agent re-reads identity/schema and rebuilds its bookkeeping.
        Returns the actor id now in effect."""
        from corrosion_tpu.agent.backup import online_restore

        def do() -> None:
            # One pooled job: swap, retire stale readers, reload — so no
            # queued write can ever run between the content swap and the
            # store reopening on the restored content. The fcntl locks
            # exclude OTHER processes; same-process readers are quiesced
            # by the caller (pool read slots) and the write lock below.
            with self.store._wlock("online_restore"):
                online_restore(
                    backup_path, self.store.path, self_actor_id=self_actor_id
                )
                if self.pool is not None:
                    self.pool.flush_read_conns()
            self.store.reload_after_restore()

        if self.pool is not None:
            async with self.pool.quiesce_reads():
                await self.pool.write_priority(do)
        else:
            do()
        self.actor_id = self.store.site_id.hex()
        self.bookie = Bookie()
        self._rehydrate()
        if self.subs is not None:
            # Backups strip __corro_subs (node-local): recreate it and
            # re-persist this node's live subscriptions.
            self.subs.reinit_after_restore()
        # Backups also strip __corro_members: recreate it and force the
        # next persist pass to rewrite every live member (an empty diff
        # snapshot makes all rows "changed"), or member persistence would
        # die silently until the next full restart.
        with self.store._wlock("members_reinit"):
            self.store.conn.execute(  # corro-lint: disable=CT042 reason=rare admin-driven restore; one DDL statement under the writer lock
                "CREATE TABLE IF NOT EXISTS __corro_members ("
                " actor_id TEXT PRIMARY KEY, addr TEXT NOT NULL,"
                " state TEXT NOT NULL, incarnation INTEGER NOT NULL,"
                " updated_at REAL NOT NULL) WITHOUT ROWID"
            )
        self._members_persisted = {}
        return self.actor_id

    def _persist_bookkeeping(self, actor, version, dbv, last_seq, ts) -> None:
        # Under the writer lock: the pool writer thread may hold an open
        # BEGIN IMMEDIATE on this connection, and joining a foreign
        # transaction would tie this row's fate to it.
        with self.store._wlock("persist_bookkeeping"):
            self.store.conn.execute(
                "INSERT OR REPLACE INTO __corro_bookkeeping"
                " (actor_id, start_version, end_version, db_version, last_seq, ts)"
                " VALUES (?, ?, NULL, ?, ?, ?)",
                (bytes.fromhex(actor), version, dbv, last_seq, ts),
            )

    def _changeset_frame(self, actor, version, changes, seqs, last_seq, ts):
        return {
            "t": "bcast",
            "actor": actor,
            "version": version,
            "changes": [list(c.to_tuple()) for c in changes],
            "seqs": list(seqs),
            "last_seq": last_seq,
            "ts": ts,
        }

    def _queue_broadcast(self, frame: dict) -> None:
        self._pending_push(
            PendingBroadcast(
                frame=frame,
                tx_left=self.cfg.max_transmissions,
                # Size estimate for the byte budget; blob values count at
                # their hex length (the codec encodes them binary — close
                # enough for a budget, no second encode at send time).
                size=len(
                    json.dumps(
                        frame,
                        separators=(",", ":"),
                        default=lambda o: o.hex()
                        if isinstance(o, (bytes, bytearray, memoryview))
                        else str(o),
                    )
                ),
            )
        )

    def _pending_push(self, pb: PendingBroadcast) -> None:
        """Append to the pending buffer under the byte budget.

        Two-tier shed, mirroring what the reference's 64 KiB buffer cutoff
        (broadcast/mod.rs:357) actually loses: over the soft budget, drop
        oldest RETRANSMISSION backlog first — frames already sent at least
        once, whose lost redundancy anti-entropy covers. Never-sent frames
        are the only broadcast copy of local writes (the reference never
        drops those), so they survive up to a hard multiple of the budget;
        only a member-less agent under sustained write load reaches that,
        and a late-joining peer recovers the difference via sync."""
        self._pending.append(pb)
        self._pending_bytes += pb.size
        soft = self.cfg.broadcast_buffer_bytes
        if self._pending_bytes > soft:
            kept = []
            last = len(self._pending) - 1
            for i, p in enumerate(self._pending):
                if (
                    self._pending_bytes > soft
                    and i < last
                    and p.tx_left < self.cfg.max_transmissions
                ):
                    self._pending_bytes -= p.size
                    self._m_bcast_dropped.inc()
                else:
                    kept.append(p)
            self._pending = kept
        hard = soft * 8
        while self._pending_bytes > hard and len(self._pending) > 1:
            dropped = self._pending.pop(0)
            self._pending_bytes -= dropped.size
            self._m_bcast_dropped.inc()
        self._m_bcast_pending_bytes.set(self._pending_bytes)

    # -- gossip inbound -------------------------------------------------------

    async def _on_gossip(self, session: Session, msg: dict) -> None:
        kind = msg.get("t")
        if kind == "swim":
            frm = msg.get("from")
            if frm and "from_addr" in msg:
                self._addr_of[frm] = tuple(msg["from_addr"])
            await self.swim.on_message(msg)
        elif kind == "bcast":
            self._m_bcast_recv.inc()
            try:
                self._ingest.put_nowait((msg, "broadcast"))
            except asyncio.QueueFull:
                pass  # broadcast is lossy; sync heals
        elif kind == "sync_start":
            await self._serve_sync(session, msg)

    # -- broadcast loop (broadcast/mod.rs:356-567) ----------------------------

    async def _broadcast_loop(self) -> None:
        pending_gauge = self.metrics.gauge(
            "corro_broadcast_pending", "pending-broadcast queue depth"
        )
        members_gauge = self.metrics.gauge(
            "corro_gossip_members", "peers currently believed alive"
        )
        sent_ctr = self.metrics.counter(
            "corro_broadcast_sent", "broadcast frames transmitted"
        )
        # Transmits are SPAWNED, not awaited inline (transmit_broadcast
        # tasks, broadcast/mod.rs:741-756): one black-holing peer must not
        # stall the whole dissemination tick for its connect timeout. The
        # semaphore bounds in-flight sends; the transport's per-peer
        # circuit breaker makes repeat failures fail fast.
        sem = asyncio.Semaphore(32)

        async def transmit(addr: tuple, frame: dict) -> None:
            async with sem:
                if await self.transport.send_frame(addr, frame):
                    sent_ctr.inc()

        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.broadcast_interval)
            pending_gauge.set(len(self._pending))
            members_gauge.set(len(self.members.alive()))
            if not self._pending:
                continue
            if not self.members.alive():
                # No peers yet: entries stay queued, budgets intact
                # (sendable gating); _pending_push's byte budget is what
                # bounds a member-less agent under sustained writes.
                continue
            pending, self._pending = self._pending, []
            self._pending_bytes = 0
            members = self.members.alive()
            ring0 = self.members.ring0()
            for pb in pending:
                # Ring-0 eager + random far targets (mod.rs:465-473,522-537).
                targets = {m.actor_id: m for m in ring0}
                others = [m for m in members if m.actor_id not in targets]
                random.shuffle(others)
                for m in others[: self.cfg.fanout]:
                    targets[m.actor_id] = m
                for m in targets.values():
                    self.tasks.spawn(
                        transmit(m.addr, pb.frame), name="transmit_broadcast"
                    )
                pb.tx_left -= 1
                if pb.tx_left > 0:
                    self._pending_push(pb)

    # -- ingest pipeline (handle_changes + process_multiple_changes) ----------

    async def _ingest_loop(self) -> None:
        while not self.tripwire.tripped:
            batch: list[tuple[dict, str]] = []
            try:
                item = await asyncio.wait_for(
                    self._ingest.get(), timeout=0.25
                )
                batch.append(item)
            except asyncio.TimeoutError:
                continue
            t0 = time.monotonic()
            while (
                len(batch) < self.cfg.ingest_batch
                and time.monotonic() - t0 < self.cfg.ingest_linger
            ):
                try:
                    batch.append(self._ingest.get_nowait())
                except asyncio.QueueEmpty:
                    await asyncio.sleep(0.005)
            await self._process_changes(batch)

    async def _store_write(self, fn):
        """Run store-only work on the pool writer (NORMAL tier — the change
        ingest class, agent.rs:2450); inline when the pool isn't up.
        Bookie and subscription state stay event-loop-confined."""
        if self.pool is not None:
            return await self.pool.write(fn)
        return fn()

    async def _process_changes(self, batch: list[tuple[dict, str]]) -> None:
        """One writer transaction per ingest batch (process_multiple_changes,
        agent.rs:1847-1851): complete changesets accumulate and flush as a
        single pooled store job; partial-version buffering (rare) flushes
        the pending run first, then takes its own pooled job. Bookie and
        subscription state stay loop-confined throughout. Duplicate copies
        of a changeset inside ONE accumulation window bypass the dedupe
        check; the CRDT store and bookie inserts are idempotent, so that
        only costs the double work, never correctness."""
        now_ms = int(time.time() * 1000)
        pending: list[tuple[str, int, list[Change], int, int]] = []
        # Causal-trace hop spans: one ``ingest_apply`` per traced
        # changeset actually applied this batch, parented on the
        # upstream hop via the frame's traceparent header. Opened with
        # Span.start() (not the context manager — batch lifetimes
        # overlap non-LIFO) and closed after the final flush, so each
        # span covers queue-drain through store apply + fan-out.
        hop_spans: list = []

        async def flush() -> None:
            if not pending:
                return
            flat = [ch for _, _, changes, _, _ in pending for ch in changes]
            # All bookkeeping rows ride the same pooled job as the merge:
            # no store write ever runs on the event loop.
            rows = [
                (actor, version, changes[0].db_version if changes else 0,
                 last_seq, ts)
                for actor, version, changes, last_seq, ts in pending
            ]

            def db_work() -> None:
                self.store.apply_changes(flat)
                for actor, version, dbv, last_seq, ts in rows:
                    self._persist_bookkeeping(actor, version, dbv, last_seq, ts)

            await self._store_write(db_work)
            dirty: list[tuple[str, int]] = []
            for (actor, version, changes, last_seq, ts), (_, _, dbv, _, _) in zip(
                pending, rows
            ):
                self._m_applied.inc()
                self.bookie.for_actor(actor).insert(
                    version, Current(db_version=dbv, last_seq=last_seq, ts=ts)
                )
                if self.subs is not None:
                    dirty.extend(self.subs.match_changes(changes))
            if dirty:
                await self._store_write(
                    lambda: self.subs.persist_watermarks_sync(dirty)
                )
            pending.clear()

        for msg, source in batch:
            actor = msg["actor"]
            if actor == self.actor_id:
                continue
            version = msg["version"]
            seqs = tuple(msg["seqs"])
            last_seq = msg["last_seq"]
            booked = self.bookie.for_actor(actor)
            if booked.contains(version, seqs):
                continue  # already known (agent.rs:1817-1843 dedupe)
            span = None
            if self._trace_writes:
                tp = extract_trace(msg)
                if tp is not None:
                    span = self.tracer.maybe_span(
                        "ingest_apply", traceparent=tp,
                        actor=actor[:8], version=version, source=source,
                    )
                    if span is not None:
                        hop_spans.append(span.start())
            self._m_recv_lag.observe(
                max(now_ms - ts_physical_ms(msg["ts"]), 0) / 1000.0,
                source=source,
            )
            self.hlc.update_with_timestamp(msg["ts"])
            changes = [Change.from_tuple(tuple(t)) for t in msg["changes"]]
            complete = seqs[0] == 0 and seqs[1] >= last_seq
            known = booked.get(version)
            if complete and not isinstance(known, Partial):
                pending.append((actor, version, changes, last_seq, msg["ts"]))
            else:
                await flush()
                self._m_buffered.inc(source=source)
                await self._buffer_partial(
                    actor, version, changes, seqs, last_seq, msg["ts"]
                )
            if source == "broadcast":
                # Rebroadcast applied changesets (agent.rs:2040-2057).
                # A traced hop re-stamps the frame with ITS span's
                # traceparent so the next hop parents here and the
                # multi-hop chain reconstructs; untraced/unsampled
                # relays forward the header untouched (the chain skips
                # them but stays connected by trace id).
                pb = dict(msg)
                if span is not None:
                    attach_trace(pb, span.traceparent)
                self._queue_broadcast(pb)
        await flush()
        for s in hop_spans:
            s.finish()

    async def _apply_complete(self, actor, version, changes, last_seq, ts) -> None:
        dbv = changes[0].db_version if changes else 0

        def db_work() -> None:
            self.store.apply_changes(changes)
            self._persist_bookkeeping(actor, version, dbv, last_seq, ts)

        await self._store_write(db_work)
        self._m_applied.inc()
        self.bookie.for_actor(actor).insert(
            version, Current(db_version=dbv, last_seq=last_seq, ts=ts)
        )
        if self.subs is not None:
            dirty = self.subs.match_changes(changes)
            if dirty:
                await self._store_write(
                    lambda: self.subs.persist_watermarks_sync(dirty)
                )

    async def _buffer_partial(
        self, actor, version, changes, seqs, last_seq, ts
    ) -> None:
        """process_incomplete_version: stash rows + seq ranges; apply once
        gap-free (agent.rs:2063-2151, 1667-1806)."""
        booked = self.bookie.for_actor(actor)
        known = booked.get(version)
        if isinstance(known, Partial):
            known.seqs.insert(seqs[0], seqs[1])
            partial = known
        else:
            partial = Partial(
                seqs=RangeSet([tuple(seqs)]), last_seq=last_seq, ts=ts
            )
            booked.insert(version, partial)
        promote = partial.is_complete()

        def db_work():
            c = self.store.conn
            with self.store._wlock("buffer_partial"):
                for ch in changes:
                    c.execute(
                        "INSERT OR IGNORE INTO __corro_buffered_changes VALUES"
                        " (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                        (
                            bytes.fromhex(actor), version, ch.table, ch.pk,
                            ch.cid, ch.val, ch.col_version, ch.db_version,
                            ch.seq, ch.site_id, ch.cl,
                        ),
                    )
                c.execute(
                    "INSERT OR REPLACE INTO __corro_seq_bookkeeping VALUES"
                    " (?, ?, ?, ?, ?, ?)",
                    (bytes.fromhex(actor), version, seqs[0], seqs[1],
                     last_seq, ts),
                )
                if not promote:
                    return None
                rows = c.execute(
                    "SELECT tbl, pk, cid, val, col_version, db_version, seq,"
                    " site_id, cl FROM __corro_buffered_changes"
                    " WHERE actor_id = ? AND version = ? ORDER BY seq",
                    (bytes.fromhex(actor), version),
                ).fetchall()
                c.execute(
                    "DELETE FROM __corro_buffered_changes"
                    " WHERE actor_id = ? AND version = ?",
                    (bytes.fromhex(actor), version),
                )
                c.execute(
                    "DELETE FROM __corro_seq_bookkeeping"
                    " WHERE actor_id = ? AND version = ?",
                    (bytes.fromhex(actor), version),
                )
                return rows

        rows = await self._store_write(db_work)
        if rows is not None:
            all_changes = [Change.from_tuple(tuple(r)) for r in rows]
            await self._apply_complete(
                actor, version, all_changes, last_seq, ts
            )

    # -- compaction (clear_overwritten_versions + write_empties_loop) ----------

    def _queue_empty(self, actor: str, start: int, end: int) -> None:
        self._empties.setdefault(actor, RangeSet()).insert(start, end)

    async def _compact_loop(self) -> None:
        """Periodically find fully-overwritten versions and clear them
        (clear_overwritten_versions, agent.rs:995-1126)."""
        streak = _StreakLogger("clear_overwritten_versions failed")
        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.compact_interval)
            try:
                await self._compact_once()
                streak.ok()
            except Exception:
                streak.fail()

    async def _buffered_meta_loop(self) -> None:
        """Periodically drop buffered partial data for versions that were
        CLEARED out-of-band (clear_buffered_meta_loop, agent.rs:2575-2619):
        an empty changeset normally prunes its buffers inline, but a crash
        between the bookkeeping write and the buffer prune — or a
        compaction that raced a partial — leaves orphaned
        __corro_buffered_changes/__corro_seq_bookkeeping rows that would
        otherwise resurrect a dead partial at the next boot."""
        streak = _StreakLogger("clear_buffered_meta failed")
        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.buffered_meta_interval)
            try:
                await self._clear_buffered_meta_once()
                streak.ok()
            except Exception:
                streak.fail()

    async def _clear_buffered_meta_once(self) -> None:
        # Work from what is actually BUFFERED (like agent.rs:2575-2619's
        # SELECT over the buffer tables), not from the full cleared
        # history: steady-state cost scales with outstanding orphans —
        # normally zero rows — not with how much was ever compacted.
        present = self.store.conn.execute(  # corro-lint: disable=CT042 reason=indexed read over normally-zero orphan rows; an executor round-trip costs more than the scan
            "SELECT actor_id, version FROM __corro_seq_bookkeeping"
            " UNION SELECT DISTINCT actor_id, version"
            " FROM __corro_buffered_changes"
        ).fetchall()
        orphans: list[tuple[bytes, int]] = []
        for site, version in present:
            booked = self.bookie.get(site.hex())
            if booked is not None and booked.cleared.contains(version):
                orphans.append((site, version))
                booked.partials.pop(version, None)
        # In-memory partials whose version was cleared (no buffered rows
        # left — e.g. restored state) reconcile too.
        for actor, booked in list(self.bookie.items()):
            for v in [
                v for v in booked.partials if booked.cleared.contains(v)
            ]:
                booked.partials.pop(v, None)
        if not orphans:
            return

        def db_work() -> None:
            with self.store._wlock("clear_buffered_meta"):
                self.store.conn.executemany(
                    "DELETE FROM __corro_buffered_changes"
                    " WHERE actor_id = ? AND version = ?",
                    orphans,
                )
                self.store.conn.executemany(
                    "DELETE FROM __corro_seq_bookkeeping"
                    " WHERE actor_id = ? AND version = ?",
                    orphans,
                )

        if self.pool is not None:
            await self.pool.write_low(db_work)
        else:
            db_work()

    async def _compact_once(self) -> None:
        for actor, booked in list(self.bookie.items()):
            versions = booked.current_versions()  # db_version -> version
            if not versions:
                continue
            site = bytes.fromhex(actor)
            # Read-side probe (the reference uses a read txn off the writer,
            # agent.rs:1046-1057); cheap enough to run on the loop here.
            cleared_dbvs = self.store.find_cleared_versions(site)
            to_clear = [
                v for dbv, v in versions.items() if dbv in cleared_dbvs
            ]
            if not to_clear:
                continue
            for v in to_clear:
                # Re-check Current: an interleaved await may have changed it.
                if isinstance(booked.get(v), Current):
                    booked.insert(v, CLEARED)
                    self._m_cleared.inc()
            # Queue each affected cleared RANGE (versions coalesce with
            # neighbours already cleared) for batched persistence.
            seen: set[tuple[int, int]] = set()
            for v in to_clear:
                for s, e in booked.cleared:
                    if s <= v <= e and (s, e) not in seen:
                        seen.add((s, e))
                        self._queue_empty(actor, s, e)
            await asyncio.sleep(0)  # yield between actors (agent.rs:1114)

    async def _empties_loop(self) -> None:
        """Batch queued cleared ranges into collapsed bookkeeping rows
        (write_empties_loop, agent.rs:2522-2571)."""
        streak = _StreakLogger("write_empties flush failed; batch re-queued")
        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.empties_flush_interval)
            if self._empties:
                try:
                    await self._flush_empties()
                    streak.ok()
                except Exception:
                    streak.fail()

    async def _flush_empties(self) -> None:
        empties, self._empties = self._empties, {}

        def db_work() -> None:
            for actor, ranges in empties.items():
                site = bytes.fromhex(actor)
                for s, e in list(ranges):
                    self.store.store_empty_changeset(site, s, e)

        try:
            # Background write tier, like process_completed_empties' low-pri
            # txn.
            if self.pool is not None:
                await self.pool.write_low(db_work)
            else:
                db_work()
        except Exception:
            # A transient write failure (busy/disk) must not lose the batch:
            # the bookie already says Cleared, so these ranges would never be
            # rediscovered. Re-merge for the next flush tick.
            for actor, ranges in empties.items():
                dst = self._empties.setdefault(actor, RangeSet())
                for s, e in ranges:
                    dst.insert(s, e)
            raise

    # -- periodic metrics (collect_metrics, agent.rs:1126-1187) ----------------

    async def _metrics_loop(self) -> None:
        """Per-table row counts, change-log size, and pool queue depths,
        sampled on the read side every few seconds (the reference's
        metrics_loop runs collect_metrics every 10 s)."""
        rows_g = self.metrics.gauge(
            "corro_db_table_rows", "rows per user table"
        )
        log_g = self.metrics.gauge(
            "corro_db_change_log_rows", "rows in the __crdt_changes log"
        )
        queue_g = self.metrics.gauge(
            "corro_sqlite_write_queue", "queued writer jobs per priority"
        )
        cluster_g = self.metrics.gauge(
            "corro_gossip_cluster_size", "known live members incl. self"
        )
        backlog_g = self.metrics.gauge(
            "corro_gossip_updates_backlog", "membership rumors awaiting send"
        )
        buffered_g = self.metrics.gauge(
            "corro_db_buffered_changes_rows_total",
            "rows in __corro_buffered_changes (partial versions)",
        )
        read_conns_g = self.metrics.gauge(
            "corro_sqlite_pool_read_connections", "read pool size"
        )
        read_idle_g = self.metrics.gauge(
            "corro_sqlite_pool_read_connections_idle", "idle read conns"
        )
        write_conns_g = self.metrics.gauge(
            "corro_sqlite_pool_write_connections", "writer connections"
        )
        subs_dropped_g = self.metrics.gauge(
            "corro_subs_dropped_events",
            "subscription listener-queue overflow drops (each evicts "
            "its stream; clients resume via ?from=)",
        )
        interval = self.cfg.metrics_interval
        while not self.tripwire.tripped:
            await asyncio.sleep(interval)
            cluster_g.set(len(self.members.alive()) + 1)
            if self.subs is not None:
                subs_dropped_g.set(
                    sum(
                        h.dropped_events
                        for h in self.subs._by_id.values()
                    )
                )
            if self.swim is not None:
                backlog_g.set(len(self.swim.rumors))
            if self.pool is None:
                continue  # pool-less agent: nothing to sample
            try:
                # Full-table counts ride the read POOL (off the event
                # loop): at millions of log rows an on-loop scan would
                # stall gossip/API for its duration.
                for name in self.store.tables():
                    _, rows = await self.pool.query(
                        Statement(f'SELECT count(*) FROM "{name}"')
                    )
                    rows_g.set(rows[0][0], table=name)
                _, rows = await self.pool.query(
                    Statement("SELECT count(*) FROM __crdt_changes")
                )
                log_g.set(rows[0][0])
                _, rows = await self.pool.query(
                    Statement(
                        "SELECT count(*) FROM __corro_buffered_changes"
                    )
                )
                buffered_g.set(rows[0][0])
                for label, depth in self.pool.queue_depths().items():
                    queue_g.set(depth, priority=label)
                read_conns_g.set(self.pool._n_read)
                read_idle_g.set(len(self.pool._read_pool))
                write_conns_g.set(1)  # single-writer discipline
            except Exception:
                # Keep sampling; stale gauges with no signal would hide
                # the failure entirely.
                logging.getLogger(__name__).debug(
                    "metrics sample failed", exc_info=True
                )

    # -- member-state persistence (diff_member_states) -------------------------

    def _load_members(self) -> list:
        """Seed Members from __corro_members (setup-time, before loops)."""
        from corrosion_tpu.agent.config import parse_addr
        from corrosion_tpu.agent.membership import DOWN, SUSPECT

        restored = []
        with self.store._wlock("members_load"):
            # Down rows are last-run corpses: the live cluster re-teaches
            # anything real, and without this a restart before the 48 h GC
            # horizon would orphan them forever (no in-memory entry means
            # the persist loop's `gone` diff never covers them).
            self.store.conn.execute(
                "DELETE FROM __corro_members WHERE state = ?", (DOWN,)
            )
        for aid, addr_s, state, inc, _ts in self.store.conn.execute(
            "SELECT actor_id, addr, state, incarnation, updated_at"
            " FROM __corro_members"
        ).fetchall():
            if aid == self.actor_id:
                continue
            addr = parse_addr(addr_s)
            if self.members.apply_update(aid, addr, state, inc):
                m = self.members.states[aid]
                if state == SUSPECT:
                    # Fresh suspicion timer: a stale persisted suspect_at
                    # of 0 would expire to DOWN on the first probe round
                    # and gossip a spurious DOWN rumor cluster-wide.
                    m.suspect_at = time.monotonic()
                restored.append(m)
        return restored

    async def _persist_members_once(self) -> None:
        """One diff-persist pass: only rows whose (addr, state,
        incarnation) moved are written; members GC'd from the in-memory
        table are deleted."""
        async with self._members_persist_lock:
            current = {
                aid: (f"{m.addr[0]}:{m.addr[1]}", m.state, m.incarnation)
                for aid, m in self.members.states.items()
            }
            if self.swim is not None and self.gossip_addr is not None:
                # Own-incarnation row: seeds identity freshness at the
                # next boot (see start()); state ALIVE so the load-time
                # DOWN purge never eats it.
                from corrosion_tpu.agent.membership import ALIVE

                current[self.actor_id] = (
                    f"{self.gossip_addr[0]}:{self.gossip_addr[1]}",
                    ALIVE,
                    self.swim.incarnation,
                )
            changed = [
                (aid, v) for aid, v in current.items()
                if self._members_persisted.get(aid) != v
            ]
            gone = [
                aid for aid in self._members_persisted if aid not in current
            ]
            if not changed and not gone:
                return
            now = time.time()

            def db_work() -> None:
                with self.store._wlock("members_persist"):
                    self.store.conn.executemany(
                        "INSERT OR REPLACE INTO __corro_members"
                        " VALUES (?, ?, ?, ?, ?)",
                        [
                            (aid, addr, state, inc, now)
                            for aid, (addr, state, inc) in changed
                        ],
                    )
                    self.store.conn.executemany(
                        "DELETE FROM __corro_members WHERE actor_id = ?",
                        [(aid,) for aid in gone],
                    )

            if self.pool is not None:
                await self.pool.write_low(db_work)
            else:
                db_work()
            self._members_persisted = current

    async def _members_persist_loop(self) -> None:
        """Persist member-state diffs on a cadence (diff_member_states,
        broadcast/mod.rs:570-702); stop() runs a final pass so a clean
        shutdown loses nothing."""
        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.member_persist_interval)
            try:
                await self._persist_members_once()
            except Exception:
                logging.getLogger(__name__).debug(
                    "member persist failed", exc_info=True
                )

    async def _runtime_metrics_loop(self) -> None:
        """Event-loop/runtime profiling — the tokio-metrics reporter's role
        (command/agent.rs:87-213: scheduled/idle/poll durations, task
        counts). asyncio's equivalents: loop LAG (how late a 1 s sleep
        fires — the 'scheduled duration' signal that catches a blocked
        loop), live task count, and the counted-handle registry depth."""
        from corrosion_tpu.utils.metrics import (
            process_open_fds,
            process_rss_bytes,
            register_process_gauges,
        )

        lag_hist = self.metrics.histogram(
            "corro_runtime_loop_lag_seconds",
            "event-loop wakeup lag of a 1s timer (blocked-loop detector)",
        )
        tasks_g = self.metrics.gauge(
            "corro_runtime_tasks", "live asyncio tasks in this process"
        )
        counted_g = self.metrics.gauge(
            "corro_runtime_counted_handles",
            "tasks tracked by the counted-spawn registry",
        )
        # Process self-observability (docs/OBSERVABILITY.md): RSS,
        # open-fd count, and the last loop-lag sample as gauges, so an
        # hours-long soak's leak signals are on /metrics, not just in
        # post-hoc reports.
        rss_g, fds_g, lag_g = register_process_gauges(self.metrics)
        if (
            self.cfg.metric_series_path
            and self._series_recorder is None
        ):
            # Endurance plane install: attach() is idempotent per path,
            # so an in-process relaunch (kill_restart) adopts the
            # previous life's live recorder instead of raising or
            # double-sampling; a cleanly-closed life reopens mode="a"
            # and the series continues across the restart discontinuity
            # (obs/endurance.py rebases the counter drop).
            from corrosion_tpu.obs.series import MetricSeriesRecorder

            self._series_recorder = MetricSeriesRecorder.attach(
                self.cfg.metric_series_path,
                source=f"agent:{self.actor_id[:8]}",
                max_bytes=self.cfg.metric_series_max_bytes,
            )
        log = logging.getLogger(__name__)
        interval = self.cfg.runtime_metrics_interval
        while not self.tripwire.tripped:
            t0 = time.monotonic()
            await asyncio.sleep(interval)
            lag = max(time.monotonic() - t0 - interval, 0.0)
            lag_hist.observe(lag)
            lag_g.set(lag)
            if lag > 1.0:
                # Slow-turn watchdog (the foca loop warns past 1 s,
                # broadcast/mod.rs:296-300): something blocked the loop.
                log.warning("event loop blocked for %.2fs", lag)
            try:
                tasks_g.set(len(asyncio.all_tasks()))
            except RuntimeError:
                pass
            counted_g.set(self.tasks.pending)
            rss = process_rss_bytes()
            if rss is not None:
                rss_g.set(rss)
            fds = process_open_fds()
            if fds is not None:
                fds_g.set(fds)
            if self._series_recorder is not None:
                try:
                    self._series_recorder.sample(self.metrics)
                except ValueError:
                    # Closed under us (abort racing the tick) — the
                    # loop is about to see the tripwire anyway.
                    pass

    async def _wal_checkpoint_loop(self) -> None:
        """Periodic WAL truncation on the writer, timed (the reference's
        db_cleanup loop: PRAGMA wal_checkpoint(TRUNCATE) every 15 min with
        a duration histogram, agent.rs:956-967, 1413-1435). Background
        write tier: user writes always preempt it."""
        hist = self.metrics.histogram(
            "corro_db_wal_truncate_seconds", "WAL truncation duration"
        )
        bytes_g = self.metrics.gauge(
            "corro_db_wal_bytes_truncated",
            "WAL size reclaimed by the last truncation",
        )
        wal_path = self.store.path + "-wal"
        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.wal_checkpoint_interval)
            try:
                t0 = time.monotonic()

                def ckpt():
                    # Size BEFORE truncating: the pragma reports the
                    # post-truncation log (0 on success), not the amount
                    # reclaimed.
                    try:
                        before = os.path.getsize(wal_path)
                    except OSError:
                        before = 0
                    with self.store._wlock("wal_checkpoint"):
                        row = self.store.conn.execute(
                            "PRAGMA wal_checkpoint(TRUNCATE)"
                        ).fetchone()
                    return before, row

                before, row = await self.pool.write_low(ckpt)
                busy = bool(row and row[0])
                if not busy:
                    # Only a real truncation counts — with busy=1 the
                    # pragma returns without reclaiming anything and the
                    # metrics would show healthy truncations while the
                    # WAL grows.
                    hist.observe(time.monotonic() - t0)
                    bytes_g.set(before)
            except Exception:
                logging.getLogger(__name__).debug(
                    "wal checkpoint failed", exc_info=True
                )

    # -- SWIM loop -------------------------------------------------------------

    async def _swim_loop(self) -> None:
        streak = _StreakLogger("SWIM probe round failed")
        while not self.tripwire.tripped:
            await asyncio.sleep(self.cfg.probe_interval)
            try:
                await self.swim.probe_round()
                streak.ok()
            except Exception:
                streak.fail()

    # -- sync (client: handle_sync/parallel_sync; server: serve_sync) ---------

    async def _sync_loop(self) -> None:
        streak = _StreakLogger("sync session failed")
        while not self.tripwire.tripped:
            await asyncio.sleep(
                self.cfg.sync_interval * (0.75 + random.random() * 0.5)
            )
            try:
                await self._sync_once()
                streak.ok()
            except Exception:
                streak.fail()

    async def _sync_once(self) -> None:
        """Concurrent multi-peer sync (parallel_sync, peer.rs:925-1286):
        sessions to the chosen peers run CONCURRENTLY, a shared claim set
        dedups in-flight need blocks across them (scheduler peer.rs:1108-
        1223), and each session pulls in waves of ``sync_wave_needs``
        blocks so one slow peer never delays the others."""
        peers = self.members.by_ring()  # ring asc (agent.rs:2383-2423)
        if not peers:
            return
        peers = peers[: self.cfg.sync_peers]
        in_flight: set = set()
        await asyncio.gather(
            *(self._sync_with_peer(m, in_flight) for m in peers)
        )

    # Need blocks align to an absolute 10-version grid so concurrent
    # sessions claim identical keys for identical work even when the
    # bookie moved between their waves (chunked ranges, peer.rs:833-841).
    _NEED_BLOCK = 10

    def _claim_needs(
        self, needs: dict, in_flight: set, cap: int
    ) -> tuple[dict, list]:
        """Split needs into grid-aligned blocks, claim up to ``cap`` blocks
        not already in flight elsewhere. Returns (wire-ready needs by
        actor, claimed keys)."""
        out: dict[str, list] = {}
        keys: list = []
        b = self._NEED_BLOCK
        for actor, lst in needs.items():
            for need in lst:
                if isinstance(need, FullNeed):
                    start = need.start
                    while start <= need.end:
                        block_end = min(((start - 1) // b + 1) * b, need.end)
                        key = (actor, "full", (start - 1) // b)
                        if key not in in_flight:
                            in_flight.add(key)
                            keys.append(key)
                            out.setdefault(actor, []).append(
                                FullNeed(start, block_end)
                            )
                            if len(keys) >= cap:
                                return out, keys
                        start = block_end + 1
                else:
                    key = (actor, "part", need.version)
                    if key not in in_flight:
                        in_flight.add(key)
                        keys.append(key)
                        out.setdefault(actor, []).append(need)
                        if len(keys) >= cap:
                            return out, keys
        return out, keys

    async def _sync_with_peer(self, m, in_flight: set) -> None:
        needs_gauge = self.metrics.gauge(
            "corro_sync_needs", "version gaps at last sync generation"
        )
        sess_hist = self.metrics.histogram(
            "corro_sync_client_seconds", "client-side sync session duration"
        )
        attempts_ctr = self.metrics.counter(
            "corro_sync_attempts_count", "sync sessions attempted"
        )
        member_ctr = self.metrics.counter(
            "corro_sync_client_member", "sync sessions established, by peer"
        )
        head_gauge = self.metrics.gauge(
            "corro_sync_client_head",
            "peer-advertised head per actor at session start",
        )
        need_hist = self.metrics.histogram(
            "corro_sync_client_request_operations_need_count",
            "need blocks per sync request wave",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000),
        )
        recv_ctr = self.metrics.counter(
            "corro_sync_changes_recv", "changes received through sync"
        )
        attempts_ctr.inc()
        # Cross-node trace propagation: the session span's traceparent
        # travels in the wire protocol (SyncTraceContextV1, sync.rs:32-67
        # injected peer.rs:941-944).
        span = self.tracer.span("sync_client", peer=m.actor_id[:8])
        span.__enter__()
        t_start = time.monotonic()
        session = await self.transport.open_session(
            m.addr,
            {"t": "sync_start", "actor": self.actor_id,
             "clock": self.hlc.new_timestamp(),
             "trace": span.traceparent},
        )
        if session is None:
            span.__exit__(None, None, None)
            return
        claimed: list = []
        try:
            reply = await session.recv(timeout=5.0)
            if not reply or reply.get("t") != "sync_state":
                return
            member_ctr.inc(peer=m.actor_id[:8])
            self.hlc.update_with_timestamp(reply.get("clock", 0))
            server_state = _state_from_wire(reply["state"])
            # The peer's OWN advertised head only, and a hard series cap:
            # one gauge series per actor in heads would grow with cluster
            # size and never shrink (label cardinality explosion at the
            # 100k target; scrapes render every series).
            peer_head = server_state.heads.get(server_state.actor_id)
            if peer_head is not None:
                lbl = (("actor", server_state.actor_id[:8]),)
                if lbl in head_gauge._values or len(head_gauge._values) < 128:
                    head_gauge.set(
                        peer_head, actor=server_state.actor_id[:8]
                    )
            while not self.tripwire.tripped:
                # Regenerate per wave: blocks ingested from concurrent
                # sessions (and this one's earlier waves) shrink the next
                # request; claims cover what's served but not yet ingested.
                my_state = generate_sync(self.bookie, self.actor_id)
                needs_gauge.set(my_state.need_len())
                needs = my_state.compute_available_needs(server_state)
                wave, keys = self._claim_needs(
                    needs, in_flight, self.cfg.sync_wave_needs
                )
                claimed.extend(keys)
                if not wave:
                    break
                need_hist.observe(sum(len(v) for v in wave.values()))
                await session.send(
                    {"t": "sync_request", "needs": _needs_to_wire(wave)}
                )
                done = False
                while True:
                    frame = await session.recv(timeout=10.0)
                    if frame is None or frame.get("t") == "sync_done":
                        done = True
                        break
                    t = frame.get("t")
                    if t == "sync_wave_done":
                        break
                    if t == "sync_changes":
                        recv_ctr.inc(len(frame.get("changes", ())))
                        inner = dict(frame)
                        inner["t"] = "bcast"
                        try:
                            self._ingest.put_nowait((inner, "sync"))
                        except asyncio.QueueFull:
                            done = True
                            break
                    elif t == "sync_cleared":
                        booked = self.bookie.for_actor(frame["actor"])
                        for s, e in frame["versions"]:
                            booked.insert_many(s, e, CLEARED)
                            # Persist via the empties batcher so the range
                            # survives restart (store path of
                            # process_multiple_changes' empty handling).
                            self._queue_empty(frame["actor"], s, e)
                if done:
                    break
                # Let the ingest batcher absorb this wave before computing
                # the next (smaller) one.
                await asyncio.sleep(self.cfg.ingest_linger * 2)
            try:
                await session.send({"t": "sync_finish"})
            except (ConnectionError, OSError):
                pass
        finally:
            # Release claims so a failed session's blocks become requestable
            # by the next round (in-flight dedup is session-lifetime only).
            for k in claimed:
                in_flight.discard(k)
            session.close()
            sess_hist.observe(time.monotonic() - t_start)
            span.__exit__(None, None, None)

    async def _serve_sync(self, session: Session, start: dict) -> None:
        """Server side (peer.rs:1289-1527): serves request waves until the
        client finishes, under a per-wave version budget (fairness: a peer
        requesting a huge range cannot monopolize the server; the reference
        caps concurrent jobs and chunks adaptively, peer.rs:675-686) with
        adaptive chunk sizing and a 5 s blocking-send abort
        (peer.rs:352-355, 638-653). Continues the client's trace via the
        frame's traceparent (extracted like peer.rs:1296-1298)."""
        with self.tracer.span(
            "sync_server", traceparent=start.get("trace"),
            peer=str(start.get("actor", ""))[:8],
        ):
            self.hlc.update_with_timestamp(start.get("clock", 0))
            state = generate_sync(self.bookie, self.actor_id)
            await session.send(
                {"t": "sync_state", "state": _state_to_wire(state),
                 "clock": self.hlc.new_timestamp()}
            )
            chunker = AdaptiveChunker(
                max_bytes=self.cfg.sync_chunk_max_bytes,
                min_bytes=self.cfg.sync_chunk_min_bytes,
                threshold_s=self.cfg.sync_adapt_threshold,
            )
            try:
                while not self.tripwire.tripped:
                    req = await session.recv(timeout=5.0)
                    if not req or req.get("t") != "sync_request":
                        break  # sync_finish, timeout, or disconnect
                    served = 0
                    budget = self.cfg.sync_serve_budget
                    for actor, needs in _needs_from_wire(
                        req["needs"]
                    ).items():
                        booked = self.bookie.get(actor)
                        if booked is None:
                            continue
                        for need in needs:
                            if served >= budget:
                                break
                            served += await self._serve_need(
                                session, actor, booked, need,
                                chunker=chunker,
                                budget=budget - served,
                            )
                    await session.send(
                        {"t": "sync_wave_done", "served": served}
                    )
                await session.send({"t": "sync_done"})
            except asyncio.TimeoutError:
                # Blocking-send stall: abort the session (the client
                # re-requests unserved blocks next round).
                session.close()

    async def _timed_send(self, session, frame, chunker) -> None:
        """Send with the stall abort + chunk-size feedback loop. Both
        defenses count when they engage: the abort edge here, the
        halving edge via AdaptiveChunker.record's return."""
        t0 = time.monotonic()
        try:
            nbytes = await asyncio.wait_for(
                session.send(frame), self.cfg.sync_stall_timeout
            )
        except asyncio.TimeoutError:
            self._m_stall_aborts.inc()
            raise
        if chunker is not None and chunker.record(time.monotonic() - t0):
            self._m_chunk_halvings.inc()
        if frame.get("t") == "sync_changes":
            self._m_sync_sent_bytes.inc(nbytes or 0)
            self._m_sync_sent.inc(len(frame.get("changes", ())))

    async def _serve_need(
        self, session, actor, booked, need, chunker=None, budget=None
    ) -> int:
        """Serve one need; returns the number of versions streamed (cleared
        spans are range metadata, not streamed rows, and don't count).
        ``budget`` truncates a large FullNeed — the client's claim
        machinery re-requests the rest next round."""
        served = 0
        if isinstance(need, FullNeed):
            # Cleared spans come straight from the interval set — a large
            # compacted range must not be walked version-by-version (it
            # would block the event loop and stall SWIM probes).
            cleared = [
                (max(s, need.start), min(e, need.end))
                for s, e in booked.cleared
                if s <= need.end and e >= need.start
            ]
            if cleared:
                await self._timed_send(
                    session,
                    {"t": "sync_cleared", "actor": actor, "versions": cleared},
                    chunker,
                )
            for v, known in sorted(booked.current.items()):
                if v < need.start or v > need.end:
                    continue
                if budget is not None and served >= budget:
                    break
                changes = self.store.changes_for(
                    bytes.fromhex(actor), known.db_version
                )
                max_bytes = chunker.max_bytes if chunker else None
                for chunk, (s, e) in chunk_changes(
                    changes, known.last_seq,
                    **({"max_bytes": max_bytes} if max_bytes else {}),
                ):
                    await self._timed_send(
                        session,
                        self._sync_changes_frame(
                            actor, v, chunk, (s, e), known.last_seq, known.ts,
                        ),
                        chunker,
                    )
                served += 1
        elif isinstance(need, PartialNeed):
            known = booked.get(need.version)
            if isinstance(known, Partial):
                # Read connection (not the writer): the pool's writer
                # thread may hold an open BEGIN IMMEDIATE on store.conn,
                # and this read runs on the event loop — same discipline
                # as changes_for.
                rows = self.store.read_conn.execute(  # corro-lint: disable=CT042 reason=WAL read connection off the writer; bounded rows per need frame (changes_for discipline)
                    "SELECT tbl, pk, cid, val, col_version, db_version,"
                    " seq, site_id, cl FROM __corro_buffered_changes"
                    " WHERE actor_id = ? AND version = ? ORDER BY seq",
                    (bytes.fromhex(actor), need.version),
                ).fetchall()
                by_seq = {r[6]: Change.from_tuple(tuple(r)) for r in rows}
                last_seq, ts = known.last_seq, known.ts
            elif isinstance(known, Current):
                # The version is COMPLETE here: a partial need must still
                # be answerable (sync.rs:248-266 — the requester's gaps
                # came from lossy dissemination; holders of the applied
                # version are exactly who can fill them). Without this
                # branch a node whose partial buffer lost chunks stalls
                # FOREVER once every peer has compacted the version to
                # Current (measured: a 2-node catch-up wedged at
                # 39/40 versions permanently).
                changes = self.store.changes_for(
                    bytes.fromhex(actor), known.db_version
                )
                by_seq = {c.seq: c for c in changes}
                last_seq, ts = known.last_seq, known.ts
            else:
                return 0
            for s, e in need.seqs:
                have = [by_seq[q] for q in range(s, e + 1) if q in by_seq]
                if not have:
                    continue
                lo = min(c.seq for c in have)
                hi = max(c.seq for c in have)
                await self._timed_send(
                    session,
                    self._sync_changes_frame(
                        actor, need.version, have, (lo, hi),
                        last_seq, ts,
                    ),
                    chunker,
                )
            served = 1
        return served

    def _sync_changes_frame(self, actor, version, changes, seqs, last_seq, ts):
        f = self._changeset_frame(actor, version, changes, seqs, last_seq, ts)
        f["t"] = "sync_changes"
        return f


# -- sync state wire codec ---------------------------------------------------


def _state_to_wire(state) -> dict:
    return {
        "actor_id": state.actor_id,
        "heads": dict(state.heads),
        "need": {a: [list(r) for r in rs] for a, rs in state.need.items()},
        "partial_need": {
            a: {str(v): [list(r) for r in rs] for v, rs in partials.items()}
            for a, partials in state.partial_need.items()
        },
    }


def _state_from_wire(w: dict):
    from corrosion_tpu.core.bookkeeping import SyncState

    return SyncState(
        actor_id=w["actor_id"],
        heads=dict(w["heads"]),
        need={a: [tuple(r) for r in rs] for a, rs in w["need"].items()},
        partial_need={
            a: {int(v): [tuple(r) for r in rs] for v, rs in partials.items()}
            for a, partials in w["partial_need"].items()
        },
    )


def _needs_to_wire(needs) -> dict:
    out: dict = {}
    for actor, lst in needs.items():
        items = []
        for n in lst:
            if isinstance(n, FullNeed):
                items.append({"full": [n.start, n.end]})
            else:
                items.append(
                    {"partial": {"version": n.version,
                                 "seqs": [list(s) for s in n.seqs]}}
                )
        out[actor] = items
    return out


def _needs_from_wire(w: dict):
    out: dict = {}
    for actor, lst in w.items():
        items = []
        for n in lst:
            if "full" in n:
                items.append(FullNeed(n["full"][0], n["full"][1]))
            else:
                items.append(
                    PartialNeed(
                        n["partial"]["version"],
                        [tuple(s) for s in n["partial"]["seqs"]],
                    )
                )
        out[actor] = items
    return out
