"""Host-side SWIM membership + RTT rings.

The role foca plays for the reference (corro-agent/src/broadcast/mod.rs
runtime_loop + corro-types/src/members.rs), for real (non-simulated) agents:

- probe/ack with indirect probes, suspicion with timeout -> down,
  incarnation-based refutation (foca semantics; the batched kernel version
  of the same state machine is ops/swim.py).
- membership updates piggyback on probe traffic with a retransmission
  budget (~log2(n) like make_foca_config, broadcast/mod.rs:704-713).
- per-member RTT ring buckets 0-5/5-15/15-50/50-100/100-200/200-300 ms
  (members.rs:33,101-136); ring 0 gets eager broadcasts and sync priority.
"""

from __future__ import annotations

import asyncio
import math
import random
import time
from dataclasses import dataclass, field

RING_BUCKETS_MS = (5.0, 15.0, 50.0, 100.0, 200.0, 300.0)  # members.rs:33

ALIVE, SUSPECT, DOWN = "alive", "suspect", "down"


def rtt_ring(rtt_ms: float) -> int:
    for i, edge in enumerate(RING_BUCKETS_MS):
        if rtt_ms < edge:
            return i
    return len(RING_BUCKETS_MS) - 1


@dataclass
class MemberState:
    actor_id: str
    addr: tuple[str, int]
    state: str = ALIVE
    incarnation: int = 0
    rtts: list[float] = field(default_factory=list)  # ms, circular (cap 20)
    ring: int | None = None
    suspect_at: float = 0.0
    down_at: float = 0.0  # monotonic time the member was declared down

    def add_rtt(self, ms: float) -> None:
        self.rtts.append(ms)
        if len(self.rtts) > 20:
            self.rtts.pop(0)
        self.ring = rtt_ring(sum(self.rtts) / len(self.rtts))


class Members:
    """Known peers, keyed by actor id (corro-types/src/members.rs:12-137)."""

    def __init__(self, self_id: str) -> None:
        self.self_id = self_id
        self.states: dict[str, MemberState] = {}
        # Optional event hooks (the agent wires these to the
        # corro_gossip_member_added/_removed counters).
        self.on_added = None
        self.on_removed = None

    def alive(self) -> list[MemberState]:
        return [m for m in self.states.values() if m.state != DOWN]

    def ring0(self) -> list[MemberState]:
        return [m for m in self.alive() if m.ring == 0]

    def by_ring(self) -> list[MemberState]:
        return sorted(
            self.alive(), key=lambda m: m.ring if m.ring is not None else 99
        )

    def apply_update(
        self, actor_id: str, addr: tuple[str, int], state: str, inc: int
    ) -> bool:
        """Merge a membership rumor; returns True if it changed anything
        (and so should keep disseminating)."""
        if actor_id == self.self_id:
            return False
        m = self.states.get(actor_id)
        if m is None:
            if state == DOWN:
                return False
            self.states[actor_id] = MemberState(
                actor_id=actor_id, addr=addr, state=state, incarnation=inc
            )
            if self.on_added is not None:
                self.on_added(actor_id)
            return True
        # foca precedence: higher incarnation wins; same incarnation,
        # down > suspect > alive.
        rank = {ALIVE: 0, SUSPECT: 1, DOWN: 2}
        if inc < m.incarnation:
            return False
        if inc == m.incarnation and rank[state] <= rank[m.state]:
            return False
        m.state = state
        m.incarnation = inc
        m.addr = addr
        if state == SUSPECT:
            m.suspect_at = time.monotonic()
        elif state == DOWN:
            m.down_at = time.monotonic()
        return True

    def gc_down(self, horizon_s: float) -> list[str]:
        """Forget members down longer than ``horizon_s`` (foca's
        remove_down_after, 48 h in the WAN preset, broadcast/mod.rs:704-713)
        so a long-lived cluster's member table doesn't accumulate corpses.
        Returns the removed actor ids."""
        now = time.monotonic()
        gone = [
            aid for aid, m in self.states.items()
            if m.state == DOWN and m.down_at and now - m.down_at > horizon_s
        ]
        for aid in gone:
            del self.states[aid]
            if self.on_removed is not None:
                self.on_removed(aid)
        return gone


@dataclass
class Rumor:
    actor_id: str
    addr: tuple[str, int]
    state: str
    incarnation: int
    tx_left: int

    def wire(self) -> dict:
        return {
            "id": self.actor_id,
            "addr": list(self.addr),
            "state": self.state,
            "inc": self.incarnation,
        }


class Swim:
    """Probe scheduler + rumor queue. The owning agent wires `send` to the
    transport and calls `on_message` for inbound swim frames."""

    def __init__(
        self,
        members: Members,
        self_addr: tuple[str, int],
        send,  # async (addr, dict) -> bool
        probe_interval: float = 1.0,
        probe_timeout: float = 0.5,
        suspect_timeout: float = 3.0,
        indirect_probes: int = 2,
        max_transmissions: int = 6,
    ) -> None:
        self.members = members
        self.self_addr = self_addr
        self.send = send
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.suspect_timeout = suspect_timeout
        self.indirect_probes = indirect_probes
        self.max_transmissions = max_transmissions
        # Cluster-size-adaptive dissemination (the reference resizes foca's
        # config on every cluster-size notification, agent.rs:1345-1358 →
        # make_foca_config, broadcast/mod.rs:704-713): retransmission budget
        # scales ~log2 of the cluster so rumors still infect everyone, and
        # down members are forgotten after ``down_gc_s`` (remove_down_after,
        # 48 h in the WAN preset).
        self._base_max_transmissions = max_transmissions
        self._base_indirect = indirect_probes
        self._last_size = 0
        self.down_gc_s = 48 * 3600.0
        self.incarnation = 0
        self.rumors: list[Rumor] = []
        self._acks: dict[int, asyncio.Event] = {}
        self._seq = 0

    def _adapt_config(self) -> None:
        """Recompute dissemination parameters from the current cluster size
        (called every probe round; cheap, idempotent)."""
        size = len(self.members.alive()) + 1
        if size == self._last_size:
            return
        self._last_size = size
        self.max_transmissions = max(
            self._base_max_transmissions, math.ceil(1.5 * math.log2(size + 1))
        )
        self.indirect_probes = max(
            self._base_indirect, min(5, math.ceil(math.log2(size + 1) / 2))
        )

    # -- dissemination -------------------------------------------------------

    def queue_rumor(self, actor_id, addr, state, inc) -> None:
        self.rumors = [r for r in self.rumors if r.actor_id != actor_id]
        self.rumors.append(
            Rumor(actor_id, tuple(addr), state, inc, self.max_transmissions)
        )

    def _piggyback(self) -> list[dict]:
        sent, out = self.rumors[:8], []
        for r in sent:
            out.append(r.wire())
            r.tx_left -= 1
        # Rotate: spent rumors drop, unsent ones move to the front so a
        # deep backlog still disseminates everything over later packets.
        keep = [r for r in sent if r.tx_left > 0]
        self.rumors = self.rumors[8:] + keep
        return out

    def _absorb(self, updates: list[dict]) -> None:
        for u in updates:
            aid, addr = u["id"], tuple(u["addr"])
            if aid == self.members.self_id:
                # Refutation: bump incarnation and re-announce
                # (actor.rs:184-194's renew-on-down).
                if u["state"] in (SUSPECT, DOWN) and u["inc"] >= self.incarnation:
                    self.incarnation = u["inc"] + 1
                    self.queue_rumor(
                        aid, self.self_addr, ALIVE, self.incarnation
                    )
                continue
            if self.members.apply_update(aid, addr, u["state"], u["inc"]):
                self.queue_rumor(aid, addr, u["state"], u["inc"])

    async def leave_cluster(self) -> None:
        """Graceful departure (foca.leave_cluster on shutdown,
        broadcast/mod.rs:306): announce self DOWN at the CURRENT
        incarnation directly to a handful of alive peers, so the cluster
        learns immediately instead of paying a probe-timeout + suspect
        window. Peers won't refute it (only the node itself refutes), and
        a later restart re-announces alive at a higher incarnation."""
        peers = [m for m in self.members.alive() if m.state == ALIVE]
        random.shuffle(peers)
        frame = {
            "t": "swim",
            "k": "leave",
            "from": self.members.self_id,
            "from_addr": list(self.self_addr),
            "updates": [
                {
                    "id": self.members.self_id,
                    "addr": list(self.self_addr),
                    "state": DOWN,
                    "inc": self.incarnation,
                }
            ],
        }
        for m in peers[: max(self.indirect_probes * 2, 4)]:
            try:
                await self.send(m.addr, frame)
            except Exception:
                continue

    # -- probe loop ----------------------------------------------------------

    async def probe_round(self) -> None:
        self._adapt_config()
        self.members.gc_down(self.down_gc_s)
        alive = [m for m in self.members.alive() if m.state == ALIVE]
        # Expire suspects first (suspect -> down).
        now = time.monotonic()
        for m in list(self.members.states.values()):
            if m.state == SUSPECT and now - m.suspect_at > self.suspect_timeout:
                m.state = DOWN
                m.down_at = now
                self.queue_rumor(m.actor_id, m.addr, DOWN, m.incarnation)
        if not alive:
            return
        target = random.choice(alive)
        t0 = time.monotonic()
        ok = await self._probe(target.addr)
        if ok:
            target.add_rtt((time.monotonic() - t0) * 1000.0)
            return
        # Indirect probes (num_indirect_probes, foca config).
        others = [m for m in alive if m.actor_id != target.actor_id]
        random.shuffle(others)
        for via in others[: self.indirect_probes]:
            if await self._probe_req(via.addr, target):
                return
        if target.state == ALIVE:
            target.state = SUSPECT
            target.suspect_at = time.monotonic()
            self.queue_rumor(
                target.actor_id, target.addr, SUSPECT, target.incarnation
            )

    async def _probe(self, addr) -> bool:
        self._seq += 1
        seq = self._seq
        ev = asyncio.Event()
        self._acks[seq] = ev
        try:
            sent = await self.send(
                addr,
                {
                    "t": "swim",
                    "k": "ping",
                    "seq": seq,
                    "from": self.members.self_id,
                    "from_addr": list(self.self_addr),
                    "inc": self.incarnation,
                    "updates": self._piggyback(),
                },
            )
            if not sent:
                return False
            await asyncio.wait_for(ev.wait(), self.probe_timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._acks.pop(seq, None)

    async def _probe_req(self, via_addr, target: MemberState) -> bool:
        self._seq += 1
        seq = self._seq
        ev = asyncio.Event()
        self._acks[seq] = ev
        try:
            sent = await self.send(
                via_addr,
                {
                    "t": "swim",
                    "k": "ping_req",
                    "seq": seq,
                    "from": self.members.self_id,
                    "from_addr": list(self.self_addr),
                    "target": list(target.addr),
                    "updates": self._piggyback(),
                },
            )
            if not sent:
                return False
            await asyncio.wait_for(ev.wait(), self.probe_timeout * 2)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._acks.pop(seq, None)

    # -- inbound -------------------------------------------------------------

    async def on_message(self, msg: dict) -> None:
        kind = msg.get("k")
        self._absorb(msg.get("updates", []))
        if kind == "ping":
            frm = msg["from"]
            addr = tuple(msg["from_addr"])
            if self.members.apply_update(frm, addr, ALIVE, msg.get("inc", 0)):
                self.queue_rumor(frm, addr, ALIVE, msg.get("inc", 0))
            updates = self._piggyback()
            m = self.members.states.get(frm)
            if m is not None and m.state != ALIVE:
                # Suspicion feedback (the announce handler's about_frm
                # rule, applied to pings): a ping from a peer we believe
                # SUSPECT/DOWN is refused by incarnation precedence, so
                # without telling the pinger what we believe about IT the
                # peer pings forever without learning it must refute —
                # and a healed partition never heals the membership.
                # The suspect rumor's own retransmission budget is spent
                # long before a multi-second partition clears; this
                # feedback is deterministic, not budget-gated.
                updates.append(
                    Rumor(frm, m.addr, m.state, m.incarnation, 1).wire()
                )
            await self.send(
                addr,
                {
                    "t": "swim",
                    "k": "ack",
                    "seq": msg["seq"],
                    "from": self.members.self_id,
                    "from_addr": list(self.self_addr),
                    "updates": updates,
                },
            )
        elif kind == "ack":
            ev = self._acks.get(msg.get("seq"))
            if ev:
                ev.set()
        elif kind == "ping_req":
            # Probe the target on the requester's behalf; relay the ack.
            target = tuple(msg["target"])
            ok = await self._probe(target)
            if ok:
                await self.send(
                    tuple(msg["from_addr"]),
                    {
                        "t": "swim",
                        "k": "ack",
                        "seq": msg["seq"],
                        "from": self.members.self_id,
                        "from_addr": list(self.self_addr),
                        "updates": [],
                    },
                )
        elif kind == "announce":
            frm = msg["from"]
            addr = tuple(msg["from_addr"])
            inc = msg.get("inc", 0)
            if self.members.apply_update(frm, addr, ALIVE, inc):
                self.queue_rumor(frm, addr, ALIVE, inc)
            # Reply with everything we know (bootstrap catch-up) — and,
            # crucially, our belief about the ANNOUNCER itself when it is
            # not plain alive: a node that left gracefully and restarted
            # must learn it is believed DOWN so it can refute with a
            # higher incarnation (otherwise it stays invisible until the
            # down-member GC).
            known = [
                Rumor(m.actor_id, m.addr, m.state, m.incarnation, 1).wire()
                for m in self.members.alive()
            ]
            about_frm = self.members.states.get(frm)
            if about_frm is not None and about_frm.state != ALIVE:
                known.append(
                    Rumor(
                        frm, about_frm.addr, about_frm.state,
                        about_frm.incarnation, 1,
                    ).wire()
                )
            known.append(
                Rumor(
                    self.members.self_id, self.self_addr, ALIVE,
                    self.incarnation, 1,
                ).wire()
            )
            await self.send(
                addr,
                {"t": "swim", "k": "known", "updates": known},
            )
        elif kind == "known":
            pass  # updates already absorbed above

    async def announce(self, addr: tuple[str, int]) -> None:
        await self.send(
            addr,
            {
                "t": "swim",
                "k": "announce",
                "from": self.members.self_id,
                "from_addr": list(self.self_addr),
                "inc": self.incarnation,
                "updates": [],
            },
        )
