"""Builders for the five BASELINE.md scenarios.

The knobs mirror the reference's protocol constants (BASELINE.md): broadcast
flush tick 500 ms == 1 round, sync backoff 1-15 s → sync_interval ~8 rounds
(jittered per node), fanout ~ ring-0 eager + num_indirect_probes random,
retransmissions ~ foca max_transmissions for the cluster size.
"""

from __future__ import annotations

import math

import numpy as np

from corrosion_tpu.ops.gossip import GossipConfig, make_topology
from corrosion_tpu.ops.swim import SwimConfig
from corrosion_tpu.sim.engine import ClusterConfig, Schedule


def _max_tx(n: int) -> int:
    # foca scales retransmissions ~ log2(cluster size) + margin.
    return max(4, int(math.ceil(math.log2(max(n, 2)))) + 2)


def _cfg(
    n, writers, regions=None, region_rtt=None, swim_kw=None, **gossip_kw
) -> tuple[ClusterConfig, object]:
    regions = regions or [n]
    gossip_kw.setdefault("max_transmissions", _max_tx(n))
    g = GossipConfig(
        n_nodes=n,
        n_writers=len(writers),
        **gossip_kw,
    )
    s = SwimConfig(
        n_nodes=n,
        max_transmissions=_max_tx(n),
        suspect_rounds=3,
        gossip_fanout=3,
        **(swim_kw or {}),
    )
    topo = make_topology(
        regions, writers, region_rtt=region_rtt,
        sync_interval=g.sync_interval,
    )
    return ClusterConfig(swim=s, gossip=g), topo


def three_node(n_inserts: int = 1000, samples: int = 256):
    """Config 1: 3-node local cluster, single-table schema, 1k INSERTs.

    All three nodes write round-robin, 4 versions per writer per round, then
    the run drains until convergence (like integration-tests' baseline).
    """
    cfg, topo = _cfg(3, writers=[0, 1, 2], sync_interval=4, n_cells=256)
    per_round = 3 * 4
    write_rounds = (n_inserts + per_round - 1) // per_round
    drain = 30
    writes = np.zeros((write_rounds + drain, 3), np.uint32)
    writes[:write_rounds, :] = 4
    # Trim the tail so exactly n_inserts versions commit.
    extra = write_rounds * per_round - n_inserts
    w = 2
    r = write_rounds - 1
    while extra > 0:
        take = min(extra, 4)
        writes[r, w] -= take
        extra -= take
        w -= 1
        if w < 0:
            w, r = 2, r - 1
    sched = Schedule(writes=writes).make_samples(samples)
    return cfg, topo, sched


def churn_32(rounds: int = 400, samples: int = 128, seed: int = 1):
    """Config 2: 32-node membership churn storm (join/leave/suspect).

    A third of the cluster flaps on a staggered cadence while a light write
    load measures visibility impact. The metric of record is the
    `mismatches` curve (SWIM convergence time after each churn event).
    """
    n = 32
    cfg, topo = _cfg(n, writers=list(range(n)), sync_interval=8, n_cells=256)
    rng = np.random.default_rng(seed)
    writes = np.zeros((rounds, n), np.uint32)
    write_mask = rng.random((rounds, n)) < 0.02
    writes[write_mask] = 1
    # Drain tail so the final state is a convergence check, not a snapshot
    # of in-flight writes (clamped for short runs).
    drain = min(40, max(rounds // 4, 1))
    writes[rounds - drain :, :] = 0
    kill = np.zeros((rounds, n), bool)
    revive = np.zeros((rounds, n), bool)
    flappers = rng.choice(n, size=10, replace=False)
    for i, node in enumerate(flappers):
        down_at = 40 + i * 25
        up_at = down_at + 60
        if down_at < rounds:
            kill[down_at, node] = True
        if up_at < rounds:
            revive[up_at, node] = True
    # No writes from currently-dead writers (the engine masks this too, but
    # keeping the schedule honest makes sample bookkeeping exact).
    dead = np.zeros(n, bool)
    for r in range(rounds):
        dead |= kill[r]
        dead &= ~revive[r]
        writes[r, dead] = 0
    sched = Schedule(writes=writes, kill=kill, revive=revive).make_samples(samples)
    return cfg, topo, sched


def anti_entropy_1k(n: int = 1000, burst: int = 2000, samples: int = 256):
    """Config 3: 1k-node anti-entropy: a burst of versions from a few hot
    writers overwhelms broadcast retransmission budgets; convergence happens
    through version-vector diff + budgeted sync replay."""
    writers = list(range(16))
    cfg, topo = _cfg(
        n,
        writers=writers,
        regions=[n // 4] * 4,
        sync_interval=8,
        # The burst leaves nodes hundreds of versions behind 16 hot
        # writers; with the union pull capping each writer's grant once
        # per session, catch-up needs the wider per-writer chunk and a
        # budget above the deep per-writer deficits (measured: p99
        # 24 s -> 8.0 s vs chunk 64 / budget 256).
        sync_budget=512,
        sync_chunk=128,
        queue=16,
        n_cells=512,
    )
    per_round = len(writers) * 4
    burst_rounds = (burst + per_round - 1) // per_round
    drain = 120
    writes = np.zeros((burst_rounds + drain, len(writers)), np.uint32)
    writes[:burst_rounds, :] = 4
    sched = Schedule(writes=writes).make_samples(samples)
    return cfg, topo, sched


def merge_10k(n: int = 10_000, rounds: int = 120, samples: int = 256,
              seed: int = 3):
    """Config 4: 10k nodes, everyone writes concurrently (LWW merge storm).

    Writes are sparse per round (Poisson-ish 1% of writers/round) so the
    broadcast plane stays in its operating regime. The CRDT cell plane is
    live (n_cells > 0): every applied version scatter-merges its derived
    (cl, col_version, value_rank) rows into the receiving node's registers,
    so convergence here is over merged cell state, not just watermarks.
    """
    writers = list(range(n))
    cfg, topo = _cfg(
        n,
        writers=writers,
        regions=[n // 8] * 8,
        sync_interval=5,
        # The reference's parallel_sync streams every requested need per
        # session (chunked adaptively, peer.rs:925-1286). With the
        # budget-priority broadcast carrying most deliveries, 512 converges
        # identically to 1024 and cuts the per-round grant enumeration in
        # half (measured: step 591 -> 503 ms, same p50/p99); 256 is below
        # the residual need and fails to converge.
        sync_budget=512,
        sync_chunk=128,
        # Under a cluster-wide write storm the pending queue churns, so
        # spread needs width: more far targets + deeper queues, and an
        # intake cap sized to the ~100 new versions/round write rate
        # (docs/SCALING.md "Queue policy under write storms"; measured
        # p50 5.5->3.5 s, p99 10.5->7.0 s at 10k).
        fanout_near=3,
        fanout_far=3,
        queue=24,
        max_transmissions=6,
        rebroadcast_intake=200,
        n_cells=1024,
        cells_per_write=2,
        # Sparse membership: the dense u32[N, N] view plus its scatter
        # temporaries dominate peak HBM at 10k when combined with the
        # [N, W] data plane in one round graph.
        swim_kw={"view_capacity": 64},
    )
    rng = np.random.default_rng(seed)
    writes = (rng.random((rounds, n)) < 0.01).astype(np.uint32)
    # Drain tail, clamped so short runs still write (rounds - 40 would go
    # negative and zero the whole schedule).
    drain = min(40, max(rounds // 3, 1))
    writes[rounds - drain :, :] = 0
    sched = Schedule(writes=writes).make_samples(samples)
    return cfg, topo, sched


def wan_100k(n: int = 100_000, n_regions: int = 20, n_writers: int = 512,
             rounds: int = 240, samples: int = 128, seed: int = 4,
             partition: bool = True):
    """Config 5: 100k-node partitioned WAN topology.

    20 regions; writers spread across regions; mid-run a region pair is cut
    off for 60 rounds and must catch up after healing (``partition=False``
    gives the steady-state propagation variant — the north-star visibility
    measurement, uncontaminated by partition recovery). Node axis is meant
    to be sharded over a mesh (see corrosion_tpu.parallel)."""
    rng = np.random.default_rng(seed)
    region_size = n // n_regions
    writers = sorted(rng.choice(n, size=n_writers, replace=False).tolist())
    cfg, topo = _cfg(
        n,
        writers=writers,
        regions=[region_size] * n_regions,
        region_rtt="geo",  # graded WAN rings (members.rs:33)
        sync_interval=6,
        sync_budget=512,
        sync_chunk=64,
        fanout_near=2,
        fanout_far=1,
        n_cells=256,
        # Queue policy measured on the 20k-node CPU sweep (2026-07-30):
        # fresh per-holder budgets (the reference's requeue semantics,
        # broadcast/mod.rs:549-563) + first-receipt-only intake + keep-most-
        # budget priority + intake sized to the cluster write rate. The
        # version-number keep-priority starved fresh versions under load
        # (cross-writer version comparison is arbitrary) and tripled p50;
        # inherited hop-TTL budgets + stale recirculation doubled p99.
        queue=48,
        max_transmissions=6,
        rebroadcast_intake=26,
        rebroadcast_fresh_budget=True,
        rebroadcast_stale=False,
        queue_priority="budget",
        # Dense SWIM is u32[N, N] = 40 GB at 100k nodes; the sparse
        # exception-table kernel is ~0.5 KiB/node (ops/swim_sparse.py).
        swim_kw={"view_capacity": 64},
    )
    writes = (rng.random((rounds, n_writers)) < 0.05).astype(np.uint32)
    # Drain tail so the run can converge; clamp for short smoke runs
    # (rounds - 80 would go negative and zero the whole schedule).
    drain = min(80, max(rounds // 3, 1))
    writes[rounds - drain :, :] = 0
    part = None
    if partition:
        part = np.zeros((rounds, n_regions, n_regions), bool)
        cut_a = 0
        part[60:120, cut_a, :] = True
        part[60:120, :, cut_a] = True
        part[60:120, cut_a, cut_a] = False
    sched = Schedule(writes=writes, partition=part).make_samples(samples)
    return cfg, topo, sched


def anywrite_sparse(
    n: int = 100_000, w_hot: int = 2048, rounds: int = 320,
    n_regions: int = 20, epoch_rounds: int = 16, cohort: int = 768,
    burst_writes: int = 2, samples: int = 256, seed: int = 7,
    k_dev: int = 256, demote_after: int = 1, partition: bool = False,
):
    """Config 5s: any-node-writes at scale over the rotating-slot sparse
    writer plane (BASELINE-5 variant, VERDICT r4 missing #1).

    Every node is write-eligible (the reference's model — writes originate
    anywhere, doc/crdts.md:25-28). Each epoch a fresh cohort of
    ``cohort`` random nodes bursts ``burst_writes`` versions across its
    first epoch, then goes quiescent; the planner rotates them through
    ``w_hot`` hot slots (zero-lag demotion once the cluster has caught
    up). Over the run ``cohort * (rounds/epoch_rounds - drain)`` distinct
    writer streams flow through the cluster — far more than fit a dense
    writer axis at 100k nodes.

    Returns (SparseClusterConfig, Topology, Schedule)."""
    from corrosion_tpu.ops.sparse_writers import SparseConfig
    from corrosion_tpu.sim.sparse_engine import SparseClusterConfig

    rng = np.random.default_rng(seed)
    region_size = n // n_regions
    g = GossipConfig(
        n_nodes=n,
        n_writers=w_hot,
        track_writer_ids=True,
        sync_interval=6,
        sync_budget=512,
        sync_chunk=64,
        # Wider fanout than wan_100k: this config's cluster write rate
        # (cohort*burst/epoch ≈ 96 versions/round) is ~4x config 5's, and
        # relay capacity per round is fanout x queue.
        fanout_near=3,
        fanout_far=2,
        # Queue policy scaled to the write rate (the wan_100k values are
        # sized for ~26 new versions/round; an intake below the write
        # rate collapses the epidemic growth factor — measured: nothing
        # propagated, every node lagged on every slot).
        queue=64,
        max_transmissions=_max_tx(n),
        rebroadcast_intake=8 + cohort * burst_writes // epoch_rounds,
        rebroadcast_fresh_budget=True,
        rebroadcast_stale=False,
        queue_priority="budget",
        n_cells=256,
    )
    s = SwimConfig(
        n_nodes=n,
        max_transmissions=_max_tx(n),
        suspect_rounds=3,
        gossip_fanout=3,
        view_capacity=64,
    )
    sp = SparseConfig(
        epoch_rounds=epoch_rounds, k_dev=k_dev,
        d_max=max(256, cohort + cohort // 2),
        p_max=max(256, cohort + cohort // 2),
        demote_after=demote_after,
    )
    topo = make_topology(
        [region_size] * n_regions,
        np.zeros(w_hot, np.int32),  # slots; rebound per epoch by the engine
        region_rtt="geo",
        sync_interval=g.sync_interval,
    )
    n_epochs = rounds // epoch_rounds
    drain_epochs = max(2, n_epochs // 3)
    writes = np.zeros((rounds, n), np.uint32)
    pool = rng.permutation(n)
    used = 0
    for e in range(n_epochs - drain_epochs):
        take = min(cohort, n - used)
        writers = pool[used:used + take]
        used += take
        # Burst spread over the epoch's rounds: burst_writes single-version
        # commits at distinct random rounds.
        for w in writers:
            rs = rng.choice(
                epoch_rounds, size=min(burst_writes, epoch_rounds),
                replace=False,
            )
            writes[e * epoch_rounds + rs, w] = 1
    part = None
    if partition:
        part = np.zeros((rounds, n_regions, n_regions), bool)
        cut = 0
        p0 = rounds // 4
        p1 = p0 + min(60, max(rounds // 4, epoch_rounds))
        part[p0:p1, cut, :] = True
        part[p0:p1, :, cut] = True
        part[p0:p1, cut, cut] = False
    sched = Schedule(writes=writes, partition=part).make_samples(samples)
    cfg = SparseClusterConfig(swim=s, gossip=g, sparse=sp)
    return cfg, topo, sched


def mixed_storm(
    n: int = 1000, streams: int = 16, last_seq: int = 2047,
    rounds: int = 200, samples: int = 256, seed: int = 13,
    n_cells: int = 512,
):
    """Config 3c: MIXED workload — ``streams`` large multi-chunk
    transactions disseminating seq-granularly WHILE a background
    version-granular write storm flows through the same cluster round
    (the reference's ingest handles both inline, agent.rs:2063-2151;
    VERDICT r4 missing #2). 64 writers; the first ``streams`` of them
    each commit one large transaction mid-run, interleaved with their
    own and everyone else's small writes.

    Returns (ClusterConfig, ChunkConfig, Topology, Schedule, StreamSpec).
    """
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.mixed_engine import StreamSpec

    writers = list(range(64))
    cfg, topo = _cfg(
        n,
        writers=writers,
        regions=[n // 4] * 4,
        sync_interval=8,
        sync_budget=512,
        sync_chunk=128,
        queue=16,
        # n_cells=0 drops the whole CRDT merge graph — schema-level
        # tests use it to keep compiles cheap; convergence tests keep
        # the live cell plane.
        n_cells=n_cells,
    )
    rng = np.random.default_rng(seed)
    # Background storm: every writer commits small writes at ~4%/round.
    writes = (rng.random((rounds, len(writers))) < 0.04).astype(np.uint32)
    drain = min(60, max(rounds // 3, 1))
    writes[rounds - drain :, :] = 0
    # Big transactions: stream s = writer s, committed mid-run. Its
    # version number is the writer's NEXT version at the commit round
    # (small writes before it + 1); the engine bumps head past it, so
    # later small writes number after it.
    commit_round = np.sort(
        rng.integers(rounds // 8, rounds // 2, streams)
    ).astype(np.int32)
    version = np.zeros(streams, np.uint32)
    for s in range(streams):
        version[s] = writes[: commit_round[s], s].sum() + 1
    # Shift the writer's small-write versions after the big one: the
    # engine does this implicitly (head bump at commit), but the SAMPLE
    # bookkeeping below must account for it, so make_samples runs on the
    # small-write schedule only and big versions are tracked separately.
    spec = StreamSpec(
        writer=np.arange(streams, dtype=np.int32),
        version=version,
        commit_round=commit_round,
        last_seq=np.full(streams, last_seq, np.int32),
    )
    ccfg = ChunkConfig(
        n_nodes=n,
        n_streams=streams,
        cap=16,
        chunk_len=256,
        fanout=3,
        k_in=6,
        sync_interval=5,
        gap_requests=4,
        sync_seq_budget=4096,
    )
    sched = Schedule(writes=writes).make_samples(samples)
    # Sample versions at/after each big version shift up by one (the big
    # version occupies the slot the naive per-column count would give).
    for i in range(len(sched.sample_writer)):
        w = sched.sample_writer[i]
        if w < streams and sched.sample_ver[i] >= version[w]:
            sched.sample_ver[i] += 1
    return cfg, ccfg, topo, sched, spec


def anti_entropy_chunks(
    n: int = 1000, streams: int = 16, last_seq: int = 8191,
    rounds: int = 240,
):
    """Config 3b: the seq-chunk plane at BASELINE-3 scale. ``streams`` hot
    writers each commit one LARGE multi-chunk transaction (last_seq+1 seqs
    ≈ a large_tx_sync 10k-row INSERT, agent.rs:3340) that disseminates as
    ≤8 KiB seq-range chunks (change.rs:8-116) with partial-need sync
    (SyncNeedV1::Partial, sync.rs:248-266) reassembling the gaps — the
    engine-scale exercise of ops/chunks.py.

    Returns (ChunkConfig, origin[S], last_seq[S], rounds) for
    sim.chunk_engine.simulate_chunks."""
    from corrosion_tpu.ops.chunks import ChunkConfig

    rng = np.random.default_rng(11)
    cfg = ChunkConfig(
        n_nodes=n,
        n_streams=streams,
        cap=16,
        chunk_len=256,
        fanout=3,
        k_in=6,
        sync_interval=5,
        gap_requests=4,
        sync_seq_budget=4096,
    )
    origin = np.sort(rng.choice(n, size=streams, replace=False)).astype(np.int32)
    ls = np.full((streams,), last_seq, np.int32)
    return cfg, origin, ls, rounds
