"""The five BASELINE.md cluster configurations as ready-to-run models.

Each builder returns (ClusterConfig, Topology, Schedule) reproducing one of
the driver-set scenarios (BASELINE.md "Targets"):

1. `three_node`   — 3-node local cluster, 1k INSERTs.
2. `churn_32`     — 32-node SWIM membership churn storm.
3. `anti_entropy_1k` — 1k-node sync: version-vector diff + changeset replay.
   (`anti_entropy_chunks` — 3b: the same scale with multi-chunk transactions
   on the seq-chunk plane, ops/chunks.py.)
4. `merge_10k`    — 10k-node concurrent-writer CRDT merge.
5. `wan_100k`     — 100k-node partitioned WAN topology (region-aware fanout).
   (`anywrite_sparse` — 5s: any-node-writes at 100k over the rotating-slot
   sparse writer plane, ops/sparse_writers.py.)
"""

from corrosion_tpu.models.baselines import (  # noqa: F401
    anti_entropy_1k,
    anti_entropy_chunks,
    anywrite_sparse,
    churn_32,
    merge_10k,
    three_node,
    wan_100k,
)
