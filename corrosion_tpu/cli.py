"""corrosion CLI — the crates/corrosion binary's command surface.

Subcommands mirror corrosion/src/main.rs (Cli :447-513, Command :515-641):
agent, query, exec, backup, restore, sync generate, locks, cluster members,
reload, template. Run as `python -m corrosion_tpu ...`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys

from corrosion_tpu.agent.config import Config, parse_addr, resolve_bootstrap


def _build_parser() -> argparse.ArgumentParser:
    # Global flags accepted before OR after the subcommand (the reference's
    # clap marks them global). SUPPRESS defaults keep a subparser's parse
    # from overwriting a value given before the subcommand.
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument(
        "--config", "-c", default=argparse.SUPPRESS, help="TOML config path"
    )
    common.add_argument(
        "--api-addr", default=argparse.SUPPRESS,
        help="host:port of the HTTP API",
    )
    common.add_argument(
        "--admin-path", default=argparse.SUPPRESS,
        help="admin unix socket path",
    )
    p = argparse.ArgumentParser(
        prog="corrosion", description=__doc__, parents=[common]
    )
    sub = p.add_subparsers(dest="command", required=True)

    def add(name, **kw):
        return sub.add_parser(name, parents=[common], **kw)

    add("agent", help="run the agent until interrupted")

    q = add("query", help="run a read-only SQL statement")
    q.add_argument("sql")
    q.add_argument("--columns", action="store_true")
    q.add_argument("--timer", action="store_true")

    e = add("exec", help="run write statements in a transaction")
    e.add_argument("sql", nargs="+")
    e.add_argument("--timer", action="store_true")

    b = add("backup", help="snapshot the db (VACUUM INTO + strip)")
    b.add_argument("out")
    b.add_argument("--db", required=True)

    r = add("restore", help="swap a backup into place")
    r.add_argument("backup")
    r.add_argument("--db", required=True)
    r.add_argument(
        "--self-actor-id", action="store_true",
        help="keep the backup's actor identity instead of assigning fresh",
    )
    r.add_argument(
        "--online", action="store_true",
        help="restore into a RUNNING agent via the admin socket (SQLite "
        "file locks held during the swap)",
    )

    s = add("sync", help="sync protocol utilities")
    s.add_argument("sync_cmd", choices=["generate"])

    lk = add("locks", help="show longest-held lock acquisitions")
    lk.add_argument("--top", type=int, default=10)

    cl = add("cluster", help="cluster introspection")
    cl.add_argument("cluster_cmd", choices=["members"])

    add("reload", help="re-apply schema paths from config")

    t = add("template", help="render templates (--watch to follow)")
    t.add_argument("files", nargs="+", help="TEMPLATE[:OUTPUT] specs")
    t.add_argument("--watch", action="store_true")

    cs = add("consul", help="consul bridge")
    cs.add_argument("consul_cmd", choices=["sync"])

    # command/tls.rs:1-94: `corrosion tls {ca,server,client} generate`
    tl = add("tls", help="certificate generation")
    tl.add_argument("tls_kind", choices=["ca", "server", "client"])
    tl.add_argument("tls_cmd", choices=["generate"])
    tl.add_argument("host", nargs="?", default=None,
                    help="server SAN host (server generate)")
    tl.add_argument("--dir", default=".", help="output directory")
    tl.add_argument("--ca-dir", default=".",
                    help="directory holding ca_cert.pem/ca_key.pem")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    config_path = getattr(args, "config", None)
    cfg = Config.load(config_path) if config_path else Config.load()
    if getattr(args, "api_addr", None):
        cfg.api.addr = args.api_addr
    if getattr(args, "admin_path", None):
        cfg.admin.uds_path = args.admin_path
    try:
        return asyncio.run(_dispatch(args, cfg)) or 0
    except BrokenPipeError:
        return 0  # stdout closed early (e.g. piped into head)


async def _dispatch(args, cfg: Config) -> int:
    if args.command == "agent":
        return await _run_agent(cfg)
    if args.command == "query":
        return await _query(args, cfg)
    if args.command == "exec":
        return await _exec(args, cfg)
    if args.command == "backup":
        from corrosion_tpu.agent.backup import backup

        backup(args.db, args.out)
        print(f"backed up {args.db} -> {args.out}")
        return 0
    if args.command == "restore":
        if args.online:
            frames = await _admin(
                cfg,
                {"c": "restore", "path": os.path.abspath(args.backup),
                 "self_actor_id": args.self_actor_id},
            )
            print(f"restored online (actor {frames[0]['actor_id']})")
            return 0
        from corrosion_tpu.agent.backup import restore

        site = restore(args.backup, args.db, self_actor_id=args.self_actor_id)
        print(f"restored {args.db} (actor {site.hex()})")
        return 0
    if args.command == "tls":
        from corrosion_tpu.agent import tls as tls_mod

        if args.tls_kind == "ca":
            paths = tls_mod.generate_ca(args.dir)
        elif args.tls_kind == "server":
            if not args.host:
                print("tls server generate requires a host", file=sys.stderr)
                return 2
            paths = tls_mod.generate_server_cert(
                args.dir, args.ca_dir, args.host
            )
        else:
            paths = tls_mod.generate_client_cert(args.dir, args.ca_dir)
        print(f"wrote {paths.cert} and {paths.key}")
        return 0
    if args.command == "sync":
        frames = await _admin(cfg, {"c": "sync"})
        print(json.dumps(frames[0], indent=2))
        return 0
    if args.command == "locks":
        frames = await _admin(cfg, {"c": "locks", "top": args.top})
        print(json.dumps(frames[0]["locks"], indent=2))
        return 0
    if args.command == "cluster":
        frames = await _admin(cfg, {"c": "cluster"})
        print(json.dumps(frames[0]["members"], indent=2))
        return 0
    if args.command == "reload":
        frames = await _admin(
            cfg, {"c": "reload", "schema_sql": cfg.schema_sql()}
        )
        print(json.dumps(frames[0], indent=2))
        return 0
    if args.command == "template":
        from corrosion_tpu.tpl import run_templates

        await run_templates(args.files, cfg, watch=args.watch)
        return 0
    if args.command == "consul":
        from corrosion_tpu.integrations.consul import run_consul_sync

        await run_consul_sync(cfg)
        return 0
    return 2


async def _run_agent(cfg: Config) -> int:
    import os

    from corrosion_tpu.agent.agent import Agent, AgentConfig
    from corrosion_tpu.agent.subs import SubsManager
    from corrosion_tpu.utils.logfmt import setup_logging

    # Log format from config (LogFormat, config.rs:318-326).
    setup_logging(fmt=cfg.log.format, colors=cfg.log.colors)

    gossip_host, gossip_port = parse_addr(cfg.gossip.addr)
    api_host, api_port = parse_addr(cfg.api.addr)
    tls_cfg = None
    if not cfg.gossip.plaintext:
        # Fail closed: demanding TLS without cert material is a config
        # error, not a silent plaintext fallback.
        if not (cfg.gossip.tls_cert_file and cfg.gossip.tls_key_file):
            raise SystemExit(
                "gossip.plaintext = false requires tls_cert_file and "
                "tls_key_file ([gossip.tls] cert_file/key_file)"
            )
        from corrosion_tpu.agent.agent import AgentTls

        tls_cfg = AgentTls(
            cert=cfg.gossip.tls_cert_file,
            key=cfg.gossip.tls_key_file,
            ca=cfg.gossip.tls_ca_file,
            client_cert=cfg.gossip.tls_client_cert_file,
            client_key=cfg.gossip.tls_client_key_file,
            mtls=cfg.gossip.tls_mtls,
            insecure=cfg.gossip.tls_insecure,
        )
    elif cfg.gossip.tls_cert_file:
        raise SystemExit(
            "gossip TLS material configured but plaintext = true — set "
            "gossip.plaintext = false to enable TLS"
        )
    acfg = AgentConfig(
        data_dir=os.path.dirname(cfg.db.path) or ".",
        gossip_host=gossip_host,
        gossip_port=gossip_port,
        api_host=api_host,
        api_port=api_port,
        bootstrap=resolve_bootstrap(cfg.gossip.bootstrap),
        bootstrap_raw=list(cfg.gossip.bootstrap),
        schema_sql=cfg.schema_sql(),
        probe_interval=cfg.gossip.probe_interval_ms / 1000.0,
        sync_interval=cfg.gossip.sync_interval_ms / 1000.0,
        max_transmissions=cfg.gossip.max_transmissions,
        admin_uds=cfg.admin.uds_path,
        tls=tls_cfg,
        prometheus_addr=cfg.telemetry.prometheus_addr or "",
        otlp_endpoint=cfg.telemetry.otlp_endpoint or "",
    )
    agent = Agent(acfg)
    agent.subs = SubsManager(agent.store)
    await agent.start()
    from corrosion_tpu.utils.tripwire import Tripwire

    agent.tripwire = Tripwire.new_signals()
    # Through the logging stack, not print: the startup banner must honor
    # the configured log format (a JSON shipper chokes on bare text).
    logging.getLogger("corrosion_tpu.cli").info(
        "agent %s api=%s gossip=%s",
        agent.actor_id, agent.api_addr, agent.gossip_addr,
    )
    await agent.tripwire.wait()
    await agent.stop()
    return 0


async def _query(args, cfg: Config) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    import time

    t0 = time.monotonic()
    cols, rows = await client.query(args.sql)
    if args.columns:
        print("|".join(cols))
    for row in rows:
        print("|".join("" if v is None else str(v) for v in row))
    if args.timer:
        print(f"time: {time.monotonic() - t0:.6f}s", file=sys.stderr)
    return 0


async def _exec(args, cfg: Config) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    resp = await client.execute(list(args.sql))
    print(json.dumps(resp))
    return 0


async def _admin(cfg: Config, command: dict) -> list[dict]:
    from corrosion_tpu.agent.admin import AdminClient

    frames = await AdminClient(cfg.admin.uds_path).call(command)
    if not frames:
        raise SystemExit("admin: connection closed without a response")
    if "error" in frames[0]:
        raise SystemExit(f"admin: {frames[0]['error']}")
    return frames


if __name__ == "__main__":
    raise SystemExit(main())
