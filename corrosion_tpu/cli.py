"""corrosion CLI — the crates/corrosion binary's command surface.

Subcommands mirror corrosion/src/main.rs (Cli :447-513, Command :515-641):
agent, query, exec, backup, restore, sync generate, locks, cluster members,
reload, template. Run as `python -m corrosion_tpu ...`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys

from corrosion_tpu.agent.config import Config, parse_addr, resolve_bootstrap


def _build_parser() -> argparse.ArgumentParser:
    # Global flags accepted before OR after the subcommand (the reference's
    # clap marks them global). SUPPRESS defaults keep a subparser's parse
    # from overwriting a value given before the subcommand.
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument(
        "--config", "-c", default=argparse.SUPPRESS, help="TOML config path"
    )
    common.add_argument(
        "--api-addr", default=argparse.SUPPRESS,
        help="host:port of the HTTP API",
    )
    common.add_argument(
        "--admin-path", default=argparse.SUPPRESS,
        help="admin unix socket path",
    )
    p = argparse.ArgumentParser(
        prog="corrosion", description=__doc__, parents=[common]
    )
    sub = p.add_subparsers(dest="command", required=True)

    def add(name, **kw):
        return sub.add_parser(name, parents=[common], **kw)

    add("agent", help="run the agent until interrupted")

    q = add("query", help="run a read-only SQL statement")
    q.add_argument("sql")
    q.add_argument("--columns", action="store_true")
    q.add_argument("--timer", action="store_true")

    e = add("exec", help="run write statements in a transaction")
    e.add_argument("sql", nargs="+")
    e.add_argument("--timer", action="store_true")

    b = add("backup", help="snapshot the db (VACUUM INTO + strip)")
    b.add_argument("out")
    b.add_argument("--db", required=True)

    r = add("restore", help="swap a backup into place")
    r.add_argument("backup")
    r.add_argument("--db", required=True)
    r.add_argument(
        "--self-actor-id", action="store_true",
        help="keep the backup's actor identity instead of assigning fresh",
    )
    r.add_argument(
        "--online", action="store_true",
        help="restore into a RUNNING agent via the admin socket (SQLite "
        "file locks held during the swap)",
    )

    s = add("sync", help="sync protocol utilities")
    s.add_argument("sync_cmd", choices=["generate"])

    lk = add("locks", help="show longest-held lock acquisitions")
    lk.add_argument("--top", type=int, default=10)

    cl = add("cluster", help="cluster introspection")
    cl.add_argument("cluster_cmd", choices=["members"])

    add("reload", help="re-apply schema paths from config")

    t = add("template", help="render templates (--watch to follow)")
    t.add_argument("files", nargs="+", help="TEMPLATE[:OUTPUT] specs")
    t.add_argument("--watch", action="store_true")

    cs = add("consul", help="consul bridge")
    cs.add_argument("consul_cmd", choices=["sync"])

    # Kernel convergence observability (sim/health.py): turn a flight
    # recording into a protocol-health verdict, follow one live, diff
    # two runs for regressions, or record a small demo flight.
    ob = add("obs", help="kernel convergence observability")
    ob_sub = ob.add_subparsers(dest="obs_cmd", required=True)

    orp = ob_sub.add_parser(
        "report", parents=[common],
        help="derive a convergence report from a flight JSONL",
    )
    orp.add_argument("flight", help="flight-recorder JSONL path")
    orp.add_argument("--round-ms", type=float, default=500.0)
    orp.add_argument("--kill-round", type=int, action="append",
                     default=None, help="ground-truth churn kill round "
                     "(repeatable; refines detection latency)")
    orp.add_argument("--json", action="store_true")

    otl = ob_sub.add_parser(
        "tail", parents=[common],
        help="stream a flight record's progress (live with --follow)",
    )
    otl.add_argument("flight")
    otl.add_argument("--follow", "-f", action="store_true",
                     help="keep polling for new records (tail -f)")
    otl.add_argument("--rounds", action="store_true",
                     help="print every round record, not chunk summaries")
    otl.add_argument("--poll", type=float, default=0.25)
    otl.add_argument("--idle-timeout", type=float, default=None,
                     help="stop following after this many idle seconds")

    odf = ob_sub.add_parser(
        "diff", parents=[common],
        help="flag convergence regressions between two runs",
    )
    odf.add_argument("baseline", help="flight JSONL or report JSON")
    odf.add_argument("candidate", help="flight JSONL or report JSON")
    odf.add_argument("--tolerance", type=float, default=0.2,
                     help="relative regression tolerance (default 0.2)")
    odf.add_argument("--round-ms", type=float, default=500.0)
    odf.add_argument("--json", action="store_true")

    orc = ob_sub.add_parser(
        "record", parents=[common],
        help="record a small-cluster demo flight (CI artifact source)",
    )
    orc.add_argument("--out", default="flight.jsonl")
    orc.add_argument("--nodes", type=int, default=128)
    orc.add_argument("--rounds", type=int, default=64)
    orc.add_argument("--churn", action="store_true")
    orc.add_argument("--seed", type=int, default=0)
    orc.add_argument("--geo", action="store_true",
                     help="WAN variant: 4 regions on the synthetic "
                     "circle geography with the propagation-topology "
                     "plane enabled (the `obs epidemic` source)")
    orc.add_argument("--adaptive", action="store_true",
                     help="enable the adaptive-dissemination plane at "
                     "the committed health.ADAPTIVE_GOSSIP tuning "
                     "(geo only; the EPIDEMIC_BASELINE_ADAPTIVE.json "
                     "source — docs/PERFORMANCE.md)")

    # Propagation-topology plane (corrosion_tpu/obs/epidemic.py,
    # docs/OBSERVABILITY.md "Propagation plane"): SI-model fit over the
    # rumor-age coverage curve, traffic-matrix shares, redundancy, and
    # the EPIDEMIC_BASELINE diff gate.
    oep = ob_sub.add_parser(
        "epidemic", parents=[common],
        help="epidemic-model analyzer: fit/report/diff the "
        "corro-epidemic/1 propagation verdicts from a flight JSONL",
    )
    oep_sub = oep.add_subparsers(dest="epidemic_cmd", required=True)

    def _epi_common(p):
        p.add_argument("--fanout", type=int, default=4,
                       help="config fanout_near+fanout_far for the "
                       "push-gossip theory comparison (default 4)")
        p.add_argument("--nodes", type=int, default=None,
                       help="cluster size for the theoretical "
                       "half-coverage prediction")
        p.add_argument("--round-ms", type=float, default=500.0)
        p.add_argument("--geo-regions", type=int, default=None,
                       help="region count of the synthetic geo "
                       "geography (adds ring-resolved traffic shares)")

    oer = oep_sub.add_parser(
        "report", parents=[common],
        help="derive the corro-epidemic/1 report from a flight JSONL "
        "(exit 1 when the on-device accounting fails to reconcile)",
    )
    oer.add_argument("flight", help="flight-recorder JSONL path")
    _epi_common(oer)
    oer.add_argument("--oracle-records", default=None,
                     help="loadgen oracle delivery-records JSON: adds "
                     "the host-plane spread fit as a cross-validation "
                     "block (docs/FIDELITY.md)")
    oer.add_argument("--json", action="store_true")
    oer.add_argument("--out", default=None, help="report JSON path")

    oef = oep_sub.add_parser(
        "fit", parents=[common],
        help="print the SI/logit fit detail (per-bucket coverage "
        "points) for a flight JSONL",
    )
    oef.add_argument("flight")
    _epi_common(oef)
    oef.add_argument("--json", action="store_true")

    oed = oep_sub.add_parser(
        "diff", parents=[common],
        help="flag propagation regressions between two reports (or "
        "flights) — the EPIDEMIC_BASELINE CI gate",
    )
    oed.add_argument("baseline", help="flight JSONL or epidemic report")
    oed.add_argument("candidate", help="flight JSONL or epidemic report")
    oed.add_argument("--tolerance", type=float, default=0.25,
                     help="relative regression tolerance (default 0.25)")
    _epi_common(oed)
    oed.add_argument("--json", action="store_true")

    # Endurance plane (corrosion_tpu/obs/series.py + obs/endurance.py,
    # docs/OBSERVABILITY.md "Endurance plane"): leak/wedge/stall/SLO
    # detectors over a recorded corro-metric-series/1 JSONL, and the
    # SOAK_BASELINE diff gate.
    osk = ob_sub.add_parser(
        "soak", parents=[common],
        help="endurance analyzer: leak/wedge/stall/SLO verdicts from a "
        "corro-metric-series/1 record, and the SOAK_BASELINE diff gate",
    )
    osk_sub = osk.add_subparsers(dest="soak_cmd", required=True)

    osr = osk_sub.add_parser(
        "report", parents=[common],
        help="derive the corro-endurance/1 verdict from a metric-series "
        "JSONL (exit 1 on any leak/wedge/stall/SLO breach)",
    )
    osr.add_argument("series", help="corro-metric-series/1 JSONL path")
    osr.add_argument("--t-scale-s", type=float, default=1.0,
                     help="seconds per sample-t unit (1.0 for wall-clock "
                     "series; kernel series record t in rounds)")
    osr.add_argument("--label", default="",
                     help="label stamped into the report")
    osr.add_argument("--wedge-min-span-s", type=float, default=5.0,
                     help="min flat-while-offered span to call a wedge")
    osr.add_argument("--leak-ceiling", action="append", default=None,
                     metavar="NAME=PER_HOUR",
                     help="override a leak-slope ceiling (repeatable)")
    osr.add_argument("--json", action="store_true")
    osr.add_argument("--out", default=None, help="report JSON path")

    osd = osk_sub.add_parser(
        "diff", parents=[common],
        help="flag endurance regressions between two soak reports — "
        "the SOAK_BASELINE.json CI gate",
    )
    osd.add_argument("baseline", help="soak/endurance report JSON")
    osd.add_argument("candidate", help="soak/endurance report JSON")
    osd.add_argument("--tolerance", type=float, default=0.5,
                     help="relative leak-slope tolerance (default 0.5); "
                     "new breaches are never tolerated")
    osd.add_argument("--json", action="store_true")

    # Serving query-cost plane (corrosion_tpu/obs/serving.py,
    # docs/SERVING.md "Query-cost plane"): join a cost-armed storm's
    # per-subscription ledger with the fan-out oracle's delivery records
    # into the lag-vs-cost heatmap, and the SERVING_COST_BASELINE gate.
    osv = ob_sub.add_parser(
        "serving", parents=[common],
        help="serving query-cost analyzer: per-subscription lag-vs-cost "
        "attribution from a cost-armed loadgen run, and the "
        "SERVING_COST_BASELINE diff gate",
    )
    osv_sub = osv.add_subparsers(dest="serving_cmd", required=True)

    osvr = osv_sub.add_parser(
        "report", parents=[common],
        help="build the corro-serving-cost/1 heatmap report from a "
        "loadgen run emitted with --sub-costs (exit 1 when the ledger "
        "fails to reconcile against the oracle)",
    )
    osvr.add_argument("--from-run", required=True,
                      help="loadgen run report JSON produced with "
                      "sub_costs armed (reads run.sub_costs)")
    osvr.add_argument("--top", type=int, default=10,
                      help="top-K slow subscriptions to list")
    osvr.add_argument("--json", action="store_true")
    osvr.add_argument("--out", default=None, help="report JSON path")

    osvd = osv_sub.add_parser(
        "diff", parents=[common],
        help="flag serving-cost regressions between two "
        "corro-serving-cost/1 reports — the SERVING_COST_BASELINE.json "
        "CI gate",
    )
    osvd.add_argument("baseline", help="serving-cost report JSON")
    osvd.add_argument("candidate", help="serving-cost report JSON")
    osvd.add_argument("--tolerance", type=float, default=1.5,
                      help="multiplier on baseline eval/lag figures "
                      "(default 1.5)")
    osvd.add_argument("--floor-ms", type=float, default=5.0,
                      help="absolute floor under which deltas never "
                      "regress (loopback noise guard)")
    osvd.add_argument("--json", action="store_true")

    otm = ob_sub.add_parser(
        "timeline", parents=[common],
        help="correlate a traced loadgen run's spans + oracle delivery "
        "records (and optionally a kernel flight + write trace) into a "
        "corro-timeline/1 latency-budget artifact",
    )
    otm.add_argument("--from-run", default=None,
                     help="loadgen run report JSON produced with "
                     "--trace-dir (reads run.trace)")
    otm.add_argument("--spans", nargs="*", default=None,
                     help="span-export JSONL file(s) (with --records)")
    otm.add_argument("--records", default=None,
                     help="oracle delivery-records JSON (with --spans)")
    otm.add_argument("--sample", type=float, default=1.0,
                     help="trace sampling rate the run used (--spans "
                     "mode; --from-run reads it from the report)")
    otm.add_argument("--flight", default=None,
                     help="kernel flight JSONL for the write-journey "
                     "block (requires --trace)")
    otm.add_argument("--trace", default=None,
                     help="recorded write trace JSONL "
                     "(sim.trace.Trace.save; requires --flight)")
    otm.add_argument("--round-ms", type=float, default=500.0)
    otm.add_argument("--tolerance-ms", type=float, default=100.0,
                     help="stage-sum vs wall reconciliation tolerance")
    otm.add_argument("--min-coverage", type=float, default=0.99,
                     help="reconstructed/expected writes floor for "
                     "exit 0")
    otm.add_argument("--out", default=None)
    otm.add_argument("--json", action="store_true")

    # Device-cost observability plane (corrosion_tpu/obs/costs.py,
    # docs/PERFORMANCE.md "Cost model & roofline"): the XLA cost model
    # over every engine entry, the baseline diff gate, and the HBM
    # capacity curve.
    oct_ = ob_sub.add_parser(
        "cost", parents=[common],
        help="XLA cost model: show/diff the corro-cost-model/1 "
        "artifact, derive the corro-capacity/1 HBM curve",
    )
    oct_sub = oct_.add_subparsers(dest="cost_cmd", required=True)

    ocs = oct_sub.add_parser(
        "show", parents=[common],
        help="AOT-lower every engine plane entry and emit the "
        "corro-cost-model/1 artifact",
    )
    ocs.add_argument("--engines", default="dense,sparse,chunk,mixed")
    ocs.add_argument("--variants", default="plain,donated")
    ocs.add_argument("--devices", default="1,8",
                     help="comma-separated device counts (sets the "
                     "virtual CPU mesh flag itself when jax is not yet "
                     "initialized)")
    ocs.add_argument("--out", default=None,
                     help="artifact path (e.g. COST_BASELINE.json)")
    ocs.add_argument("--json", action="store_true")

    ocd = oct_sub.add_parser(
        "diff", parents=[common],
        help="rebuild the cost model at the baseline's dims and diff "
        "at tolerance — exit 1 on cost regressions",
    )
    ocd.add_argument("baseline", help="committed corro-cost-model/1 "
                     "JSON (COST_BASELINE.json)")
    ocd.add_argument("--tolerance", type=float, default=None,
                     help="relative-increase tolerance (default: the "
                     "baseline's, else 0.25)")
    ocd.add_argument("--out", default=None, help="diff report path")
    ocd.add_argument("--json", action="store_true")

    occ = oct_sub.add_parser(
        "capacity", parents=[common],
        help="predicted per-device HBM curve (corro-capacity/1), "
        "validated against the measured 512-node and 100k points",
    )
    occ.add_argument("--nodes", default=None,
                     help="comma-separated node counts (default: the "
                     "100k..1M flagship grid)")
    occ.add_argument("--devices", type=int, default=8)
    occ.add_argument("--hbm-gib", type=float, default=16.0,
                     help="per-device HBM budget (default: v5e 16 GiB)")
    occ.add_argument("--no-validate", action="store_true",
                     help="skip the live 512-node validation point")
    occ.add_argument("--out", default=None)
    occ.add_argument("--json", action="store_true")

    # Bench trajectory (corrosion_tpu/obs/trajectory.py): the committed
    # BENCH_r*/MULTICHIP_r* artifacts as one provenance-checked series.
    otj = ob_sub.add_parser(
        "trajectory", parents=[common],
        help="aggregate committed BENCH_r*/MULTICHIP_r* artifacts into "
        "a provenance-checked trajectory (refuses cross-platform/"
        "kernel deltas)",
    )
    otj.add_argument("--root", default=".",
                     help="directory holding the artifacts")
    otj.add_argument("--out", default=None)
    otj.add_argument("--json", action="store_true")

    # Chaos plane (sim/faults.py + sim/invariants.py, docs/CHAOS.md):
    # declarative fault injection, post-heal invariant checking, and a
    # seeded fuzzer that shrinks failing plans to minimal JSON repros.
    ch = add("chaos", help="fault injection + post-heal invariant suite")
    ch_sub = ch.add_subparsers(dest="chaos_cmd", required=True)

    cls_ = ch_sub.add_parser(
        "list", parents=[common], help="list the named fault scenarios"
    )
    cls_.add_argument("--rounds", type=int, default=64)

    crn = ch_sub.add_parser(
        "run", parents=[common],
        help="run a named scenario (or a fault-plan JSON) through the "
        "invariant suite",
    )
    crn.add_argument("scenario",
                     help="scenario name (chaos list) or plan JSON path")
    crn.add_argument("--engines", default="dense,sparse,chunk,mixed")
    crn.add_argument("--rounds", type=int, default=64,
                     help="run length for named scenarios")
    crn.add_argument("--seed", type=int, default=0)
    crn.add_argument("--json", action="store_true")

    cfz = ch_sub.add_parser(
        "fuzz", parents=[common],
        help="seeded random fault plans through the invariant suite, "
        "shrinking failures to minimal repros",
    )
    cfz.add_argument("--seed", type=int, default=0)
    cfz.add_argument("--plans", type=int, default=4)
    cfz.add_argument("--engines", default="dense,sparse,chunk,mixed")
    cfz.add_argument("--rounds", type=int, default=64)
    cfz.add_argument("--out", default=None,
                     help="directory for minimal-repro JSON artifacts")
    cfz.add_argument("--broken", action="store_true",
                     help="generate deliberately NON-healing plans (the "
                     "suite must fail and shrink them — chaos self-test)")
    cfz.add_argument("--no-wipe", action="store_true",
                     help="churn components use pause-resume only")
    cfz.add_argument("--shrink-evals", type=int, default=24)
    cfz.add_argument("--json", action="store_true")

    crp = ch_sub.add_parser(
        "replay", parents=[common],
        help="re-run a shrunk repro artifact's plan on its engine",
    )
    crp.add_argument("repro", help="chaos repro JSON path")

    # Host chaos plane (corrosion_tpu/hostchaos + agent/netem.py,
    # docs/CHAOS.md "Host plane"): deterministic WAN impairment against
    # real agents, crash/restart scenarios, post-heal invariants, and
    # mechanical machinery-fired assertions.
    hc = add("hostchaos", help="host-plane chaos: WAN fault injection, "
             "crash/restart, machinery-fired proof")
    hc_sub = hc.add_subparsers(dest="hostchaos_cmd", required=True)

    hcl = hc_sub.add_parser(
        "list", parents=[common], help="list the standing host scenarios"
    )
    hcl.add_argument("--json", action="store_true")

    hcr = hc_sub.add_parser(
        "run", parents=[common],
        help="run a standing scenario (real loopback agents + netem + "
        "oracle + post-heal invariants); exit 1 on any failure",
    )
    hcr.add_argument("scenario", help="scenario name (hostchaos list)")
    hcr.add_argument("--seed", type=int, default=0)
    hcr.add_argument("--dir", default=None,
                     help="data dir (default: a fresh tempdir)")
    hcr.add_argument("--out", default=None, help="report JSON path")
    hcr.add_argument("--json", action="store_true")

    hcp = hc_sub.add_parser(
        "replay", parents=[common],
        help="verify a report's impairment schedule replays identically "
        "from its (plan, seed) — the determinism contract",
    )
    hcp.add_argument("report", help="hostchaos run report JSON path")

    # Elastic survival plane (corrosion_tpu/elastic, docs/SCALING.md
    # "Elastic ops"): live mesh resharding + device-shard preemption,
    # convergence pinned bit-identical.
    el = add("elastic", help="elastic survival plane: live mesh reshard "
             "+ device-shard preemption, pinned bit-identical")
    el_sub = el.add_subparsers(dest="elastic_cmd", required=True)

    ell = el_sub.add_parser(
        "list", parents=[common], help="list the standing elastic drills"
    )
    ell.add_argument("--json", action="store_true")

    elr = el_sub.add_parser(
        "run", parents=[common],
        help="run one elastic drill (elastic list); exit 1 on any "
        "divergence, oracle violation, or idle recovery machinery",
    )
    elr.add_argument("scenario", help="drill name (elastic list)")
    elr.add_argument("--seed", type=int, default=0)
    elr.add_argument("--checkpoint-dir", default=None,
                     help="round-trip checkpoints through disk here "
                     "(default: in-memory only)")
    elr.add_argument("--out", default=None, help="report JSON path")
    elr.add_argument("--json", action="store_true")

    elm = el_sub.add_parser(
        "matrix", parents=[common],
        help="run the full dense reshard matrix "
        "(4→8, 8→4, 8→2, 1→8) plus one drill per "
        "other engine",
    )
    elm.add_argument("--seed", type=int, default=0)
    elm.add_argument("--out", default=None, help="report JSON path")
    elm.add_argument("--json", action="store_true")

    # Static-analysis plane (corrosion_tpu/analysis, docs/ANALYSIS.md):
    # kernel-purity + schema-parity + concurrency lints, and the
    # strict-dtype/debug-nans/retrace sanitizer.
    ln = add("lint", help="static analysis: kernel purity, telemetry "
             "schema parity, lock discipline")
    ln.add_argument("paths", nargs="*", default=None,
                    help="files or trees to lint (default: the "
                    "corrosion_tpu package)")
    ln.add_argument("--format", choices=["text", "json"], default="text")
    ln.add_argument("--rules", default=None,
                    help="comma-separated CT0xx ids to run (default all)")
    ln.add_argument("--sanitize", action="store_true",
                    help="also run tiny engine instances under strict "
                    "dtype promotion + debug_nans + retrace tripwire")
    ln.add_argument("--engines", default="dense,sparse,chunk,mixed",
                    help="engines for --sanitize")
    ln.add_argument("--no-static", action="store_true",
                    help="skip the static rules (with --sanitize: "
                    "sanitizer only)")
    ln.add_argument("--show-suppressed", action="store_true",
                    help="list reason-suppressed findings and stale "
                    "(CT009) suppressions in text output (JSON always "
                    "carries them)")
    ln.add_argument("--changed", nargs="?", const="HEAD~1", default=None,
                    metavar="REF",
                    help="lint only files changed vs a git ref "
                    "(default HEAD~1) — fast local/pre-push runs; exit "
                    "codes unchanged")
    ln.add_argument("--update-seams", action="store_true",
                    help="regenerate analysis/SEAM_MAP.json seam "
                    "fragments from the live engine diff (keeps whys of "
                    "seams that still match; fill in the TODO whys "
                    "before committing)")
    ln.add_argument("--list-rules", action="store_true")

    # Serving-plane load subsystem (corrosion_tpu/loadgen, docs/SERVING.md):
    # open-loop load generation against a self-launched in-process agent
    # cluster, with the fan-out correctness oracle.
    lg = add("loadgen", help="serving-plane load generator + fan-out oracle")
    lg_sub = lg.add_subparsers(dest="loadgen_cmd", required=True)

    lgr = lg_sub.add_parser(
        "run", parents=[common],
        help="subscription fan-out storm + sustained write storm "
        "(oracle-checked)",
    )
    lgr.add_argument("--subs", type=int, default=2000)
    lgr.add_argument("--sub-groups", type=int, default=4)
    lgr.add_argument("--writes", type=int, default=80)
    lgr.add_argument("--write-rate", type=float, default=10.0,
                     help="open-loop write arrivals/s (each commit fans "
                     "out to subs/groups streams — size rate x subs to "
                     "the harness host)")
    lgr.add_argument("--read-rate", type=float, default=20.0)
    lgr.add_argument("--pg-rate", type=float, default=10.0)
    lgr.add_argument("--agents", type=int, default=1)
    lgr.add_argument("--drain-timeout", type=float, default=30.0)
    lgr.add_argument("--dir", default=None,
                     help="data dir (default: a fresh tempdir)")
    lgr.add_argument("--out", default=None, help="report JSON path")
    lgr.add_argument("--trace-dir", default=None,
                     help="enable causal write tracing; span exports + "
                     "oracle delivery records land here and the report "
                     "gains the run.trace block `obs timeline` consumes")
    lgr.add_argument("--trace-sample", type=float, default=1.0,
                     help="trace-id-keyed sampling rate for traced runs")

    lgs = lg_sub.add_parser(
        "sweep", parents=[common],
        help="saturation sweep: ramp arrivals past api_concurrency, "
        "verify 503 shed + bounded admitted p99",
    )
    lgs.add_argument("--rates", default="50,200,400",
                     help="comma-separated stage arrival rates (Hz)")
    lgs.add_argument("--stage-duration", type=float, default=2.0)
    lgs.add_argument("--api-concurrency", type=int, default=4)
    lgs.add_argument("--burst", type=int, default=16,
                     help="top-stage arrivals packed per instant "
                     "(> api_concurrency forces shed engagement)")
    lgs.add_argument("--bounded-p99-ms", type=float, default=5000.0)
    lgs.add_argument("--dir", default=None)
    lgs.add_argument("--out", default=None)

    lgk = lg_sub.add_parser(
        "soak", parents=[common],
        help="intake-policy soak: measure the docs/SCALING.md "
        "rebroadcast_intake collapse rule on the kernel plane",
    )
    lgk.add_argument("--nodes", type=int, default=96)
    lgk.add_argument("--rounds", type=int, default=72)
    lgk.add_argument("--write-prob", type=float, default=0.08)
    lgk.add_argument("--intake-margin", type=int, default=8)
    lgk.add_argument("--starved-intake", type=int, default=1)
    lgk.add_argument("--seed", type=int, default=0)
    lgk.add_argument("--out", default=None)
    lgk.add_argument(
        "--series-out", default=None,
        help="keep the corro-metric-series/1 process record at this "
        "path (feedable to `obs soak report`)",
    )

    # Fidelity plane (corrosion_tpu/fidelity, docs/FIDELITY.md): the
    # calibrated round-length model and the mixed-mode live-vs-kernel
    # divergence measurement.
    fd = add("fidelity", help="calibrated round model + live-vs-kernel "
             "divergence measurement")
    fd_sub = fd.add_subparsers(dest="fidelity_cmd", required=True)

    fdc = fd_sub.add_parser(
        "calibrate", parents=[common],
        help="derive a corro-round-model/1 JSON from a live loopback "
        "cluster (or a transport-characterization artifact)",
    )
    fdc.add_argument("--out", default="round_model.json")
    fdc.add_argument("--agents", type=int, default=3)
    fdc.add_argument("--probes", type=int, default=40,
                     help="SWIM probe samples per directed agent pair")
    fdc.add_argument("--dir", default=None,
                     help="data dir (default: a fresh tempdir)")
    fdc.add_argument("--from-characterization", default=None,
                     help="derive from a transport_characterization JSON "
                     "artifact instead of launching agents")
    fdc.add_argument("--flush-ms", type=float, default=None,
                     help="broadcast flush tick for "
                     "--from-characterization (default: the reference's "
                     "500 ms)")

    fdm = fd_sub.add_parser(
        "compare", parents=[common],
        help="run the standing scenarios live AND as kernel replays; "
        "report calibrated-vs-uncalibrated divergence",
    )
    fdm.add_argument("--scenario", default="all",
                     choices=["steady", "burst", "dcn", "all"])
    fdm.add_argument("--agents", type=int, default=3)
    fdm.add_argument("--writes", type=int, default=24)
    fdm.add_argument("--dcn-rounds", type=int, default=64)
    fdm.add_argument("--model", default=None,
                     help="pre-built round-model JSON for the MIXED-MODE "
                     "scenarios (steady/burst; default: calibrate inline "
                     "on the launched cluster). The dcn scenario always "
                     "uses the synthetic WAN ring model — loopback "
                     "calibrations have no WAN geography to offer it")
    fdm.add_argument("--seed", type=int, default=0)
    fdm.add_argument("--dir", default=None)
    fdm.add_argument("--out", default=None, help="report JSON path")

    fdr = fd_sub.add_parser(
        "replay", parents=[common],
        help="replay a saved trace JSONL through the kernel under a "
        "round model",
    )
    fdr.add_argument("trace", help="trace JSONL (sim.trace.Trace.save)")
    fdr.add_argument("--model", default=None,
                     help="round-model JSON (default: the uncalibrated "
                     "500 ms identity)")
    fdr.add_argument("--observers", type=int, default=0)
    fdr.add_argument("--seed", type=int, default=0)
    fdr.add_argument("--json", action="store_true")

    # command/tls.rs:1-94: `corrosion tls {ca,server,client} generate`
    tl = add("tls", help="certificate generation")
    tl.add_argument("tls_kind", choices=["ca", "server", "client"])
    tl.add_argument("tls_cmd", choices=["generate"])
    tl.add_argument("host", nargs="?", default=None,
                    help="server SAN host (server generate)")
    tl.add_argument("--dir", default=".", help="output directory")
    tl.add_argument("--ca-dir", default=".",
                    help="directory holding ca_cert.pem/ca_key.pem")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    config_path = getattr(args, "config", None)
    cfg = Config.load(config_path) if config_path else Config.load()
    if getattr(args, "api_addr", None):
        cfg.api.addr = args.api_addr
    if getattr(args, "admin_path", None):
        cfg.admin.uds_path = args.admin_path
    try:
        return asyncio.run(_dispatch(args, cfg)) or 0
    except BrokenPipeError:
        return 0  # stdout closed early (e.g. piped into head)


async def _dispatch(args, cfg: Config) -> int:
    if args.command == "lint":
        return _lint(args)
    if args.command == "obs":
        return _obs(args)
    if args.command == "chaos":
        return _chaos(args)
    if args.command == "hostchaos":
        return await _hostchaos(args)
    if args.command == "elastic":
        return _elastic(args)
    if args.command == "loadgen":
        return await _loadgen(args)
    if args.command == "fidelity":
        return await _fidelity(args)
    if args.command == "agent":
        return await _run_agent(cfg)
    if args.command == "query":
        return await _query(args, cfg)
    if args.command == "exec":
        return await _exec(args, cfg)
    if args.command == "backup":
        from corrosion_tpu.agent.backup import backup

        backup(args.db, args.out)
        print(f"backed up {args.db} -> {args.out}")
        return 0
    if args.command == "restore":
        if args.online:
            frames = await _admin(
                cfg,
                {"c": "restore", "path": os.path.abspath(args.backup),
                 "self_actor_id": args.self_actor_id},
            )
            print(f"restored online (actor {frames[0]['actor_id']})")
            return 0
        from corrosion_tpu.agent.backup import restore

        site = restore(args.backup, args.db, self_actor_id=args.self_actor_id)
        print(f"restored {args.db} (actor {site.hex()})")
        return 0
    if args.command == "tls":
        from corrosion_tpu.agent import tls as tls_mod

        if args.tls_kind == "ca":
            paths = tls_mod.generate_ca(args.dir)
        elif args.tls_kind == "server":
            if not args.host:
                print("tls server generate requires a host", file=sys.stderr)
                return 2
            paths = tls_mod.generate_server_cert(
                args.dir, args.ca_dir, args.host
            )
        else:
            paths = tls_mod.generate_client_cert(args.dir, args.ca_dir)
        print(f"wrote {paths.cert} and {paths.key}")
        return 0
    if args.command == "sync":
        frames = await _admin(cfg, {"c": "sync"})
        print(json.dumps(frames[0], indent=2))
        return 0
    if args.command == "locks":
        frames = await _admin(cfg, {"c": "locks", "top": args.top})
        print(json.dumps(frames[0]["locks"], indent=2))
        return 0
    if args.command == "cluster":
        frames = await _admin(cfg, {"c": "cluster"})
        print(json.dumps(frames[0]["members"], indent=2))
        return 0
    if args.command == "reload":
        frames = await _admin(
            cfg, {"c": "reload", "schema_sql": cfg.schema_sql()}
        )
        print(json.dumps(frames[0], indent=2))
        return 0
    if args.command == "template":
        from corrosion_tpu.tpl import run_templates

        await run_templates(args.files, cfg, watch=args.watch)
        return 0
    if args.command == "consul":
        from corrosion_tpu.integrations.consul import run_consul_sync

        await run_consul_sync(cfg)
        return 0
    return 2


async def _hostchaos(args) -> int:
    """`corrosion hostchaos {list,run,replay}` — the host chaos plane
    (docs/CHAOS.md "Host plane"). Exit 0 = green, 1 = a failed
    invariant / idle machinery / schedule mismatch."""
    import tempfile

    from corrosion_tpu.hostchaos import SCENARIOS, get_scenario, run_scenario
    from corrosion_tpu.hostchaos.harness import verify_schedule_determinism

    if args.hostchaos_cmd == "list":
        if args.json:
            print(json.dumps({
                name: {
                    "summary": SCENARIOS[name]().summary(),
                    "notes": SCENARIOS[name]().notes,
                }
                for name in sorted(SCENARIOS)
            }, indent=2))
            return 0
        for name in sorted(SCENARIOS):
            spec = SCENARIOS[name]()
            print(f"{name:16s} {spec.summary()}")
            print(f"{'':16s}   {spec.notes}")
        return 0

    if args.hostchaos_cmd == "run":
        try:
            spec = get_scenario(args.scenario)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        if args.dir:
            report = await run_scenario(
                spec, args.dir, seed=args.seed, progress=sys.stderr
            )
        else:
            with tempfile.TemporaryDirectory() as tmp:
                report = await run_scenario(
                    spec, tmp, seed=args.seed, progress=sys.stderr
                )
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
            print(f"wrote {args.out}", file=sys.stderr)
        if args.json:
            slim = dict(report)
            if slim.get("netem"):
                slim["netem"] = {
                    "seed": slim["netem"]["seed"],
                    "agents": {
                        k: {kk: vv for kk, vv in v.items() if kk != "trace"}
                        for k, v in slim["netem"]["agents"].items()
                    },
                }
            print(json.dumps(slim, indent=1))
        else:
            print(
                f"{report['scenario']}: "
                f"{'OK' if report['ok'] else 'FAILED'} — "
                f"oracle violations={report['oracle']['violations']}, "
                f"converged={report['converged']}, "
                f"machinery={report['machinery']}"
            )
            for f_ in report["failures"]:
                print(f"  FAIL: {f_}")
        return 0 if report["ok"] else 1

    if args.hostchaos_cmd == "replay":
        with open(args.report) as f:
            report = json.load(f)
        ok, problems = verify_schedule_determinism(report)
        if ok:
            agents = sorted((report.get("netem") or {})
                            .get("agents", {}))
            print(
                f"schedule replay OK: seed {report.get('seed')} "
                f"reproduces every recorded decision on {agents}"
            )
            return 0
        print("schedule replay MISMATCH:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    return 2


def _elastic(args) -> int:
    """`corrosion elastic {list,run,matrix}` — the elastic survival
    plane (docs/SCALING.md "Elastic ops"). Exit 0 = every drill pinned
    bit-identical with its oracles green, 1 = divergence / oracle
    violation / idle recovery machinery, 2 = usage."""
    from corrosion_tpu.elastic import scenarios as el_scenarios

    def _summary(rep: dict) -> str:
        extra = ""
        if rep.get("machinery") is not None:
            m = rep["machinery"]
            extra = (
                f", machinery fired={m['fired']} "
                f"(replayed {m['gap_rounds_replayed']} rounds)"
            )
        return (
            f"{rep['scenario']}: {'OK' if rep['ok'] else 'FAILED'} — "
            f"bit_identical={rep['bit_identical']}, "
            f"reconcile={'ok' if (rep.get('reconcile') or {}).get('ok') else 'FAILED'}, "
            f"violations={len(rep.get('violations') or [])}{extra}"
        )

    if args.elastic_cmd == "list":
        names = el_scenarios.scenario_names()
        if args.json:
            print(json.dumps(names, indent=1))
        else:
            for n in names:
                print(n)
        return 0

    if args.elastic_cmd == "run":
        try:
            rep = el_scenarios.run_scenario(
                args.scenario, seed=args.seed,
                checkpoint_dir=args.checkpoint_dir,
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rep, f, indent=1, default=str)
            print(f"wrote {args.out}", file=sys.stderr)
        if args.json:
            print(json.dumps(rep, indent=1, default=str))
        else:
            print(_summary(rep))
            for m in rep.get("mismatches") or []:
                print(f"  DIVERGED: {m}")
            for v in rep.get("violations") or []:
                print(f"  FAIL: {v}")
        return 0 if rep["ok"] else 1

    if args.elastic_cmd == "matrix":
        reps = []
        for a, b in el_scenarios.RESHARD_MATRIX:
            reps.append(el_scenarios.run_reshard_scenario(
                "dense", a, b, seed=args.seed
            ))
        for eng in el_scenarios.RESHARD_ENGINES:
            if eng != "dense":
                reps.append(el_scenarios.run_reshard_scenario(
                    eng, 4, 8, seed=args.seed
                ))
        out = {
            "schema": el_scenarios.ELASTIC_SCHEMA,
            "kind": "matrix",
            "scenarios": reps,
            "ok": all(r["ok"] for r in reps),
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1, default=str)
            print(f"wrote {args.out}", file=sys.stderr)
        if args.json:
            print(json.dumps(out, indent=1, default=str))
        else:
            for r in reps:
                print(_summary(r))
        return 0 if out["ok"] else 1
    return 2


def _lint(args) -> int:
    """`corrosion lint [paths] [--sanitize]` — the static-analysis plane
    (corrosion_tpu/analysis, rules in docs/ANALYSIS.md). Pure lint never
    imports jax; --sanitize pulls in the engines lazily. Exit 0 = clean,
    1 = findings, 2 = usage."""
    from corrosion_tpu.analysis import RULES, lint_paths
    from corrosion_tpu.analysis.findings import LintResult

    if args.list_rules:
        for rid, (title, why) in sorted(RULES.items()):
            print(f"{rid}  {title}: {why}")
        return 0
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}", file=sys.stderr)
            return 2
    if args.update_seams:
        from corrosion_tpu.analysis import clonemap
        from corrosion_tpu.analysis.runner import default_seam_root

        map_path = clonemap.default_seam_map_path()
        try:
            smap = clonemap.load_seam_map(map_path)
        except (OSError, ValueError) as e:
            print(f"seam map: {e}", file=sys.stderr)
            return 2
        refreshed, fresh = clonemap.refresh_seams(smap, default_seam_root())
        clonemap.save_seam_map(refreshed, map_path)
        print(f"{map_path}: seams regenerated, {fresh} new seam(s) need "
              "a why filled in" if fresh else
              f"{map_path}: seams regenerated, all declared whys kept")
        return 0
    paths = args.paths or [os.path.dirname(os.path.abspath(__file__))]
    only = None
    if args.changed is not None:
        from corrosion_tpu.analysis.runner import changed_files

        try:
            only = changed_files(args.changed, cwd=paths[0]
                                 if os.path.isdir(paths[0]) else None)
        except RuntimeError as e:
            print(f"--changed: {e}", file=sys.stderr)
            return 2
    if args.no_static:
        result = LintResult()
    else:
        result = lint_paths(paths, rules=rules, only=only)
    if args.sanitize:
        from corrosion_tpu.analysis.sanitize import ENGINES, sanitize_engines

        engines = tuple(
            e.strip() for e in args.engines.split(",") if e.strip()
        )
        unknown = set(engines) - set(ENGINES)
        if unknown:
            print(f"unknown engine(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        result.findings.extend(sanitize_engines(engines))
    if args.format == "json":
        print(result.to_json())
    else:
        print(result.render_text(show_suppressed=args.show_suppressed))
    return 0 if result.ok else 1


def _chaos(args) -> int:
    """`corrosion chaos {list,run,fuzz,replay}` — the chaos plane's CLI
    (docs/CHAOS.md). Exit 0 = every invariant held, 1 = violations (a
    shrunk repro is written/printed), 2 = usage."""
    from corrosion_tpu.sim import faults as faults_mod
    from corrosion_tpu.sim import invariants as inv

    if args.chaos_cmd == "list":
        try:
            plans = faults_mod.named_scenarios(
                args.rounds, inv.STD_REGIONS, inv.STD_NODES,
                protect=inv.PROTECTED,
            )
        except ValueError as e:
            print(f"chaos list: {e}", file=sys.stderr)
            return 2
        for name in sorted(plans):
            print(f"{name:18} {plans[name].describe()}")
        return 0

    if args.chaos_cmd in ("run", "fuzz"):
        engines = tuple(
            e.strip() for e in args.engines.split(",") if e.strip()
        )
        unknown = set(engines) - set(inv.ENGINES)
        if unknown:
            print(f"unknown engine(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    if args.chaos_cmd == "run":
        # Bad inputs are usage errors (exit 2), not tracebacks: a
        # malformed plan file, a plan exceeding the standard scenario's
        # shape, or rounds the scenario catalog rejects.
        try:
            if os.path.exists(args.scenario):
                with open(args.scenario) as f:
                    d = json.load(f)
                # A repro artifact carries its plan; a plan file IS one.
                plan = faults_mod.FaultPlan.from_dict(d.get("plan", d))
            else:
                plans = faults_mod.named_scenarios(
                    args.rounds, inv.STD_REGIONS, inv.STD_NODES,
                    protect=inv.PROTECTED,
                )
                if args.scenario not in plans:
                    print(
                        f"unknown scenario {args.scenario!r}; `chaos list` "
                        f"names them", file=sys.stderr,
                    )
                    return 2
                plan = plans[args.scenario]
            if plan.max_region() >= inv.STD_REGIONS:
                raise ValueError(
                    f"plan references region {plan.max_region()} but the "
                    f"standard scenario has {inv.STD_REGIONS} regions"
                )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            print(f"chaos run: invalid plan/scenario: {e!r}", file=sys.stderr)
            return 2
        reports = inv.run_suite(
            plan, engines, seed=args.seed, progress=sys.stderr
        )
        if args.json:
            print(json.dumps([r.to_dict() for r in reports]))
        else:
            for r in reports:
                print(r.render())
        return 0 if all(r.ok for r in reports) else 1

    if args.chaos_cmd == "fuzz":
        out = inv.fuzz(
            seed=args.seed, plans=args.plans, engines=engines,
            rounds=args.rounds, out_dir=args.out,
            break_heal=args.broken, allow_wipe=not args.no_wipe,
            shrink_evals=args.shrink_evals, progress=sys.stderr,
        )
        if args.json:
            print(json.dumps(out))
        else:
            for i, entry in enumerate(out["plans"]):
                mark = "ok" if entry["ok"] else "FAIL"
                print(f"plan {i}: [{mark}] {entry['describe']}")
                if not entry["ok"]:
                    repro = entry.get("repro", {})
                    mini = faults_mod.FaultPlan.from_dict(
                        repro.get("plan", entry["plan"])
                    )
                    print(f"  shrunk repro: {mini.describe()}")
                    for v in repro.get("violations", []):
                        print(f"  violation: {v}")
                    if "repro_path" in entry:
                        print(f"  artifact: {entry['repro_path']}")
            print(
                f"{args.plans - out['failures']}/{args.plans} plans passed "
                f"on engines {','.join(engines)}"
            )
        return 1 if out["failures"] else 0

    if args.chaos_cmd == "replay":
        try:
            rep = inv.replay_repro(args.repro, progress=sys.stderr)
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            print(f"chaos replay: {e!r}", file=sys.stderr)
            return 2
        print(rep.render())
        return 0 if rep.ok else 1
    return 2


async def _loadgen(args) -> int:
    """`corrosion loadgen {run,sweep,soak}` — the serving-plane load
    subsystem (docs/SERVING.md). Every report funnels through the
    self-describing emit path; exit 0 = the scenario's promise held
    (zero oracle violations / shed engaged + p99 bounded / collapse
    rule demonstrated), 1 = it did not."""
    import tempfile

    from corrosion_tpu.loadgen import scenarios
    from corrosion_tpu.loadgen.report import (
        emit_serving_report, serving_context,
    )

    def emit(report: dict, ok: bool) -> int:
        emit_serving_report(report)
        out = json.dumps(report, indent=2)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
        print(out)
        return 0 if ok else 1

    if args.loadgen_cmd == "run":
        with tempfile.TemporaryDirectory() as tmp:
            run = await scenarios.fanout_storm(
                args.dir or tmp,
                subs=args.subs, sub_groups=args.sub_groups,
                writes=args.writes, write_rate=args.write_rate,
                read_rate=args.read_rate, pg_rate=args.pg_rate,
                n_agents=args.agents, drain_timeout_s=args.drain_timeout,
                trace_dir=args.trace_dir,
                trace_sample=args.trace_sample,
                progress=sys.stderr,
            )
        report = {
            **serving_context(
                "fanout_storm", args.agents, args.subs, args.writes,
                args.write_rate,
            ),
            "subs": args.subs,
            "run": run,
        }
        # Zero violations is vacuous if nothing committed or delivered:
        # a fully broken write path must not exit 0.
        ok = (
            run["oracle"]["violations"] == 0
            and run["oracle"]["commits"] > 0
            and run["oracle"]["delivered_changes"]
            + run["oracle"]["delivered_snapshot"] > 0
        )
        return emit(report, ok)

    if args.loadgen_cmd == "sweep":
        rates = tuple(
            float(r) for r in args.rates.split(",") if r.strip()
        )
        with tempfile.TemporaryDirectory() as tmp:
            sweep = await scenarios.saturation_sweep(
                args.dir or tmp,
                api_concurrency=args.api_concurrency, rates=rates,
                stage_duration_s=args.stage_duration, burst=args.burst,
                bounded_p99_ms=args.bounded_p99_ms, progress=sys.stderr,
            )
        report = {
            **serving_context(
                "saturation_sweep", 1, args.api_concurrency, rates,
                args.burst,
            ),
            "sweep": sweep,
        }
        ok = (
            sweep["shed_engaged"]
            and sweep["admitted_p99_bounded"]
            and sweep["shed_accounting_consistent"]
        )
        return emit(report, ok)

    if args.loadgen_cmd == "soak":
        soak = scenarios.intake_policy(
            nodes=args.nodes, rounds=args.rounds,
            write_prob=args.write_prob,
            intake_margin=args.intake_margin,
            starved_intake=args.starved_intake, seed=args.seed,
            progress=sys.stderr, series_path=args.series_out,
        )
        report = {
            **serving_context(
                "intake_policy", args.nodes, args.rounds,
                args.write_prob, args.seed,
            ),
            "soak": soak,
        }
        return emit(report, soak["collapse_rule_holds"])
    return 2


async def _fidelity(args) -> int:
    """`corrosion fidelity {calibrate,compare,replay}` — the fidelity
    plane's CLI (docs/FIDELITY.md). `compare` exits 0 iff every
    mixed-mode scenario's calibrated replay lands strictly closer to the
    live CDF than the uncalibrated one AND the DCN invariant cross-check
    holds; 1 otherwise; 2 = usage."""
    import tempfile

    from corrosion_tpu.fidelity.calibrate import (
        REFERENCE_ROUND_MS, RoundModel, calibrate_live,
        from_characterization,
    )

    if args.fidelity_cmd == "calibrate":
        if args.from_characterization:
            try:
                with open(args.from_characterization) as f:
                    char = json.load(f)
                model = from_characterization(
                    char,
                    # `is None`, not `or`: an explicit --flush-ms 0 must
                    # reach derive_model's loud positivity check, never
                    # silently become the 500 ms default.
                    flush_ms=(
                        args.flush_ms if args.flush_ms is not None
                        else REFERENCE_ROUND_MS
                    ),
                )
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"fidelity calibrate: {e!r}", file=sys.stderr)
                return 2
        else:
            if args.flush_ms is not None:
                print(
                    "fidelity calibrate: --flush-ms only applies with "
                    "--from-characterization (live calibration reads the "
                    "launched agents' configured tick)", file=sys.stderr,
                )
                return 2
            from corrosion_tpu.agent.testing import (
                launch_test_cluster, stop_cluster,
            )

            with tempfile.TemporaryDirectory() as tmp:
                agents = await launch_test_cluster(
                    args.dir or tmp, args.agents
                )
                try:
                    model = await calibrate_live(agents, probes=args.probes)
                finally:
                    await stop_cluster(agents)
        model.save(args.out)
        print(f"wrote {args.out}: {model.describe()}")
        return 0

    if args.fidelity_cmd == "compare":
        from corrosion_tpu.fidelity import scenarios as fid_scenarios
        from corrosion_tpu.fidelity.report import emit_fidelity_report

        try:
            model = RoundModel.load(args.model) if args.model else None
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"fidelity compare: bad --model: {e!r}", file=sys.stderr)
            return 2
        out: dict = {"scenarios": {}}
        with tempfile.TemporaryDirectory() as tmp:
            base = args.dir or tmp
            if args.scenario in ("steady", "all"):
                out["scenarios"]["steady"] = await fid_scenarios.steady_load(
                    base, writes=args.writes, n_agents=args.agents,
                    model=model, seed=args.seed, progress=sys.stderr,
                )
            if args.scenario in ("burst", "all"):
                out["scenarios"]["burst"] = await fid_scenarios.burst_drain(
                    base, writes=args.writes, n_agents=args.agents,
                    model=model, seed=args.seed, progress=sys.stderr,
                )
            if args.scenario in ("dcn", "all"):
                out["scenarios"]["dcn"] = fid_scenarios.dcn_partition(
                    rounds=args.dcn_rounds, seed=args.seed,
                    progress=sys.stderr,
                )
        from corrosion_tpu.fidelity.calibrate import trace_fingerprint
        from corrosion_tpu.fidelity.report import fidelity_context

        fp = trace_fingerprint([
            (i, blk.get("trace_fingerprint", name), i)
            for i, (name, blk) in enumerate(sorted(out["scenarios"].items()))
        ])
        report = {
            **fidelity_context(
                f"cli_{args.scenario}", args.agents, fp,
                args.writes, args.dcn_rounds, args.seed,
            ),
            **out,
        }
        emit_fidelity_report(report)
        text = json.dumps(report, indent=2)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
        print(text)
        ok = all(
            blk.get("calibrated_closer", True)
            and blk.get("invariants_ok", True)
            for blk in report["scenarios"].values()
        )
        return 0 if ok else 1

    if args.fidelity_cmd == "replay":
        from corrosion_tpu.fidelity.calibrate import identity_model
        from corrosion_tpu.fidelity.compare import (
            bucket_hist, hist_cdf, kernel_replay,
        )
        from corrosion_tpu.sim.trace import Trace

        try:
            trace = Trace.load(args.trace)
            model = (
                RoundModel.load(args.model) if args.model
                else identity_model()
            )
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"fidelity replay: {e!r}", file=sys.stderr)
            return 2
        rep = kernel_replay(
            trace, model.round_ms,
            n_nodes=len(trace.actors) + args.observers,
            model=model, seed=args.seed,
            vis_offset_rounds=model.vis_offset_rounds,
        )
        lat = rep.pop("lat_rounds")
        rep["hist"] = bucket_hist(lat + model.vis_offset_rounds)
        rep["cdf"] = [round(c, 6) for c in hist_cdf(rep["hist"])]
        rep["model"] = model.describe()
        if args.json:
            print(json.dumps(rep))
        else:
            for k, v in rep.items():
                print(f"{k}: {v}")
        return 0 if rep["unseen"] == 0 else 1
    return 2


def _obs(args) -> int:
    """`corrosion obs {report,tail,diff,record,epidemic,soak,serving,
    timeline,cost,trajectory}` — delegates to the obs package
    (corrosion_tpu/obs/commands.py), which owns the convergence-plane
    verdicts, the propagation/epidemic analyzer, the endurance and
    serving query-cost analyzers, and the causal-tracing correlator."""
    from corrosion_tpu.obs import commands as obs_commands

    return obs_commands.run(args)


async def _run_agent(cfg: Config) -> int:
    import os

    from corrosion_tpu.agent.agent import Agent, AgentConfig
    from corrosion_tpu.agent.subs import SubsManager
    from corrosion_tpu.utils.logfmt import setup_logging

    # Log format from config (LogFormat, config.rs:318-326).
    setup_logging(fmt=cfg.log.format, colors=cfg.log.colors)

    gossip_host, gossip_port = parse_addr(cfg.gossip.addr)
    api_host, api_port = parse_addr(cfg.api.addr)
    tls_cfg = None
    if not cfg.gossip.plaintext:
        # Fail closed: demanding TLS without cert material is a config
        # error, not a silent plaintext fallback.
        if not (cfg.gossip.tls_cert_file and cfg.gossip.tls_key_file):
            raise SystemExit(
                "gossip.plaintext = false requires tls_cert_file and "
                "tls_key_file ([gossip.tls] cert_file/key_file)"
            )
        from corrosion_tpu.agent.agent import AgentTls

        tls_cfg = AgentTls(
            cert=cfg.gossip.tls_cert_file,
            key=cfg.gossip.tls_key_file,
            ca=cfg.gossip.tls_ca_file,
            client_cert=cfg.gossip.tls_client_cert_file,
            client_key=cfg.gossip.tls_client_key_file,
            mtls=cfg.gossip.tls_mtls,
            insecure=cfg.gossip.tls_insecure,
        )
    elif cfg.gossip.tls_cert_file:
        raise SystemExit(
            "gossip TLS material configured but plaintext = true — set "
            "gossip.plaintext = false to enable TLS"
        )
    acfg = AgentConfig(
        data_dir=os.path.dirname(cfg.db.path) or ".",
        gossip_host=gossip_host,
        gossip_port=gossip_port,
        api_host=api_host,
        api_port=api_port,
        bootstrap=resolve_bootstrap(cfg.gossip.bootstrap),
        bootstrap_raw=list(cfg.gossip.bootstrap),
        schema_sql=cfg.schema_sql(),
        probe_interval=cfg.gossip.probe_interval_ms / 1000.0,
        sync_interval=cfg.gossip.sync_interval_ms / 1000.0,
        max_transmissions=cfg.gossip.max_transmissions,
        admin_uds=cfg.admin.uds_path,
        tls=tls_cfg,
        prometheus_addr=cfg.telemetry.prometheus_addr or "",
        otlp_endpoint=cfg.telemetry.otlp_endpoint or "",
    )
    agent = Agent(acfg)
    agent.subs = SubsManager(agent.store)
    await agent.start()
    from corrosion_tpu.utils.tripwire import Tripwire

    agent.tripwire = Tripwire.new_signals()
    # Through the logging stack, not print: the startup banner must honor
    # the configured log format (a JSON shipper chokes on bare text).
    logging.getLogger("corrosion_tpu.cli").info(
        "agent %s api=%s gossip=%s",
        agent.actor_id, agent.api_addr, agent.gossip_addr,
    )
    await agent.tripwire.wait()
    await agent.stop()
    return 0


async def _query(args, cfg: Config) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    import time

    t0 = time.monotonic()
    cols, rows = await client.query(args.sql)
    if args.columns:
        print("|".join(cols))
    for row in rows:
        print("|".join("" if v is None else str(v) for v in row))
    if args.timer:
        print(f"time: {time.monotonic() - t0:.6f}s", file=sys.stderr)
    return 0


async def _exec(args, cfg: Config) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    resp = await client.execute(list(args.sql))
    print(json.dumps(resp))
    return 0


async def _admin(cfg: Config, command: dict) -> list[dict]:
    from corrosion_tpu.agent.admin import AdminClient

    frames = await AdminClient(cfg.admin.uds_path).call(command)
    if not frames:
        raise SystemExit("admin: connection closed without a response")
    if "error" in frames[0]:
        raise SystemExit(f"admin: {frames[0]['error']}")
    return frames


if __name__ == "__main__":
    raise SystemExit(main())
