"""Consul bridge: poll the local Consul agent, upsert diffs into corrosion.

The reference polls Consul every 1 s, hashes services/checks (seahash), and
writes only changed rows into the `consul_services` / `consul_checks`
tables, remembering hashes in `__corro_consul_*` node-local tables
(corrosion/src/command/consul/sync.rs:20-246,408-530; HTTP client in
consul-client). Same structure here with a stdlib HTTP client and blake2b
hashing.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging

from corrosion_tpu.agent.config import Config, parse_addr
from corrosion_tpu.client import CorrosionApiClient

SETUP_SQL = """
CREATE TABLE IF NOT EXISTS __corro_consul_services (
  node TEXT NOT NULL, id TEXT NOT NULL, hash TEXT NOT NULL,
  PRIMARY KEY (node, id)
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS __corro_consul_checks (
  node TEXT NOT NULL, id TEXT NOT NULL, hash TEXT NOT NULL,
  PRIMARY KEY (node, id)
) WITHOUT ROWID;
"""

# The replicated tables the operator's schema must provide (doc'd by the
# reference's consul docs): consul_services(node, id, name, tags, meta,
# port, address, updated_at) / consul_checks(node, id, service_id,
# service_name, name, status, output, updated_at).


def hash_service(svc: dict) -> bytes:
    """Stable digest over the identity-relevant fields (sync.rs:214-233)."""
    key = json.dumps(
        {
            "id": svc.get("ID"),
            "name": svc.get("Service"),
            "tags": sorted(svc.get("Tags") or []),
            "meta": svc.get("Meta") or {},
            "port": svc.get("Port"),
            "address": svc.get("Address"),
        },
        sort_keys=True,
    )
    return hashlib.blake2b(key.encode(), digest_size=8).digest()


def hash_check(chk: dict) -> bytes:
    """Checks hash on status-relevant fields only (sync.rs:235-246)."""
    key = json.dumps(
        {
            "id": chk.get("CheckID"),
            "service_id": chk.get("ServiceID"),
            "status": chk.get("Status"),
            "output": chk.get("Output"),
        },
        sort_keys=True,
    )
    return hashlib.blake2b(key.encode(), digest_size=8).digest()


def diff_statements(
    node: str,
    services: dict[str, dict],
    checks: dict[str, dict],
    known_services: dict[str, bytes],
    known_checks: dict[str, bytes],
) -> tuple[list[list], dict[str, bytes], dict[str, bytes]]:
    """Compute upsert/delete statements + the new hash tables
    (update_consul/execute, sync.rs:408-530). Pure, for testing."""
    stmts: list[list] = []
    new_svc_hashes: dict[str, bytes] = {}
    for sid, svc in services.items():
        h = hash_service(svc)
        new_svc_hashes[sid] = h
        if known_services.get(sid) == h:
            continue
        stmts.append(
            [
                "INSERT INTO consul_services"
                " (node, id, name, tags, meta, port, address, updated_at)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, strftime('%s','now'))"
                " ON CONFLICT (node, id) DO UPDATE SET"
                " name=excluded.name, tags=excluded.tags, meta=excluded.meta,"
                " port=excluded.port, address=excluded.address,"
                " updated_at=excluded.updated_at",
                [
                    node, sid, svc.get("Service") or "",
                    json.dumps(svc.get("Tags") or []),
                    json.dumps(svc.get("Meta") or {}),
                    svc.get("Port") or 0, svc.get("Address") or "",
                ],
            ]
        )
    for sid in known_services:
        if sid not in services:
            stmts.append(
                ["DELETE FROM consul_services WHERE node = ? AND id = ?",
                 [node, sid]]
            )
    new_chk_hashes: dict[str, bytes] = {}
    for cid, chk in checks.items():
        h = hash_check(chk)
        new_chk_hashes[cid] = h
        if known_checks.get(cid) == h:
            continue
        stmts.append(
            [
                "INSERT INTO consul_checks"
                " (node, id, service_id, service_name, name, status, output,"
                "  updated_at)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, strftime('%s','now'))"
                " ON CONFLICT (node, id) DO UPDATE SET"
                " service_id=excluded.service_id,"
                " service_name=excluded.service_name, name=excluded.name,"
                " status=excluded.status, output=excluded.output,"
                " updated_at=excluded.updated_at",
                [
                    node, cid, chk.get("ServiceID") or "",
                    chk.get("ServiceName") or "", chk.get("Name") or "",
                    chk.get("Status") or "", chk.get("Output") or "",
                ],
            ]
        )
    for cid in known_checks:
        if cid not in checks:
            stmts.append(
                ["DELETE FROM consul_checks WHERE node = ? AND id = ?",
                 [node, cid]]
            )
    return stmts, new_svc_hashes, new_chk_hashes


class ConsulHttp:
    """Minimal Consul agent HTTP client (consul-client's role)."""

    def __init__(self, address: str):
        self.host, self.port = parse_addr(address)

    async def _get(self, path: str):
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            writer.write(
                f"GET {path} HTTP/1.1\r\nhost: {self.host}\r\n"
                "connection: close\r\n\r\n".encode()
            )
            await writer.drain()
            raw = await reader.read()
        finally:
            writer.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        status = int(head.split()[1])
        if status != 200:
            raise RuntimeError(f"consul HTTP {status}")
        if b"chunked" in head.lower():
            body = _dechunk(body)
        return json.loads(body)

    async def agent_services(self) -> dict:
        return await self._get("/v1/agent/services")

    async def agent_checks(self) -> dict:
        return await self._get("/v1/agent/checks")


def _dechunk(body: bytes) -> bytes:
    out = b""
    while body:
        size_line, _, rest = body.partition(b"\r\n")
        n = int(size_line, 16)
        if n == 0:
            break
        out += rest[:n]
        body = rest[n + 2:]
    return out


async def _setup(
    client: CorrosionApiClient, node: str
) -> tuple[dict, dict]:
    """Create the node-local hash tables and load persisted hashes
    (sync.rs setup, :119-160). ``__corro_*`` tables are not CRRs, so these
    writes stay node-local — exactly the reference's split between the
    replicated consul_* tables and the local bookkeeping. Hashes key by
    (node, id): a hostname change must re-upsert everything under the new
    node name, not silently skip it."""
    stmts = [s for s in SETUP_SQL.split(";") if s.strip()]
    await client.execute([[s] for s in stmts])
    # Hash tables are pure caches: a layout mismatch (e.g. the pre-node
    # id-keyed schema — CREATE IF NOT EXISTS cannot migrate it) is fixed
    # by dropping and recreating; worst case one full re-upsert.
    try:
        await client.query(
            "SELECT node FROM __corro_consul_services LIMIT 0"
        )
        await client.query(
            "SELECT node FROM __corro_consul_checks LIMIT 0"
        )
    except Exception:
        await client.execute(
            [["DROP TABLE IF EXISTS __corro_consul_services"],
             ["DROP TABLE IF EXISTS __corro_consul_checks"]]
            + [[s] for s in stmts]
        )
    known_services: dict[str, bytes] = {}
    known_checks: dict[str, bytes] = {}
    from corrosion_tpu.core.values import Statement

    _, rows = await client.query(Statement(
        "SELECT id, hash FROM __corro_consul_services WHERE node = ?",
        params=[node],
    ))
    for sid, h in rows:
        known_services[sid] = bytes.fromhex(h)
    _, rows = await client.query(Statement(
        "SELECT id, hash FROM __corro_consul_checks WHERE node = ?",
        params=[node],
    ))
    for cid, h in rows:
        known_checks[cid] = bytes.fromhex(h)
    return known_services, known_checks


def _hash_persist_statements(
    node: str, old: dict[str, bytes], new: dict[str, bytes], table: str
) -> list[list]:
    stmts: list[list] = []
    for key, h in new.items():
        if old.get(key) != h:
            # Hex: blobs don't ride the JSON statement API.
            stmts.append(
                [f"INSERT OR REPLACE INTO {table} (node, id, hash)"
                 " VALUES (?, ?, ?)",
                 [node, key, h.hex()]]
            )
    for key in old:
        if key not in new:
            stmts.append(
                [f"DELETE FROM {table} WHERE node = ? AND id = ?",
                 [node, key]]
            )
    return stmts


async def run_consul_sync(cfg: Config, iterations: int | None = None) -> None:
    """Poll-and-upsert loop (sync.rs run, :20-117). Diff hashes persist in
    ``__corro_consul_*`` so a bridge restart does not re-upsert the world
    (and churn every subscription on consul_services)."""
    import socket

    node = socket.gethostname()
    consul = ConsulHttp(cfg.consul.address)
    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    known = None  # lazily set up: the API may not be listening yet
    warned = False
    i = 0
    while iterations is None or i < iterations:
        i += 1
        try:
            if known is None:
                known = await _setup(client, node)
            known_services, known_checks = known
            services = await consul.agent_services()
            checks = await consul.agent_checks()
            stmts, new_services, new_checks = diff_statements(
                node, services, checks, known_services, known_checks
            )
            stmts += _hash_persist_statements(
                node, known_services, new_services, "__corro_consul_services"
            )
            stmts += _hash_persist_statements(
                node, known_checks, new_checks, "__corro_consul_checks"
            )
            if stmts:
                await client.execute(stmts)
            # Adopt the hash state only after the corrosion write succeeded;
            # a failed tick must re-diff (and re-send) next tick.
            known = (new_services, new_checks)
        except Exception:
            # Unreachable consul/corrosion or a rejected write: retry next
            # tick — but leave a VISIBLE trail (warning on the first
            # failure, debug on repeats), or a permanently failing bridge
            # looks identical to a healthy idle one.
            log = logging.getLogger(__name__)
            if not warned:
                warned = True
                log.warning("consul sync tick failed", exc_info=True)
            else:
                log.debug("consul sync tick failed", exc_info=True)
        else:
            warned = False
        await asyncio.sleep(cfg.consul.interval_ms / 1000.0)
