from corrosion_tpu.cli import main

raise SystemExit(main())
