"""Loader for the native runtime components (native/ C sources).

Two artifacts, both optional at runtime (pure-Python fallbacks exist
everywhere they are used):

- ``_corro_native`` — CPython extension: packed-PK codec, exact SQLite
  value ordering, and the compact binary wire codec (the reference's
  speedy encoding role, corro-types/src/broadcast.rs).
- ``crdt_ext.so`` — SQLite run-time loadable extension with the CRDT SQL
  helpers (``crdt_value_cmp`` et al.); the analogue of the reference
  loading cr-sqlite into every connection (corro-types/src/sqlite.rs:87-105).

``build()`` compiles both from source with the in-image toolchain; tests
and the CLI call it so a fresh checkout self-builds without any package
installation.
"""

from __future__ import annotations

import os
import sqlite3
import subprocess
import sys
from types import ModuleType

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "_native")
_REPO_NATIVE_SRC = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "native"
)

CRDT_EXT_PATH = os.path.join(_NATIVE_DIR, "crdt_ext")


def _import_native() -> ModuleType | None:
    if _NATIVE_DIR not in sys.path and os.path.isdir(_NATIVE_DIR):
        sys.path.insert(0, _NATIVE_DIR)
    try:
        import _corro_native  # type: ignore[import-not-found]

        return _corro_native
    except ImportError:
        return None


native = _import_native()


def available() -> bool:
    """True when the CPython codec module is importable."""
    return native is not None


def crdt_ext_available() -> bool:
    return os.path.exists(CRDT_EXT_PATH + ".so")


def load_crdt_extension(conn: sqlite3.Connection) -> bool:
    """Load the CRDT SQL helpers into a connection; False if unavailable.

    Mirrors init_cr_conn (corro-types/src/sqlite.rs:87-105): every Store
    connection gets the extension when the artifact exists.
    """
    if not crdt_ext_available():
        return False
    try:
        conn.enable_load_extension(True)
        try:
            conn.load_extension(CRDT_EXT_PATH)
        finally:
            conn.enable_load_extension(False)
        return True
    except sqlite3.OperationalError:
        return False


def build(quiet: bool = True) -> bool:
    """Compile the native artifacts in-tree. Returns success. Safe to call
    repeatedly (make is incremental); never raises on a missing toolchain."""
    global native
    if not os.path.isdir(_REPO_NATIVE_SRC):
        return False
    try:
        proc = subprocess.run(
            ["make", "-C", _REPO_NATIVE_SRC],
            capture_output=True,
            text=True,
            timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        if not quiet:
            sys.stderr.write(proc.stdout + proc.stderr)
        return False
    if native is None:
        native = _import_native()
    return True
