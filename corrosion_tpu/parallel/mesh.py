"""Mesh construction + NamedSharding placement for ClusterState/Topology.

Placement policy (1-D mesh over axis "nodes"):

- per-node vectors (alive, incarnation, region, …):        P("nodes")
- node-major matrices (SWIM view, contig, seen, queues):   P("nodes", None)
- visibility samples [S, N]:                               P(None, "nodes")
- writer-indexed vectors (head, writer_nodes) + scalars:   replicated

The SWIM view's column axis and the data plane's writer axis stay
unsharded: gossip scatters address arbitrary (row, col) pairs, so sharding
rows makes each delivery a cross-shard send exactly once (the all-to-all the
reference does over QUIC, here over ICI), while the column gather stays
local. XLA partitions the scatter/gather ops and inserts the collectives.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.ops.gossip import DataState, Topology
from corrosion_tpu.sim.engine import ClusterState


def make_mesh(n_devices: int | None = None, axis: str = "nodes") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_wan_mesh(n_dcn: int, n_ici: int) -> Mesh:
    """2-D (dcn, ici) mesh for the partitioned-WAN configs.

    Node indices are region-blocked (make_topology lays regions out as
    contiguous index ranges), and a multi-axis node sharding
    ``P(("dcn", "ici"))`` splits the node axis with ``dcn`` as the outer
    (slow) axis — so whole regions land inside one dcn group when
    n_regions is a multiple of n_dcn. In-region traffic (ring-0 near
    pulls, most broadcast volume) then stays inside an ICI group's
    all-to-all, and only cross-region gossip crosses the DCN axis —
    matching how the reference's WAN deployments keep gossip chatter
    regional (the ICI/DCN split of SURVEY §5's comm-backend plan).
    """
    devs = jax.devices()
    if n_dcn * n_ici > len(devs):
        raise ValueError(
            f"need {n_dcn * n_ici} devices, have {len(devs)}"
        )
    arr = np.array(devs[: n_dcn * n_ici]).reshape(n_dcn, n_ici)
    return Mesh(arr, ("dcn", "ici"))


def _node_axis(mesh: Mesh, axis):
    """Node-dimension spec entry: the mesh's full axis tuple for multi-axis
    meshes (dcn outer, ici inner), else the single named axis."""
    if axis is not None:
        return axis
    return mesh.axis_names if len(mesh.axis_names) > 1 else mesh.axis_names[0]


def _put(x, mesh: Mesh, spec: P):
    return jax.device_put(x, NamedSharding(mesh, spec))


def spec_shard_factor(spec: P, mesh: Mesh) -> int:
    """How many ways a leaf with PartitionSpec ``spec`` splits over
    ``mesh`` — the product of the named axis sizes it mentions. The
    byte arithmetic behind ``obs.costs``'s capacity predictions: a
    leaf's per-device bytes are ``nbytes / spec_shard_factor`` (1 for
    replicated leaves). One rule derived from the SAME spec trees the
    shard helpers below place with, so prediction and placement cannot
    drift."""
    factor = 1
    for entry in spec:
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for name in names:
            factor *= int(mesh.shape[name])
    return factor


def predicted_per_device_bytes(shapes, specs, mesh: Mesh) -> int:
    """Static per-device state bytes for a pytree of
    ``jax.ShapeDtypeStruct`` (or arrays) under a matching spec pytree —
    the arithmetic twin of ``shard_driver.per_device_state_bytes``
    (which measures live addressable shards). Every sharded DIMENSION
    must divide its mesh factor — the same placeability rule
    ``jax.device_put`` enforces — so a configuration that could never
    be placed raises here rather than yielding a byte count for a
    phantom placement."""
    import math

    total = 0
    for leaf, spec in zip(jax.tree.leaves(shapes), jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )):
        for dim, entry in enumerate(spec):
            if entry is None:
                continue
            names = entry if isinstance(entry, (tuple, list)) else (entry,)
            dim_factor = 1
            for name in names:
                dim_factor *= int(mesh.shape[name])
            if leaf.shape[dim] % dim_factor:
                raise ValueError(
                    f"leaf {leaf.shape}/{leaf.dtype} dimension {dim} "
                    f"({leaf.shape[dim]}) does not divide its mesh "
                    f"factor {dim_factor} — this placement is not "
                    f"expressible (pad the node count)"
                )
        nbytes = math.prod(leaf.shape or (1,)) * leaf.dtype.itemsize
        total += nbytes // spec_shard_factor(spec, mesh)
    return total


def shard_topology(topo: Topology, mesh: Mesh, axis=None) -> Topology:
    axis = _node_axis(mesh, axis)
    n = P(axis)
    r = P()  # replicated
    return Topology(
        region=_put(topo.region, mesh, n),
        region_start=_put(topo.region_start, mesh, n),
        region_size=_put(topo.region_size, mesh, n),
        region_rtt=_put(topo.region_rtt, mesh, r),
        writer_nodes=_put(topo.writer_nodes, mesh, r),
        writer_of_node=_put(topo.writer_of_node, mesh, n),
        sync_phase=_put(topo.sync_phase, mesh, n),
        sync_cohorts=(
            None if topo.sync_cohorts is None
            else _put(topo.sync_cohorts, mesh, r)
        ),
        writer_ids=(
            None if topo.writer_ids is None
            else _put(topo.writer_ids, mesh, r)
        ),
    )


def _put_specs(tree, specs, mesh: Mesh):
    """device_put every leaf with its matching PartitionSpec leaf."""
    return jax.tree.map(
        lambda x, s: _put(x, mesh, s), tree, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def data_state_specs(d: DataState, mesh: Mesh, axis=None) -> DataState:
    """The PartitionSpec tree for a gossip DataState (shared by the
    dense, sparse, and mixed shard helpers AND the capacity
    prediction in ``obs.costs``): node-major tensors shard their row
    axis, writer heads and the window-live flag replicate, window
    words shard dim 1 ([B, N, W]), and the flat cell plane shards on
    node boundaries (K divides each shard when N does)."""
    axis = _node_axis(mesh, axis)
    row = P(axis, None)
    vec = P(axis)
    rep = P()
    return DataState(
        head=rep,
        contig=row,
        seen=row,
        oo=P(None, axis, None),
        oo_any=rep,
        q_writer=row,
        q_ver=row,
        q_tx=row,
        q_gw=row,
        q_dup=row,
        cells=jax.tree.map(lambda a: vec, d.cells),
    )


def node_major_specs(tree, mesh: Mesh, axis=None):
    """Leading-axis sharding specs for every leaf (SWIM state, chunk
    coverage)."""
    axis = _node_axis(mesh, axis)
    return jax.tree.map(
        lambda x: P(axis, *([None] * (x.ndim - 1))), tree
    )


def shard_node_major(tree, mesh: Mesh, axis):
    """Shard every leaf's leading axis (SWIM state, chunk coverage)."""
    return _put_specs(tree, node_major_specs(tree, mesh, axis), mesh)


def cluster_state_specs(
    state: ClusterState, mesh: Mesh, axis=None
) -> ClusterState:
    """Spec tree for the dense engine's ClusterState — the one
    placement rule ``shard_cluster_state`` applies and
    ``obs.costs.capacity_model`` predicts per-device bytes from."""
    axis = _node_axis(mesh, axis)
    return ClusterState(
        # Every SWIM-plane field (dense SwimState or SparseSwimState) is
        # node-major: shard the leading axis, replicate the rest.
        swim=node_major_specs(state.swim, mesh, axis),
        data=data_state_specs(state.data, mesh, axis),
        round=P(),
        vis_round=P(None, axis),
    )


def shard_cluster_state(
    state: ClusterState, mesh: Mesh, axis=None
) -> ClusterState:
    return _put_specs(
        state, cluster_state_specs(state, mesh, axis), mesh
    )


def sparse_state_specs(sstate, mesh: Mesh, axis=None):
    """Spec tree for the sparse writer plane
    (ops/sparse_writers.SparseState): node-major tensors shard like the
    dense plane; slot-indexed vectors replicate (slots are global
    metadata, a few KB)."""
    from corrosion_tpu.ops.sparse_writers import SparseState

    axis = _node_axis(mesh, axis)
    row = P(axis, None)
    return SparseState(
        data=data_state_specs(sstate.data, mesh, axis),
        head_full=P(axis),
        slot_writer=P(),
        dev_writer=row,
        dev_contig=row,
        dev_any=P(),
    )


def shard_sparse_state(sstate, mesh: Mesh, axis=None):
    return _put_specs(
        sstate, sparse_state_specs(sstate, mesh, axis), mesh
    )


def shard_chunk_state(state, mesh: Mesh, axis=None):
    """NamedSharding placement for the seq-chunk plane
    (ops/chunks.ChunkState): coverage rows are node-major flat
    [N * S, C], so sharding the row axis splits on node boundaries when
    N divides the mesh size (each shard holds whole nodes' streams)."""
    axis = _node_axis(mesh, axis)
    return shard_node_major(state, mesh, axis)


def mixed_state_specs(state, mesh: Mesh, axis=None):
    """Spec tree for the mixed chunk+version engine
    (sim/mixed_engine.MixedState): the version plane shards like the
    dense engine, chunk coverage like the chunk plane, the per-stream
    completion latch is node-major, and the round counter replicates."""
    from corrosion_tpu.sim.mixed_engine import MixedState

    axis = _node_axis(mesh, axis)
    return MixedState(
        data=data_state_specs(state.data, mesh, axis),
        swim=node_major_specs(state.swim, mesh, axis),
        chunks=node_major_specs(state.chunks, mesh, axis),
        applied_before=P(axis, None),
        round=P(),
        vis_round=P(None, axis),
    )


def shard_mixed_state(state, mesh: Mesh, axis=None):
    return _put_specs(
        state, mixed_state_specs(state, mesh, axis), mesh
    )
