"""Mesh construction + NamedSharding placement for ClusterState/Topology.

Placement policy (1-D mesh over axis "nodes"):

- per-node vectors (alive, incarnation, region, …):        P("nodes")
- node-major matrices (SWIM view, contig, seen, queues):   P("nodes", None)
- visibility samples [S, N]:                               P(None, "nodes")
- writer-indexed vectors (head, writer_nodes) + scalars:   replicated

The SWIM view's column axis and the data plane's writer axis stay
unsharded: gossip scatters address arbitrary (row, col) pairs, so sharding
rows makes each delivery a cross-shard send exactly once (the all-to-all the
reference does over QUIC, here over ICI), while the column gather stays
local. XLA partitions the scatter/gather ops and inserts the collectives.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.ops.gossip import DataState, Topology
from corrosion_tpu.sim.engine import ClusterState


def make_mesh(n_devices: int | None = None, axis: str = "nodes") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_wan_mesh(n_dcn: int, n_ici: int) -> Mesh:
    """2-D (dcn, ici) mesh for the partitioned-WAN configs.

    Node indices are region-blocked (make_topology lays regions out as
    contiguous index ranges), and a multi-axis node sharding
    ``P(("dcn", "ici"))`` splits the node axis with ``dcn`` as the outer
    (slow) axis — so whole regions land inside one dcn group when
    n_regions is a multiple of n_dcn. In-region traffic (ring-0 near
    pulls, most broadcast volume) then stays inside an ICI group's
    all-to-all, and only cross-region gossip crosses the DCN axis —
    matching how the reference's WAN deployments keep gossip chatter
    regional (the ICI/DCN split of SURVEY §5's comm-backend plan).
    """
    devs = jax.devices()
    if n_dcn * n_ici > len(devs):
        raise ValueError(
            f"need {n_dcn * n_ici} devices, have {len(devs)}"
        )
    arr = np.array(devs[: n_dcn * n_ici]).reshape(n_dcn, n_ici)
    return Mesh(arr, ("dcn", "ici"))


def _node_axis(mesh: Mesh, axis):
    """Node-dimension spec entry: the mesh's full axis tuple for multi-axis
    meshes (dcn outer, ici inner), else the single named axis."""
    if axis is not None:
        return axis
    return mesh.axis_names if len(mesh.axis_names) > 1 else mesh.axis_names[0]


def _put(x, mesh: Mesh, spec: P):
    return jax.device_put(x, NamedSharding(mesh, spec))


def shard_topology(topo: Topology, mesh: Mesh, axis=None) -> Topology:
    axis = _node_axis(mesh, axis)
    n = P(axis)
    r = P()  # replicated
    return Topology(
        region=_put(topo.region, mesh, n),
        region_start=_put(topo.region_start, mesh, n),
        region_size=_put(topo.region_size, mesh, n),
        region_rtt=_put(topo.region_rtt, mesh, r),
        writer_nodes=_put(topo.writer_nodes, mesh, r),
        writer_of_node=_put(topo.writer_of_node, mesh, n),
        sync_phase=_put(topo.sync_phase, mesh, n),
        sync_cohorts=(
            None if topo.sync_cohorts is None
            else _put(topo.sync_cohorts, mesh, r)
        ),
        writer_ids=(
            None if topo.writer_ids is None
            else _put(topo.writer_ids, mesh, r)
        ),
    )


def _shard_data_state(d: DataState, mesh: Mesh, axis) -> DataState:
    """NamedSharding placement for a gossip DataState (shared by the
    dense, sparse, and mixed shard helpers): node-major tensors shard
    their row axis, writer heads and the window-live flag replicate,
    window words shard dim 1 ([B, N, W]), and the flat cell plane
    shards on node boundaries (K divides each shard when N does)."""
    row = P(axis, None)
    vec = P(axis)
    rep = P()
    return DataState(
        head=_put(d.head, mesh, rep),
        contig=_put(d.contig, mesh, row),
        seen=_put(d.seen, mesh, row),
        oo=_put(d.oo, mesh, P(None, axis, None)),
        oo_any=_put(d.oo_any, mesh, rep),
        q_writer=_put(d.q_writer, mesh, row),
        q_ver=_put(d.q_ver, mesh, row),
        q_tx=_put(d.q_tx, mesh, row),
        q_gw=_put(d.q_gw, mesh, row),
        cells=jax.tree.map(lambda a: _put(a, mesh, vec), d.cells),
    )


def shard_node_major(tree, mesh: Mesh, axis):
    """Shard every leaf's leading axis (SWIM state, chunk coverage)."""
    return jax.tree.map(
        lambda x: _put(x, mesh, P(axis, *([None] * (x.ndim - 1)))), tree
    )


def shard_cluster_state(
    state: ClusterState, mesh: Mesh, axis=None
) -> ClusterState:
    axis = _node_axis(mesh, axis)
    return ClusterState(
        # Every SWIM-plane field (dense SwimState or SparseSwimState) is
        # node-major: shard the leading axis, replicate the rest.
        swim=shard_node_major(state.swim, mesh, axis),
        data=_shard_data_state(state.data, mesh, axis),
        round=_put(state.round, mesh, P()),
        vis_round=_put(state.vis_round, mesh, P(None, axis)),
    )


def shard_sparse_state(sstate, mesh: Mesh, axis=None):
    """NamedSharding placement for the sparse writer plane
    (ops/sparse_writers.SparseState): node-major tensors shard like the
    dense plane; slot-indexed vectors replicate (slots are global
    metadata, a few KB)."""
    from corrosion_tpu.ops.sparse_writers import SparseState

    axis = _node_axis(mesh, axis)
    row = P(axis, None)
    return SparseState(
        data=_shard_data_state(sstate.data, mesh, axis),
        head_full=_put(sstate.head_full, mesh, P(axis)),
        slot_writer=_put(sstate.slot_writer, mesh, P()),
        dev_writer=_put(sstate.dev_writer, mesh, row),
        dev_contig=_put(sstate.dev_contig, mesh, row),
        dev_any=_put(sstate.dev_any, mesh, P()),
    )


def shard_chunk_state(state, mesh: Mesh, axis=None):
    """NamedSharding placement for the seq-chunk plane
    (ops/chunks.ChunkState): coverage rows are node-major flat
    [N * S, C], so sharding the row axis splits on node boundaries when
    N divides the mesh size (each shard holds whole nodes' streams)."""
    axis = _node_axis(mesh, axis)
    return shard_node_major(state, mesh, axis)


def shard_mixed_state(state, mesh: Mesh, axis=None):
    """NamedSharding placement for the mixed chunk+version engine
    (sim/mixed_engine.MixedState): the version plane shards like the
    dense engine, chunk coverage like the chunk plane, the per-stream
    completion latch is node-major, and the round counter replicates."""
    from corrosion_tpu.sim.mixed_engine import MixedState

    axis = _node_axis(mesh, axis)
    return MixedState(
        data=_shard_data_state(state.data, mesh, axis),
        swim=shard_node_major(state.swim, mesh, axis),
        chunks=shard_node_major(state.chunks, mesh, axis),
        applied_before=_put(state.applied_before, mesh, P(axis, None)),
        round=_put(state.round, mesh, P()),
        vis_round=_put(state.vis_round, mesh, P(None, axis)),
    )
