"""Device-mesh sharding for the cluster simulation.

The simulation's parallelism axis is the *virtual node* dimension (SURVEY.md
§2 P1: every Corrosion node holds full state — here each TPU core hosts a
shard of virtual nodes). All O(N) and O(N·N)/O(N·W) state is sharded along
its node-row axis; writer heads and schedules stay replicated. Cross-shard
gossip deliveries become XLA collectives inserted automatically at the
scatter boundaries (all-to-all-shaped traffic riding ICI).
"""

from corrosion_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_wan_mesh,
    shard_chunk_state,
    shard_cluster_state,
    shard_mixed_state,
    shard_node_major,
    shard_sparse_state,
    shard_topology,
)
from corrosion_tpu.parallel.shard_driver import (  # noqa: F401
    make_sharded_broadcast,
    per_device_state_bytes,
    replicate,
    simulate_chunks_sharded,
    simulate_mixed_sharded,
    simulate_sharded,
    simulate_sparse_sharded,
    traffic_model,
)
