"""Explicit shard_map round driver for the multi-chip scale-out plane.

``parallel/mesh.py`` places state with NamedSharding and lets GSPMD
decide where the collectives go inside the 1.8k-line ``ops/gossip.py``
step. That works — the placements are bit-identity-pinned — but the
multi-chip cost model is then whatever XLA felt like: nothing states
*which* traffic crosses shards, nothing measures it, and a partitioner
regression would change the wire volume silently. This module makes the
broadcast delivery chain's cross-shard structure EXPLICIT:

- **One batched queue exchange per round.** The pending-broadcast queue
  tables (``q_writer``/``q_ver``/``q_tx`` and ``q_gw`` under rotating
  slots) are the entire wire format of the delivery plane — a bounded
  ``[N, Q]`` digest of everything any node may transmit this round.
  Each shard publishes its block once: an ``all_gather`` over the fast
  (ici) axis first, then one coalesced second hop across the slow (dcn)
  axis. Every receiver then needs *nothing else* remote — source
  sampling, link checks, the sorted delivery pass, window admission,
  CRDT merges, and the queue rebuild are all row-local
  (``gossip._broadcast_round`` with a ``ShardCtx``). The per-backend
  trace-time dispatch in ``ops/onehot.py`` stays the inner-kernel seam,
  so the sharded driver composes with native/dense/pallas unchanged.
  (The exchange is an all_gather, not an element-routed all_to_all, on
  purpose: far peers are sampled uniformly over N, so every shard may
  need any row — same-data-to-all is the correct collective, and the
  queue tables are already the compact bounded form.)
- **One cross-shard reduction per round.** A source's retransmission
  budget burns when at least one receiver — on any shard — pulled it:
  a single psum over the mesh covers it, coalesced with the round's
  scalar stats.
- **Bit-identity by construction.** Every RNG draw whose shape would
  otherwise depend on the shard (source sampling, injected loss) is
  drawn at the FULL shape and row-sliced, so dense and sparse rounds
  are bit-identical across device_count ∈ {1, 2, 4, 8, ...} — pinned in
  tests/test_shard_driver.py.
- **Exact traffic accounting.** The exchange is staged explicitly, so
  its per-round byte volume is computed from the actual operands of
  each staged collective (shapes × dtype widths at trace time) and
  emitted through the canonical RoundCurves keys
  ``xshard_bytes_ici``/``xshard_bytes_dcn`` (zero when unsharded).
  :func:`traffic_model` derives the same numbers INDEPENDENTLY from the
  config arithmetic; the two are pinned equal in
  tests/test_shard_driver.py and the bench lane, so a wire-format
  regression surfaces as a curve/model mismatch. The SWIM/sync planes
  stay GSPMD-placed (their gathers are data-dependent and
  cohort-bounded); the model carries a documented estimate for them in
  ``detail``.

The anti-entropy sync plane deliberately remains on the GSPMD path: its
candidate/peer gathers touch ``sync_candidates + sync_peers + 1`` rows
per cohort row, are already cohort-bounded (N / sync_interval rows per
round), and XLA's placement there has never been the regression class —
the r04→r05 incident lived in the broadcast chain this module pins.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.ops import crdt
from corrosion_tpu.ops import gossip as gossip_ops
from corrosion_tpu.ops.gossip import DataState, ShardCtx


def node_spec_entry(mesh: Mesh):
    """The PartitionSpec entry that shards a node-major dimension over
    every mesh axis (dcn outer, ici inner) — the same placement rule
    ``parallel.mesh._node_axis`` applies for NamedSharding."""
    names = mesh.axis_names
    return names if len(names) > 1 else names[0]


def replicate(tree, mesh: Mesh):
    """device_put every leaf replicated over the mesh (P())."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def _data_specs(mesh: Mesh) -> DataState:
    node = node_spec_entry(mesh)
    return DataState(
        head=P(),  # writer heads are replicated (every shard commits them)
        contig=P(node),
        seen=P(node),
        oo=P(None, node),  # [B, N, W]: node axis is dim 1
        oo_any=P(),
        q_writer=P(node),
        q_ver=P(node),
        q_tx=P(node),
        q_gw=P(node),
        # Receiver-local duplicate counters: sharded like the queue but
        # NEVER part of the queue exchange (senders don't need them), so
        # the pinned xshard byte accounting is unchanged.
        q_dup=P(node),
        cells=crdt.CellState(
            cl=P(node), col_version=P(node), value_rank=P(node)
        ),
    )


def traffic_model(cfg: gossip_ops.GossipConfig, mesh: Mesh) -> dict:
    """Static per-round cross-shard byte accounting for the explicit
    broadcast exchange, plus documented estimates for the GSPMD planes.

    The queue exchange is staged per mesh axis (innermost first), so its
    volume is exact arithmetic: before the hop over an axis of size s,
    each of the D devices holds a ``cur`` -byte block and receives
    ``(s - 1) * cur`` from its group peers; the block then grows s-fold
    for the next (outer) hop. ``xshard_bytes_ici`` is the innermost-axis
    hop (intra-group), ``xshard_bytes_dcn`` sums every outer hop (zero
    on a 1-D mesh). Counts are cluster totals per round, in bytes.

    ``detail`` additionally models the control-plane collectives (the
    alive-vector gather at the shard_map boundary, the pulled-count
    psum) and the GSPMD sync plane's expected gather volume — estimates,
    labeled as such, because their placement belongs to XLA.
    """
    axes = tuple(mesh.axis_names)
    sizes = tuple(int(mesh.shape[a]) for a in axes)
    d = int(np.prod(sizes))
    n, q = cfg.n_nodes, cfg.queue
    if d <= 1:
        return {
            "xshard_bytes_ici": 0.0,
            "xshard_bytes_dcn": 0.0,
            "detail": {"device_count": d},
        }
    nl = n // d
    per_entry = 12 + (4 if cfg.track_writer_ids else 0)
    block = float(nl * q * per_entry)
    per_hop = []
    cur = block
    ici_bytes = dcn_bytes = 0.0
    for a, s in zip(reversed(axes), reversed(sizes)):
        hop = d * (s - 1) * cur
        per_hop.append({"axis": a, "group": s, "bytes": hop})
        if a == axes[-1]:
            ici_bytes += hop
        else:
            dcn_bytes += hop
        cur *= s
    # Control plane: the alive vector replicates at the shard_map
    # boundary (bool[N] per device), and the pulled-count psum is an
    # i32[N] all-reduce (ring model: 2 (D-1)/D volumes per device).
    alive_gather = float(d * (n - nl) * 1)
    pulled_reduce = float(2 * (d - 1) * n * 4)
    # GSPMD sync plane (estimate): per cohort row, the score pass
    # gathers C candidate contig+seen rows and the union pull gathers
    # S+1 peer rows, each [W] u32; a gathered row is remote with
    # probability (D-1)/D under uniform sampling.
    cohort = -(-n // max(cfg.sync_interval, 1))
    sync_rows = cohort * (2 * cfg.sync_candidates + cfg.sync_peers + 1)
    sync_est = float(sync_rows * cfg.n_writers * 4) * (d - 1) / d
    return {
        "xshard_bytes_ici": ici_bytes,
        "xshard_bytes_dcn": dcn_bytes,
        "detail": {
            "device_count": d,
            "queue_block_bytes": block,
            "per_hop": per_hop,
            "alive_gather_bytes": alive_gather,
            "pulled_reduce_bytes": pulled_reduce,
            "sync_gather_bytes_est": sync_est,
        },
    }


@functools.lru_cache(maxsize=None)
def make_sharded_broadcast(mesh: Mesh):
    """Build a drop-in replacement for ``gossip.broadcast_round`` that
    runs the delivery chain as a shard_map over ``mesh``.

    The returned function has the broadcast_round signature
    ``(data, topo, alive, partition, writes, rng, cfg, loss=None)`` and
    expects ``data`` node-sharded over the mesh
    (``parallel.shard_cluster_state`` / ``shard_sparse_state``) with
    ``topo`` replicated. It returns the stats dict of the unsharded
    round plus ``xshard_bytes_ici``/``xshard_bytes_dcn`` (the exchange's
    exact per-round byte volume), which the engine scan bodies forward
    into the canonical RoundCurves. Cached per mesh so jitted callers
    see one stable callable per mesh (one compile per config).
    """
    axes = tuple(mesh.axis_names)
    sizes = tuple(int(mesh.shape[a]) for a in axes)
    dev = int(np.prod(sizes))

    def bcast(data, topo, alive, partition, writes, rng, cfg, loss=None):
        n_total = cfg.n_nodes
        if n_total % dev:
            raise ValueError(
                f"shard_map driver needs n_nodes divisible by the mesh "
                f"size: {n_total} % {dev} != 0"
            )
        nl = n_total // dev
        track = cfg.track_writer_ids

        def body(data_l, topo_f, alive_f, part, w, key, *rest):
            loss_f = rest[0] if rest else None
            idx = jnp.int32(0)
            for a, s in zip(axes, sizes):
                idx = idx * s + jax.lax.axis_index(a)
            row_start = idx * nl
            # The one batched cross-shard exchange: publish this shard's
            # queue block over the fast axis, then the coalesced outer
            # hop(s). Row order matches the (dcn-major, ici-minor) node
            # partitioning, so the gathered tables are globally indexed.
            # The emitted byte curves are computed HERE, from the actual
            # operands of each staged collective (local shapes x dtype
            # widths at trace time) — NOT from traffic_model — so the
            # model stays an independent prediction and the measured==
            # model pins (tests/test_shard_driver.py, the bench lane)
            # catch a wire-format regression: gathering an extra table,
            # widening a dtype, or moving a hop changes these numbers
            # while the model's arithmetic does not.
            qs = [data_l.q_writer, data_l.q_ver, data_l.q_tx]
            if track:
                qs.append(data_l.q_gw)
            hop_ici = hop_dcn = 0.0
            for a, s in zip(reversed(axes), reversed(sizes)):
                cur = sum(
                    int(np.prod(x.shape)) * x.dtype.itemsize for x in qs
                )
                hop = float(dev * (s - 1) * cur)
                if a == axes[-1]:
                    hop_ici += hop
                else:
                    hop_dcn += hop
                qs = [
                    jax.lax.all_gather(x, a, axis=0, tiled=True)
                    for x in qs
                ]
            ctx = ShardCtx(
                axes=axes,
                row_start=row_start,
                q_writer=qs[0],
                q_ver=qs[1],
                q_tx=qs[2],
                q_gw=qs[3] if track else None,
            )
            out, stats = gossip_ops._broadcast_round(
                data_l, topo_f, alive_f, part, w, key, cfg,
                loss=loss_f, shard=ctx,
            )
            stats["xshard_bytes_ici"] = jnp.float32(hop_ici)
            stats["xshard_bytes_dcn"] = jnp.float32(hop_dcn)
            return out, stats

        dspecs = _data_specs(mesh)
        topo_specs = jax.tree.map(lambda _: P(), topo)
        stat_keys = (
            "applied_broadcast", "msgs", "cell_merges",
            "window_degraded", "lost_msgs",
            "xshard_bytes_ici", "xshard_bytes_dcn",
        )
        if cfg.prop_observe:
            # Propagation plane: per-shard partial counts join the
            # round's coalesced psum inside the body, so the outputs
            # are replicated like every other stat.
            stat_keys = stat_keys + (
                "prop_link", "prop_useful", "prop_dup",
                "prop_kills", "prop_pulls",
            )
        stats_specs = {k: P() for k in stat_keys}
        in_specs = [dspecs, topo_specs, P(), P(), P(), P()]
        args = [data, topo, alive, partition, writes, rng]
        if loss is not None:
            in_specs.append(P())
            args.append(loss)
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(dspecs, stats_specs),
            check_rep=False,
        )
        return fn(*args)

    return bcast


def simulate_sharded(
    cfg,
    topo,
    sched,
    mesh: Mesh,
    seed: int = 0,
    state=None,
    max_chunk: int | None = None,
    telemetry=None,
):
    """Dense-engine run under the shard_map round driver.

    State is node-sharded over ``mesh`` (``shard_cluster_state``), the
    topology is replicated, the broadcast plane runs through
    :func:`make_sharded_broadcast`, and SWIM/sync/track stay
    GSPMD-placed over the sharded carry. Curves carry the exchange's
    per-round cross-shard bytes; results are bit-identical to
    ``sim.simulate`` on one device (tests/test_shard_driver.py).
    """
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import engine

    if state is None:
        state = engine.init_cluster(cfg, len(sched.sample_writer))
        state = mesh_mod.shard_cluster_state(state, mesh)
    return engine.simulate(
        cfg, replicate(topo, mesh), sched, seed=seed, state=state,
        max_chunk=max_chunk, telemetry=telemetry,
        bcast_fn=make_sharded_broadcast(mesh),
    )


def per_device_state_bytes(tree) -> dict:
    """Live-buffer bytes per device over a state pytree's addressable
    shards — the measured (not arithmetic) side of the O(N/D) memory
    claim in docs/SCALING.md. Replicated leaves (writer heads, slot
    metadata) count fully on every device, sharded leaves only their
    block, so the per-device total is exactly what that device's
    allocator holds for the state."""
    out: dict = {}
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for s in leaf.addressable_shards:
            nbytes = int(np.prod(s.data.shape or (1,))) * s.data.dtype.itemsize
            out[s.device] = out.get(s.device, 0) + nbytes
    return out


def simulate_sparse_sharded(
    cfg,
    topo,
    sched,
    mesh: Mesh,
    seed: int = 0,
    telemetry=None,
    resume: dict | None = None,
    stop_after_epoch: int | None = None,
):
    """Sparse-engine (any-node-writes) run under the shard_map driver:
    slot-plane broadcast through the explicit exchange (queue entries
    carry global writer ids, so ``q_gw`` rides the same gather), epoch
    rotation/cold sync/SWIM over GSPMD-sharded state."""
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import sparse_engine

    node = node_spec_entry(mesh)
    if resume is None:
        resume = sparse_engine.initial_resume(
            cfg, len(sched.sample_writer)
        )
        resume["sstate"] = mesh_mod.shard_sparse_state(
            resume["sstate"], mesh
        )
        resume["swim"] = jax.tree.map(
            lambda x: jax.device_put(
                x,
                NamedSharding(
                    mesh, P(node, *([None] * (x.ndim - 1)))
                ),
            ),
            resume["swim"],
        )
        resume["vis_round"] = jax.device_put(
            resume["vis_round"], NamedSharding(mesh, P(None, node))
        )
    return sparse_engine.simulate_sparse(
        cfg, replicate(topo, mesh), sched, seed=seed, resume=resume,
        stop_after_epoch=stop_after_epoch,
        telemetry=telemetry, bcast_fn=make_sharded_broadcast(mesh),
    )


def simulate_chunks_sharded(
    ccfg,
    origin,
    last_seq,
    rounds: int,
    mesh: Mesh,
    seed: int = 0,
    max_chunk: int | None = None,
    telemetry=None,
    faults=None,
    state=None,
    vis=None,
    start_round: int = 0,
):
    """Chunk-plane (seq-chunk) run with coverage node-sharded over
    ``mesh``. The chunk round's gossip is row-local gathers over the
    bounded coverage tables, so GSPMD placement alone partitions it —
    there is no version-plane broadcast queue to exchange explicitly,
    and the xshard curve keys stay zero by design.

    ``state``/``vis``/``start_round`` are the elastic resume seam:
    pass a carried (re-placed) coverage state and visibility latch with
    the absolute resume round to continue a checkpointed run
    bit-identically (sim/chunk_engine.simulate_chunks)."""
    import jax.numpy as jnp

    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.ops import chunks as chunk_ops
    from corrosion_tpu.sim import chunk_engine

    node = node_spec_entry(mesh)
    origin = jnp.asarray(origin, jnp.int32)
    last_seq = jnp.asarray(last_seq, jnp.int32)
    if state is None:
        state = mesh_mod.shard_chunk_state(
            chunk_ops.init_chunks(ccfg, origin, last_seq), mesh
        )
    if vis is None:
        vis = jax.device_put(
            jnp.full((ccfg.n_nodes, ccfg.n_streams), -1, jnp.int32),
            NamedSharding(mesh, P(node, None)),
        )
    return chunk_engine.simulate_chunks(
        ccfg, origin, replicate(last_seq, mesh), rounds, seed=seed,
        max_chunk=max_chunk, telemetry=telemetry, faults=faults,
        state=state, vis=vis, start_round=start_round,
    )


def simulate_mixed_sharded(
    cfg,
    ccfg,
    topo,
    sched,
    streams,
    mesh: Mesh,
    seed: int = 0,
    max_chunk: int | None = None,
    telemetry=None,
    state=None,
):
    """Mixed chunk+version run under the shard_map broadcast driver:
    the version plane's delivery chain runs through the explicit queue
    exchange (same ShardCtx path as the dense engine), the chunk plane
    and big-version admission stay GSPMD-placed over the node-sharded
    MixedState.

    ``state`` is the elastic resume seam: a re-placed MixedState whose
    carried ``round`` anchors the tail schedule in absolute rounds
    (sim/mixed_engine.simulate_mixed)."""
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import mixed_engine

    if state is None:
        state = mesh_mod.shard_mixed_state(
            mixed_engine.init_mixed_state(cfg, ccfg, topo, sched, streams),
            mesh,
        )
    return mixed_engine.simulate_mixed(
        cfg, ccfg, replicate(topo, mesh), sched, streams, seed=seed,
        max_chunk=max_chunk, telemetry=telemetry, state=state,
        bcast_fn=make_sharded_broadcast(mesh),
    )
