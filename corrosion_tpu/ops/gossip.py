"""Changeset broadcast + anti-entropy sync kernels (the data plane).

TPU-native equivalent of the reference's dissemination machinery:

- **Broadcast** (corro-agent/src/broadcast/mod.rs:356-567): local writes enter
  a bounded pending queue and fan out each round to ring-0 (same-region)
  peers eagerly plus random far peers (mod.rs:465-473, 522-537), with a
  per-entry retransmission budget (`max_transmissions`); receivers rebroadcast
  newly-applied changes (agent.rs:2040-2057).
- **Anti-entropy sync** (corro-agent/src/api/peer.rs:925-1527 +
  corro-types/src/sync.rs:123-246): periodically each node pulls from a peer:
  version-vector diff (`compute_available_needs` ≡ the vectorized ``deficit``
  here) and a budgeted, chunk-capped transfer (the 8 KiB chunk / scheduler
  semantics collapse to a per-writer ``sync_chunk`` and per-session
  ``sync_budget`` in versions).

State model: ``W`` writer streams; node i tracks per writer w a contiguous
watermark ``contig[i, w]`` (i holds versions 1..contig), ``seen[i, w]``
(highest version heard of), and — when ``window_k > 0`` — an out-of-order
possession window ``oo[:, i, w]``: a ``window_k``-bit little-endian bitmask
whose bit b means "i also holds version contig + 1 + b". The reference
applies *complete* versions in any order and tracks arbitrary gap ranges
per actor (`process_multiple_changes`, corro-agent/src/agent.rs:1809-2060;
gap ranges in `sync_need`, corro-types/src/agent.rs:1041-1046); the window
is the bounded-tensor form of that RangeSet — versions applied ahead of a
loss-induced gap become visible immediately, while anti-entropy fills the
holes and promotes the watermark through them. A change (w, v) is *visible*
at i once ``contig[i, w] >= v`` or its window bit is set; the unbounded
tail (v > contig + window_k) degrades to the old pessimistic in-order
behavior (tracked in ``seen`` only, healed by sync), which under-claims
possession — always safe, never wrong.

Delivery without per-pair buffers: queues stay version-sorted, and delivery
scans queue slots in order, so a burst of versions from one sender applies
in sequence within a single round; arrivals beyond a gap land in the
window.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import crdt, faulting, onehot, routing


@dataclass(frozen=True)
class GossipConfig:
    n_nodes: int
    n_writers: int
    queue: int = 16  # pending-broadcast queue capacity
    max_writes_per_round: int = 4  # static bound on versions a writer adds/round
    fanout_near: int = 2  # eager ring-0 (same-region) targets
    fanout_far: int = 2  # random cluster-wide targets (num_indirect_probes)
    max_transmissions: int = 6
    loss_prob: float = 0.0
    sync_interval: int = 10  # rounds between a node's sync sessions
    sync_budget: int = 256  # versions transferred per session (total)
    sync_chunk: int = 64  # versions per writer per peer (chunk cap)
    sync_peers: int = 3  # peers pulled from per session (ref: 3-10, agent.rs:84)
    sync_candidates: int = 8  # candidate peers scored by need per session
    # Rebroadcast-intake policy. ``rebroadcast_fresh_budget`` gives a newly
    # applied entry the holder's own full ``max_transmissions`` (the
    # reference's per-holder requeue, broadcast/mod.rs:549-563) instead of
    # inheriting the sender's remaining budget minus one (a hop-TTL).
    # ``rebroadcast_stale`` re-admits re-deliveries of versions the node
    # already held (keeps old versions circulating; incompatible with fresh
    # budgets — entries would never expire). Defaults follow the reference:
    # per-holder budgets, first receipts only — measured 2x better p50/p99
    # under write storms (docs/SCALING.md "Queue policy under write
    # storms").
    rebroadcast_fresh_budget: bool = True
    rebroadcast_stale: bool = False
    # Applied messages admitted to the queue per node per round (0 = the
    # fanout*2 default). Under a cluster-wide write storm this cap — not
    # queue depth — bounds how many of its appliers rebroadcast a version:
    # an intake share of k_in/new-versions-per-round multiplies the
    # epidemic growth factor.
    rebroadcast_intake: int = 0
    # Queue keep-priority when over capacity: "version" keeps the lowest
    # version numbers (cross-writer — arbitrary under many writers, and
    # measured to starve fresh versions under load), "budget" keeps the
    # most remaining transmissions (youngest entries under fresh budgets).
    queue_priority: str = "budget"
    # CRDT cell plane: per-node LWW/causal-length registers that every
    # applied version scatter-merges into (0 = plane disabled). The global
    # cell key space has n_cells keys; each write touches cells_per_write.
    n_cells: int = 0
    cells_per_write: int = 1
    # Out-of-order possession window (bits per (node, writer) above contig;
    # multiple of 32, 0 = strict in-order). Models the reference's apply-
    # in-any-order + gap-range bookkeeping (agent.rs:1809-2060) within a
    # bounded tensor; see the module docstring.
    window_k: int = 32
    # Writer columns are rotating SLOTS (ops/sparse_writers.py): queue
    # entries carry the writer's GLOBAL id alongside the slot so CRDT cell
    # derivation keys on identity, not slot index — slot reuse across
    # epochs must never collide cell keys. Requires topo.writer_ids.
    track_writer_ids: bool = False
    # One-hot/kernel backend for every delivery/sync primitive (see
    # ops/onehot.resolve_backend): "native" (CPU scatter/gather),
    # "dense" (one-hot broadcast / MXU), "pallas" (fused VMEM kernels,
    # interpret-mode off-TPU), or None = auto by platform. Static, so
    # the choice bakes into the trace like every other config field.
    kernel_backend: str | None = None
    # Propagation-topology observables (sim/telemetry.PROP_CURVE_KEYS):
    # per-round region-pair traffic matrix + effective-fanout split
    # computed inside the broadcast round, and the rumor-age histogram
    # in the engine scan bodies. Static — False (the default) keeps the
    # pre-propagation trace bit-identical with zero extra work, the
    # same skip contract as the chaos axes. Requires the topology's
    # region count <= telemetry.PROP_REGIONS.
    prop_observe: bool = False
    # ---- Adaptive dissemination plane (docs/PERFORMANCE.md "Adaptive
    # dissemination"). Three composable mechanisms against the measured
    # 97% redundant-delivery waste, all static and defaulting OFF (the
    # chaos axes' zero-cost-skip contract: a default config's trace is
    # bit-identical to the pre-adaptive plane).
    #
    # (a) Feedback-based rumor death (Demers counter kill): every
    # delivered copy matching one of the receiver's own pending-queue
    # entries (same writer, same version) is necessarily a redundant
    # receipt of a rumor the node is actively spreading — count them
    # per (node, slot) in ``q_dup`` and retire the entry once the count
    # reaches ``rumor_kill_k``. A killed entry leaves the queue in the
    # SAME round's rebuild, so its capacity slot is immediately
    # available to that round's ``rebroadcast_intake`` admissions (the
    # intake default is fanout*2 — without same-round frees a kill
    # would leak the slot for a round; regression-pinned in
    # tests/test_dissemination.py). 0 = off (``q_dup`` is zero-width).
    rumor_kill_k: int = 0
    # (b) Push->pull phase switching (Karp et al. phases): a node whose
    # pending queue holds ONLY old rumors (version age above this
    # threshold vs the writer's committed head; an empty queue does not
    # saturate — a node with nothing to spread still discovers through
    # its far slots) is "saturated": its far-fanout source slots stop
    # pulling random queue rows (the redundant-copy firehose) and the
    # node instead escalates to an immediate anti-entropy pull session
    # (digests-then-deltas through the existing sync-plane grant path —
    # no new wire format), without waiting out its sync cohort slot.
    # 0 = off.
    pull_switch_age: int = 0
    # (c) Age-targeted forwarding: rebroadcast-intake priority flips
    # from oldest-version-first (the measured pathology: old saturated
    # versions monopolize the fanout*2 intake slots) to youngest-first,
    # binned on the propagation plane's rumor-age edges
    # (AGE_FORWARD_EDGES == telemetry.RUMOR_AGE_EDGES, pinned) with the
    # version number as the in-bin tie-break.
    age_forward: bool = False
    # Anti-entropy candidate scoring sketch: above _EXACT_SCORE_MAX the
    # scorer falls back from the exact per-writer deficit to a digest;
    # >0 replaces the scalar total-progress digest with a B-bucket
    # set-reconciliation sketch (per-writer progress folded into B
    # contiguous writer blocks, per-bucket one-sided deficits quantized
    # through the u8/bf16 ``digest_quantize`` path and summed) — a
    # strictly tighter lower bound on the true deficit that still costs
    # O(B) per candidate instead of O(W). The exact path is untouched
    # and stays the pinned reference. 0 = legacy scalar digest.
    sync_sketch_buckets: int = 0

    def __post_init__(self):
        if self.window_k < 0 or self.window_k % 32 != 0:
            raise ValueError(
                f"window_k must be a non-negative multiple of 32, got "
                f"{self.window_k}"
            )
        if self.sync_peers > self.sync_candidates:
            raise ValueError(
                f"sync_peers ({self.sync_peers}) must be <= "
                f"sync_candidates ({self.sync_candidates})"
            )
        if self.rebroadcast_fresh_budget and self.rebroadcast_stale:
            raise ValueError(
                "rebroadcast_fresh_budget requires rebroadcast_stale=False: "
                "stale re-admissions with refreshed budgets never expire"
            )
        if self.queue_priority not in ("version", "budget"):
            raise ValueError(
                f"queue_priority must be 'version' or 'budget', got "
                f"{self.queue_priority!r}"
            )
        if self.kernel_backend is not None and (
            self.kernel_backend not in onehot.BACKENDS
        ):
            raise ValueError(
                f"kernel_backend must be one of {onehot.BACKENDS} or "
                f"None, got {self.kernel_backend!r}"
            )
        if self.rumor_kill_k < 0:
            raise ValueError(
                f"rumor_kill_k must be >= 0 (0 = off), got "
                f"{self.rumor_kill_k}"
            )
        if self.pull_switch_age < 0:
            raise ValueError(
                f"pull_switch_age must be >= 0 (0 = off), got "
                f"{self.pull_switch_age}"
            )
        if self.sync_sketch_buckets < 0:
            raise ValueError(
                f"sync_sketch_buckets must be >= 0 (0 = scalar digest), "
                f"got {self.sync_sketch_buckets}"
            )
        if self.age_forward and self.rebroadcast_stale:
            raise ValueError(
                "age_forward orders the intake by version age; under "
                "rebroadcast_stale the intake re-admits already-held old "
                "versions, which the age priority would immediately "
                "starve — enable one or the other"
            )

    @property
    def fanout(self) -> int:
        return self.fanout_near + self.fanout_far


class Topology(NamedTuple):
    """Region layout (contiguous index blocks) + writer placement + rings.

    Regions model geography; ``region_rtt`` classifies every region pair
    into an RTT ring bucket 0-5 (the 0-5/5-15/15-50/50-100/100-200/
    200-300 ms buckets of corro-types/src/members.rs:33). Same-region pairs
    are ring 0 — the eager-broadcast / preferred-sync peers; cross-region
    links can be partitioned.
    """

    region: jax.Array  # i32[N] region id per node
    region_start: jax.Array  # i32[N] first node index of own region
    region_size: jax.Array  # i32[N] size of own region
    region_rtt: jax.Array  # i32[R, R] ring bucket per region pair (0-5)
    writer_nodes: jax.Array  # i32[W] node hosting each writer stream
    writer_of_node: jax.Array  # i32[N] writer index or -1
    sync_phase: jax.Array  # i32[N] per-node jitter offset for sync cadence
    # Balanced sync cohorts (None = unscheduled): row c lists the nodes
    # whose sync timer fires when (round + phase) % interval == 0 lands on
    # phase class c, padded with -1. With cohorts the whole sync round runs
    # on cohort-sized tensors — a sync_interval× cut in per-round work.
    sync_cohorts: jax.Array | None = None
    # Global identity per writer column (u32[W]); None = columns ARE the
    # identity (the dense model). With rotating slots
    # (cfg.track_writer_ids) this maps slot -> writing node id and is
    # swapped at epoch boundaries by ops/sparse_writers.rotate.
    writer_ids: jax.Array | None = None


# corro-lint: disable=CT001,CT002,CT004 reason=host-side topology builder
def make_topology(
    region_sizes: list[int], writer_nodes, seed: int = 0, region_rtt=None,
    sync_interval: int | None = None,
) -> Topology:
    """Build a topology; ``region_rtt`` defaults to a ring-1 flat geography
    (everything near but not ring 0). Pass an [R, R] matrix of ring classes
    0-5, or "geo" for a synthetic circle geography with graded rings.

    ``sync_interval`` (must match GossipConfig.sync_interval) switches the
    sync plane to balanced cohorts: nodes are split into ``interval`` equal
    phase classes, and each round only that round's class syncs, on
    cohort-sized tensors."""
    import numpy as np

    n = int(sum(region_sizes))
    r_count = len(region_sizes)
    region = np.zeros(n, np.int32)
    rstart = np.zeros(n, np.int32)
    rsize = np.zeros(n, np.int32)
    off = 0
    for rid, sz in enumerate(region_sizes):
        region[off : off + sz] = rid
        rstart[off : off + sz] = off
        rsize[off : off + sz] = sz
        off += sz
    if region_rtt is None:
        rtt = np.ones((r_count, r_count), np.int32)
        np.fill_diagonal(rtt, 0)
    elif isinstance(region_rtt, str) and region_rtt == "geo":
        # Regions on a circle; ring class grows with arc distance, spanning
        # the full bucket range like a WAN deployment.
        d = np.abs(np.arange(r_count)[:, None] - np.arange(r_count)[None, :])
        d = np.minimum(d, r_count - d)  # circular distance
        max_d = max(int(d.max()), 1)
        rtt = np.ceil(d / max_d * 5).astype(np.int32)
    else:
        rtt = np.asarray(region_rtt, np.int32)
        assert rtt.shape == (r_count, r_count)
    writer_nodes = np.asarray(writer_nodes, np.int32)
    won = np.full(n, -1, np.int32)
    won[writer_nodes] = np.arange(len(writer_nodes), dtype=np.int32)
    rng = np.random.default_rng(seed)
    if sync_interval is None:
        phase = rng.integers(0, 1 << 30, n).astype(np.int32)
        cohorts = None
    else:
        # Balanced phases: every residue class gets ⌈n/interval⌉ or ⌊…⌋
        # members; cohort row c = the nodes due when (round + phase) %
        # interval == 0 selects class c, i.e. phase == c.
        perm = rng.permutation(n).astype(np.int32)
        phase = np.empty(n, np.int32)
        phase[perm] = np.arange(n, dtype=np.int32) % sync_interval
        nc = -(-n // sync_interval)  # ceil
        cohorts = np.full((sync_interval, nc), -1, np.int32)
        for c in range(sync_interval):
            members = np.nonzero(phase == c)[0].astype(np.int32)
            cohorts[c, : len(members)] = members
    return Topology(
        region=jnp.asarray(region),
        region_start=jnp.asarray(rstart),
        region_size=jnp.asarray(rsize),
        region_rtt=jnp.asarray(rtt),
        writer_nodes=jnp.asarray(writer_nodes),
        writer_of_node=jnp.asarray(won),
        sync_phase=jnp.asarray(phase),
        sync_cohorts=None if cohorts is None else jnp.asarray(cohorts),
    )


class ShardCtx(NamedTuple):
    """Per-shard context for the explicit shard_map broadcast driver
    (corrosion_tpu/parallel/shard_driver.py).

    When present, ``_broadcast_round`` runs as the LOCAL-rows body of a
    ``shard_map`` call: ``data`` holds only this shard's node rows while
    ``topo``/``alive``/``partition`` are the full replicated tables, the
    pending-queue tables arrive pre-gathered (the one batched cross-shard
    exchange per round — staged all_gather per mesh axis), and the few
    cross-shard scalar reductions ride ``lax.psum`` over ``axes``. All
    RNG draws whose shape would otherwise depend on the shard sample at
    the FULL shape and slice local rows, so the sharded round is
    bit-identical to the unsharded one for any device count.
    """

    axes: tuple  # mesh axis names, outer -> inner (trace-time static)
    row_start: jax.Array  # i32[] global node index of this shard's first row
    q_writer: jax.Array  # i32[N, Q] full gathered queue tables
    q_ver: jax.Array  # u32[N, Q]
    q_tx: jax.Array  # i32[N, Q]
    q_gw: jax.Array | None  # u32[N, Q] (track_writer_ids configs only)


class DataState(NamedTuple):
    head: jax.Array  # u32[W] writer's committed version head
    contig: jax.Array  # u32[N, W] contiguous watermark per (node, writer)
    seen: jax.Array  # u32[N, W] highest version heard of
    oo: jax.Array  # u32[B, N, W] out-of-order window words (B = window_k/32)
    oo_any: jax.Array  # bool[] any window bit set anywhere (lax.cond gate)
    q_writer: jax.Array  # i32[N, Q] (-1 = empty)
    q_ver: jax.Array  # u32[N, Q]
    q_tx: jax.Array  # i32[N, Q] transmissions left
    q_gw: jax.Array  # u32[N, Q] global writer id (Q=0 unless track_writer_ids)
    # Duplicate-receipt counter per pending entry (Demers rumor death,
    # cfg.rumor_kill_k; Q=0 when the mechanism is off — the q_gw
    # zero-width idiom). Receiver-local: never part of the shard
    # driver's queue exchange.
    q_dup: jax.Array  # i32[N, Q or 0]
    cells: crdt.CellState  # u32[N * K] x3 per-node registers (K=0: disabled)


def init_data(cfg: GossipConfig) -> DataState:
    n, w, q = cfg.n_nodes, cfg.n_writers, cfg.queue
    return DataState(
        head=jnp.zeros((w,), jnp.uint32),
        contig=jnp.zeros((n, w), jnp.uint32),
        seen=jnp.zeros((n, w), jnp.uint32),
        oo=jnp.zeros((cfg.window_k // 32, n, w), jnp.uint32),
        oo_any=jnp.array(False, dtype=bool),
        q_writer=jnp.full((n, q), -1, jnp.int32),
        q_ver=jnp.zeros((n, q), jnp.uint32),
        q_tx=jnp.zeros((n, q), jnp.int32),
        q_gw=jnp.zeros((n, q if cfg.track_writer_ids else 0), jnp.uint32),
        q_dup=jnp.zeros((n, q if cfg.rumor_kill_k > 0 else 0), jnp.int32),
        cells=crdt.make_cells(n * cfg.n_cells),
    )


# -- out-of-order possession window -------------------------------------------
#
# The window is a B-word little-endian bitfield per (node, writer), anchored
# one above contig: bit b of the field means possession of version
# contig + 1 + b. All ops are word-unrolled elementwise jnp (B is 1-2 in
# practice), so they fuse into the surrounding round.


def _trailing_ones(oo: jax.Array) -> jax.Array:
    """i32[...]: count of consecutive set bits from bit 0 of the B-word
    field — how far contig can promote through the window."""
    t = jnp.zeros(oo.shape[1:], jnp.int32)
    carry = jnp.ones(oo.shape[1:], bool)
    for b in range(oo.shape[0]):
        tb = jax.lax.population_count(
            oo[b] & ~(oo[b] + jnp.uint32(1))
        ).astype(jnp.int32)
        t = t + jnp.where(carry, tb, 0)
        carry = carry & (tb == 32)
    return t


def _window_shift(oo: jax.Array, t: jax.Array) -> jax.Array:
    """Right-shift the B-word bitfield by t (i32[...], 0 <= t <= 32B) —
    the re-anchor after contig advances by t."""
    nw = oo.shape[0]
    outs = []
    for i in range(nw):
        acc = jnp.zeros_like(oo[i])
        for j in range(i, nw):
            s = t - 32 * (j - i)
            sr = jnp.clip(s, 0, 31).astype(jnp.uint32)
            sl = jnp.clip(-s, 0, 31).astype(jnp.uint32)
            acc = (
                acc
                | jnp.where((s >= 0) & (s < 32), oo[j] >> sr, jnp.uint32(0))
                | jnp.where((s > -32) & (s < 0), oo[j] << sl, jnp.uint32(0))
            )
        outs.append(acc)
    return jnp.stack(outs) if nw else oo


def window_absorb(
    contig: jax.Array,  # u32[..., W] watermark BEFORE this round's advance
    oo: jax.Array,  # u32[B, ..., W] window anchored at ``contig``
    adv: jax.Array,  # i32[..., W] in-order advance being applied now
    new_bits: jax.Array,  # u32[B, ..., W] new possession, anchored at contig+adv
) -> tuple[jax.Array, jax.Array]:
    """Advance the watermark by ``adv``, fold newly-possessed out-of-order
    versions into the window, then promote contig through any now-contiguous
    prefix (the RangeSet-coalesce step of the reference's bookkeeping,
    corro-types/src/agent.rs:1009-1047). Returns (contig', oo')."""
    oo = _window_shift(oo, adv) | new_bits
    t = _trailing_ones(oo)
    return (
        contig + adv.astype(jnp.uint32) + t.astype(jnp.uint32),
        _window_shift(oo, t),
    )


def _window_admit(
    oo: jax.Array,  # u32[B, N, W] window anchored at contig_pre
    contig_pre: jax.Array,  # u32[N, W]
    adv: jax.Array,  # u32[N, W] this round's in-order advance
    adv_m: jax.Array,  # u32[N, K] adv gathered per message's (row, writer)
    d: jax.Array,  # u32[N, K] true delta of each message above contig_pre
    valid: jax.Array,  # bool[N, K] live, deduped messages (sentinels out)
    wk: int,
    gather_word=None,  # (u32[N, W]) -> u32[N, K]: per-message word lookup
    assemble_word=None,  # (u32[N, K]) -> u32[N, W]: OR contributions
    fast_idx: jax.Array | None = None,  # i32[N, K] writer column (fast path)
    width: int | None = None,
    backend: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Shared out-of-order admission for both delivery paths (they differ
    only in gather/scatter primitive): decide which arrivals land in the
    window, assemble their bits, absorb. Each admitted (row, writer, bit)
    is unique — ``valid`` is deduped and already-set bits are masked — so
    the assemble step's ADD is an exact bitwise OR. The fast path passes
    its writer-column index (``fast_idx``) instead of lambdas, routing
    through ``onehot.window_delivery`` — under the pallas backend the
    gathers, the old-bit check, and the bit assembly fuse into one VMEM
    kernel; elsewhere that helper is the identical rowgather/rowsum
    composition. Returns (contig', oo', newly_possessed mask)."""
    if fast_idx is not None:
        new_poss, words = onehot.window_delivery(
            oo, fast_idx, d, adv_m, valid, wk, width, backend=backend
        )
        contig2, oo2 = window_absorb(
            contig_pre, oo, adv.astype(jnp.int32), words
        )
        return contig2, oo2, new_poss
    d_rel = d - adv_m  # meaningful only when d > adv_m
    in_win = valid & (d > adv_m) & (d_rel <= jnp.uint32(wk))
    # Already possessed in the OLD window (bit d-1 relative to contig_pre)?
    # Those were merged + rebroadcast at first receipt — only `seen` cares
    # about this copy.
    bit_old = d - 1
    prev_poss = jnp.zeros_like(in_win)
    for b in range(oo.shape[0]):
        wordv = gather_word(oo[b])
        sh = jnp.minimum(bit_old - jnp.uint32(32 * b), jnp.uint32(31))
        inb = (bit_old >= 32 * b) & (bit_old < 32 * (b + 1))
        prev_poss = prev_poss | (inb & (((wordv >> sh) & 1) == 1))
    new_poss = in_win & ~prev_poss
    bit_new = d_rel - 1
    words = []
    for b in range(oo.shape[0]):
        sh = jnp.minimum(bit_new - jnp.uint32(32 * b), jnp.uint32(31))
        inb = new_poss & (bit_new >= 32 * b) & (bit_new < 32 * (b + 1))
        words.append(
            assemble_word(
                jnp.where(inb, jnp.uint32(1) << sh, jnp.uint32(0))
            )
        )
    contig2, oo2 = window_absorb(
        contig_pre, oo, adv.astype(jnp.int32), jnp.stack(words)
    )
    return contig2, oo2, new_poss


def window_possession(data: DataState) -> jax.Array:
    """i64-free possession count per (node, writer): contig + set window
    bits. (Diagnostics/tests; visibility() answers per-version queries.)"""
    bits = jnp.zeros(data.contig.shape, jnp.uint32)
    for b in range(data.oo.shape[0]):
        bits = bits + jax.lax.population_count(data.oo[b])
    return data.contig + bits


# Row-local scatter-max / take_along_axis as one-hot reductions (Pallas
# VMEM kernels at scale, jnp broadcast below threshold — see ops/onehot.py
# for the measured rationale).
_onehot_rowmax = onehot.rowmax
_onehot_rowgather = onehot.rowgather

# Writer-axis width above which delivery switches from the dense one-hot
# form to the sort+scatter form (module-level so tests can force either
# path at small sizes).
_FAST_MAX_WRITERS = 2048
# Writer-axis width at which the sync grant enumeration switches to the
# two-level block decomposition (same test-override convention).
_BLOCK_ENUM_MIN_WRITERS = 2048
# Anti-entropy candidate pipeline form. True (default) scores all C
# candidates and pulls all S+1 selected peers with single tiled
# [R, C, W] / [R, S+1, W] gathers + reductions; False keeps the original
# per-candidate Python loop (C sequential [R, W] gathers that bloat the
# trace and serialize on device) as the bit-identical reference —
# selection and post-sync state are pinned equal in
# tests/test_perf_plane.py. Flip BEFORE tracing (clear_cache() on
# sync_round, the convention test_data_plane_crdt already uses).
_BATCHED_SYNC = True
# Row×writer×candidate volume above which candidate scoring falls back
# from the exact per-writer deficit to the total-progress digest
# (module-level so tests can force digest mode at small sizes).
_EXACT_SCORE_MAX = 1 << 25
# Digest-path quantization (the exact path is untouched — it must stay
# bit-identical). The digest deficit saturates at the largest integer the
# narrow dtype represents EXACTLY, then casts: below saturation the
# quantized digest is the identity on the u32 deficit, so peer ranking is
# provably unchanged (the property tests in tests/test_perf_plane.py pin
# rank-equality across the exact<->digest threshold); at or above
# saturation candidates tie on need and the ring tie-break decides.
# Saturated ties are harmless ONLY while the session budget itself sits
# at or below the saturation point (every tied candidate fills the pull),
# so quantization is GATED on cfg.sync_budget <= sat — larger budgets
# keep the unclamped u32 digest, where ranking among deep deficits still
# changes what a session can drain. None = legacy unclamped u32 scoring.
# bf16 default: its 256 exact-integer saturation point covers the default
# sync_budget (256); "u8" needs sync_budget <= 255 to engage.
_DIGEST_QUANT: str | None = "bf16"
_DIGEST_SAT = {"u8": 255, "bf16": 256}


def digest_quantize(defc: jax.Array, sync_budget: int) -> jax.Array:
    """u32 digest deficit -> the quantized wire/score representation
    (u8 or bf16, saturating), or i32 passthrough when disabled or when
    ``sync_budget`` exceeds the dtype's exact-integer saturation point
    (outside the provably-harmless regime)."""
    if _DIGEST_QUANT is None or sync_budget > _DIGEST_SAT[_DIGEST_QUANT]:
        return defc.astype(jnp.int32)
    sat = jnp.uint32(_DIGEST_SAT[_DIGEST_QUANT])
    q = jnp.minimum(defc, sat)
    if _DIGEST_QUANT == "u8":
        return q.astype(jnp.uint8)
    return q.astype(jnp.bfloat16)


def _digest_score(defc: jax.Array, sync_budget: int) -> jax.Array:
    """Quantize a u32 digest deficit and widen back to i32 for the packed
    need/ring score. Exact (identity) below the saturation threshold."""
    return digest_quantize(defc, sync_budget).astype(jnp.int32)


def bucket_sketch(contig: jax.Array, buckets: int) -> jax.Array:
    """u32[N, B] set-reconciliation sketch of per-node progress
    (cfg.sync_sketch_buckets): the writer axis folds into ``buckets``
    contiguous blocks (zero-padded to a multiple) and each bucket sums
    its block's watermarks. Per-bucket one-sided differences against a
    peer lower-bound the true per-writer deficit bucket by bucket —
    Σ_b max(0, Σ_{w∈b} c_w − Σ_{w∈b} s_w) <= Σ_w max(0, c_w − s_w) —
    and equal it exactly when the peer dominates per-writer, so ranking
    among genuinely-ahead candidates is preserved (the property
    tests/test_perf_plane.py pins). B=1 degenerates to the legacy
    total-progress digest."""
    n, w = contig.shape
    wp = -(-w // buckets) * buckets
    c = jnp.pad(contig, ((0, 0), (0, wp - w)))
    return jnp.sum(
        c.reshape(n, buckets, wp // buckets), axis=2, dtype=jnp.uint32
    )


def _sketch_score(
    skc: jax.Array,  # u32[..., B] candidate sketches
    sk_self: jax.Array,  # u32[..., B] own sketch (broadcastable)
    sync_budget: int,
) -> jax.Array:
    """i32[...]: summed per-bucket one-sided sketch deficit, each bucket
    quantized through the same saturating u8/bf16 path as the scalar
    digest (a bucket deeper than the session budget saturates — the
    session cannot drain more anyway) then widened exactly."""
    d = skc - jnp.minimum(skc, sk_self)
    return jnp.sum(
        digest_quantize(d, sync_budget).astype(jnp.int32), axis=-1
    )


# Age-bin upper edges (in versions behind the writer's committed head)
# for the age-targeted forwarding priority (cfg.age_forward). Mirrors
# the propagation plane's rumor-age histogram edges so the forwarding
# policy and the observable that motivated it share one binning —
# pinned equal to telemetry.RUMOR_AGE_EDGES in
# tests/test_dissemination.py (ops cannot import sim).
AGE_FORWARD_EDGES = (1, 2, 3, 4, 5, 6, 8, 10, 12, 16, 24, 32, 48, 64)


def _intake_priority(
    head: jax.Array,  # u32[W] committed heads (post this round's writes)
    w_idx: jax.Array,  # i32[N, K] writer column per message (clamped)
    v: jax.Array,  # u32[N, K] version per message
    cfg: GossipConfig,
    bk: str,
) -> jax.Array:
    """Rebroadcast-intake keep-priority (int32-safe, higher = kept).

    Default: oldest versions first (-v, the historical policy). Under
    ``age_forward``: youngest age bin first — age = head − v binned on
    AGE_FORWARD_EDGES rides the high bits, the version number breaks
    ties inside a bin (young-first there too). Packing is i32-safe:
    15 bins * 2^24 + a 24-bit version clamp < 2^31."""
    if not cfg.age_forward:
        return -v.astype(jnp.int32)
    hw = onehot.table_gather_u32(head, w_idx, backend=bk)
    age = hw - jnp.minimum(v, hw)
    b = jnp.zeros(age.shape, jnp.int32)
    for e in AGE_FORWARD_EDGES:
        b = b + (age > jnp.uint32(e)).astype(jnp.int32)
    return -(b * jnp.int32(1 << 24)) + jnp.minimum(
        v, jnp.uint32((1 << 24) - 1)
    ).astype(jnp.int32)


def _queue_saturation(
    q_writer: jax.Array,  # i32[n, Q] local pending-queue writer slots
    q_ver: jax.Array,  # u32[n, Q]
    head: jax.Array,  # u32[W] committed heads
    alive_r: jax.Array,  # bool[n]
    cfg: GossipConfig,
    bk: str | None = None,
) -> jax.Array:
    """bool[n]: push->pull saturation signal (cfg.pull_switch_age).

    A node saturates when its pending queue is non-empty and EVERY
    entry is old (version age above the threshold vs the writer's
    committed head): its pushes are all stale and its far pulls mostly
    duplicate. An empty queue does not saturate — a node with nothing
    to spread still discovers new rumors through its far slots. Shared
    by the broadcast round (far-slot suppression) and the sync round
    (pull escalation) so the two halves of the phase switch act on the
    same signal."""
    occ = q_writer >= 0
    if bk is None:
        hq = head[jnp.maximum(q_writer, 0)]
    else:
        hq = onehot.table_gather_u32(
            head, jnp.maximum(q_writer, 0), backend=bk
        )
    age_q = hq - jnp.minimum(q_ver, hq)
    young = occ & (age_q <= jnp.uint32(cfg.pull_switch_age))
    return alive_r & jnp.any(occ, axis=1) & ~jnp.any(young, axis=1)


def _merge_versions_dense(
    cells: crdt.CellState,
    rows: jax.Array,  # i32[R] node id per row (unique); or None for 0..N-1
    writer: jax.Array,  # [R, M] writer id per change
    version: jax.Array,  # u32[R, M]
    mask: jax.Array,  # bool[R, M]
    row_ok: jax.Array | None,  # bool[R] rows whose merge lands (None = all)
    n_nodes: int,
    cfg: GossipConfig,
) -> tuple[crdt.CellState, jax.Array]:
    """Row-dense CRDT scatter-merge: every change targets a cell of its own
    row's register shard, so the flat scatter into [N·K] becomes per-row
    one-hot passes over the K cell keys (see _onehot_rowmax — the flat
    scatter was the broadcast plane's single largest cost at 100k). Exact
    same semantics as crdt.apply_changes: lexicographic (cl, col_version,
    value_rank) max via the packed (cl<<24 | col_version) word, then
    value_rank among winners."""
    k = cfg.n_cells
    r = writer.shape[0]
    bk = onehot.resolve_backend(cfg.kernel_backend)
    if rows is None:
        cl2 = cells.cl.reshape(n_nodes, k)
        cv2 = cells.col_version.reshape(n_nodes, k)
        vr2 = cells.value_rank.reshape(n_nodes, k)
    else:
        cl2 = cells.cl.reshape(n_nodes, k)[rows]
        cv2 = cells.col_version.reshape(n_nodes, k)[rows]
        vr2 = cells.value_rank.reshape(n_nodes, k)[rows]
    n_merges = jnp.sum(mask, dtype=jnp.uint32) * cfg.cells_per_write
    for j in range(cfg.cells_per_write):
        ckey, ccl, ccv, cvr = crdt.derive_change(
            writer, version, jnp.uint32(j), k
        )
        packed_state = (cl2 << 24) | cv2
        packed_in = (ccl << 24) | ccv
        p1 = jnp.maximum(
            packed_state,
            _onehot_rowmax(ckey, packed_in, mask, k, backend=bk),
        )
        vr_seed = jnp.where(p1 == packed_state, vr2, 0)
        in_win = mask & (
            packed_in == _onehot_rowgather(p1, ckey, backend=bk)
        )
        vr2 = jnp.maximum(
            vr_seed, _onehot_rowmax(ckey, cvr, in_win, k, backend=bk)
        )
        cl2 = p1 >> 24
        cv2 = p1 & jnp.uint32((1 << 24) - 1)
    if rows is None:
        out = crdt.CellState(
            cl=cl2.reshape(-1), col_version=cv2.reshape(-1),
            value_rank=vr2.reshape(-1),
        )
    else:
        idx = rows if row_ok is None else jnp.where(row_ok, rows, n_nodes)
        out = crdt.CellState(
            cl=cells.cl.reshape(n_nodes, k).at[idx].set(cl2, mode="drop").reshape(-1),
            col_version=cells.col_version.reshape(n_nodes, k)
            .at[idx].set(cv2, mode="drop").reshape(-1),
            value_rank=cells.value_rank.reshape(n_nodes, k)
            .at[idx].set(vr2, mode="drop").reshape(-1),
        )
    return out, n_merges


def _region_link_matrix(
    m_ok: jax.Array,  # bool[n, kk] delivered (post-loss) message mask
    recv_region: jax.Array,  # i32[n] receiver region per local row
    src_region: jax.Array,  # i32[n, F] source region per sampled source
    q_cap: int,
    n_regions: int,
) -> jax.Array:
    """u32[R, R] delivered-copies matrix over this caller's receiver
    rows: entry (i, j) counts copies a region-i receiver pulled from a
    region-j source queue this round. Post-loss, so the matrix's mass
    equals the ``msgs`` curve exactly (the conservation check the
    epidemic analyzer pins). One [n, kk] pass per source region plus
    R^2 scalar reduces — cheap, and only traced when
    ``cfg.prop_observe`` is set."""
    n, kk = m_ok.shape
    sr = jnp.repeat(src_region[:, :, None], q_cap, axis=2).reshape(n, kk)
    rows = []
    for j in range(n_regions):
        cj = jnp.sum(
            m_ok & (sr == j), axis=1, dtype=jnp.uint32
        )  # u32[n] copies each local receiver heard from region j
        rows.append(
            jnp.stack([
                jnp.sum(
                    jnp.where(recv_region == i, cj, jnp.uint32(0)),
                    dtype=jnp.uint32,
                )
                for i in range(n_regions)
            ])
        )
    return jnp.stack(rows, axis=1)  # [R_recv, R_src]


def _broadcast_round(
    data: DataState,
    topo: Topology,
    alive: jax.Array,
    partition: jax.Array,  # bool[R, R] True = receiver row can't hear col
    writes: jax.Array,  # u32[W] versions committed by each writer this round
    rng: jax.Array,
    cfg: GossipConfig,
    loss: jax.Array | None = None,  # f32[R] injected per-region loss prob
    shard: ShardCtx | None = None,
) -> tuple[DataState, dict]:
    w_count, q_cap = cfg.n_writers, cfg.queue
    n_total = cfg.n_nodes  # global node count
    # Receiver rows owned by this caller. Unsharded: all of them. Under
    # the shard_map driver (``shard`` present): this shard's slice of the
    # node axis — every delivery tensor below is then [n_rows, ...] and
    # the queue tables/alive/topo vectors are read at FULL width through
    # the ShardCtx / replicated arguments.
    n = data.contig.shape[0]
    if shard is None:
        nodes = jnp.arange(n)  # global node id per local row
        region_r = topo.region
        rstart_r = topo.region_start
        rsize_r = topo.region_size
        won_r = topo.writer_of_node
        alive_r = alive
        qf_w, qf_v, qf_t = data.q_writer, data.q_ver, data.q_tx
        qf_g = data.q_gw
    else:
        rs = shard.row_start

        def _rows(x):
            return jax.lax.dynamic_slice_in_dim(x, rs, n, axis=0)

        nodes = rs + jnp.arange(n)
        region_r = _rows(topo.region)
        rstart_r = _rows(topo.region_start)
        rsize_r = _rows(topo.region_size)
        won_r = _rows(topo.writer_of_node)
        alive_r = _rows(alive)
        qf_w, qf_v, qf_t, qf_g = (
            shard.q_writer, shard.q_ver, shard.q_tx, shard.q_gw
        )
    # One trace-time backend resolution for the whole round: config
    # override first, then the onehot module's globals/platform auto.
    bk = onehot.resolve_backend(cfg.kernel_backend)
    k_near, k_far, k_loss = jax.random.split(rng, 3)

    # ---- 1. local writes ---------------------------------------------------
    writes = jnp.minimum(
        writes.astype(jnp.uint32), cfg.max_writes_per_round
    ) * alive[topo.writer_nodes].astype(jnp.uint32)
    head = data.head + writes
    wi = jnp.arange(w_count)
    # Writer-hosting rows owned elsewhere drop out of the scatter
    # (mode="drop"); unsharded every index is in bounds, so the mode is
    # inert there and both paths share one scatter form.
    if shard is None:
        w_rows = topo.writer_nodes
    else:
        w_rows = jnp.where(
            (topo.writer_nodes >= shard.row_start)
            & (topo.writer_nodes < shard.row_start + n),
            topo.writer_nodes - shard.row_start,
            n,
        )
    contig = data.contig.at[w_rows, wi].max(head, mode="drop")
    seen = data.seen.at[w_rows, wi].max(head, mode="drop")
    # Captured after local commits so applied_broadcast counts only versions
    # applied via *delivery*, not the writer's own head bump.
    contig_before = contig

    # New queue entries for the writing node, one per committed version.
    mw = cfg.max_writes_per_round
    nw = jnp.where(
        won_r >= 0,
        writes[jnp.maximum(won_r, 0)],
        0,
    )  # u32[n_rows] versions written by each local node this round
    head_old_n = jnp.where(
        won_r >= 0,
        data.head[jnp.maximum(won_r, 0)],
        0,
    )
    new_ver = head_old_n[:, None] + 1 + jnp.arange(mw, dtype=jnp.uint32)[None, :]
    # u32 arange: nw is u32 and strict dtype promotion (the corro lint
    # sanitizer) rejects an implicit i32/u32 comparison.
    new_valid = (
        jnp.arange(mw, dtype=jnp.uint32)[None, :] < nw[:, None]
    ) & alive_r[:, None]
    new_writer = jnp.broadcast_to(won_r[:, None], (n, mw))
    track = cfg.track_writer_ids
    if track and topo.writer_ids is None:
        raise ValueError("track_writer_ids requires topo.writer_ids")
    # Under rotating slots a node's global writer identity IS its node id
    # (writer_ids[slot_of_node] == node), so the writer's own enqueue
    # needs no table lookup.
    new_gw = (
        jnp.broadcast_to(nodes[:, None].astype(jnp.uint32), (n, mw))
        if track else None
    )

    cells = data.cells
    n_merges = jnp.uint32(0)
    if cfg.n_cells > 0:
        # The writer materializes its own commit (the local-write txn path,
        # public/mod.rs:60-123).
        cells, m = _merge_versions_dense(
            cells, None,
            new_gw if track else jnp.maximum(new_writer, 0),
            new_ver, new_valid,
            None, n, cfg,
        )
        n_merges += m

    # ---- 2. source selection (pull/gather dissemination) -------------------
    # Receiver-centric: each node pulls the pending queues of F sampled
    # sources (near = same region, the ring-0 eager path; far = uniform).
    # Epidemically equivalent to sender-push fanout (in-degree exactly F vs
    # Binomial(N·F, 1/N)), but every delivery tensor is [N, F·Q] with
    # row-local sorts — no multi-million-element global sort per round,
    # which dominated step time at 10k+ nodes.
    f = cfg.fanout
    if f > 0:
        if shard is None:
            near_off = jax.random.randint(
                k_near, (n, cfg.fanout_near), 0, 1 << 30
            )
            far = jax.random.randint(k_far, (n, cfg.fanout_far), 0, n)
        else:
            # Sample at the FULL shape and slice local rows: every shard
            # draws the same [N, F] tensors the unsharded round draws, so
            # source choice is device-count invariant bit-for-bit.
            near_off = jax.lax.dynamic_slice_in_dim(
                jax.random.randint(
                    k_near, (n_total, cfg.fanout_near), 0, 1 << 30
                ),
                shard.row_start, n, axis=0,
            )
            far = jax.lax.dynamic_slice_in_dim(
                jax.random.randint(
                    k_far, (n_total, cfg.fanout_far), 0, n_total
                ),
                shard.row_start, n, axis=0,
            )
        near = rstart_r[:, None] + near_off % jnp.maximum(
            rsize_r[:, None], 1
        )
        src = jnp.concatenate([near, far], axis=1)  # i32[N, F] sources
        # Gather i32, never bool: TPU vectorizes integer row gathers but
        # serializes pred gathers element-by-element (~50 ms per million-
        # element bool gather measured on v5e).
        alive_i = alive.astype(jnp.int32)
        part_i = partition.astype(jnp.int32)
        link_ok = (
            (part_i[region_r[:, None], topo.region[src]] == 0)
            & alive_r[:, None]
            & (alive_i[src] > 0)
            & (src != nodes[:, None])
        )
        # ---- (b) push->pull phase switching (adaptive dissemination) --
        # Saturated receivers (only old rumors queued) drop their
        # far-slot pulls — the redundant-copy firehose once coverage
        # saturates — and escalate to a digest pull in this round's
        # sync stage instead (_sync_round). Near/ring-0 slots stay on:
        # young rumors still percolate within the region. Local-only
        # inputs (own queue rows + replicated heads), so the sharded
        # round needs no extra exchange.
        if cfg.pull_switch_age > 0 and cfg.fanout_far > 0:
            sat = _queue_saturation(
                data.q_writer, data.q_ver, head, alive_r, cfg, bk=bk
            )
            link_ok = jnp.concatenate(
                [
                    link_ok[:, : cfg.fanout_near],
                    link_ok[:, cfg.fanout_near :] & ~sat[:, None],
                ],
                axis=1,
            )
            n_pulls = jnp.sum(sat, dtype=jnp.uint32)
        else:
            n_pulls = jnp.uint32(0)
        # ---- 3. delivery (row-local sorted pass per receiver) --------------
        # Gathered message (receiver row, src f, slot q) → [N, K = F·Q] of
        # (writer, version, tx). Promotion must respect version order: sort
        # each row by (writer, version) and find, per (writer) segment, the
        # longest contiguous version run starting at contig+1 — including
        # runs stitched across sources.
        kk = f * q_cap
        m_w = qf_w[src].reshape(n, kk)
        m_v = qf_v[src].reshape(n, kk)
        m_tx = qf_t[src].reshape(n, kk)
        m_gw = qf_g[src].reshape(n, kk) if track else None
        m_ok = (
            jnp.repeat(link_ok[:, :, None], q_cap, axis=2).reshape(n, kk)
            & (m_w >= 0)
        )
        # Shared static-skip loss (ops/faulting.py): config loss and the
        # chaos plane's per-region schedule compose here; receiver-side,
        # so a region's loss burst degrades what IT hears. Sharded rounds
        # draw the loss mask at the full shape (same device-count
        # invariance as the source sampling above).
        dyn_loss = None if loss is None else loss[region_r][:, None]
        m_ok, n_lost = faulting.apply_loss(
            k_loss, m_ok, cfg.loss_prob, dyn_loss,
            full_rows=(
                None if shard is None else (n_total, shard.row_start)
            ),
        )
        n_msgs = jnp.sum(m_ok)
        # ---- (a) feedback rumor death: duplicate-receipt counting -----
        # Two duplicate-feedback signals, both counted per (node, slot)
        # against the PRE-rebuild queue layout, post-loss (a lost copy
        # is not a receipt):
        #
        # 1. Receiver-side (the pull flavor of the Demers counter): a
        #    delivered copy matching one of the receiver's OWN pending
        #    entries (same writer, same version) is necessarily a
        #    redundant receipt of a rumor the node is actively
        #    spreading. [n, Q, kk] broadcast compare — Q and kk are
        #    config-bounded (16 x fanout*16 at the defaults).
        # 2. Sender-side (the push flavor — the dominant signal): every
        #    delivered copy whose receiver already possessed the
        #    version (v <= the receiver's pre-delivery watermark)
        #    increments the SOURCE queue entry's counter. Delivered
        #    copies ARE source queue slots in the pull/gather model
        #    ([n, F, Q] = qf[src]), so the feedback is one row
        #    scatter-add back onto the source rows — the same
        #    full-shape-scatter + psum pattern as the ``pulled`` budget
        #    burn when sharded (one extra [N, Q] reduction per round,
        #    only when the mechanism is on).
        if cfg.rumor_kill_k > 0:
            hits = jnp.sum(
                m_ok[:, None, :]
                & (m_w[:, None, :] == data.q_writer[:, :, None])
                & (m_v[:, None, :] == data.q_ver[:, :, None]),
                axis=2,
                dtype=jnp.int32,
            )  # i32[n, Q]
            cw = _onehot_rowgather(
                contig_before, jnp.maximum(m_w, 0), backend=bk
            )  # u32[n, kk] receiver's pre-delivery watermark per copy
            red = (
                m_ok & (m_v <= cw)
            ).reshape(n * f, q_cap).astype(jnp.int32)
            src_flat = src.reshape(n * f)
            if shard is None:
                hits = hits + (
                    jnp.zeros((n, q_cap), jnp.int32)
                    .at[src_flat]
                    .add(red, mode="drop")
                )
            else:
                fb = (
                    jnp.zeros((n_total, q_cap), jnp.int32)
                    .at[src_flat]
                    .add(red, mode="drop")
                )
                fb = jax.lax.psum(fb, shard.axes)
                hits = hits + jax.lax.dynamic_slice_in_dim(
                    fb, shard.row_start, n, axis=0
                )
        k_in = cfg.rebroadcast_intake or cfg.fanout * 2

        # One-hot delivery is O(N·K·W) dense compute: a clear win while the
        # writer axis is narrow (wan_100k: W=512), but at W ≈ 10k (the
        # merge_10k flagship, every node a writer) the dense form does 70×
        # the work of the sort+scatter path. Gate on W.
        fast = (
            cfg.rebroadcast_fresh_budget
            and not cfg.rebroadcast_stale
            and w_count <= _FAST_MAX_WRITERS
        )
        wk = cfg.window_k
        if fast:
            # ---- 3a. delta-packed one-hot delivery (default policy) --------
            # Two structural moves, both TPU-shaped:
            #
            # 1. Under first-receipt intake with per-holder budgets, a
            #    message only matters for promotion when
            #    contig < v <= contig + K (a run of d versions needs d
            #    distinct deltas among K messages) — stale and far-ahead
            #    copies affect nothing but `seen`. Dropping them up front
            #    lets the sort key be ONE u32, (writer, v - contig) packed.
            # 2. Every cross-axis move (the base gather, the watermark
            #    scatter-max, the CRDT merge) is a dense one-hot
            #    compare+reduce over the writer/cell axis instead of a
            #    take_along_axis / .at[].max — TPU scatters and gathers
            #    serialize per element and dominated the round (269 ms +
            #    2×207 ms + 501 ms of a 1.58 s plane at 100k); the dense
            #    forms measure <1 ms each.
            mw_safe = jnp.maximum(m_w, 0)
            contig_pre = contig
            base_m = _onehot_rowgather(
                contig_pre, mw_safe, backend=bk
            )  # u32[N, kk]
            lim = max(kk, wk)
            k2 = lim + 3
            assert w_count * k2 < (1 << 32) - 1, "packed delivery key overflow"
            # Stale copies (v <= contig) affect nothing at all (seen >=
            # contig is invariant); far-ahead copies (delta > max(kk, wk) —
            # beyond both the longest possible run and the window) matter
            # only for `seen`, so their delta clamps to the lim+1 sentinel
            # and their true version rides the sort as an operand.
            useful = m_ok & (m_v > base_m)
            d_raw = jnp.where(useful, m_v - base_m, 0)
            dc = jnp.minimum(d_raw, jnp.uint32(lim + 1))
            sent_key = jnp.uint32(w_count * k2)
            pkd = jnp.where(
                useful, m_w.astype(jnp.uint32) * k2 + dc, sent_key
            )
            # Operands are ~free in lax.sort (3-key sort measured the same
            # 37 ms as 1-key at [100k, 144]); carrying v avoids a second
            # one-hot base gather after the sort. v rides as a SECOND KEY so
            # clamped far-ahead entries (shared delta sentinel, distinct
            # versions) sort by version within the sentinel run — adjacency
            # dedup for the degraded counter needs it; for unclamped
            # entries (w, d) determines v, so ordering is unchanged.
            if track:
                skey, v2, gw2 = jax.lax.sort(
                    (pkd, m_v, m_gw), dimension=1, num_keys=2,
                    is_stable=False,
                )
            else:
                skey, v2 = jax.lax.sort(
                    (pkd, m_v), dimension=1, num_keys=2, is_stable=False
                )
                gw2 = None
            valid2 = skey < sent_key
            w2 = jnp.minimum((skey // k2).astype(jnp.int32), w_count - 1)
            d2 = (skey % k2).astype(jnp.uint32)
            seg_start = jnp.concatenate(
                [jnp.ones((n, 1), bool), w2[:, 1:] != w2[:, :-1]], axis=1
            )
            prev_d = jnp.concatenate(
                [jnp.zeros((n, 1), d2.dtype), d2[:, :-1]], axis=1
            )
            # Deltas are relative to contig, so a run is simply the chain
            # 1, 2, ... (duplicates repeat a delta and keep the chain);
            # clamped far-ahead entries (lim+1) never extend a run, and a
            # run can't be longer than the kk messages that carry it.
            ok_link = (
                jnp.where(seg_start, d2 == 1, d2 <= prev_d + 1)
                & (d2 <= kk)
            )
            run = routing.segmented_prefix_and_rows(
                ok_link & valid2, seg_start
            )
            applied = run & valid2
            # One-hot reductions over the writer axis: the applied
            # watermark advance per (row, writer) is the max applied
            # delta (runs are 1..len), and `seen` is the max heard
            # version. Under the pallas backend both reductions fuse
            # into one VMEM pass (onehot.delivery_reduce); elsewhere it
            # is the two-rowmax reference composition, bit-identical.
            adv, seen = onehot.delivery_reduce(
                w2, d2, v2, applied, valid2, seen, w_count, backend=bk
            )  # u32[N, W] x2
            # First receipts: one copy per newly possessed version. Stale
            # and duplicate copies re-merge content already merged when the
            # version was first applied/granted — idempotent, so masking
            # them off the CRDT merge changes nothing but the traffic.
            first_copy = ~((~seg_start) & (d2 == prev_d))
            fresh_run = applied & first_copy
            # Degraded admissions, far component: arrivals whose delta
            # clamped to the sentinel (beyond both the longest run and the
            # window) can never be possessed this round — they degrade to
            # seen-only tracking (VERDICT r4 weak #4: without this counter
            # the partition p99 attribution is an assumption). Deduped by
            # (writer, version) adjacency — sentinel entries share d2, so
            # first_copy alone would collapse DISTINCT versions; v2 is a
            # sort key, so same-version copies are adjacent.
            prev_v2 = jnp.concatenate(
                [jnp.zeros((n, 1), v2.dtype), v2[:, :-1]], axis=1
            )
            n_degraded = jnp.sum(
                valid2 & (d2 == jnp.uint32(lim + 1))
                & ~((~seg_start) & (d2 == prev_d) & (v2 == prev_v2)),
                dtype=jnp.uint32,
            )
            if wk:
                # Out-of-order arrivals land in the possession window
                # (module docstring). All window machinery — the per-message
                # advance gather, the old-bit check, the bit assembly and
                # the absorb shifts — rides a lax.cond gated on "any live
                # window bit or any arrival beyond its run", so rounds with
                # purely in-order delivery (the no-loss steady state) pay
                # one elementwise predicate and nothing else.
                oo_pred = data.oo_any | jnp.any(
                    valid2 & ~applied & (d2 <= jnp.uint32(lim))
                )

                def _with_window(oo):
                    # d2 <= lim excludes the clamped sentinel: its TRUE
                    # delta is unknown (> lim), so admitting it would set a
                    # bit for a version the node does not hold. Deltas are
                    # window-relative above contig_pre + adv; adv per
                    # message comes from a segmented running max, not a
                    # gather — applied entries are a sorted PREFIX of their
                    # writer segment, so the running max of applied deltas
                    # already equals the writer's advance at every later
                    # position.
                    adv_m = routing.segmented_running_max(
                        jnp.where(applied, d2, 0), seg_start, lim + 2
                    )
                    contig2, oo2, new_poss = _window_admit(
                        oo, contig_pre, adv,
                        adv_m,
                        d2,
                        valid2 & first_copy & (d2 <= jnp.uint32(lim)),
                        wk,
                        fast_idx=w2,
                        width=w_count,
                        backend=bk,
                    )
                    # Near component: within the clamp limit but beyond the
                    # window above the writer's advance.
                    near_deg = jnp.sum(
                        valid2 & first_copy & (d2 <= jnp.uint32(lim))
                        & (d2 > adv_m)
                        & (d2 - adv_m > jnp.uint32(wk)),
                        dtype=jnp.uint32,
                    )
                    return (
                        contig2, oo2, fresh_run | new_poss, jnp.any(oo2),
                        near_deg,
                    )

                def _no_window(oo):
                    return (
                        contig_pre + adv, oo, fresh_run,
                        jnp.array(False, dtype=bool),
                        jnp.uint32(0),
                    )

                contig, oo_new, fresh, oo_any_new, near_deg = jax.lax.cond(
                    oo_pred, _with_window, _no_window, data.oo
                )
                n_degraded = n_degraded + near_deg
            else:
                contig = contig_pre + adv
                oo_new, oo_any_new = data.oo, data.oo_any
                fresh = fresh_run
                # Windowless degraded count, deduped by (writer, version)
                # adjacency exactly like the windowed branches: duplicate
                # same-round copies of one arrival degrade ONE version,
                # not one per copy (v2 rides the sort as the second key,
                # so same-version copies are adjacent; the v2 check
                # matters for sentinel-clamped entries, which share d2
                # across distinct versions).
                n_degraded = jnp.sum(
                    valid2 & ~applied
                    & ~((~seg_start) & (d2 == prev_d) & (v2 == prev_v2)),
                    dtype=jnp.uint32,
                )
            if cfg.n_cells > 0:
                cells, m = _merge_versions_dense(
                    cells, None, gw2 if track else w2, v2, fresh, None, n,
                    cfg,
                )
                n_merges += m

            if cfg.prop_observe:
                # Fast path: ``fresh`` is exactly the newly-possessed
                # first-receipt mask (stale copies were dropped before
                # the sort), so the propagation counter reads it as-is.
                prop_fresh = fresh
            in_mask, in_payloads = routing.rebuild_bounded_queue(
                fresh,
                # Oldest versions first by default; youngest age bin
                # first under cfg.age_forward (mechanism (c)).
                _intake_priority(head, w2, v2, cfg, bk),
                (w2, v2, gw2) if track else (w2, v2),
                k_in,
            )
            in_w, in_v = in_payloads[0], in_payloads[1]
            in_gw = in_payloads[2] if track else None
            in_tx = jnp.full(in_w.shape, cfg.max_transmissions, jnp.int32)
            in_w = jnp.where(in_mask, in_w, -1)
        else:
            # ---- 3b. legacy lexicographic delivery -------------------------
            # Needed when stale re-deliveries re-enter the queue or budgets
            # are inherited hop-TTLs: both need tx carried through the sort
            # (-tx orders duplicate copies highest-budget-first so the dedup
            # keeps the strongest requeue).
            wkey = jnp.where(m_ok, m_w, w_count)  # invalid → sentinel
            if track:
                w2, v2, neg_tx, gw2 = jax.lax.sort(
                    (wkey, m_v, -m_tx, m_gw), dimension=1, num_keys=3,
                    is_stable=False,
                )
            else:
                w2, v2, neg_tx = jax.lax.sort(
                    (wkey, m_v, -m_tx), dimension=1, num_keys=3,
                    is_stable=False,
                )
                gw2 = None
            tx2 = -neg_tx
            valid2 = w2 < w_count

            seg_start = jnp.concatenate(
                [jnp.ones((n, 1), bool), w2[:, 1:] != w2[:, :-1]], axis=1
            )
            # MXU block gather — take_along_axis at [N, K]←[N, 10k] lowers
            # as a serialized per-element gather (~17 ms + a 40 ms staging
            # copy at the flagship shapes).
            base = onehot.rowgather_wide(
                contig, jnp.minimum(w2, w_count - 1), backend=bk
            )
            prev_v = jnp.concatenate(
                [jnp.zeros((n, 1), v2.dtype), v2[:, :-1]], axis=1
            )
            # A message extends the run when it lands at or below one past
            # the better of (previous message in segment, already-held
            # watermark): a stale retransmission ahead of v=contig+1 must
            # not break the chain (v <= prev_v + 1 alone would — the prev
            # can lag base).
            ok_link = jnp.where(
                seg_start,
                v2 <= base + 1,
                v2 <= jnp.maximum(prev_v, base) + 1,
            )
            run = routing.segmented_prefix_and_rows(
                ok_link & valid2, seg_start
            )
            # Applied = delivered versions on an unbroken run from contig+1.
            contig_pre = contig
            w2c = jnp.minimum(w2, w_count - 1)
            # LOCAL row index (scatters target this caller's [n, W]
            # tables; ``nodes`` is the global id and only names identity).
            rw2 = jnp.arange(n)[:, None] * w_count + w2c
            applied_v = jnp.where(run & valid2, v2, 0)
            contig_run = (
                contig.reshape(-1)
                .at[rw2.reshape(-1)]
                .max(applied_v.reshape(-1))
                .reshape(n, w_count)
            )
            seen = (
                seen.reshape(-1)
                .at[rw2.reshape(-1)]
                .max(jnp.where(valid2, v2, 0).reshape(-1))
                .reshape(n, w_count)
            )
            prev_same = (~seg_start) & (v2 == prev_v)

            if wk:
                # Out-of-order window, sort+scatter flavor (see the fast
                # path above for the policy comments). Uniqueness of each
                # (row, writer, bit) contribution makes scatter-ADD of
                # distinct powers of two an exact bitwise OR.
                adv = contig_run - contig_pre  # u32[N, W]
                oo_pred = data.oo_any | jnp.any(
                    valid2 & ~run & (v2 > base)
                )

                def _with_window(oo):
                    # Per-message advance from a segmented running max
                    # (applied entries are a sorted prefix of their writer
                    # segment; see the fast path) — take_along_axis here
                    # lowers as a serialized gather. v2 > base masks stale
                    # retransmissions (possible under rebroadcast_stale):
                    # their wrapped u32 delta must never enter the packing.
                    d_m = jnp.where(valid2, v2 - base, 0)
                    adv_m = routing.segmented_running_max(
                        jnp.where(run & valid2 & (v2 > base), d_m, 0),
                        seg_start,
                        1 << 24,  # versions < 2^24 (CRDT pack domain)
                    )
                    contig2, oo2, new_poss = _window_admit(
                        oo, contig_pre, adv,
                        adv_m,
                        d_m,
                        valid2 & ~prev_same,
                        wk,
                        lambda word: onehot.rowgather_wide(
                            word, w2c, backend=bk
                        ),
                        lambda contrib: (
                            jnp.zeros((n * w_count,), jnp.uint32)
                            .at[rw2.reshape(-1)]
                            .add(contrib.reshape(-1))
                            .reshape(n, w_count)
                        ),
                    )
                    near_deg = jnp.sum(
                        valid2 & ~prev_same & (v2 > base)
                        & (d_m > adv_m)
                        & (d_m - adv_m > jnp.uint32(wk)),
                        dtype=jnp.uint32,
                    )
                    return contig2, oo2, new_poss, jnp.any(oo2), near_deg

                def _no_window(oo):
                    return (
                        contig_run, oo,
                        jnp.zeros_like(valid2),
                        jnp.array(False, dtype=bool),
                        jnp.uint32(0),
                    )

                contig, oo_new, extra_poss, oo_any_new, n_degraded = (
                    jax.lax.cond(oo_pred, _with_window, _no_window, data.oo)
                )
            else:
                contig = contig_run
                oo_new, oo_any_new = data.oo, data.oo_any
                extra_poss = jnp.zeros_like(valid2)
                # First copies only (~prev_same), matching the windowed
                # branch's dedup: same-round duplicate deliveries of one
                # (writer, version) degrade a single version.
                n_degraded = jnp.sum(
                    valid2 & ~run & (v2 > base) & ~prev_same,
                    dtype=jnp.uint32,
                )

            if cfg.n_cells > 0:
                # Receivers materialize every message on the applied run
                # plus window-possessed arrivals. Row-dense merge (the
                # cell-key axis is always narrow).
                cells, m = _merge_versions_dense(
                    cells, None, gw2 if track else w2c, v2,
                    (run & valid2) | extra_poss, None, n, cfg,
                )
                n_merges += m

            # ---- 4. rebroadcast intake (epidemic requeue) ------------------
            # Same-round duplicate copies of one (writer, version) never
            # take two intake slots; ``rebroadcast_stale`` additionally
            # re-admits re-deliveries of already-held versions (old versions
            # keep circulating at inherited budgets), while the fresh-budget
            # policy admits only first receipts but with the holder's full
            # budget (the reference's per-holder requeue,
            # broadcast/mod.rs:549-563). Window-possessed arrivals are
            # newly applied changes and rebroadcast like any other
            # (agent.rs:2040-2057).
            fresh = run & valid2 & ~prev_same
            if cfg.prop_observe:
                # Propagation counter: newly POSSESSED first receipts
                # only — under rebroadcast_stale the intake mask below
                # also re-admits already-held versions, which are
                # redundant copies by the epidemic's accounting.
                prop_fresh = (
                    (run & valid2 & (v2 > base) & ~prev_same) | extra_poss
                )
            if not cfg.rebroadcast_stale:
                fresh &= v2 > base
            fresh = fresh | extra_poss
            if cfg.rebroadcast_fresh_budget:
                intake_ok = fresh
                in_budget = jnp.full_like(tx2, cfg.max_transmissions)
            else:
                intake_ok = fresh & (tx2 > 1)
                in_budget = tx2 - 1
            in_mask, in_payloads = routing.rebuild_bounded_queue(
                intake_ok,
                # Oldest versions first by default (like the queue);
                # youngest age bin first under cfg.age_forward.
                _intake_priority(head, w2c, v2, cfg, bk),
                (w2c, v2, in_budget, gw2) if track else (w2c, v2, in_budget),
                k_in,
            )
            in_w, in_v, in_tx = in_payloads[:3]
            in_gw = in_payloads[3] if track else None
            in_w = jnp.where(in_mask, in_w, -1)
        # Propagation-topology observables (prop_observe): the region-
        # pair traffic matrix over delivered copies and the effective-
        # fanout split. ``prop_fresh`` (both delivery flavors set it) is
        # the per-message first-receipt-of-a-newly-possessed-version
        # mask — the epidemic's productive pushes; everything else
        # delivered was redundant.
        if cfg.prop_observe:
            prop_useful = jnp.sum(prop_fresh, dtype=jnp.uint32)
            prop_link = _region_link_matrix(
                m_ok, region_r, topo.region[src], q_cap,
                partition.shape[0],
            )
        # A source's budgets burn when at least one receiver pulled it.
        # Sources live on arbitrary shards, so the sharded driver counts
        # pulls into the FULL vector, psums across shards, and keeps its
        # local rows — the round's one cross-shard reduction.
        if shard is None:
            pulled = (
                jnp.zeros((n,), jnp.int32)
                .at[jnp.where(link_ok, src, n)]
                .add(1, mode="drop")
            )
            sent_any = pulled > 0
        else:
            pulled = (
                jnp.zeros((n_total,), jnp.int32)
                .at[jnp.where(link_ok, src, n_total)]
                .add(1, mode="drop")
            )
            pulled = jax.lax.psum(pulled, shard.axes)
            sent_any = (
                jax.lax.dynamic_slice_in_dim(
                    pulled, shard.row_start, n, axis=0
                )
                > 0
            )
    else:
        # Sync-only configuration: no fanout, no delivery, budgets retained.
        n_msgs = jnp.uint32(0)
        in_mask = jnp.zeros((n, 0), dtype=bool)
        in_w = jnp.zeros((n, 0), jnp.int32)
        in_v = jnp.zeros((n, 0), jnp.uint32)
        in_tx = jnp.zeros((n, 0), jnp.int32)
        in_gw = jnp.zeros((n, 0), jnp.uint32) if track else None
        sent_any = jnp.zeros((n,), dtype=bool)
        oo_new, oo_any_new = data.oo, data.oo_any
        n_degraded = jnp.uint32(0)
        n_lost = jnp.uint32(0)
        n_pulls = jnp.uint32(0)
        if cfg.rumor_kill_k > 0:
            hits = jnp.zeros_like(data.q_dup)
        if cfg.prop_observe:
            prop_useful = jnp.uint32(0)
            prop_link = jnp.zeros(
                (partition.shape[0], partition.shape[0]), jnp.uint32
            )

    # ---- 5. queue rebuild (oldest versions first, like the FIFO buffer) ----
    # An entry's tx budget burns only when the sender actually reached at
    # least one peer this round (dead/fully-partitioned senders keep their
    # budget, matching the membership plane's sendable gating).
    old_tx = jnp.where(
        (data.q_writer >= 0) & sent_any[:, None], data.q_tx - 1,
        jnp.where(data.q_writer >= 0, data.q_tx, 0),
    )
    old_live = (data.q_writer >= 0) & (old_tx > 0)
    if cfg.rumor_kill_k > 0:
        # ---- (a) rumor death: retire over-duplicated entries ----------
        # The counter kill à la Demers: an entry whose accumulated
        # duplicate receipts reach k leaves the rebuild THIS round —
        # its capacity slot is immediately available to this round's
        # intake admissions (rebuild_bounded_queue keeps the top
        # ``capacity`` VALID candidates, so one fewer old candidate is
        # one more intake candidate kept). ``prop_rumor_kills`` counts
        # entries the kill retired that budgets alone would have kept.
        q_dup2 = data.q_dup + hits
        kill = (data.q_writer >= 0) & (q_dup2 >= cfg.rumor_kill_k)
        n_kills = jnp.sum(kill & old_live, dtype=jnp.uint32)
        old_live = old_live & ~kill
    else:
        n_kills = jnp.uint32(0)
    cand_w = jnp.concatenate([data.q_writer, new_writer, in_w], axis=1)
    cand_v = jnp.concatenate([data.q_ver, new_ver, in_v], axis=1)
    cand_tx = jnp.concatenate(
        [
            old_tx,
            jnp.full((n, mw), cfg.max_transmissions, jnp.int32),
            in_tx,
        ],
        axis=1,
    )
    cand_ok = jnp.concatenate(
        [
            old_live,
            new_valid,
            in_mask,
        ],
        axis=1,
    )
    # Keep-priority over capacity ("version": lowest version numbers;
    # "budget": most remaining transmissions). Dropped entries are healed
    # by sync. Delivery re-sorts rows, so slot order is free.
    if cfg.queue_priority == "budget":
        prio = cand_tx
    else:
        prio = -cand_v.astype(jnp.int32)
    payloads = [cand_w, cand_v, cand_tx]
    if track:
        payloads.append(jnp.concatenate([data.q_gw, new_gw, in_gw], axis=1))
    if cfg.rumor_kill_k > 0:
        # Surviving old entries carry their accumulated counter; new
        # writes and intake admissions start at zero.
        payloads.append(
            jnp.concatenate(
                [
                    q_dup2,
                    jnp.zeros((n, mw), jnp.int32),
                    jnp.zeros(in_w.shape, jnp.int32),
                ],
                axis=1,
            )
        )
    keep, out = routing.rebuild_bounded_queue(
        cand_ok, prio, tuple(payloads), q_cap
    )
    q_writer, q_ver, q_tx = out[0], out[1], out[2]
    q_gw = out[3] if track else data.q_gw
    q_dup = out[-1] if cfg.rumor_kill_k > 0 else data.q_dup
    q_writer = jnp.where(keep, q_writer, -1)

    applied_b = jnp.sum(
        (contig - contig_before).astype(jnp.uint32), dtype=jnp.uint32
    )
    if shard is not None:
        # One coalesced cross-shard scalar reduction for the round's
        # stats, plus the global OR for the window-live flag (a psum of
        # a replicated flag still reduces to the right truth value, so
        # the windowless/sync-only branches need no special case). The
        # propagation counters (local-receiver-row partial sums) join
        # the same coalesced reduction when the plane is on.
        if cfg.prop_observe:
            (
                applied_b, n_msgs, n_merges, n_degraded, n_lost, oo_cnt,
                prop_useful, prop_link, n_kills, n_pulls,
            ) = jax.lax.psum(
                (
                    applied_b, n_msgs, n_merges, n_degraded, n_lost,
                    oo_any_new.astype(jnp.uint32), prop_useful, prop_link,
                    n_kills, n_pulls,
                ),
                shard.axes,
            )
        else:
            applied_b, n_msgs, n_merges, n_degraded, n_lost, oo_cnt = (
                jax.lax.psum(
                    (
                        applied_b, n_msgs, n_merges, n_degraded, n_lost,
                        oo_any_new.astype(jnp.uint32),
                    ),
                    shard.axes,
                )
            )
        oo_any_new = oo_cnt > 0
    stats = {
        "applied_broadcast": applied_b,
        "msgs": n_msgs,
        "cell_merges": n_merges,
        # Arrivals that could not be possessed this round (beyond the
        # out-of-order window above the writer's advance): they degrade to
        # seen-only tracking and are healed by sync. Nonzero sustained
        # values mean window_k is undersized for the loss/outage pattern.
        "window_degraded": n_degraded,
        # Messages dropped by loss injection (config ambient + chaos
        # plan) this round — the chaos plane's ground-truth drop count.
        "lost_msgs": n_lost,
    }
    if cfg.prop_observe:
        # Delivered copies partition exactly into useful (first receipt
        # of a newly possessed version) + redundant; the link matrix's
        # mass equals msgs. Both identities are pinned by the epidemic
        # analyzer's conservation checks.
        stats["prop_link"] = prop_link
        stats["prop_useful"] = prop_useful
        stats["prop_dup"] = (
            n_msgs.astype(jnp.uint32) - prop_useful
        )
        # Adaptive-dissemination counters: rumors retired by the
        # duplicate-receipt kill (mechanism a) and nodes whose far-fanout
        # slots flipped from push to pull this round (mechanism b). Both
        # are exactly zero when the mechanisms are disabled.
        stats["prop_kills"] = n_kills
        stats["prop_pulls"] = n_pulls
    return (
        DataState(
            head=head,
            contig=contig,
            seen=seen,
            oo=oo_new,
            oo_any=oo_any_new,
            q_writer=q_writer,
            q_ver=q_ver,
            q_tx=q_tx,
            q_gw=q_gw,
            q_dup=q_dup,
            cells=cells,
        ),
        stats,
    )


# Public entry points. The ``_donated`` twins alias the DataState argument
# into the output (donate_argnums) so XLA reuses the round-trip state
# buffers in place — ~10 MiB/round at 512 nodes, two orders more at the
# 100k configs — instead of allocating a fresh copy. Donation only takes
# effect on TOP-LEVEL calls (inside a jitted scan body the call inlines
# and the outer entry point's donation governs); after a donated call the
# caller's input DataState is dead and must not be read again, which is
# why the plain entry stays the default for tests and ad-hoc stepping.
# docs/PERFORMANCE.md ("Donation invariants") has the contract.
broadcast_round = partial(jax.jit, static_argnames=("cfg",))(
    _broadcast_round
)
broadcast_round_donated = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(0,)
)(_broadcast_round)


def _sync_round(
    data: DataState,
    topo: Topology,
    alive: jax.Array,
    partition: jax.Array,
    round_idx: jax.Array,
    rng: jax.Array,
    cfg: GossipConfig,
) -> tuple[DataState, dict]:
    """Anti-entropy pull sessions for nodes whose sync timer is due.

    With cohort scheduling (make_topology(sync_interval=...)) the round's
    due set is one statically-shaped cohort and every tensor in the session
    is cohort-sized — a sync_interval× cut in work and memory vs computing
    over all N rows. Without cohorts, all N rows are processed with a due
    mask (the jittered-phase model).

    Under push→pull switching (cfg.pull_switch_age > 0) a SECOND session
    runs after the scheduled one: nodes whose queues are saturated (only
    old rumors pending — the same predicate that suppressed their
    far-fanout pushes in _broadcast_round this round) pull
    digests-then-deltas immediately instead of waiting out their cohort
    slot. Nodes already due this round are excluded (phase == c IS cohort
    membership, so the mask works for both scheduling modes) — no row
    syncs twice, and with the mechanism off the extra session does not
    exist (zero-cost-skip contract).
    """
    if cfg.pull_switch_age > 0:
        rng, k_esc = jax.random.split(rng)
    if topo.sync_cohorts is not None:
        if topo.sync_cohorts.shape[0] != cfg.sync_interval:
            raise ValueError(
                f"topology cohorts were built for sync_interval="
                f"{topo.sync_cohorts.shape[0]} but cfg.sync_interval="
                f"{cfg.sync_interval}; rebuild make_topology with the "
                f"matching interval"
            )
        cohort = jnp.mod(-round_idx, jnp.int32(cfg.sync_interval))
        rows = topo.sync_cohorts[cohort]  # i32[R], -1 padded
        # i32 gather (pred gathers serialize on TPU).
        row_ok = (rows >= 0) & (
            alive.astype(jnp.int32)[jnp.maximum(rows, 0)] > 0
        )
        data, stats = _sync_rows(
            data, topo, alive, partition, jnp.maximum(rows, 0), row_ok,
            rng, cfg,
        )
    else:
        nodes = jnp.arange(cfg.n_nodes)
        due = alive & (
            (round_idx + topo.sync_phase) % jnp.int32(cfg.sync_interval)
            == 0
        )
        data, stats = _sync_rows(
            data, topo, alive, partition, nodes, due, rng, cfg
        )
    if cfg.pull_switch_age == 0:
        return data, stats
    # ---- (b) pull escalation (adaptive dissemination) ------------------
    # Saturation re-read from the post-broadcast queue so the escalated
    # pull reflects what the node actually holds NOW; rows the scheduled
    # session just served are excluded via the phase identity above.
    bk = onehot.resolve_backend(cfg.kernel_backend)
    sat = _queue_saturation(
        data.q_writer, data.q_ver, data.head, alive, cfg, bk=bk
    )
    already = (
        (round_idx + topo.sync_phase) % jnp.int32(cfg.sync_interval) == 0
    )
    data, estats = _sync_rows(
        data, topo, alive, partition, jnp.arange(cfg.n_nodes),
        sat & ~already, k_esc, cfg,
    )
    return data, {k: stats[k] + estats[k] for k in stats}


sync_round = partial(jax.jit, static_argnames=("cfg",))(_sync_round)
sync_round_donated = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(0,)
)(_sync_round)


def _sync_rows(
    data: DataState,
    topo: Topology,
    alive: jax.Array,
    partition: jax.Array,
    rows: jax.Array,  # i32[R] node id per participating row (unique)
    row_ok: jax.Array,  # bool[R] live + unpadded
    rng: jax.Array,
    cfg: GossipConfig,
) -> tuple[DataState, dict]:
    """One anti-entropy session per row (corro-agent/src/agent.rs:2383-2423
    peer choice; peer.rs:925-1286 parallel_sync): score ``sync_candidates``
    sampled peers (half ring-0/same-region, half cluster-wide) by how many
    versions they hold that we lack (need desc), tie-break toward ring 0
    (ring asc), and pull from the top ``sync_peers`` under one shared
    session budget — the reference's 3-10 peers ordered by need."""
    n = cfg.n_nodes
    r = rows.shape[0]
    bk = onehot.resolve_backend(cfg.kernel_backend)
    k_near, k_far = jax.random.split(rng)
    region_r = topo.region[rows]
    contig0 = data.contig[rows]  # u32[R, W]
    seen_r = data.seen[rows]

    # Candidate sample: same-region ("ring 0") and uniform far peers.
    c_near = cfg.sync_candidates // 2
    c_far = cfg.sync_candidates - c_near
    near = topo.region_start[rows][:, None] + jax.random.randint(
        k_near, (r, c_near), 0, 1 << 30
    ) % jnp.maximum(topo.region_size[rows][:, None], 1)
    far = jax.random.randint(k_far, (r, c_far), 0, n)
    cand = jnp.concatenate([near, far], axis=1)  # i32[R, C]
    # Gather i32, never bool (pred gathers serialize on TPU).
    alive_i = alive.astype(jnp.int32)
    part_i = partition.astype(jnp.int32)
    ok_c = (
        row_ok[:, None]
        & (alive_i[cand] > 0)
        & (cand != rows[:, None])
        & (part_i[region_r[:, None], topo.region[cand]] == 0)
    )

    # Candidate need scoring. Exact mode computes, per candidate, the count
    # of versions the candidate holds that we lack, while very large
    # row counts fall back to a total-progress digest (ranking peers by
    # advertised heads). Selection is heuristic either way; the grant
    # pass below recomputes the exact deficit for the chosen peers.
    # Cohorts keep R = N / sync_interval, so even the 100k config scores
    # exactly. The batched form (default) issues ONE tiled [R, C, W]
    # gather + reduction; the looped form is the bit-identical reference
    # (max/sum over candidates commute, so the two orders agree exactly).
    c_count = cfg.sync_candidates
    exact = r * cfg.n_writers * c_count <= _EXACT_SCORE_MAX
    total = None
    sketch = None
    if not exact:
        if cfg.sync_sketch_buckets > 0:
            # Bucketed set-reconciliation sketch: B per-bucket one-sided
            # differences instead of one scalar total — a strictly
            # tighter deficit lower bound at B× the digest's gather
            # width (still << the [R, C, W] exact gather). B=1 is
            # bit-identical to the legacy total-progress digest
            # (pinned in tests/test_perf_plane.py).
            sketch = bucket_sketch(data.contig, cfg.sync_sketch_buckets)
            sketch_r = sketch[rows]
        else:
            total = jnp.sum(data.contig, axis=1, dtype=jnp.uint32)
            total_r = total[rows]
    if _BATCHED_SYNC:
        if exact:
            cc = data.contig[cand]  # u32[R, C, W] one tiled gather
            defc = jnp.sum(
                (cc - jnp.minimum(cc, contig0[:, None, :])).astype(
                    jnp.uint32
                ),
                axis=-1,
                dtype=jnp.int32,
            )  # i32[R, C]
            # Scoring reads the candidate's state — that digest also
            # carries its heads, so adopt them (the reference learns heads
            # from every SyncState exchange, not only from pulled peers).
            seen_r = jnp.maximum(
                seen_r,
                jnp.max(
                    jnp.where(ok_c[:, :, None], data.seen[cand], 0), axis=1
                ),
            )
        elif sketch is not None:
            skc = sketch[cand]  # u32[R, C, B] one tiled gather
            defc = _sketch_score(
                skc, sketch_r[:, None, :], cfg.sync_budget
            )
        else:
            tc = total[cand]  # u32[R, C]
            defc = _digest_score(
                tc - jnp.minimum(tc, total_r[:, None]), cfg.sync_budget
            )
    else:
        need_cols = []
        for c in range(c_count):
            if exact:
                cc = data.contig[cand[:, c]]  # [R, W]
                need_cols.append(
                    jnp.sum(
                        (cc - jnp.minimum(cc, contig0)).astype(jnp.uint32),
                        axis=-1,
                        dtype=jnp.int32,
                    )
                )
                seen_r = jnp.maximum(
                    seen_r,
                    jnp.where(ok_c[:, c, None], data.seen[cand[:, c]], 0),
                )
            elif sketch is not None:
                need_cols.append(
                    _sketch_score(
                        sketch[cand[:, c]], sketch_r, cfg.sync_budget
                    )
                )
            else:
                tc = total[cand[:, c]]
                need_cols.append(
                    _digest_score(
                        tc - jnp.minimum(tc, total_r), cfg.sync_budget
                    )
                )
        defc = jnp.stack(need_cols, axis=1)  # i32[R, C]

    # RTT ring of each candidate (members.rs:33 buckets via region pairs).
    ring = topo.region_rtt[region_r[:, None], topo.region[cand]]
    # Candidates are sampled with replacement; mask duplicate columns so a
    # single peer cannot occupy several of the top slots (and soak up
    # sync_peers x chunk from one source). dup[r, i] = any earlier column
    # j < i holding the same peer — one [R, C, C] compare instead of C
    # unrolled scatter updates.
    if _BATCHED_SYNC:
        tri = (
            jnp.arange(c_count)[None, :] < jnp.arange(c_count)[:, None]
        )  # tri[i, j] = j strictly before i
        dup = jnp.any(
            (cand[:, :, None] == cand[:, None, :]) & tri[None, :, :],
            axis=2,
        )
    else:
        dup = jnp.zeros_like(ok_c)
        for i in range(1, c_count):
            dup = dup.at[:, i].set(
                jnp.any(cand[:, :i] == cand[:, i : i + 1], axis=1)
            )
    # need desc, ring asc (agent.rs:2383-2423): scale need so the ring
    # ordering only breaks need ties.
    score = jnp.where(ok_c & ~dup & (defc > 0), defc * 8 + (5 - ring), -1)
    order = jnp.argsort(-score, axis=1, stable=True)[:, : cfg.sync_peers]
    sel = jnp.take_along_axis(cand, order, axis=1)  # i32[R, S]
    sel_ok = jnp.take_along_axis(score, order, axis=1) > 0

    # Pull from selected peers in need order under one shared budget, plus
    # one origin-targeted pull: the writer behind the row's largest known
    # head gap certainly holds its own versions, so "needle" versions with
    # few replicas are always reachable (the reference syncs with peers
    # chosen by per-actor need — the origin actor is the canonical holder).
    gap = (seen_r - jnp.minimum(seen_r, contig0)).astype(jnp.int32)  # [R, W]
    w_star = jnp.argmax(gap, axis=1)  # [R]
    origin = topo.writer_nodes[w_star]
    origin_ok = (
        row_ok
        & (jnp.max(gap, axis=1) > 0)
        & (alive_i[origin] > 0)
        & (origin != rows)
        & (part_i[region_r, topo.region[origin]] == 0)
    )
    # Union pull: the session pulls from the UNION of what its chosen
    # peers hold — one elementwise max over the peers' watermark rows,
    # then a single budgeted grant pass, instead of a deficit + cumsum
    # sweep over [R, W] per peer (the per-peer sweeps were the sync
    # plane's dominant cost mid-run: 4x the [cohort, writers] traffic).
    # Versions teleport within a round in this model, so which peer a
    # granted version "came from" is unobservable; the only semantic
    # shift is that sync_chunk caps a writer's grant once per session
    # rather than once per peer. Batched (default): ONE [R, S+1, W]
    # gather + max-reduce over the peer axis; looped: the per-peer
    # reference (elementwise max commutes, so both orders agree exactly).
    if _BATCHED_SYNC:
        peers = jnp.concatenate([sel, origin[:, None]], axis=1)
        ok_p = jnp.concatenate([sel_ok, origin_ok[:, None]], axis=1)
        avail = jnp.maximum(
            contig0,
            jnp.max(
                jnp.where(ok_p[:, :, None], data.contig[peers], 0), axis=1
            ),
        )
        if not exact:
            seen_r = jnp.maximum(
                seen_r,
                jnp.max(
                    jnp.where(ok_p[:, :, None], data.seen[peers], 0),
                    axis=1,
                ),
            )
    else:
        pulls = [(sel[:, s], sel_ok[:, s]) for s in range(cfg.sync_peers)]
        pulls.append((origin, origin_ok))
        avail = contig0
        for p, ok_s in pulls:
            avail = jnp.maximum(
                avail, jnp.where(ok_s[:, None], data.contig[p], 0)
            )
            if not exact:
                seen_r = jnp.maximum(
                    seen_r, jnp.where(ok_s[:, None], data.seen[p], 0)
                )
    deficit = (avail - jnp.minimum(avail, contig0)).astype(jnp.uint32)
    per_w = jnp.minimum(deficit, jnp.uint32(cfg.sync_chunk)).astype(
        jnp.int32
    )
    cum = jnp.cumsum(per_w, axis=1)
    grant = jnp.clip(
        jnp.int32(cfg.sync_budget) - (cum - per_w), 0, per_w
    ).astype(jnp.uint32)
    contig_r = contig0 + grant

    # Healing a gap promotes the watermark through any out-of-order
    # versions possessed above it (the RangeSet coalesce the reference does
    # on insert, agent.rs:1009-1047). Gated on oo_any: window-free rounds
    # skip the gathers, shifts, and the cluster-wide flag recompute.
    if cfg.window_k:

        def _absorb(args):
            c_r, oo_full = args
            oo_r = oo_full[:, rows]
            # Budget spent re-granting versions the row already possesses
            # out-of-order (idempotent re-merges): window bits at positions
            # below the grant. The deficit the grant is cut from does not
            # exclude window possession, so under loss with a tight budget
            # this is the hole-filling slowdown ADVICE r4 #2 names — the
            # counter measures it instead of guessing.
            gi = grant.astype(jnp.int32)
            regrant = jnp.uint32(0)
            for b in range(oo_r.shape[0]):
                g = jnp.clip(gi - 32 * b, 0, 32)
                m = jnp.where(
                    g >= 32,
                    jnp.uint32(0xFFFFFFFF),
                    (jnp.uint32(1) << jnp.minimum(g, 31).astype(jnp.uint32))
                    - 1,
                )
                regrant = regrant + jnp.sum(
                    jnp.where(
                        row_ok[:, None],
                        jax.lax.population_count(oo_r[b] & m),
                        0,
                    ),
                    dtype=jnp.uint32,
                )
            c2, oo2 = window_absorb(
                contig0, oo_r, gi,
                jnp.zeros_like(oo_r),
            )
            oo_out = oo_full.at[:, jnp.where(row_ok, rows, cfg.n_nodes)].set(
                oo2, mode="drop"
            )
            c2 = jnp.where(row_ok[:, None], c2, c_r)
            return c2, oo_out, jnp.any(oo_out), regrant

        contig_r, oo_new, oo_any_new, n_regrant = jax.lax.cond(
            data.oo_any,
            _absorb,
            lambda args: (args[0], args[1], data.oo_any, jnp.uint32(0)),
            (contig_r, data.oo),
        )
    else:
        oo_new, oo_any_new = data.oo, data.oo_any
        n_regrant = jnp.uint32(0)
    seen_r = jnp.maximum(seen_r, contig_r)

    cells = data.cells
    n_merges = jnp.uint32(0)
    if cfg.n_cells > 0:
        # Materialize every granted version: enumerate the per-(row, writer)
        # grant ranges into flat (node, writer, version) triples — the
        # changeset replay the server streams in the reference
        # (peer.rs:610-666) — and scatter-merge their derived cells.
        # Wrapped in lax.cond: a session round that granted nothing (the
        # converged steady state) skips the worst-case-sized enumeration.
        # Enumerates the GRANTED ranges only — versions promoted out of the
        # window were merged when they first arrived, and grant <= budget
        # keeps the [R, B] enumeration exact.
        gr = grant.astype(jnp.int32)  # [R, W]

        def enumerate_and_merge(cells):
            cum = jnp.cumsum(gr, axis=1)  # [R, W]
            total_g = cum[:, -1]  # [R] <= sync_budget
            b = cfg.sync_budget
            e = jnp.arange(b, dtype=jnp.int32)  # [B]
            w_count_ = cfg.n_writers
            if w_count_ < _BLOCK_ENUM_MIN_WRITERS:
                # Writer owning granted unit e: the count of inclusive
                # span ends at or before e. Zero-grant writers (cum equal
                # to their predecessor's) count too, which is exactly the
                # index shift they cause. On CPU a batched binary search
                # over the sorted cum rows (O(B log W)); on accelerators
                # a dense counting reduce over the writer axis (the prior
                # scatter-marks + cummax formulation serialized an [R·B]
                # scatter, ~120 ms at the 100k cohort). Identical counts:
                # side="right" on a non-decreasing row IS the <= count.
                if bk == "native":
                    w_idx = jax.vmap(
                        lambda c: jnp.searchsorted(c, e, side="right")
                    )(cum).astype(jnp.int32)
                else:
                    w_idx = jnp.sum(
                        cum[:, None, :] <= e[None, :, None], axis=2,
                        dtype=jnp.int32,
                    )
                w_idx = jnp.minimum(w_idx, w_count_ - 1)
                # One-hot rowgathers (fused) — take_along_axis at
                # [R, B]←[R, W] lowers as a serialized dynamic gather.
                prev = jnp.where(
                    w_idx > 0,
                    _onehot_rowgather(
                        cum.astype(jnp.uint32),
                        jnp.maximum(w_idx - 1, 0),
                        backend=bk,
                    ).astype(jnp.int32),
                    0,
                )
                ver = (
                    _onehot_rowgather(contig0, w_idx, backend=bk)
                    + 1
                    + (e[None, :] - prev).astype(jnp.uint32)
                )
            else:
                # Wide writer axes (the 10k flagship): two-level block
                # decomposition. Count fully-covered 128-wide blocks, pull
                # the boundary block's cums AND the matching contig block
                # with one-hot f32 matmuls on the MXU (exact: cum <= the
                # sync budget and versions < 2^24), then finish inside the
                # 128 lanes — ~80x less VPU work than the flat counting
                # reduce + two W-wide one-hot gathers.
                blk = 128
                nb = -(-w_count_ // blk)
                wp = nb * blk
                # cum rides f32 exactly because it is bounded by the
                # budget (static check); contig0 is NOT bounded by config,
                # so it travels as u16 halves (exact for all of u32).
                assert cfg.sync_budget < (1 << 24), (
                    "sync_budget exceeds f32-exact block enumeration"
                )
                cum_p = jnp.pad(
                    cum, ((0, 0), (0, wp - w_count_)),
                    mode="edge",
                )
                c0_p = jnp.pad(
                    contig0, ((0, 0), (0, wp - w_count_))
                )
                be = cum_p[:, blk - 1 :: blk]  # [R, NB] block-end cums
                nfull = jnp.sum(
                    be[:, None, :] <= e[None, :, None], axis=2,
                    dtype=jnp.int32,
                )  # [R, B] fully-covered blocks
                bsel = jnp.minimum(nfull, nb - 1)
                onehot_b = (
                    bsel[:, :, None]
                    == jnp.arange(nb)[None, None, :]
                ).astype(jnp.float32)  # [R, B, NB]
                dotp = partial(
                    jnp.einsum, precision=jax.lax.Precision.HIGHEST
                )
                blk_cum = dotp(
                    "reb,rbj->rej", onehot_b,
                    cum_p.reshape(-1, nb, blk).astype(jnp.float32),
                ).astype(jnp.int32)  # [R, B, 128]
                # Shared exact-u32 block gather (u16 halves on the MXU).
                blk_c0 = onehot.block_matmul_gather_u32(
                    c0_p.reshape(-1, nb, blk), onehot_b
                )
                within = jnp.sum(
                    blk_cum <= e[None, :, None], axis=2, dtype=jnp.int32
                )
                w_idx = jnp.minimum(nfull * blk + within, w_count_ - 1)
                # prev = cum[w_idx - 1] = the LARGEST cum <= e (cum is
                # non-decreasing): max of the boundary block's <= e values
                # and the previous block's end.
                prev_in = jnp.max(
                    jnp.where(blk_cum <= e[None, :, None], blk_cum, 0),
                    axis=2,
                )
                onehot_pb = (
                    (jnp.maximum(bsel - 1, 0))[:, :, None]
                    == jnp.arange(nb)[None, None, :]
                ).astype(jnp.float32)
                prev_be = jnp.where(
                    bsel > 0,
                    jnp.sum(
                        onehot_pb * be[:, None, :].astype(jnp.float32),
                        axis=2,
                    ).astype(jnp.int32),
                    0,
                )
                prev = jnp.maximum(prev_in, prev_be)
                wsel = w_idx - nfull * blk  # index within boundary block
                hit_w = (
                    wsel[:, :, None] == jnp.arange(blk)[None, None, :]
                )
                ver = (
                    jnp.max(jnp.where(hit_w, blk_c0, 0), axis=2)
                    + 1
                    + (e[None, :] - prev).astype(jnp.uint32)
                )
            mask = e[None, :] < total_g[:, None]  # [R, B]
            if cfg.track_writer_ids:
                # Slot -> global id via the shared-table one-hot gather
                # (a flat [R, B] fancy-index gather serializes on TPU;
                # the pallas backend accumulates native u32 on chip).
                w_merge = onehot.table_gather_u32(
                    topo.writer_ids, w_idx, backend=bk
                )
            else:
                w_merge = w_idx
            # Row-dense merge (cohort rows only): gathers the cohort's cell
            # rows, runs the one-hot merge passes, scatters rows back.
            return _merge_versions_dense(
                cells, rows, w_merge, ver, mask, row_ok, cfg.n_nodes, cfg
            )

        cells, n_merges = jax.lax.cond(
            jnp.any(gr > 0),
            enumerate_and_merge,
            lambda cells: (cells, jnp.uint32(0)),
            cells,
        )

    # Scatter the session results back into the full tables; rows that did
    # not participate keep their state (dropped writes).
    idx = jnp.where(row_ok, rows, n)
    contig = data.contig.at[idx].set(contig_r, mode="drop")
    seen = data.seen.at[idx].max(seen_r, mode="drop")

    stats = {
        "applied_sync": jnp.sum(
            jnp.where(row_ok[:, None], contig_r - contig0, 0),
            dtype=jnp.uint32,
        ),
        # Due rows with at least one reachable candidate (whether or not
        # any need was found) — matches the pre-multi-peer meaning.
        "sessions": jnp.sum(jnp.any(ok_c, axis=1)),
        "cell_merges": n_merges,
        "sync_regrant": n_regrant,
    }
    return (
        data._replace(
            contig=contig, seen=seen, cells=cells, oo=oo_new,
            oo_any=oo_any_new,
        ),
        stats,
    )


def revive_sync(
    data: DataState,
    topo: Topology,
    alive: jax.Array,
    partition: jax.Array,
    revived: jax.Array,  # bool[N] nodes that just came back
    rng: jax.Array,
    cfg: GossipConfig,
) -> tuple[DataState, dict]:
    """Immediate anti-entropy for nodes that just rejoined, instead of
    waiting out their cohort slot — the reference syncs on rejoin
    (agent.rs:2383-2423 peer choice fires as soon as the member is back).
    Wrapped in lax.cond so churn-free rounds skip the full-N session.

    Churn semantics served by this session (docs/CHAOS.md):

    - **pause-resume** (the default kill): the killed node RETAINS its
      DataState; on revive this session only covers the versions that
      committed while it was down. The dense, sparse, and mixed engines
      all use pause-resume unless a fault plan says otherwise.
    - **crash-with-state-wipe** (``FaultPlan`` churn with ``wipe=True``,
      applied via ops/faulting.wipe_nodes): the node restarts from an
      EMPTY replica state and this same session is its bootstrap
      catch-up — budgeted, so full recovery may take further cohort
      sessions. Supported by the dense and mixed engines; the sparse
      engine degrades wipe to pause-resume (a total wipe exceeds its
      bounded deviation tables) and sim/faults.py documents that
      loudly. The chunk plane wipes coverage directly in its own round
      (ops/chunks.wipe_coverage) — it has no version-plane sync."""
    nodes = jnp.arange(cfg.n_nodes)
    row_ok = revived & alive

    def go(data):
        return _sync_rows(
            data, topo, alive, partition, nodes, row_ok, rng, cfg
        )

    def skip(data):
        return data, {
            "applied_sync": jnp.uint32(0),
            "sessions": jnp.int32(0),
            "cell_merges": jnp.uint32(0),
            "sync_regrant": jnp.uint32(0),
        }

    return jax.lax.cond(jnp.any(row_ok), go, skip, data)


def node_cells(data: DataState, cfg: GossipConfig) -> crdt.CellState:
    """View the flat cell plane as per-node [N, K] register arrays."""
    n, k = cfg.n_nodes, cfg.n_cells
    return crdt.CellState(
        cl=data.cells.cl.reshape(n, k),
        col_version=data.cells.col_version.reshape(n, k),
        value_rank=data.cells.value_rank.reshape(n, k),
    )


def cells_agree(data: DataState, cfg: GossipConfig) -> jax.Array:
    """True iff every node's merged cell state is identical (CRDT
    convergence over actual register contents, not watermarks)."""
    pc = node_cells(data, cfg)
    return (
        jnp.all(pc.cl == pc.cl[:1])
        & jnp.all(pc.col_version == pc.col_version[:1])
        & jnp.all(pc.value_rank == pc.value_rank[:1])
    )


# corro-lint: disable=CT001,CT002,CT004 reason=host ground-truth reference
def serial_merge_reference(
    head, cfg: GossipConfig
) -> crdt.CellState:
    """Ground truth: merge every committed version (w, v<=head[w]) into one
    fresh cell state — the order-independent serial merge that all replicas
    must converge to. Host-side (numpy loop), for tests/bench validation."""
    import numpy as np

    head = np.asarray(head)
    state = crdt.make_cells(cfg.n_cells)
    ws, vs = [], []
    for w, h in enumerate(head):
        for v in range(1, int(h) + 1):
            ws.append(w)
            vs.append(v)
    if not ws:
        return state
    ws = jnp.asarray(np.array(ws, np.uint32))
    vs = jnp.asarray(np.array(vs, np.uint32))
    mask = jnp.ones(ws.shape, bool)
    for j in range(cfg.cells_per_write):
        key, cl, cv, vr = crdt.derive_change(ws, vs, jnp.uint32(j), cfg.n_cells)
        state = crdt.apply_changes(
            state,
            crdt.ChangeBatch(key=key, cl=cl, col_version=cv, value_rank=vr, mask=mask),
        )
    return state


def total_need(data: DataState) -> jax.Array:
    """Cluster-wide outstanding need (Σ heard-of minus possessed) — the
    `corro.sync.*` needs gauge analogue. Window-possessed versions are not
    needed (their content is applied; only the watermark lags)."""
    need = jnp.sum(
        (data.seen - data.contig).astype(jnp.uint32), dtype=jnp.uint32
    )
    if data.oo.shape[0] == 0:
        return need

    def _minus_window(oo):
        pop = jnp.uint32(0)
        for b in range(oo.shape[0]):
            pop = pop + jnp.sum(
                jax.lax.population_count(oo[b]), dtype=jnp.uint32
            )
        return need - pop

    return jax.lax.cond(data.oo_any, _minus_window, lambda oo: need, data.oo)


def staleness(data: DataState) -> tuple[jax.Array, jax.Array]:
    """(staleness_sum f32[], staleness_max u32[]): per-node watermark lag
    against the writers' committed heads.

    A node's lag is Σ_w (head[w] - contig[node, w]) — how many committed
    versions its applied watermark trails, the "how stale can a node
    get" question (SURVEY north star). ``staleness_sum`` is the
    cluster-wide mass (f32: N·W·versions exceeds u32 at 100k scale),
    ``staleness_max`` the worst single node. Window-possessed versions
    still count as lag: their content is applied but the watermark — and
    therefore a causally-consistent read — has not crossed them.
    """
    gap = data.head[None, :] - jnp.minimum(data.contig, data.head[None, :])
    node_lag = jnp.sum(gap, axis=1, dtype=jnp.uint32)  # u32[N]
    return (
        jnp.sum(node_lag.astype(jnp.float32)),
        jnp.max(node_lag),
    )


def queue_backlog(data: DataState) -> jax.Array:
    """u32[]: occupied pending-broadcast queue slots cluster-wide — the
    anti-entropy backlog mass (the `corro_broadcast_pending` analogue
    for the kernel plane). Sustained growth means the epidemic plane is
    admitting faster than budgets expire entries."""
    return jnp.sum(data.q_writer >= 0, dtype=jnp.uint32)


def visibility(
    data: DataState,
    sample_writer: jax.Array,
    sample_ver: jax.Array,
    backend: str | None = None,
) -> jax.Array:
    """bool[S, N]: is sampled write s visible at each node yet? Visible =
    at or below the contiguous watermark, OR possessed out-of-order in the
    window (the reference applies complete versions in any order —
    agent.rs:1809-2060 — so an applied version is queryable immediately).

    On accelerators the column gather contig[:, sample_writer] is strided
    and lowers poorly at [100k, 512]→[100k, S]; the dense backend rides a
    one-hot f32 matmul on the MXU instead (exact: one nonzero per output
    column, values < 2^24 in f32 with HIGHEST precision; window words
    split into u16 halves for the same exactness), while the pallas
    backend gathers native u32 through the rowgather kernel — no halves.
    On CPU the plain column gather is a tight loop and both kernel forms
    are pure overhead — same u32 compares, same bits, chosen at trace
    time. The engine drivers thread ``GossipConfig.kernel_backend`` in
    via ``backend``."""
    w = data.contig.shape[1]
    bk = onehot.resolve_backend(backend)
    if bk == "native":
        cols = jnp.clip(sample_writer.astype(jnp.int32), 0, w - 1)

        def _cols(x):  # u32[N, W] -> u32[N, S]
            return x[:, cols]

    elif bk == "pallas":
        n = data.contig.shape[0]
        s = sample_writer.shape[0]
        cols2d = jnp.broadcast_to(
            jnp.clip(sample_writer.astype(jnp.int32), 0, w - 1)[None, :],
            (n, s),
        )

        def _cols(x):  # u32[N, W] -> u32[N, S]
            return onehot.rowgather(x, cols2d, backend="pallas")

    else:
        _cols = None
    if _cols is not None:
        c_int = _cols(data.contig)
        vis = c_int >= sample_ver[None, :]  # [N, S]
    else:
        oh = (
            jnp.arange(w, dtype=sample_writer.dtype)[:, None]
            == sample_writer[None, :]
        ).astype(jnp.float32)

        def _dot(x):
            return jax.lax.dot(
                x.astype(jnp.float32), oh,
                precision=jax.lax.Precision.HIGHEST,
            )  # [N, S]

        c = _dot(data.contig)
        c_int = c.astype(jnp.uint32)
        vis = c >= sample_ver[None, :].astype(jnp.float32)  # [N, S]
    if data.oo.shape[0] == 0:
        return vis.T

    def _with_window(oo):
        out = vis
        bit = sample_ver[None, :] - c_int - 1  # u32, wraps when visible
        for b in range(oo.shape[0]):
            if _cols is not None:
                word = _cols(oo[b])  # [N, S]
            else:
                lo = _dot(oo[b] & jnp.uint32(0xFFFF)).astype(jnp.uint32)
                hi = _dot(oo[b] >> 16).astype(jnp.uint32)
                word = (hi << 16) | lo  # [N, S]
            sh = jnp.minimum(bit - jnp.uint32(32 * b), jnp.uint32(31))
            inb = (bit >= 32 * b) & (bit < 32 * (b + 1))
            out = out | (inb & (((word >> sh) & 1) == 1))
        return out

    return jax.lax.cond(
        data.oo_any, _with_window, lambda oo: vis, data.oo
    ).T
