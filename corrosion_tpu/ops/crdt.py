"""Batched CRDT merge — the TPU-native replacement for cr-sqlite's C engine.

Semantics mirror cr-sqlite 0.15 as used by the reference
(/root/reference/doc/crdts.md:11-28, loaded via corro-types/src/sqlite.rs):

- Row liveness is a **causal length** ``cl``: odd = live, even = deleted;
  merges take the max, so a delete (cl 1→2) beats concurrent updates at cl 1
  and a re-insert (cl 2→3) beats the delete.
- Cell values are **LWW registers**: biggest ``col_version`` wins; on a tie
  the "biggest" value wins. The sim orders values by a precomputed
  ``value_rank`` (uint32); the host store uses the exact SQLite type/value
  ordering (corrosion_tpu.core.values.value_cmp_key) — SURVEY.md §7 hard
  part (c).

A *cell* in the sim is one (table, pk, column) register, identified by a
dense key index. Merging a batch of changes is a scatter-reduce: a
lexicographic max over the tuple ``(cl, col_version, value_rank)``, computed
exactly with two uint32 scatter-max passes — (cl, col_version) packed into
one word, then value_rank among the winners. Domain (asserted by the pack
layout, staying in the TPU's native integer width): ``cl < 2^8`` and
``col_version < 2^24``.

All functions are jit-safe and static-shape.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class CellState(NamedTuple):
    """Struct-of-arrays LWW register state for K cells."""

    cl: jax.Array  # u32[K] causal length of the owning row
    col_version: jax.Array  # u32[K]
    value_rank: jax.Array  # u32[K] orderable value surrogate


class ChangeBatch(NamedTuple):
    """B changes addressed to dense cell keys.

    Mirrors the fields of a `crsql_changes` row that matter for merge
    (corro-api-types Change: col_version, cl, val; key stands for
    (table, pk, cid)). ``mask`` marks live entries so fixed-size batches can
    carry fewer than B real changes.
    """

    key: jax.Array  # i32[B] in [0, K)
    cl: jax.Array  # u32[B]
    col_version: jax.Array  # u32[B]
    value_rank: jax.Array  # u32[B]
    mask: jax.Array  # bool[B]


def make_cells(n_cells: int) -> CellState:
    z = jnp.zeros((n_cells,), dtype=jnp.uint32)
    return CellState(cl=z, col_version=z, value_rank=z)


def _mix(h: jax.Array) -> jax.Array:
    """murmur3-style avalanche over uint32 (deterministic value hashing)."""
    h = h.astype(jnp.uint32)
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h *= jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def derive_change(
    writer: jax.Array,
    version: jax.Array,
    slot: jax.Array,
    n_cells: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Deterministic change content for (writer, version, cell-slot).

    In the reference a changeset's rows are a pure function of
    (site_id, version) — the writer's committed transaction (read back from
    `crsql_changes` at broadcast time, public/mod.rs:128-142). The sim keeps
    that property: a version id IS its payload, so any replica applying
    (w, v) derives identical (key, cl, col_version, value_rank) rows and the
    scatter-merge is replay-order independent.

    ~1/16 of writes are row deletes (even causal length) so causal-length
    precedence is exercised alongside LWW.
    """
    w = writer.astype(jnp.uint32)
    v = version.astype(jnp.uint32)
    j = slot.astype(jnp.uint32)
    h = _mix(w * jnp.uint32(2654435761) + v * jnp.uint32(40503) + j * jnp.uint32(2246822519))
    key = (h % jnp.uint32(n_cells)).astype(jnp.int32)
    cl = jnp.where(h % 16 == 0, jnp.uint32(2), jnp.uint32(1))
    col_version = v
    value_rank = _mix(h + jnp.uint32(0x9E3779B9))
    return key, cl, col_version, value_rank


def _lex_gt(a_cl, a_cv, a_vr, b_cl, b_cv, b_vr):
    """(a_cl, a_cv, a_vr) > (b_cl, b_cv, b_vr) lexicographically."""
    return (
        (a_cl > b_cl)
        | ((a_cl == b_cl) & (a_cv > b_cv))
        | ((a_cl == b_cl) & (a_cv == b_cv) & (a_vr > b_vr))
    )


@jax.jit
def merge_cells(local: CellState, incoming: CellState) -> CellState:
    """Elementwise merge of two aligned cell states (replica join).

    Idempotent, commutative, associative — the CRDT laws; property-tested in
    tests/test_ops_crdt.py.
    """
    take = _lex_gt(
        incoming.cl, incoming.col_version, incoming.value_rank,
        local.cl, local.col_version, local.value_rank,
    )
    return CellState(
        cl=jnp.where(take, incoming.cl, local.cl),
        col_version=jnp.where(take, incoming.col_version, local.col_version),
        value_rank=jnp.where(take, incoming.value_rank, local.value_rank),
    )


@jax.jit
def apply_changes(state: CellState, batch: ChangeBatch) -> CellState:
    """Scatter-merge a change batch into cell state.

    Exact lexicographic (cl, col_version, value_rank) max per key across the
    batch AND the current state, via two scatter-max passes:

      1. scatter-max of ``(cl << 24) | col_version`` per key (seeded with
         the current state) — exact while cl < 2^8 and col_version < 2^24;
      2. among entries matching the winning (cl, col_version), scatter-max
         value_rank.

    Equivalent to replaying `INSERT INTO crsql_changes` rows through the
    extension's merge (reference agent.rs:2192-2214), batched.
    """
    k = batch.key
    live = batch.mask

    # Pass 1: (cl, col_version) packed into one u32 — exact lexicographic
    # max in a single scatter. Domain: cl < 2^8 (causal length counts
    # delete/re-insert cycles of one row; the sim derives cl ∈ {1, 2}) and
    # col_version < 2^24 (a writer's version counter — millions of writes
    # per writer before overflow). Halves the serialized scatter traffic
    # vs three chained passes.
    packed_state = (state.cl << 24) | state.col_version
    packed_in = (batch.cl << 24) | batch.col_version
    p1 = packed_state.at[k].max(jnp.where(live, packed_in, 0))
    cl1 = p1 >> 24
    cv1 = p1 & jnp.uint32((1 << 24) - 1)
    # Pass 2: value_rank among (cl, cv) winners.
    state_vr_seed = jnp.where(p1 == packed_state, state.value_rank, 0)
    in_win = live & (packed_in == p1[k])
    vr1 = state_vr_seed.at[k].max(jnp.where(in_win, batch.value_rank, 0))

    return CellState(cl=cl1, col_version=cv1, value_rank=vr1)


@jax.jit
def row_live(state: CellState) -> jax.Array:
    """bool[K] — causal-length liveness (odd cl = live)."""
    return (state.cl & 1) == 1


def local_write(
    state: CellState, key: jax.Array, value_rank: jax.Array
) -> CellState:
    """A local UPDATE of one cell: bump col_version, keep cl.

    (cr-sqlite bumps the column's version on every local write; the row's cl
    only moves on delete/re-insert.)
    """
    return CellState(
        cl=state.cl.at[key].max(1),  # writing resurrects nothing; ensures live
        col_version=state.col_version.at[key].add(1),
        value_rank=state.value_rank.at[key].set(value_rank),
    )


def local_insert_row(state: CellState, keys: jax.Array) -> CellState:
    """(Re-)insert a row: bump its cells' cl to the next odd value.

    A re-insert after a delete moves cl even→odd, beating the delete in
    merges (causal-length resurrection); col_version restarts at 1 in the
    new causal epoch. An insert onto an already-live row is an upsert: cl
    stays, and col_version bumps (it must stay monotonic within an epoch or
    stale remote values would win the LWW compare).
    """
    cl = state.cl[keys]
    resurrect = (cl & 1) == 0
    new_cl = jnp.where(resurrect, cl + 1, cl)
    new_cv = jnp.where(resurrect, 1, state.col_version[keys] + 1)
    return CellState(
        cl=state.cl.at[keys].set(new_cl),
        col_version=state.col_version.at[keys].set(new_cv),
        value_rank=state.value_rank,
    )


def local_delete_row(state: CellState, keys: jax.Array) -> CellState:
    """Delete a row: bump its cells' cl to the next even value, reset cols."""
    cl = state.cl[keys]
    new_cl = jnp.where((cl & 1) == 1, cl + 1, cl)
    return CellState(
        cl=state.cl.at[keys].set(new_cl),
        col_version=state.col_version.at[keys].set(0),
        value_rank=state.value_rank.at[keys].set(0),
    )
