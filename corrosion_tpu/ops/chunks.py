"""Seq-granular chunk dissemination + partial-version buffering kernel.

The reference streams a large transaction as <=8 KiB chunks tagged with
inclusive seq ranges (corro-types/src/change.rs:8-116), buffers out-of-order
chunks with gap tracking until the version is complete
(corro-agent/src/agent.rs:2063-2151, 1667-1806), and lets anti-entropy
request individual missing seq ranges (`SyncNeedV1::Partial`,
corro-types/src/sync.rs:248-266).

This kernel is the batched TPU equivalent for S concurrent large
transactions ("streams", each a (writer, version) pair): per (node, stream)
coverage is a fixed-capacity interval tensor (ops.intervals); chunks gossip
epidemically as random covered sub-ranges; due nodes run partial-need sync —
compute their seq gaps, request up to ``gap_requests`` of them from a peer,
and insert what the peer can grant under a per-session seq budget. A stream
is *applied* at a node once its contiguous watermark reaches ``last_seq``
(the gap-free condition that triggers process_fully_buffered_changes in the
reference).

The main data plane (ops.gossip) tracks whole versions — matching the
reference, where seq state exists only while a version is partial and
collapses once applied.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import faulting, intervals, routing
from corrosion_tpu.ops.intervals import IntervalSet


@dataclass(frozen=True)
class ChunkConfig:
    n_nodes: int
    n_streams: int  # concurrent large transactions
    cap: int = 16  # interval slots per (node, stream)
    chunk_len: int = 256  # seqs per gossiped chunk (~8 KiB / row bytes)
    fanout: int = 3
    k_in: int = 6  # bounded chunk intake per (node, stream) per round
    loss_prob: float = 0.0
    sync_interval: int = 5
    gap_requests: int = 4  # partial-need ranges requested per session
    sync_seq_budget: int = 4096  # seqs granted per session
    # Propagation-topology observables (sim/telemetry.PROP_CURVE_KEYS).
    # The chunk plane has no region structure, so its traffic matrix is
    # the degenerate single-region link_00 = chunks gossiped; useful =
    # chunks accepted by bounded intake, redundant = the rest. Static —
    # False keeps the pre-propagation trace bit-identical (the chaos
    # axes' zero-cost-skip contract).
    prop_observe: bool = False

    @property
    def rows(self) -> int:
        return self.n_nodes * self.n_streams


class ChunkState(NamedTuple):
    have: IntervalSet  # starts/ends i32[N*S, C] seq coverage per (node, stream)


def init_chunks(cfg: ChunkConfig, origin: jax.Array, last_seq: jax.Array) -> ChunkState:
    """Origin node of each stream starts with full coverage [0, last_seq]."""
    iv = IntervalSet(
        starts=jnp.full((cfg.rows, cfg.cap), intervals.EMPTY, jnp.int32),
        ends=jnp.full((cfg.rows, cfg.cap), intervals.EMPTY - 1, jnp.int32),
    )
    rows = origin * cfg.n_streams + jnp.arange(cfg.n_streams)
    starts = iv.starts.at[rows, 0].set(0)
    ends = iv.ends.at[rows, 0].set(last_seq.astype(jnp.int32))
    return ChunkState(have=IntervalSet(starts=starts, ends=ends))


def _select(mask, new, old):
    """Per-row select over vmapped IntervalSets."""
    return jax.tree.map(
        lambda a, b: jnp.where(mask[:, None], a, b), new, old
    )


_v_insert = jax.vmap(intervals.insert)
_v_gaps = jax.vmap(intervals.gaps)
_v_watermark = jax.vmap(intervals.contiguous_watermark)


@partial(jax.jit, static_argnames=("cfg",))
def chunk_round(
    state: ChunkState,
    last_seq: jax.Array,  # i32[S]
    alive: jax.Array,  # bool[N]
    round_idx: jax.Array,
    rng: jax.Array,
    cfg: ChunkConfig,
    loss: jax.Array | None = None,  # f32[] injected chunk-loss prob
) -> tuple[ChunkState, dict]:
    n, s_count, f = cfg.n_nodes, cfg.n_streams, cfg.fanout
    rows = cfg.rows
    have = state.have
    k_tgt, k_slot, k_pos, k_loss, k_peer = jax.random.split(rng, 5)

    row_node = jnp.arange(rows) // s_count
    row_stream = jnp.arange(rows) % s_count
    row_last = last_seq[row_stream]
    live = intervals.slot_mask(have)  # bool[rows, C]
    has_any = jnp.any(live, axis=1)  # bool[rows]

    # ---- 1. epidemic chunk send: random covered sub-range to f targets ----
    with jax.named_scope("corro_broadcast"):
        tgt = jax.random.randint(k_tgt, (rows, f), 0, n)  # receiver node
        u = jax.random.uniform(k_slot, (rows, f, cfg.cap))
        scores = jnp.where(live[:, None, :], u, -1.0)
        slot = jnp.argmax(scores, axis=-1)  # [rows, f]
        ss = jnp.take_along_axis(have.starts, slot, axis=1)
        se = jnp.take_along_axis(have.ends, slot, axis=1)
        span = jnp.maximum(se - ss + 1, 1)
        pos = ss + jax.random.randint(k_pos, (rows, f), 0, 1 << 30) % span
        ce = jnp.minimum(pos + cfg.chunk_len - 1, se)
        ok = (
            has_any[:, None]
            & alive[row_node][:, None]
            & alive[tgt]
            & (tgt != row_node[:, None])
        )
        # Shared static-skip loss (ops/faulting.py): the chunk plane has
        # no region structure, so the chaos plan's loss arrives as one
        # per-round scalar (its worst-region value).
        ok, n_lost = faulting.apply_loss(k_loss, ok, cfg.loss_prob, loss)

        m_row = (tgt * s_count + row_stream[:, None]).reshape(-1)
        in_mask, (in_s, in_e) = routing.bounded_intake(
            m_row, ok.reshape(-1), (pos.reshape(-1), ce.reshape(-1)), rows,
            cfg.k_in,
        )
        for j in range(cfg.k_in):
            inserted = _v_insert(have, in_s[:, j], in_e[:, j])
            have = _select(in_mask[:, j], inserted, have)

    # ---- 2. partial-need sync (SyncNeedV1::Partial analogue) --------------
    with jax.named_scope("corro_sync"):
        phase = (row_node * jnp.int32(40503)) % jnp.int32(cfg.sync_interval)
        due = (
            alive[row_node]
            & ((round_idx + phase) % jnp.int32(cfg.sync_interval) == 0)
        )
        peer = jax.random.randint(k_peer, (n,), 0, n)
        peer_ok = alive[peer] & (peer != jnp.arange(n))
        p_row = peer[row_node] * s_count + row_stream
        gaps = _v_gaps(have, jnp.zeros((rows,), jnp.int32), row_last)
        ps, pe = have.starts[p_row], have.ends[p_row]
        p_live = ps <= pe
        budget_left = jnp.full((rows,), cfg.sync_seq_budget, jnp.int32)
        granted = jnp.zeros((rows,), jnp.int32)
        for g in range(cfg.gap_requests):
            gs, ge = gaps.starts[:, g], gaps.ends[:, g]
            valid_gap = gs <= ge
            overlap = p_live & (ps <= ge[:, None]) & (pe >= gs[:, None])
            any_ov = jnp.any(overlap, axis=1)
            idx = jnp.argmax(overlap, axis=1)
            g_s = jnp.maximum(
                gs, jnp.take_along_axis(ps, idx[:, None], axis=1)[:, 0]
            )
            g_e = jnp.minimum(
                ge, jnp.take_along_axis(pe, idx[:, None], axis=1)[:, 0]
            )
            g_e = jnp.minimum(g_e, g_s + budget_left - 1)
            ok_g = (
                due & peer_ok[row_node] & valid_gap & any_ov
                & (budget_left > 0)
            )
            inserted = _v_insert(have, g_s, g_e)
            have = _select(ok_g, inserted, have)
            got = jnp.where(ok_g, g_e - g_s + 1, 0)
            budget_left -= got
            granted += got

    new_state = ChunkState(have=have)
    # Remaining seq deficit to full coverage, summed cluster-wide. f32:
    # rows x seqs can exceed the u32 domain at 100k-node scale, and the
    # telemetry plane treats it as a level gauge anyway.
    live_new = intervals.slot_mask(have)
    covered = jnp.sum(
        jnp.where(live_new, have.ends - have.starts + 1, 0), axis=1
    )
    row_deficit = jnp.maximum(row_last + 1 - covered, 0)
    need_seqs = jnp.sum(row_deficit.astype(jnp.float32))
    # Worst single node's seq deficit (summed over its streams) — the
    # chunk plane's staleness_max analogue. Bounded by S·(last_seq+1),
    # comfortably u32.
    need_node_max = jnp.max(
        jnp.sum(
            row_deficit.reshape(n, s_count).astype(jnp.uint32), axis=1
        )
    )
    # Node-level sync sessions this round (phase depends only on the node).
    phase_n = (jnp.arange(n) * jnp.int32(40503)) % jnp.int32(
        cfg.sync_interval
    )
    due_n = alive & ((round_idx + phase_n) % jnp.int32(cfg.sync_interval) == 0)
    stats = {
        "chunks_sent": jnp.sum(ok, dtype=jnp.uint32),
        "chunks_applied": jnp.sum(in_mask, dtype=jnp.uint32),
        "seqs_granted": jnp.sum(granted, dtype=jnp.uint32),
        "sessions": jnp.sum(due_n & peer_ok, dtype=jnp.uint32),
        "need_seqs": need_seqs,
        "need_node_max": need_node_max,
        "applied_nodes": jnp.sum(
            applied_mask(new_state, last_seq, cfg), dtype=jnp.uint32
        ),
        "lost_msgs": n_lost,
    }
    return new_state, stats


def wipe_coverage(
    state: ChunkState, wipe: jax.Array, cfg: ChunkConfig
) -> ChunkState:
    """Crash-with-state-wipe on the chunk plane: a wiped node's partial
    buffers are gone — every interval slot of its (node, stream) rows
    resets to empty (the restart-from-empty-disk twin of
    faulting.wipe_nodes). Re-gossip and partial-need sync must then
    reassemble the streams from the surviving holders; wiping a stream's
    LAST full holder makes its content unrecoverable, which is why the
    chaos plan generator protects origin nodes."""
    mask = jnp.repeat(wipe, cfg.n_streams)[:, None]  # bool[rows, 1]
    return ChunkState(
        have=IntervalSet(
            starts=jnp.where(
                mask, jnp.int32(intervals.EMPTY), state.have.starts
            ),
            ends=jnp.where(
                mask, jnp.int32(intervals.EMPTY - 1), state.have.ends
            ),
        )
    )


def applied_mask(state: ChunkState, last_seq: jax.Array, cfg: ChunkConfig) -> jax.Array:
    """bool[N, S]: stream fully reassembled (gap-free to last_seq) per node."""
    rows = cfg.rows
    row_last = last_seq[jnp.arange(rows) % cfg.n_streams]
    wm = _v_watermark(state.have, jnp.zeros((rows,), jnp.int32))
    return (wm >= row_last).reshape(cfg.n_nodes, cfg.n_streams)
