"""Shared fault-injection primitives for the kernel planes.

The chaos plane (sim/faults.py) compiles a declarative FaultPlan into
per-round device arrays; this module is the ONE place the kernels turn
those arrays (plus their static ``cfg.loss_prob``) into dropped messages
and wiped state. Before this module, gossip, SWIM, and the chunk plane
each carried their own ``if cfg.loss_prob > 0.0`` static-skip branch — a
fault plan threaded through one kernel could silently miss another.
Now every plane calls :func:`apply_loss`, so the static zero-cost skip
and the loss semantics can never diverge per plane.

Loss model: receiver-side independent drop. The static config
probability and the dynamic per-round probability compose as independent
loss processes (``p = a + b - a*b``), so a plan's loss burst stacks on
top of a config's ambient loss instead of replacing it.

Wipe model (crash-with-state-wipe, vs the default pause-resume kill):
:func:`wipe_nodes` resets a node's REPLICA state — watermarks, heard-of
heads, the out-of-order window, pending queues, and its CRDT cell shard
— while the writers' committed ``head`` ledger survives (the cluster,
not the node, is the ledger of acknowledged writes). The membership
twin lives in ``swim.apply_churn(..., wipe=...)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def apply_loss(
    key: jax.Array,
    ok: jax.Array,  # bool[...] deliverable-message mask
    static_prob: float,
    dyn_prob: jax.Array | None = None,  # f32 broadcastable to ok.shape
    full_rows: tuple | None = None,  # (n_total, row_start) shard slicing
) -> tuple[jax.Array, jax.Array]:
    """Drop each deliverable message independently with the combined
    loss probability. Returns ``(ok', lost_count u32)``.

    The static zero-cost skip shared by every plane: when the config
    probability is zero AND no dynamic schedule is threaded
    (``dyn_prob is None`` — a trace-time property), the mask passes
    through untouched and no randoms are sampled, so fault-free traces
    are bit-identical to the pre-chaos kernels. This promise was
    re-verified by measurement when the r04→r05 bench regression was
    bisected: the fault-axis threading added ZERO fault-free step time
    (the regression was the bench's platform fallback, not this plane —
    docs/PERFORMANCE.md "The r04→r05 anomaly, dissected"). It also
    composes with buffer donation: the skip returns ``ok`` unchanged, an
    alias into a possibly-donated pytree, which is safe because donation
    binds at the jitted entry point, never mid-trace.
    """
    if static_prob <= 0.0 and dyn_prob is None:
        return ok, jnp.uint32(0)
    if full_rows is None:
        u = jax.random.uniform(key, ok.shape)
    else:
        # Shard_map callers (gossip.ShardCtx): draw the mask at the FULL
        # leading-row shape and slice this shard's rows, so injected
        # loss is bit-identical across device counts.
        n_total, row_start = full_rows
        u = jax.lax.dynamic_slice_in_dim(
            jax.random.uniform(key, (n_total,) + ok.shape[1:]),
            row_start, ok.shape[0], axis=0,
        )
    p = jnp.float32(static_prob)
    if dyn_prob is not None:
        d = dyn_prob.astype(jnp.float32)
        p = p + d - p * d  # independent loss processes compose
    lost = ok & (u < p)
    return ok & ~lost, jnp.sum(lost, dtype=jnp.uint32)


def wipe_nodes(data, wipe: jax.Array, cfg):
    """Crash-with-state-wipe on the data plane: reset the wiped nodes'
    replica state as a real restart-from-empty-disk would.

    ``data`` is a gossip.DataState, ``wipe`` bool[N]. Resets per wiped
    node: ``contig``/``seen`` rows to 0, out-of-order window words to 0
    (``oo_any`` recomputed), pending-broadcast queue entries cleared,
    and its CRDT cell shard zeroed. ``head`` is untouched — committed
    versions are the cluster's ledger; whether the wiped node can ever
    recover them is exactly what anti-entropy (and the chaos invariant
    suite) must prove. Returns the new DataState.
    """
    not_w = ~wipe
    zero_u32 = jnp.uint32(0)
    contig = jnp.where(wipe[:, None], zero_u32, data.contig)
    seen = jnp.where(wipe[:, None], zero_u32, data.seen)
    oo = data.oo
    oo_any = data.oo_any
    if oo.shape[0] > 0:
        oo = jnp.where(wipe[None, :, None], zero_u32, oo)
        # Cheap exact recompute, gated on the flag: window-free runs
        # never touch the words.
        oo_any = jax.lax.cond(
            data.oo_any, lambda o: jnp.any(o), lambda o: data.oo_any, oo
        )
    q_writer = jnp.where(wipe[:, None], jnp.int32(-1), data.q_writer)
    q_tx = jnp.where(wipe[:, None], jnp.int32(0), data.q_tx)
    # Duplicate-receipt counters restart with the queue (zero-width when
    # rumor death is off, so this is a no-op then).
    q_dup = jnp.where(wipe[:, None], jnp.int32(0), data.q_dup)
    cells = data.cells
    if cfg.n_cells > 0:
        n, k = cfg.n_nodes, cfg.n_cells
        keep = jnp.repeat(not_w, k)  # bool[N*K]
        cells = type(cells)(
            cl=jnp.where(keep, cells.cl, zero_u32),
            col_version=jnp.where(keep, cells.col_version, zero_u32),
            value_rank=jnp.where(keep, cells.value_rank, zero_u32),
        )
    return data._replace(
        contig=contig, seen=seen, oo=oo, oo_any=oo_any,
        q_writer=q_writer, q_tx=q_tx, q_dup=q_dup, cells=cells,
    )
