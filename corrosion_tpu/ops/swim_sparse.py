"""Scalable SWIM membership kernel — bounded exception tables, O(N·K) state.

The dense kernel (`ops/swim.py`) keeps every node's full belief row — a
packed u32[N, N] view. That is the honest analogue of foca's member list
(every real SWIM node does track every peer), but it caps the simulator at
~30k virtual nodes: at N=100k the view alone is 40 GB, far past a single
chip's HBM (SURVEY.md §6's north star is 100k nodes).

The sparse kernel exploits the belief lattice's shape instead. A belief is
the packed ``inc << 2 | severity`` of the dense kernel, merged by ``max``,
and every pair starts at the baseline ``alive @ inc 0`` (= 0). Beliefs only
ever *rise* above the baseline for nodes that were suspected, declared down,
or refuted — i.e. nodes touched by churn, a bounded set in any real cluster.
So each node stores only its *exceptions*: up to K (target, packed) entries
that differ from the baseline; everything absent is alive@inc0. State drops
to O(N·K): at N=100k, K=64 the tables are 51 MB (≈ 0.5 KiB/node).

Semantics match the dense kernel merge-for-merge: probes, suspect→down
timers, bounded piggyback dissemination, refutation, and identity renewal
are the same code shape, with each scatter-max replaced by batched table
merges (`_merge_scan` — duplicate entries collapse to their max and
concurrent inserts match strongest-first to weakest slots, so one dense
pass preserves the read-after-write effect of a sequential merge). Two
deliberate deviations, both bounded-resource drops a real deployment also
makes:

- **View intake cap**: a node absorbs at most ``view_intake`` gossiped
  entries per round (excess datagrams drop, like UDP under burst).
- **Eviction**: when a table is full, the entry closest to the baseline
  (lowest severity, then lowest incarnation) is evicted — forgetting an
  *alive* exception is harmless (belief falls back to alive@inc0); suspect/
  down beliefs are kept in preference. foca's bounded updates backlog makes
  the same freshness-over-completeness trade for dissemination.

Reference map: foca runtime loop corro-agent/src/broadcast/mod.rs:116-568,
WAN config mod.rs:704-713, identity renewal corro-types/src/actor.rs:169-194.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import faulting, routing
from corrosion_tpu.ops.swim import (
    SEV_ALIVE,
    SEV_DOWN,
    SEV_SUSPECT,
    SwimConfig,
    pack,
    packed_inc,
    packed_sev,
)


class SparseSwimState(NamedTuple):
    exc_tgt: jax.Array  # i32[N, K] exception target (-1 = empty slot)
    exc_pkd: jax.Array  # u32[N, K] packed belief (> baseline 0)
    incarnation: jax.Array  # u32[N] own incarnation
    alive: jax.Array  # bool[N] ground-truth process liveness (churn input)
    # own suspect→down timers
    susp_target: jax.Array  # i32[N, S] (-1 = empty)
    susp_inc: jax.Array  # u32[N, S]
    susp_started: jax.Array  # i32[N, S]
    # updates backlog (piggyback dissemination queue)
    upd_target: jax.Array  # i32[N, U] (-1 = empty)
    upd_packed: jax.Array  # u32[N, U]
    upd_tx: jax.Array  # i32[N, U] transmissions left


def init_state(cfg: SwimConfig) -> SparseSwimState:
    n, s, u = cfg.n_nodes, cfg.timers, cfg.backlog
    k = cfg.view_capacity
    if k <= 0:
        raise ValueError("sparse kernel needs SwimConfig.view_capacity > 0")
    return SparseSwimState(
        exc_tgt=jnp.full((n, k), -1, dtype=jnp.int32),
        exc_pkd=jnp.zeros((n, k), dtype=jnp.uint32),
        incarnation=jnp.zeros((n,), dtype=jnp.uint32),
        alive=jnp.ones((n,), dtype=bool),
        susp_target=jnp.full((n, s), -1, dtype=jnp.int32),
        susp_inc=jnp.zeros((n, s), dtype=jnp.uint32),
        susp_started=jnp.zeros((n, s), dtype=jnp.int32),
        upd_target=jnp.full((n, u), -1, dtype=jnp.int32),
        upd_packed=jnp.zeros((n, u), dtype=jnp.uint32),
        upd_tx=jnp.zeros((n, u), dtype=jnp.int32),
    )


def state_bytes_per_node(cfg: SwimConfig) -> int:
    """Membership-plane memory budget per virtual node (the 100k plan)."""
    k, s, u = cfg.view_capacity, cfg.timers, cfg.backlog
    return 8 * k + 4 + 1 + 12 * s + 12 * u


def _lookup(exc_tgt: jax.Array, exc_pkd: jax.Array, tgt: jax.Array) -> jax.Array:
    """Belief each row holds about its (per-row) target; baseline 0."""
    hit = exc_tgt == tgt[:, None]
    return jnp.max(jnp.where(hit, exc_pkd, 0), axis=1)


def _evict_score(pkd: jax.Array) -> jax.Array:
    """Keep-priority: severity first, then incarnation (evict the minimum).

    Forgetting an alive@inc exception only resets the pair to the baseline
    (still believed up); suspect/down beliefs are the ones that must survive.
    """
    inc = jnp.minimum(packed_inc(pkd), jnp.uint32(2**27 - 1)).astype(jnp.int32)
    return (packed_sev(pkd).astype(jnp.int32) << 27) | inc


def _merge_one(
    exc_tgt: jax.Array,
    exc_pkd: jax.Array,
    tgt: jax.Array,  # i32[N] per-row target
    pkd: jax.Array,  # u32[N]
    valid: jax.Array,  # bool[N]
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Merge one (target, packed) belief into each row's table.

    Returns (exc_tgt, exc_pkd, raised[N]) where ``raised`` is True iff the
    merge strictly raised the row's belief about the target — the dense
    kernel's ``packed > view[row, tgt]`` change test.
    """
    n, k = exc_tgt.shape
    old = _lookup(exc_tgt, exc_pkd, tgt)
    raised = valid & (pkd > old)

    hit = (exc_tgt == tgt[:, None]) & raised[:, None]
    any_hit = hit.any(axis=1)
    exc_pkd = jnp.where(hit, jnp.maximum(exc_pkd, pkd[:, None]), exc_pkd)

    # Insert path: no existing slot for this target. Choose the slot with the
    # lowest keep-priority (empty slots first), evict only if strictly lower
    # priority than the incoming entry. Dense one-hot select, not a scatter:
    # each row writes exactly one slot, and [N, K] selects are pure VPU work
    # while TPU scatters serialize per element.
    ins = raised & ~any_hit & (pkd > 0)
    score = jnp.where(exc_tgt < 0, jnp.int32(-1), _evict_score(exc_pkd))
    slot = jnp.argmin(score, axis=1)
    slot_score = jnp.min(score, axis=1)
    ok = ins & (slot_score < _evict_score(pkd))
    sl = (
        jax.lax.broadcasted_iota(jnp.int32, (n, k), 1) == slot[:, None]
    ) & ok[:, None]
    exc_tgt = jnp.where(sl, tgt[:, None], exc_tgt)
    exc_pkd = jnp.where(sl, pkd[:, None], exc_pkd)
    # A raise that found no slot (table full of higher-priority entries) is
    # dropped — report it as not-raised so it is not re-gossiped as applied.
    raised = raised & (any_hit | ~ins | ok)
    return exc_tgt, exc_pkd, raised


def _merge_scan(
    exc_tgt: jax.Array,
    exc_pkd: jax.Array,
    tgts: jax.Array,  # i32[N, C] per-row targets
    pkds: jax.Array,  # u32[N, C]
    valids: jax.Array,  # bool[N, C]
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Merge C per-row entries into each row's table in ONE batched pass;
    returns raised[N, C].

    Replaces a sequential lax.scan of single-entry merges (C iterations of
    [N, K] work — ~40 ms/round of loop overhead at 100k). Equivalent to
    the sequential merge up to two policy choices: duplicate-target
    entries collapse to their max BEFORE merging (only the winning copy
    reports `raised`, so a duplicate re-gossips once, not once per copy),
    and concurrent inserts are matched strongest-first to weakest slots
    (sequential greedy could let an early weak insert take the empty slot
    and force a later strong one to evict a live belief). Both are
    bounded-resource policies of the kind the module docstring documents;
    the dense-kernel differential storms (test_ops_swim_sparse) hold.
    """
    n, k = exc_tgt.shape
    c = tgts.shape[1]
    valid = valids & (pkds > 0)
    cc = jnp.arange(c)
    kk = jnp.arange(k)

    # 1. Collapse duplicate targets: the winner is the unique max-(pkd,
    # lowest index) entry of its target group.
    same = tgts[:, :, None] == tgts[:, None, :]  # [N, C(i), C(j)]
    pj = pkds[:, None, :]
    pi = pkds[:, :, None]
    dom = (
        same
        & valid[:, None, :]
        & (
            (pj > pi)
            | ((pj == pi) & (cc[None, None, :] < cc[None, :, None]))
        )
    )
    winner = valid & ~jnp.any(dom, axis=2)  # [N, C]

    # 2. Old belief + hit detection against the table.  [N, C, K]
    hitck = exc_tgt[:, None, :] == tgts[:, :, None]
    old = jnp.max(jnp.where(hitck, exc_pkd[:, None, :], 0), axis=2)
    raised = winner & (pkds > old)
    any_hit = jnp.any(hitck, axis=2)

    # 3. Existing slots rise to the max raising entry targeting them.
    upd = jnp.max(
        jnp.where(hitck & raised[:, :, None], pkds[:, :, None], 0), axis=1
    )  # [N, K]
    exc_pkd = jnp.maximum(exc_pkd, upd)

    # 4. Inserts: rank candidates strongest-first, slots weakest-first,
    # pair rank r with rank r; an insert lands iff it strictly beats its
    # paired slot's keep-priority (empty slots score -1 and lose to any
    # real entry — same rule as the sequential path).
    ins = raised & ~any_hit
    neg_inf = jnp.int32(-(2**31) + 1)
    score_slot = jnp.where(
        exc_tgt < 0, jnp.int32(-1), _evict_score(exc_pkd)
    )
    score_ins = jnp.where(ins, _evict_score(pkds), neg_inf)
    ss_i = score_slot[:, :, None]
    ss_j = score_slot[:, None, :]
    slot_rank = jnp.sum(
        (ss_j < ss_i)
        | ((ss_j == ss_i) & (kk[None, None, :] < kk[None, :, None])),
        axis=2,
    )  # [N, K] 0 = weakest
    si_i = score_ins[:, :, None]
    si_j = score_ins[:, None, :]
    ins_rank = jnp.sum(
        (si_j > si_i)
        | ((si_j == si_i) & (cc[None, None, :] < cc[None, :, None])),
        axis=2,
    )  # [N, C] 0 = strongest
    pair = (
        (ins_rank[:, :, None] == slot_rank[:, None, :]) & ins[:, :, None]
    )  # [N, C, K]
    paired_slot_score = jnp.max(
        jnp.where(pair, score_slot[:, None, :], neg_inf), axis=2
    )
    land = ins & jnp.any(pair, axis=2) & (score_ins > paired_slot_score)
    put = pair & land[:, :, None]  # at most one c per k and one k per c
    landed = jnp.any(put, axis=1)  # [N, K]
    exc_tgt = jnp.where(
        landed, jnp.max(jnp.where(put, tgts[:, :, None], -1), axis=1),
        exc_tgt,
    )
    exc_pkd = jnp.where(
        landed, jnp.max(jnp.where(put, pkds[:, :, None], 0), axis=1),
        exc_pkd,
    )
    return exc_tgt, exc_pkd, raised & (any_hit | land)


@partial(jax.jit, static_argnames=("cfg",))
def swim_round(
    state: SparseSwimState, rng: jax.Array, round_idx: jax.Array, cfg: SwimConfig,
    probe_loss: jax.Array | None = None,
) -> SparseSwimState:
    """One bulk-synchronous SWIM protocol period for all N nodes.

    ``probe_loss`` (f32[], chaos plane) drops probe/ack exchanges only,
    like the dense kernel."""
    n = cfg.n_nodes
    nodes = jnp.arange(n)
    k_probe, k_loss, k_goss = jax.random.split(rng, 3)
    exc_tgt, exc_pkd = state.exc_tgt, state.exc_pkd
    alive = state.alive
    inc_self = state.incarnation

    cand_tgt = []
    cand_pkd = []
    cand_tx = []
    cand_ok = []

    # ---- 1. probe ----------------------------------------------------------
    tries = jax.random.randint(k_probe, (cfg.probe_tries, n), 0, n)

    def pick(carry, t):
        chosen = carry
        sev_t = packed_sev(_lookup(exc_tgt, exc_pkd, t))
        ok = (t != nodes) & (sev_t < SEV_DOWN) & (chosen < 0)
        return jnp.where(ok, t, chosen), None

    probe_tgt, _ = jax.lax.scan(pick, jnp.full((n,), -1, jnp.int32), tries)
    has_probe = (probe_tgt >= 0) & alive
    pt = jnp.maximum(probe_tgt, 0)
    # Shared static-skip loss (ops/faulting.py); i32 gather (bool
    # gathers serialize on TPU).
    ack, _ = faulting.apply_loss(
        k_loss, has_probe & (alive.astype(jnp.int32)[pt] > 0),
        cfg.loss_prob, probe_loss,
    )
    ack_pkd = pack(inc_self[pt], SEV_ALIVE)
    known = _lookup(exc_tgt, exc_pkd, pt)
    susp_pkd = pack(packed_inc(known), SEV_SUSPECT)
    probe_pkd = jnp.where(ack, ack_pkd, susp_pkd)
    exc_tgt, exc_pkd, probe_new = _merge_one(
        exc_tgt, exc_pkd, pt, probe_pkd, has_probe
    )
    cand_tgt.append(pt[:, None])
    cand_pkd.append(probe_pkd[:, None])
    cand_tx.append(jnp.full((n, 1), cfg.max_transmissions, jnp.int32))
    cand_ok.append(probe_new[:, None])

    # New suspicion → start a timer in a free/oldest slot.
    new_susp = has_probe & ~ack & probe_new
    slot_empty = state.susp_target < 0
    slot_score = jnp.where(slot_empty, -(2**30), state.susp_started)
    slot = jnp.argmin(slot_score, axis=1)
    susp_target = state.susp_target.at[nodes, slot].set(
        jnp.where(new_susp, pt, state.susp_target[nodes, slot])
    )
    susp_inc = state.susp_inc.at[nodes, slot].set(
        jnp.where(new_susp, packed_inc(known), state.susp_inc[nodes, slot])
    )
    susp_started = state.susp_started.at[nodes, slot].set(
        jnp.where(new_susp, round_idx, state.susp_started[nodes, slot])
    )

    # ---- 2. suspect→down timer expiry --------------------------------------
    active = susp_target >= 0
    expired = active & (round_idx - susp_started >= cfg.suspect_rounds)
    exp_tgt = jnp.maximum(susp_target, 0)
    down_pkd = pack(susp_inc, SEV_DOWN)
    fire = expired & alive[:, None]
    # `_merge_one` itself enforces the dense kernel's "only if we still
    # believe suspect at that incarnation" check: the merge is a no-op unless
    # down_pkd exceeds the current belief.
    exc_tgt, exc_pkd, fired = _merge_scan(
        exc_tgt, exc_pkd, exp_tgt, down_pkd, fire
    )
    cand_tgt.append(exp_tgt)
    cand_pkd.append(down_pkd)
    cand_tx.append(jnp.full(exp_tgt.shape, cfg.max_transmissions, jnp.int32))
    cand_ok.append(fired)
    susp_target = jnp.where(expired, -1, susp_target)

    # ---- 3. gossip dissemination (bounded piggyback, pull model) -----------
    # Receiver-centric like the broadcast plane (ops/gossip.py): each node
    # pulls G random sources' backlogs, so intake is a row-local [N, G·U]
    # selection instead of a global multi-million-element sort + scatter
    # (bounded_intake on N·G·U = 4.8M entries was the SWIM plane's dominant
    # cost at 100k: ~3 serialized scatters of the full message set).
    # Epidemically equivalent: in-degree becomes exactly G instead of
    # Binomial(N·G, 1/N).
    sendable = (state.upd_target >= 0) & (state.upd_tx > 0) & alive[:, None]
    src = jax.random.randint(k_goss, (n, cfg.gossip_fanout), 0, n)
    m_tgt = state.upd_target[src].reshape(n, -1)  # [N, G·U]
    m_pkd = state.upd_packed[src].reshape(n, -1)
    # Gather only INTEGER arrays and rebuild the sendable mask receiver-
    # side: a pred gather at [N, G·U] serializes per element on TPU
    # (~50 ms/round at 100k), while these i32 gathers vectorize.
    m_tx = state.upd_tx[src].reshape(n, -1)
    alive_i = alive.astype(jnp.int32)
    src_ok = (alive_i[src] > 0) & (src != nodes[:, None])  # [N, G]
    m_ok = (
        (m_tgt >= 0)
        & (m_tx > 0)
        & src_ok[:, :, None].repeat(cfg.backlog, axis=2).reshape(n, -1)
        & alive[:, None]  # dead receivers drop datagrams
    )
    upd_tx = jnp.where(sendable, state.upd_tx - 1, state.upd_tx)

    # Bounded receiver intake (the cap is the sparse kernel's datagram-drop
    # deviation; see module docstring): severity-first keep priority (the
    # entries that must survive an overloaded inbox), then a sequential
    # merge scan that doubles as the per-message change test.
    r_view = cfg.view_intake if cfg.view_intake > 0 else (
        cfg.gossip_fanout * cfg.backlog
    )
    in_mask, (in_tgt, in_pkd) = routing.rebuild_bounded_queue(
        m_ok & (m_tgt >= 0),
        _evict_score(m_pkd),
        (m_tgt, m_pkd),
        r_view,
    )
    in_tgt = jnp.maximum(in_tgt, 0)
    exc_tgt, exc_pkd, raised = _merge_scan(
        exc_tgt, exc_pkd, in_tgt, in_pkd, in_mask
    )

    # Raised entries re-enter the receiver's backlog (bounded re-gossip
    # intake, same cap as the dense kernel).
    r_bk = cfg.gossip_fanout * 2
    keep, (bk_tgt, bk_pkd) = routing.rebuild_bounded_queue(
        raised, jnp.ones_like(in_tgt), (in_tgt, in_pkd), r_bk
    )
    cand_tgt.append(jnp.where(keep, bk_tgt, -1))
    cand_pkd.append(bk_pkd)
    cand_tx.append(jnp.full((n, r_bk), cfg.max_transmissions, jnp.int32))
    cand_ok.append(keep)

    # ---- 4. refutation -----------------------------------------------------
    self_belief = _lookup(exc_tgt, exc_pkd, nodes)
    refute = alive & (packed_sev(self_belief) >= SEV_SUSPECT) & (
        packed_inc(self_belief) >= inc_self
    )
    new_inc = jnp.where(refute, packed_inc(self_belief) + 1, inc_self)
    refute_pkd = pack(new_inc, SEV_ALIVE)
    exc_tgt, exc_pkd, _ = _merge_one(
        exc_tgt, exc_pkd, nodes.astype(jnp.int32), refute_pkd, refute
    )
    cand_tgt.append(nodes[:, None].astype(jnp.int32))
    cand_pkd.append(refute_pkd[:, None])
    cand_tx.append(jnp.full((n, 1), cfg.max_transmissions, jnp.int32))
    cand_ok.append(refute[:, None])

    # ---- 5. rebuild backlog by priority ------------------------------------
    cand_tgt.append(state.upd_target)
    cand_pkd.append(state.upd_packed)
    cand_tx.append(upd_tx)
    cand_ok.append((state.upd_target >= 0) & (upd_tx > 0))

    ct = jnp.concatenate(cand_tgt, axis=1)
    cp = jnp.concatenate(cand_pkd, axis=1)
    cx = jnp.concatenate(cand_tx, axis=1)
    co = jnp.concatenate(cand_ok, axis=1)
    keep, (upd_target, upd_packed, upd_tx2) = routing.rebuild_bounded_queue(
        co, cx, (ct, cp, cx), cfg.backlog
    )
    upd_target = jnp.where(keep, upd_target, -1)

    # ---- 6. down-member GC (remove_down_after, stateless ageing) -----------
    # A DOWN exception is forgotten with probability 1/down_gc_rounds per
    # round (geometric lifetime, mean = the horizon): dead nodes stop
    # occupying severity-first-protected table slots forever, without a
    # per-slot timestamp array.
    if cfg.down_gc_rounds > 0:
        k_gc = jax.random.fold_in(k_goss, 7)
        drop = (packed_sev(exc_pkd) == SEV_DOWN) & (
            jax.random.uniform(k_gc, exc_pkd.shape) < 1.0 / cfg.down_gc_rounds
        )
        exc_tgt = jnp.where(drop, -1, exc_tgt)
        exc_pkd = jnp.where(drop, 0, exc_pkd)

    return SparseSwimState(
        exc_tgt=exc_tgt,
        exc_pkd=exc_pkd,
        incarnation=new_inc,
        alive=alive,
        susp_target=susp_target,
        susp_inc=susp_inc,
        susp_started=susp_started,
        upd_target=upd_target,
        upd_packed=upd_packed,
        upd_tx=upd_tx2,
    )


def apply_churn(
    state: SparseSwimState,
    kill: jax.Array,
    revive: jax.Array,
    rng: jax.Array | None = None,
    max_transmissions: int = 6,
    wipe: jax.Array | None = None,
) -> SparseSwimState:
    """Ground-truth churn between rounds (identity renewal on revive).

    Mirrors the dense kernel: a revived node bumps its incarnation, repairs
    its self-belief, queues a self-announce, and — when ``rng`` is given —
    bootstrap-pulls one random alive peer's exception table (the member-list
    transfer a SWIM announce gets from its seed).

    ``wipe`` marks kills as crash-with-state-wipe (see the dense
    kernel's docstring): the wiped node's exception table, timers, and
    update queue reset; its incarnation is kept so identity stays
    monotonic. NOTE: only the MEMBERSHIP plane supports wipe here — the
    sparse DATA plane degrades wipe to pause-resume (bounded deviation
    tables, see gossip.revive_sync).
    """
    if wipe is not None:
        state = state._replace(
            exc_tgt=jnp.where(wipe[:, None], jnp.int32(-1), state.exc_tgt),
            exc_pkd=jnp.where(wipe[:, None], jnp.uint32(0), state.exc_pkd),
            susp_target=jnp.where(
                wipe[:, None], jnp.int32(-1), state.susp_target
            ),
            upd_target=jnp.where(
                wipe[:, None], jnp.int32(-1), state.upd_target
            ),
            upd_tx=jnp.where(wipe[:, None], jnp.int32(0), state.upd_tx),
        )
    alive = (state.alive & ~kill) | revive
    inc = jnp.where(revive, state.incarnation + 1, state.incarnation)
    n = state.exc_tgt.shape[0]
    nodes = jnp.arange(n)
    self_pkd = pack(inc, SEV_ALIVE)
    exc_tgt, exc_pkd, _ = _merge_one(
        state.exc_tgt, state.exc_pkd, nodes.astype(jnp.int32), self_pkd, revive
    )
    if rng is not None:
        cand = jax.random.randint(rng, (4, n), 0, n)

        alive_i = alive.astype(jnp.int32)
        revive_i = revive.astype(jnp.int32)

        def pick(carry, t):
            # i32 gathers (pred gathers serialize on TPU).
            ok = (alive_i[t] > 0) & (revive_i[t] == 0) & (carry < 0)
            return jnp.where(ok, t, carry), None

        seed, _ = jax.lax.scan(pick, jnp.full((n,), -1, jnp.int32), cand)
        seed = jnp.where(seed < 0, nodes, seed)
        pull_ok = revive & (seed != nodes)
        exc_tgt, exc_pkd, _ = _merge_scan(
            exc_tgt,
            exc_pkd,
            exc_tgt[seed],
            exc_pkd[seed],
            pull_ok[:, None] & (exc_tgt[seed] >= 0),
        )
    last = state.upd_target.shape[1] - 1
    upd_target = state.upd_target.at[:, last].set(
        jnp.where(revive, nodes.astype(jnp.int32), state.upd_target[:, last])
    )
    upd_packed = state.upd_packed.at[:, last].set(
        jnp.where(revive, self_pkd, state.upd_packed[:, last])
    )
    upd_tx = state.upd_tx.at[:, last].set(
        jnp.where(revive, max_transmissions, state.upd_tx[:, last])
    )
    return state._replace(
        alive=alive,
        incarnation=inc,
        exc_tgt=exc_tgt,
        exc_pkd=exc_pkd,
        upd_target=upd_target,
        upd_packed=upd_packed,
        upd_tx=upd_tx,
    )


def mismatches(state: SparseSwimState) -> jax.Array:
    """Exact count of (live observer, peer) beliefs contradicting truth.

    Computed without materializing an N×N view: pairs with no exception
    entry are believed up (the baseline), so they mismatch exactly when the
    target is dead; exception entries then correct that default per entry
    (each row has at most one entry per target, a `_merge_one` invariant).
    """
    n = state.exc_tgt.shape[0]
    alive = state.alive
    alive_count = jnp.sum(alive)
    dead_count = n - alive_count
    default_mis = alive_count * dead_count  # i alive, j dead ⇒ i != j

    ent_valid = (
        (state.exc_tgt >= 0)
        & alive[:, None]
        & (state.exc_tgt != jnp.arange(n)[:, None])  # self-pairs excluded
    )
    t = jnp.maximum(state.exc_tgt, 0)
    believed_up = packed_sev(state.exc_pkd) < SEV_DOWN
    # i32 gather: a pred gather here serialized at ~50 ms/round at 100k —
    # the single most expensive op in the whole round, spent on a METRIC.
    truth = alive.astype(jnp.int32)[t] > 0
    ent_mis = jnp.sum(ent_valid & (believed_up != truth))
    ent_default_mis = jnp.sum(ent_valid & ~truth)
    return default_mis + ent_mis - ent_default_mis


def health_counts(state: SparseSwimState) -> tuple[jax.Array, jax.Array]:
    """(false_alarms, undetected_deaths) — the dense kernel's directional
    membership-error split, computed without materializing N×N.

    Pairs with no exception entry hold the baseline alive@inc0 belief:
    never a false alarm, always an undetected death when the target is
    dead. Exception entries then correct both defaults per entry (at
    most one entry per (row, target) — a ``_merge_one`` invariant).
    """
    n = state.exc_tgt.shape[0]
    alive = state.alive
    alive_count = jnp.sum(alive, dtype=jnp.uint32)
    dead_count = jnp.uint32(n) - alive_count
    ent_valid = (
        (state.exc_tgt >= 0)
        & alive[:, None]
        & (state.exc_tgt != jnp.arange(n)[:, None])
    )
    t = jnp.maximum(state.exc_tgt, 0)
    sev = packed_sev(state.exc_pkd)
    # i32 gather (pred gathers serialize on TPU; see mismatches()).
    truth = alive.astype(jnp.int32)[t] > 0
    false_alarms = jnp.sum(
        ent_valid & truth & (sev >= SEV_SUSPECT), dtype=jnp.uint32
    )
    # Default: every (live observer, dead target) pair is undetected;
    # entries that reached DOWN severity are the detections.
    detected = jnp.sum(
        ent_valid & ~truth & (sev == SEV_DOWN), dtype=jnp.uint32
    )
    return false_alarms, alive_count * dead_count - detected


def beliefs_about(state: SparseSwimState, target: int) -> jax.Array:
    """packed[N]: every node's belief about one target (tests/diagnostics)."""
    n = state.exc_tgt.shape[0]
    return _lookup(
        state.exc_tgt, state.exc_pkd, jnp.full((n,), target, jnp.int32)
    )


def accuracy(state: SparseSwimState) -> jax.Array:
    """Approximate fraction of correct beliefs (see dense kernel caveat)."""
    n = state.exc_tgt.shape[0]
    total = jnp.maximum(jnp.sum(state.alive) * (n - 1), 1)
    return 1.0 - mismatches(state) / total
