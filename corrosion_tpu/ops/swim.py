"""Batched SWIM membership kernel — the TPU-native replacement for foca.

The reference drives the external `foca` SWIM library from a runtime loop
(corro-agent/src/broadcast/mod.rs:116-568) with WAN-tuned config
(`make_foca_config`, mod.rs:704-713): probe rounds, suspect→down timers,
incarnation refutation, and bounded piggyback dissemination of membership
updates (`updates_backlog`). Identity renewal on being declared down
(corro-types/src/actor.rs:169-194) maps to an incarnation bump here.

This module simulates N virtual nodes in bulk-synchronous rounds. One round ≈
one SWIM protocol period. Design choices that keep it TPU-shaped:

- A membership *belief* is packed into one uint32: ``inc << 2 | severity``
  with severity 0=alive, 1=suspect, 2=down. SWIM's merge rule (higher
  incarnation wins; same incarnation → worse state wins) is then exactly
  ``max`` of the packed value, so dissemination is a single scatter-max.
- Dissemination is *bounded*, like foca's updates backlog: each node keeps a
  small queue of (target, packed, tx_left) updates and gossips them to
  ``gossip_fanout`` random peers per round; received entries that change the
  receiver's view re-enter its queue with a fresh transmission budget.
- Only the original suspector runs the suspect→down timer (bounded per-node
  timer table); the resulting "down" update disseminates epidemically.
- A node's own row entry ``view[j, j]`` doubles as its refutation mailbox:
  when gossip lands a suspect/down belief about j at j's current incarnation,
  j bumps its incarnation and gossips the refutation.

All shapes are static; the only O(N²) state is the packed view itself.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import faulting, routing

SEV_ALIVE = 0
SEV_SUSPECT = 1
SEV_DOWN = 2


def pack(inc: jax.Array, sev) -> jax.Array:
    return (inc.astype(jnp.uint32) << 2) | jnp.uint32(sev)


def packed_inc(p: jax.Array) -> jax.Array:
    return p >> 2


def packed_sev(p: jax.Array) -> jax.Array:
    return p & 3


@dataclass(frozen=True)
class SwimConfig:
    """Static round-model parameters (mirrors foca Config::new_wan intent)."""

    n_nodes: int
    suspect_rounds: int = 3  # suspect→down after this many rounds
    gossip_fanout: int = 3  # peers receiving our updates each round (num_indirect_probes)
    max_transmissions: int = 6  # per-update retransmission budget
    backlog: int = 16  # updates queue capacity (foca updates_backlog)
    timers: int = 8  # own-suspicion timer slots
    probe_tries: int = 4  # rejection-sampling tries for probe target
    loss_prob: float = 0.0  # modeled probe/ack loss
    # > 0 selects the sparse exception-table kernel (ops/swim_sparse.py)
    # with K = view_capacity belief slots per node; 0 = dense u32[N, N] view.
    view_capacity: int = 0
    # sparse kernel: gossiped view-merge messages absorbed per node per
    # round (0 = gossip_fanout * backlog, the expected arrival rate).
    view_intake: int = 0
    # Down-member GC horizon in rounds (foca remove_down_after, 48 h WAN
    # preset, broadcast/mod.rs:704-713): each round a DOWN belief is
    # forgotten with probability 1/down_gc_rounds (geometric lifetime with
    # that mean — stateless ageing; no per-belief timestamp array). 0 =
    # never forget. Frees sparse-table capacity in long-churn runs.
    down_gc_rounds: int = 0


def impl(cfg: SwimConfig):
    """Kernel module for this config: dense view or sparse exception tables.

    Both expose the same surface (init_state / swim_round / apply_churn /
    mismatches / accuracy) over their own state type; callers dispatch once
    per static config.
    """
    if cfg.view_capacity > 0:
        from corrosion_tpu.ops import swim_sparse

        return swim_sparse
    import corrosion_tpu.ops.swim as dense

    return dense


class SwimState(NamedTuple):
    view: jax.Array  # u32[N, N] packed beliefs; row i = node i's view
    incarnation: jax.Array  # u32[N] own incarnation
    alive: jax.Array  # bool[N] ground-truth process liveness (churn input)
    # own suspect→down timers
    susp_target: jax.Array  # i32[N, S] (-1 = empty)
    susp_inc: jax.Array  # u32[N, S]
    susp_started: jax.Array  # i32[N, S]
    # updates backlog (piggyback dissemination queue)
    upd_target: jax.Array  # i32[N, U] (-1 = empty)
    upd_packed: jax.Array  # u32[N, U]
    upd_tx: jax.Array  # i32[N, U] transmissions left


def init_state(cfg: SwimConfig) -> SwimState:
    n, s, u = cfg.n_nodes, cfg.timers, cfg.backlog
    view = jnp.zeros((n, n), dtype=jnp.uint32)  # everyone alive @ inc 0
    return SwimState(
        view=view,
        incarnation=jnp.zeros((n,), dtype=jnp.uint32),
        alive=jnp.ones((n,), dtype=bool),
        susp_target=jnp.full((n, s), -1, dtype=jnp.int32),
        susp_inc=jnp.zeros((n, s), dtype=jnp.uint32),
        susp_started=jnp.zeros((n, s), dtype=jnp.int32),
        upd_target=jnp.full((n, u), -1, dtype=jnp.int32),
        upd_packed=jnp.zeros((n, u), dtype=jnp.uint32),
        upd_tx=jnp.zeros((n, u), dtype=jnp.int32),
    )


def _merge_scatter(view: jax.Array, recv: jax.Array, tgt: jax.Array,
                   packed: jax.Array, valid: jax.Array) -> jax.Array:
    """view[recv, tgt] = max(view[recv, tgt], packed) where valid."""
    n = view.shape[0]
    flat = view.reshape(-1)
    idx = jnp.where(valid, recv * n + tgt, 0)
    val = jnp.where(valid, packed, 0)
    return flat.at[idx].max(val).reshape(view.shape)


@partial(jax.jit, static_argnames=("cfg",))
def swim_round(state: SwimState, rng: jax.Array, round_idx: jax.Array,
               cfg: SwimConfig,
               probe_loss: jax.Array | None = None) -> SwimState:
    """One bulk-synchronous SWIM protocol period for all N nodes.

    ``probe_loss`` (f32[], chaos plane) drops probe/ack exchanges ONLY —
    the data plane is untouched, isolating membership-protocol stress
    (false suspicions, refutation storms) from delivery loss."""
    n = cfg.n_nodes
    nodes = jnp.arange(n)
    k_probe, k_loss, k_goss = jax.random.split(rng, 3)
    view = state.view
    alive = state.alive
    inc_self = state.incarnation

    # ---- update candidates accumulated this round (per node) ----------------
    # Each node can emit: 1 probe observation, up to S timer expiries,
    # 1 refutation, and re-gossip of received changes. They are gathered into
    # one candidate pool and the backlog rebuilt by priority at the end.
    cand_tgt = []
    cand_pkd = []
    cand_tx = []
    cand_ok = []

    # ---- 1. probe ----------------------------------------------------------
    # Rejection-sample a probe target != self not believed down.
    tries = jax.random.randint(k_probe, (cfg.probe_tries, n), 0, n)

    def pick(carry, t):
        chosen = carry
        sev_t = packed_sev(view[nodes, t])
        ok = (t != nodes) & (sev_t < SEV_DOWN) & (chosen < 0)
        return jnp.where(ok, t, chosen), None

    probe_tgt, _ = jax.lax.scan(pick, jnp.full((n,), -1, jnp.int32), tries)
    has_probe = (probe_tgt >= 0) & alive
    pt = jnp.maximum(probe_tgt, 0)
    # Shared static-skip loss (ops/faulting.py): ambient config loss and
    # the chaos plane's probe/ack-only schedule compose here.
    ack, _ = faulting.apply_loss(
        k_loss, has_probe & alive[pt], cfg.loss_prob, probe_loss
    )
    # Ack carries the target's current incarnation → learn alive@inc.
    ack_pkd = pack(inc_self[pt], SEV_ALIVE)
    # Failure → suspect at the incarnation we currently believe.
    known = view[nodes, pt]
    susp_pkd = pack(packed_inc(known), SEV_SUSPECT)
    probe_pkd = jnp.where(ack, ack_pkd, susp_pkd)
    probe_new = probe_pkd > known
    view = _merge_scatter(view, nodes, pt, probe_pkd, has_probe)
    cand_tgt.append(pt[:, None])
    cand_pkd.append(probe_pkd[:, None])
    cand_tx.append(jnp.full((n, 1), cfg.max_transmissions, jnp.int32))
    cand_ok.append((has_probe & probe_new)[:, None])

    # New suspicion → start a timer in a free/oldest slot (ring by started).
    new_susp = has_probe & ~ack & probe_new
    slot_empty = state.susp_target < 0
    slot_score = jnp.where(slot_empty, -(2**30), state.susp_started)
    slot = jnp.argmin(slot_score, axis=1)  # empty first, else oldest
    susp_target = state.susp_target.at[nodes, slot].set(
        jnp.where(new_susp, pt, state.susp_target[nodes, slot]))
    susp_inc = state.susp_inc.at[nodes, slot].set(
        jnp.where(new_susp, packed_inc(known), state.susp_inc[nodes, slot]))
    susp_started = state.susp_started.at[nodes, slot].set(
        jnp.where(new_susp, round_idx, state.susp_started[nodes, slot]))

    # ---- 2. suspect→down timer expiry --------------------------------------
    active = susp_target >= 0
    expired = active & (round_idx - susp_started >= cfg.suspect_rounds)
    exp_tgt = jnp.maximum(susp_target, 0)
    down_pkd = pack(susp_inc, SEV_DOWN)
    # Only fire if we still believe suspect at that incarnation (a refutation
    # or ack may have raised the packed belief past it meanwhile).
    still = view[nodes[:, None], exp_tgt] < down_pkd
    fire = expired & still & alive[:, None]
    view = _merge_scatter(
        view,
        jnp.broadcast_to(nodes[:, None], exp_tgt.shape),
        exp_tgt, down_pkd, fire,
    )
    cand_tgt.append(exp_tgt)
    cand_pkd.append(down_pkd)
    cand_tx.append(jnp.full(exp_tgt.shape, cfg.max_transmissions, jnp.int32))
    cand_ok.append(fire)
    # Clear expired slots.
    susp_target = jnp.where(expired, -1, susp_target)

    # ---- 3. gossip dissemination (bounded piggyback) -----------------------
    sendable = (state.upd_target >= 0) & (state.upd_tx > 0) & alive[:, None]
    g_tgts = jax.random.randint(k_goss, (n, cfg.gossip_fanout), 0, n)
    # A message (sender, fanout g, slot u): receiver merges entry.
    recv = jnp.repeat(g_tgts[:, :, None], cfg.backlog, axis=2)  # [N, G, U]
    tgt = jnp.broadcast_to(state.upd_target[:, None, :], recv.shape)
    pkd = jnp.broadcast_to(state.upd_packed[:, None, :], recv.shape)
    ok = (
        jnp.broadcast_to(sendable[:, None, :], recv.shape)
        & (recv != jnp.arange(n)[:, None, None])  # not to self
        & alive[recv]  # dead receivers drop datagrams
    )
    pre = view  # receiver's view before this merge, for change detection
    view = _merge_scatter(
        view, recv.reshape(-1), jnp.maximum(tgt, 0).reshape(-1),
        pkd.reshape(-1), ok.reshape(-1))
    upd_tx = jnp.where(sendable, state.upd_tx - 1, state.upd_tx)

    # Received entries that raised the receiver's belief re-enter the
    # receiver's backlog (bounded intake, like foca's updates queue): a
    # message (r, t, p) changed r's view iff p > pre[r, t].
    flat_recv = recv.reshape(-1)
    flat_tgt = jnp.maximum(tgt, 0).reshape(-1)
    flat_pkd = pkd.reshape(-1)
    changed = ok.reshape(-1) & (flat_pkd > pre[flat_recv, flat_tgt])
    R = cfg.gossip_fanout * 2  # re-gossip intake cap per round
    in_mask, (pool_tgt, pool_pkd) = routing.bounded_intake(
        flat_recv, changed, (flat_tgt, flat_pkd), n, R)
    cand_tgt.append(jnp.where(in_mask, pool_tgt, -1))
    cand_pkd.append(pool_pkd)
    cand_tx.append(jnp.full((n, R), cfg.max_transmissions, jnp.int32))
    cand_ok.append(in_mask)

    # ---- 4. refutation -----------------------------------------------------
    self_belief = view[nodes, nodes]
    refute = alive & (packed_sev(self_belief) >= SEV_SUSPECT) & (
        packed_inc(self_belief) >= inc_self)
    new_inc = jnp.where(refute, packed_inc(self_belief) + 1, inc_self)
    refute_pkd = pack(new_inc, SEV_ALIVE)
    view = _merge_scatter(view, nodes, nodes, refute_pkd, refute)
    cand_tgt.append(nodes[:, None].astype(jnp.int32))
    cand_pkd.append(refute_pkd[:, None])
    cand_tx.append(jnp.full((n, 1), cfg.max_transmissions, jnp.int32))
    cand_ok.append(refute[:, None])

    # ---- 5. rebuild backlog by priority ------------------------------------
    cand_tgt.append(state.upd_target)
    cand_pkd.append(state.upd_packed)
    cand_tx.append(upd_tx)
    cand_ok.append((state.upd_target >= 0) & (upd_tx > 0))

    ct = jnp.concatenate(cand_tgt, axis=1)
    cp = jnp.concatenate(cand_pkd, axis=1)
    cx = jnp.concatenate(cand_tx, axis=1)
    co = jnp.concatenate(cand_ok, axis=1)
    # Priority: highest remaining tx budget first (freshest); ties broken by
    # position (stable sort), favoring this round's local observations.
    keep, (upd_target, upd_packed, upd_tx2) = routing.rebuild_bounded_queue(
        co, cx, (ct, cp, cx), cfg.backlog)
    upd_target = jnp.where(keep, upd_target, -1)

    # ---- 6. down-member GC (remove_down_after) -----------------------------
    if cfg.down_gc_rounds > 0:
        k_gc = jax.random.fold_in(k_goss, 7)
        drop = (packed_sev(view) == SEV_DOWN) & (
            jax.random.uniform(k_gc, view.shape) < 1.0 / cfg.down_gc_rounds
        )
        view = jnp.where(drop, 0, view)

    return SwimState(
        view=view,
        incarnation=new_inc,
        alive=alive,
        susp_target=susp_target,
        susp_inc=susp_inc,
        susp_started=susp_started,
        upd_target=upd_target,
        upd_packed=upd_packed,
        upd_tx=upd_tx2,
    )


def apply_churn(
    state: SwimState,
    kill: jax.Array,
    revive: jax.Array,
    rng: jax.Array | None = None,
    max_transmissions: int = 6,
    wipe: jax.Array | None = None,
) -> SwimState:
    """Ground-truth churn between rounds.

    ``kill``/``revive`` are bool[N]. A revived node renews its identity —
    incarnation bump, alive self-belief, and a self-announce queued — the
    analogue of Actor::renew auto-rejoin (actor.rs:169-194). When ``rng`` is
    given, each revived node also bootstrap-pulls the full membership view of
    one random alive peer, modeling the state transfer a SWIM announce gets
    from its seed (foca feeds joiners the member list; without this a
    rejoiner would have to re-probe every dead peer itself).

    ``wipe`` (bool[N], chaos plane) marks kills as crash-with-state-wipe:
    the process forgets every belief it held (its view row resets to the
    fresh-joiner prior), its suspicion timers, and its update queue.
    Its own INCARNATION is kept — and bumped on revive as usual —
    because identity must stay monotonic: restarting at incarnation 0
    would let stale suspect beliefs outrank the rejoin announce forever,
    the "resurrected zombie" failure the chaos invariants check for.
    Other nodes' beliefs ABOUT the wiped node are untouched; detecting
    the death is their job.
    """
    if wipe is not None:
        state = state._replace(
            view=jnp.where(wipe[:, None], jnp.uint32(0), state.view),
            susp_target=jnp.where(
                wipe[:, None], jnp.int32(-1), state.susp_target
            ),
            upd_target=jnp.where(
                wipe[:, None], jnp.int32(-1), state.upd_target
            ),
            upd_tx=jnp.where(wipe[:, None], jnp.int32(0), state.upd_tx),
        )
    alive = (state.alive & ~kill) | revive
    inc = jnp.where(revive, state.incarnation + 1, state.incarnation)
    n = state.view.shape[0]
    nodes = jnp.arange(n)
    self_pkd = pack(inc, SEV_ALIVE)
    view = _merge_scatter(state.view, nodes, nodes, self_pkd, revive)
    if rng is not None:
        # Random alive, non-revived seed per node (fallback: self → no-op).
        cand = jax.random.randint(rng, (4, n), 0, n)

        def pick(carry, t):
            ok = alive[t] & ~revive[t] & (carry < 0)
            return jnp.where(ok, t, carry), None

        seed, _ = jax.lax.scan(pick, jnp.full((n,), -1, jnp.int32), cand)
        seed = jnp.where(seed < 0, nodes, seed)
        pulled = jnp.maximum(view, view[seed])
        view = jnp.where(revive[:, None], pulled, view)
    # Queue the announce in slot of lowest priority (slot 0 after rebuilds is
    # highest; use the last slot).
    last = state.upd_target.shape[1] - 1
    upd_target = state.upd_target.at[:, last].set(
        jnp.where(revive, nodes.astype(jnp.int32), state.upd_target[:, last]))
    upd_packed = state.upd_packed.at[:, last].set(
        jnp.where(revive, self_pkd, state.upd_packed[:, last]))
    upd_tx = state.upd_tx.at[:, last].set(
        jnp.where(revive, max_transmissions, state.upd_tx[:, last]))
    return state._replace(
        alive=alive, incarnation=inc, view=view,
        upd_target=upd_target, upd_packed=upd_packed, upd_tx=upd_tx)


def mismatches(state: SwimState) -> jax.Array:
    """Exact count of (live observer, peer) beliefs that contradict truth.

    0 == the cluster has converged on the membership ground truth.
    """
    n = state.view.shape[0]
    believed_up = packed_sev(state.view) < SEV_DOWN
    truth = state.alive[None, :]
    obs = state.alive[:, None] & (jnp.arange(n)[None, :] != jnp.arange(n)[:, None])
    return jnp.sum((believed_up != truth) & obs)


def health_counts(state: SwimState) -> tuple[jax.Array, jax.Array]:
    """(false_alarms, undetected_deaths): the directional split of the
    membership error, per (live observer, non-self target) pair.

    - ``false_alarms``: the target is ALIVE but believed suspect or down
      — SWIM false suspicions (probe loss, slow refutation propagation).
      Strictly wider than the alarm half of ``mismatches()``, which only
      counts alive-believed-DOWN: a suspicion is already an alarm (the
      reference starts the suspect→down timer on it).
    - ``undetected_deaths``: the target is DEAD but still believed up
      (severity below down) — detection lag after a kill; the per-event
      rounds-to-detection curve derives from this host-side
      (sim.health.detection_latencies).
    """
    n = state.view.shape[0]
    sev = packed_sev(state.view)
    obs = state.alive[:, None] & (
        jnp.arange(n)[None, :] != jnp.arange(n)[:, None]
    )
    alive_t = state.alive[None, :]
    false_alarms = jnp.sum(
        obs & alive_t & (sev >= SEV_SUSPECT), dtype=jnp.uint32
    )
    undetected = jnp.sum(
        obs & ~alive_t & (sev < SEV_DOWN), dtype=jnp.uint32
    )
    return false_alarms, undetected


def accuracy(state: SwimState) -> jax.Array:
    """Approximate fraction of correct beliefs (f32; use mismatches() for
    exact convergence checks — XLA f32 division is reciprocal-based and
    rounds even x/x slightly below 1)."""
    n = state.view.shape[0]
    obs = state.alive[:, None] & (jnp.arange(n)[None, :] != jnp.arange(n)[:, None])
    total = jnp.maximum(jnp.sum(obs), 1)
    return 1.0 - mismatches(state) / total
