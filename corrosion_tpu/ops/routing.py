"""Message-routing primitives shared by the gossip-plane kernels.

The broadcast and membership planes both need the same awkward-on-TPU
operation: N nodes each emit a variable number of messages addressed to
arbitrary receivers, and each receiver may only absorb a bounded number K of
them per round (bounded queues — foca's updates backlog, corro-agent's
broadcast pending queue). `bounded_intake` implements it with one stable sort
by receiver plus a prefix-max rank, all static-shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def bounded_intake(
    recv: jax.Array,
    valid: jax.Array,
    payloads: tuple[jax.Array, ...],
    n_rows: int,
    k: int,
) -> tuple[jax.Array, tuple[jax.Array, ...]]:
    """Route flat messages to per-receiver slots, at most ``k`` per receiver.

    Args:
      recv: i32[M] receiver row per message.
      valid: bool[M] live messages.
      payloads: tuple of [M] arrays to deliver alongside.
      n_rows: number of receivers N.
      k: max messages absorbed per receiver per round.

    Returns:
      (mask[N, k], payloads_out) where payloads_out[i] has shape [N, k];
      slots beyond each receiver's message count are masked off. Which k
      messages win when more than k target one receiver is deterministic:
      lowest flat message index first (stable sort).
    """
    m = recv.shape[0]
    key = jnp.where(valid, recv, n_rows).astype(jnp.int32)
    # One fused sort carrying all payloads (vs argsort + one gather per
    # payload); stable keeps the documented lowest-index-wins guarantee.
    sorted_ops = jax.lax.sort((key, *payloads), num_keys=1, is_stable=True)
    s_key = sorted_ops[0]
    s_payloads = sorted_ops[1:]
    idxs = jnp.arange(m)
    run_first = jnp.where(
        jnp.concatenate(
            [jnp.array([True], dtype=bool), s_key[1:] != s_key[:-1]]
        ),
        idxs,
        0,
    )
    # lax.cummax, not associative_scan: the latter's recursive odd/even
    # decomposition makes XLA:TPU compile time explode at multi-million
    # element sizes (the 100k-node configs), while cummax lowers flat.
    run_first = jax.lax.cummax(run_first, axis=0)
    rank = idxs - run_first
    ok = (s_key < n_rows) & (rank < k)
    slot = jnp.where(ok, s_key * k + rank, n_rows * k)

    mask = (
        jnp.zeros((n_rows * k,), dtype=bool)
        .at[slot]
        .set(ok, mode="drop")
        .reshape(n_rows, k)
    )
    outs = []
    for sp in s_payloads:
        zero = jnp.zeros((n_rows * k,), dtype=sp.dtype)
        outs.append(
            zero.at[slot].set(jnp.where(ok, sp, 0), mode="drop").reshape(n_rows, k)
        )
    return mask, tuple(outs)


def segmented_prefix_and_rows(
    flags: jax.Array, seg_start: jax.Array
) -> jax.Array:
    """Per-segment running AND of ``flags`` along each row.

    [N, K] inputs with segments confined to a row (axis 1, marked by
    seg_start): out[n, i] = AND of flags[n, j] from the segment's first
    element to i. cummax/cumsum formulation — a segmented associative_scan
    would blow up XLA:TPU compile time at message-plane sizes, and the
    obvious take_along_axis(bad, segment_start) lowers as a serialized
    per-element gather (2 x 167 ms at [100k, 144] on v5e, the broadcast
    plane's single largest cost). Instead: ``g = bad-count strictly before
    i`` is non-decreasing, so the segment-start value is a running max of
    g captured at start positions — no gather at all."""
    bad = jnp.cumsum((~flags).astype(jnp.int32), axis=1)
    g = bad - (~flags).astype(jnp.int32)  # bad count strictly before i
    bad_before = jax.lax.cummax(jnp.where(seg_start, g, -1), axis=1)
    return (bad - bad_before) == 0


def segmented_running_max(
    vals: jax.Array,  # u32[N, K] values (must be < band)
    seg_start: jax.Array,  # bool[N, K] segment starts along axis 1
    band: int,  # static bound: vals < band, and #segments * band < 2^32
) -> jax.Array:
    """Per-segment inclusive running max of ``vals`` along each row.

    cummax over (segment_id * band + val): later segments' ids dominate,
    so the extracted low part resets at every segment start. Gather-free —
    the take_along_axis formulation lowers as a serialized per-element
    gather on TPU (see segmented_prefix_and_rows)."""
    k = vals.shape[1]
    # ceil(k)+1 possible segment ids per row.
    assert (k + 1) * band <= (1 << 32), "segment banding overflows u32"
    seg_id = jnp.cumsum(seg_start.astype(jnp.uint32), axis=1)
    packed = seg_id * jnp.uint32(band) + vals
    return jax.lax.cummax(packed, axis=1) % jnp.uint32(band)


def rebuild_bounded_queue(
    cand_valid: jax.Array,
    cand_prio: jax.Array,
    payloads: tuple[jax.Array, ...],
    capacity: int,
) -> tuple[jax.Array, tuple[jax.Array, ...]]:
    """Keep the ``capacity`` highest-priority candidates per row.

    cand_valid/cand_prio: [N, C]; payloads: tuple of [N, C]. Returns
    (mask[N, capacity], payloads[N, capacity]) sorted by descending priority
    (invalid candidates sort last regardless of priority). Priorities must be
    int32-safe.
    """
    neg_inf = jnp.int32(-(2**31) + 1)
    # Clamp real priorities one above the invalid sentinel so a legal
    # INT32_MIN+1 priority can never alias invalid (validity is inferred
    # from the key below).
    prio = jnp.where(
        cand_valid,
        jnp.maximum(cand_prio.astype(jnp.int32), neg_inf + 1),
        neg_inf,
    )
    # One fused sort carrying the payloads (vs argsort + a gather per
    # payload). Stable so over-capacity ties drop deterministically.
    # Validity rides the KEY (invalid = neg_inf sorts last), never as a
    # bool operand — TPU serializes pred permutations (~50 ms for a
    # [100k, 64] bool sort operand measured on v5e).
    sorted_ops = jax.lax.sort(
        (-prio, *payloads), dimension=1, num_keys=1, is_stable=True,
    )
    mask = sorted_ops[0][:, :capacity] < -neg_inf
    outs = tuple(p[:, :capacity] for p in sorted_ops[1:])
    return mask, outs
