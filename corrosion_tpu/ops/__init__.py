"""Batched JAX/XLA kernels for the cluster-simulation engine.

Each module is the TPU-native equivalent of a pure-logic component of the
reference (SURVEY.md §2, §7): interval tensors (rangemap), CRDT merge
(cr-sqlite LWW/causal-length), SWIM membership (foca), gossip fanout and
anti-entropy sync (corro-agent broadcast/peer). All ops are static-shape,
jit-safe, and vectorizable over a node/batch axis.
"""
